"""Benchmark: λ-grid GLM training + fused GAME sweep + hot-loop bandwidth.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"extra_metrics": [...]}. The primary metric is the vmapped λ-grid workload;
extra_metrics carry the flagship fused GAME sweep (SURVEY.md §3.1 call
stack) and the hot-loop HBM-bandwidth figures (autodiff/XLA vs the Pallas
kernel, vs the 819 GB/s v5e roofline).

Primary workload: the reference's hot loop (SURVEY.md §3.4) folded over a
32-point regularization grid — the λ-grid expansion of GameTrainingDriver
(:612-621) that the Spark reference trains sequentially, one L-BFGS run per
λ. Here the whole grid trains *simultaneously* (photon_ml_tpu
train_glm_grid): vmapped L-BFGS lanes share every read of the [n, d]
feature block, so per-lane margins become one X @ W matmul on the MXU, and
measured wall-clock is nearly flat in the number of lanes (extra λs are
almost free). ``vs_baseline`` is the ratio of example-iteration throughput
(examples x L-BFGS iterations per second) against scipy's Fortran L-BFGS-B
solving the same grid sequentially on the host CPU — iteration-normalized
because the two solvers terminate after different iteration counts
(stand-in for the reference's single-executor Breeze/JVM path; the
reference publishes no benchmark numbers, see BASELINE.md).

Measurement notes (tunneled/remote TPU backends):
- Every timing uses a host read as the synchronization point —
  block_until_ready alone does not synchronize on all remote platforms.
- Per-call tunnel dispatch is ~80-110 ms here; the grid metric honestly
  includes it, while the bandwidth/sweep figures are *marginal* (K-step
  differencing cancels the fixed cost — see BASELINE.md bandwidth study).
- Each rep perturbs warm starts / initial state from a fresh PRNG seed so
  no two executions are identical (some backends cache repeat executions).
- The CPU baseline runs on an n/8 subsample; both sides are expressed as
  example-iterations/sec, which is size-invariant (per-iteration cost is
  linear in n at fixed d).
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

# the measurement discipline (median-of-K, K_hi/K_lo differencing, stream
# calibration) lives in the telemetry library since r6 — bench.py is one
# consumer; probes imports no jax at module load, so the platform choice
# below still happens first
from photon_ml_tpu.telemetry.probes import (
    GATE_REPS,  # median-of-K for every gate metric (chip-lottery pool:
                # single-shot numbers swing ~2x between back-to-back reps —
                # BASELINE.md tenancy study; VERDICT r3 #8)
    MarginalTimer,
    median_spread,
    read_scalar,
    scan_step_marginal,
    stream_calibration,
)

N, D, MAX_ITER, GRID = 1 << 18, 512, 30, 32
CPU_SUBSAMPLE = 1 << 15
HBM_ROOFLINE_GBPS = 819.0  # v5e

#: the driver's artifact capture tails the last 2,000 bytes of stdout; the
#: ONE JSON line must fit or the official record loses the primary metric
#: (BENCH_r04/r05 both captured `parsed: null` from over-long unit prose).
#: Methodology prose lives in BASELINE.md + this module's docstrings; units
#: stay telegraphic. tests/test_bench_line.py pins the budget via
#: sample_report().
MAX_LINE_BYTES = 2000


# -- compact report rows (shared by the live bench and sample_report) --------


def _num(v: float):
    """Compact row number: one decimal below 1000, integer above (a 6e8
    rate's sub-unit digits are noise; the line budget is the constraint)."""
    return round(float(v), 1) if abs(v) < 1000 else int(round(float(v)))


def _row(metric: str, value: float, spread, unit: str) -> dict:
    return {"metric": metric, "value": _num(value),
            "spread": [_num(s) for s in spread], "unit": unit}


def render_report(report: dict) -> str:
    """The ONE stdout line: compact separators (no space after ,/:) — the
    driver tail-parses it as JSON either way, and the ~130 bytes of
    separator whitespace are better spent on metrics
    (tests/test_bench_line.py measures THIS rendering)."""
    return json.dumps(report, separators=(",", ":"))


def write_sidecar(report: dict, directory: str, *, config: dict | None = None):
    """The full UNSLIMMED report as ``<dir>/bench-report.json`` (ISSUE 12):
    never subject to the driver's 2,000-byte tail, every row's compact unit
    pre-parsed into typed fields (telemetry/bench_history.parse_unit), so
    ``dev/doctor.py`` reads structure instead of regexing the captured
    line — and prefers this file when present. The stdout contract is
    untouched: the ONE JSON line stays the driver's official record.
    Written atomically (tmp + os.replace); returns the final path."""
    import tempfile

    from photon_ml_tpu.telemetry.bench_history import (
        SIDECAR_FILENAME,
        parse_unit,
    )

    def with_parsed(row: dict) -> dict:
        return dict(row, parsed_unit=parse_unit(row["metric"], row["unit"]))

    sidecar = {
        "schema": 1,
        "kind": "bench_report",
        "config": config or {},
        "report": dict(
            with_parsed(report),
            extra_metrics=[with_parsed(r) for r in report["extra_metrics"]],
        ),
    }
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, SIDECAR_FILENAME)
    fd, staged = tempfile.mkstemp(dir=directory, prefix=".bench-report-",
                                  suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(sidecar, f, indent=2)
        os.replace(staged, path)
    except BaseException:
        if os.path.exists(staged):
            os.unlink(staged)
        raise
    return path


def _unit_primary(lane_iters: int, grid_sec: float) -> str:
    # config prose (n, d, λ-grid width, grid seconds) lives in the sidecar
    # config and BASELINE.md — the line budget spends on the lane-iteration
    # count
    del grid_sec
    return f"ex*it/s {lane_iters}it"


def _unit_stream() -> str:
    # same-run calibration probe; the row key names it, roof = v5e roofline
    return f"roof{HBM_ROOFLINE_GBPS:.0f}"


def _unit_hot_loop(note: str, frac: float) -> str:
    # the metric key already names the variant (the HOT_LOOP_NOTES prose
    # lives in BASELINE.md); ms/eval is derivable from GB/s over [n, d],
    # and the cal fraction from the same-run stream-probe row (the
    # documented calibration_fraction fallback) — budget-trimmed
    del note, frac
    return "GB/s"


def _unit_sweep(newton: bool) -> str:
    # the metric key names the variant — budget-trimmed
    del newton
    return "ms/sw"


def _unit_sweep_scheduled() -> str:
    # compare against fused_game_sweep_ms from the SAME run only (the
    # calibration discipline); includes the scheduler's host reads
    return "ms/sw"


def _unit_sweep_composed(ell_ms: float, cov: float) -> str:
    # compare against the embedded same-run ELL+unscheduled sweep only
    # (the calibration discipline); one Zipfian dataset, two configs —
    # cov rides the same-run hybrid row
    del cov
    return f"ms/sw ELLunsr {ell_ms:.0f}"


def _unit_sparse_1e7(ms_per_iter: float) -> str:
    del ms_per_iter  # derivable from the row value; budget-trimmed
    return "nnz*it/s d=1e7"


def _unit_sparse_hybrid(ell_ms: float, cov: float, k_hot: int) -> str:
    # compare against the embedded same-run ELL ms/it only (the calibration
    # discipline): same Zipfian data, same process, fractional comparison;
    # k_hot is fixed config (sidecar/BASELINE.md) — budget-trimmed
    del k_hot
    return f"ms/it cov{cov:.2f} ELLsr {ell_ms:.0f}"


def _unit_sparse_1e8(entry_iters_m: float) -> str:
    del entry_iters_m  # derivable from the row value; budget-trimmed
    # the metric key names d=1e8; hot512 is fixed config (BASELINE.md)
    return "ms/TRON-it"


def _unit_stream_game(visits_d: int, visits_u: int, sweeps_d: int,
                      sweeps_u: int, off_ms: float) -> str:
    # compare DuHL vs uniform from the SAME run only (the calibration
    # discipline): v = RE chunk visits to tolerance (ordered/uniform),
    # sw = sweeps to tolerance, OFF = same-run prefetch-OFF ms/sweep
    return (
        f"ms/sw v{visits_d}/{visits_u} "
        f"sw{sweeps_d}/{sweeps_u} OFF{off_ms:.0f}"
    )


def _unit_stream_game_ranks(rank_mb: float, input_mb: float,
                            one_rank_ms: float) -> str:
    # compare against the embedded same-run single-rank sweep ms only (the
    # calibration discipline); rb = max per-rank decoded bytes / global
    # input bytes — the partitioned-read evidence (each rank must decode
    # STRICTLY less than the whole input; wall-clock on virtual ranks is
    # thread-serialized and never the win criterion)
    return f"ms/sw rb{rank_mb:.2f}/{input_mb:.2f}MB 1rk{one_rank_ms:.0f}"


def _unit_refresh(lanes_solved: int, lanes_total: int, full_ms: float) -> str:
    # compare against the embedded same-run full-retrain ms only (the
    # calibration discipline); ln = RE lane-solves refresh/full — the
    # selection evidence (refresh must be STRICTLY fewer)
    return f"ms/rf ln{lanes_solved}/{lanes_total} fullsr {full_ms:.0f}"


def _unit_serve(p95_ms: float, unbatched_rate: float) -> str:
    # compare against the embedded same-run one-request-per-dispatch rate
    # only (the calibration discipline); p95 = request latency inside the
    # micro-batching loop at this replay's closed-loop arrival rate
    return f"sc/s p95 {p95_ms:.0f}ms 1/dsp sr {unbatched_rate:.0f}"


def _unit_search(seq_rate: float) -> str:
    # compare against the embedded same-run one-config-per-solve rate only
    # (the calibration discipline); seq = sequential configs/sec through
    # the SAME driver with lane_budget=1 — vmapped lanes are the only
    # lever; rounds/lane_budget are fixed config (sidecar/BASELINE.md)
    return f"cfg/s seq{seq_rate:.1f}"


def _unit_stream_chunked(off_ms: float, overlap: float, chunks: int) -> str:
    # compare against the embedded same-run prefetch-OFF ms/epoch only
    # (the calibration discipline); zdec = per-chunk zlib-inflate decode
    # stand-in; ovl = epoch overlap fraction (decode hidden behind compute)
    return f"ms/ep {chunks}ch OFF{off_ms:.0f} ovl{overlap:.2f}"


#: hot-loop row labels -> telegraphic GB/s notes (prose: BASELINE.md r4)
HOT_LOOP_NOTES = {
    "autodiff_xla": "2Xpass",
    "pallas_kernel": "1pass",
    "pallas_bf16": "bf16acc",
    "pallas_shardmap_mesh1": "shmap",
}


def sample_report() -> dict:
    """The report with worst-case-width representative values, through the
    SAME row/unit builders main() uses — what tests/test_bench_line.py
    measures against MAX_LINE_BYTES without touching a TPU.

    Widths are per metric CLASS, each comfortably above anything a sane
    run can produce (r1-r5 actuals: λ-grid rate ~6e8, GB/s ~750, sweeps
    18-50 ms, iters ≤ 750 ms, streamed epochs/sweeps ~1-3 s; main() still
    hard-raises if a pathological line exceeds the budget): training rate
    rows 1e9, bandwidth rows 1e4 GB/s (12x the roofline), per-iteration/
    sweep ms rows 1e4 (10+ s where actuals are sub-second), epoch-scale
    streaming ms rows 1e4 (10 s/epoch vs ~3 s worst observed), serving
    rows 1e6 sc/s / 1e4 ms p95 / 1e5 unbatched sc/s (decades above the
    tunnel's dispatch-bound reality), refresh lane pairs 3 digits (the
    bench fixture has 256 entities), partitioned-read MB pairs 99.99 (the
    ranks fixture is a fixed ~0.2 MB synthetic — byte counts are
    deterministic, not chip-lottery-scaled), search rows 1e4 cfg/s with a
    1e4-cfg/s embedded sequential rate (tournaments run tens of configs
    per second at best). The r20 line-budget trims: fixed-config fields
    (k_hot, d, λ-grid width) and the hot-loop cal fraction moved to the
    sidecar/BASELINE.md — the doctor recomputes the fraction from the
    same-run stream-probe row (calibration_fraction's documented
    fallback)."""
    rate, rate_sp = 999999999.9, [999999999.9, 999999999.9]
    gbps, gbps_sp = 9999.9, [9999.9, 9999.9]
    ms, ms_sp = 9999.9, [9999.9, 9999.9]
    sc, sc_sp = 999999.9, [999999.9, 999999.9]
    extra = [
        _row("fe_hot_loop_stream_gbps", gbps, gbps_sp,
             _unit_stream())
    ]
    extra += [
        _row(f"fe_hot_loop_hbm_gbps_{label}", gbps, gbps_sp,
             _unit_hot_loop(note, 9.99))
        for label, note in HOT_LOOP_NOTES.items()
    ]
    extra += [
        _row("fused_game_sweep_ms", ms, ms_sp, _unit_sweep(newton=False)),
        _row("fused_game_sweep_newton_ms", ms, ms_sp, _unit_sweep(newton=True)),
        _row("fused_game_sweep_scheduled_ms", ms, ms_sp,
             _unit_sweep_scheduled()),
        _row("sparse_giant_fe_entry_iters_per_sec", rate, rate_sp,
             _unit_sparse_1e7(9999.9)),
        _row("sparse_giant_fe_hybrid", ms, ms_sp,
             _unit_sparse_hybrid(9999.4, 9.99, 256)),
        _row("sparse_giant_fe_composed", ms, ms_sp,
             _unit_sweep_composed(9999.4, 9.99)),
        _row("sparse_1e8_fe_tron_ms_per_iter", ms, ms_sp,
             _unit_sparse_1e8(999.9)),
        _row("stream_fe_chunked", ms, ms_sp,
             _unit_stream_chunked(9999, 9.99, 99)),
        _row("stream_game_duhl", ms, ms_sp,
             _unit_stream_game(999, 999, 99, 99, 9999.4)),
        _row("stream_game_ranks", ms, ms_sp,
             _unit_stream_game_ranks(99.99, 99.99, 9999.4)),
        _row("serve_microbatch", sc, sc_sp,
             _unit_serve(9999.4, 99999.4)),
        _row("refresh_incremental", ms, ms_sp,
             _unit_refresh(999, 999, 9999.4)),
        _row("search_throughput", ms, ms_sp,
             _unit_search(9999.9)),
    ]
    report = _row(
        "glm_lambda_grid_example_iters_per_sec", rate, rate_sp,
        _unit_primary(99999, 999.999),
    )
    report["vs_baseline"] = 9999.99
    report["extra_metrics"] = extra
    return report


def _make_data(n: int, d: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(d,)).astype(np.float32) / np.sqrt(d)
    x = rng.normal(size=(n, d)).astype(np.float32)
    logits = x @ w_true
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
    return x, y


def _grid(k: int) -> np.ndarray:
    return np.logspace(-2, 2, k)


def bench_tpu(x, y):
    """Returns (median_grid_sec, [min, max], lane_iters) for one 32-λ grid."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import LabeledPointBatch
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.optim.lbfgs import minimize_lbfgs

    n, d = x.shape
    batch = LabeledPointBatch.create(jax.device_put(x), jax.device_put(y))
    # use_pallas=False: the grid vmaps 32 solver lanes over one X read — a
    # Pallas call inside the vmapped while_loop would batch into a serial
    # per-lane loop (measured 40x slower; see ops/objective.py docstring)
    objective = GLMObjective(LogisticLoss(), l2_weight=0.0, use_pallas=False)

    # The same vmapped-lane program train_glm_grid compiles, inlined so the
    # bench can read per-lane iteration counts and sync on a scalar.
    @jax.jit
    def run_grid(b, l2v, seed):
        bound = objective.bind(b)

        def solve_one(l2, key):
            def vg(w):
                v, g = bound.value_and_grad(w)
                return v + 0.5 * l2 * jnp.vdot(w, w), g + l2 * w

            w0 = 1e-4 * jax.random.normal(key, (d,), jnp.float32)
            return minimize_lbfgs(vg, w0, max_iter=MAX_ITER, tolerance=0.0)

        keys = jax.random.split(jax.random.PRNGKey(seed), l2v.shape[0])
        rs = jax.vmap(solve_one)(l2v, keys)
        return rs.iterations.sum(), rs.value.sum()

    l2v = jnp.asarray(_grid(GRID), jnp.float32)
    float(run_grid(batch, l2v, 0)[1])  # compile + sync

    def timed(k, seed0):
        # k pipelined grid solves (fresh PRNG warm starts), one final host
        # read: per-call dispatch overlaps device execution, so k-vs-1
        # differencing isolates the device time of one full grid
        t0 = time.perf_counter()
        results = [run_grid(batch, l2v, seed0 + i) for i in range(k)]
        for _, checksum in results:
            float(checksum)  # host read: hard sync
        elapsed = time.perf_counter() - t0
        return elapsed, sum(int(it) for it, _ in results)

    state = {"iters": 0, "seed": [0]}

    def once():
        s0 = state["seed"][0]
        state["seed"][0] += 100
        lo = min(timed(1, s0 + s)[0] for s in (1, 2))
        hi_t, hi_iters = min(
            (timed(3, s0 + s) for s in (10, 20)), key=lambda r: r[0]
        )
        state["iters"] = hi_iters // 3
        return max((hi_t - lo) / 2, 1e-6)

    marginal, spread = median_spread(once)
    return marginal, spread, state["iters"]


def bench_hot_loop_bandwidth(x, y) -> list[dict]:
    """Marginal per-eval cost of the FE value+gradient hot loop: the
    single-pass Pallas kernel (the TPU DEFAULT since r4 — f32 and bf16
    feature blocks) vs autodiff/XLA (2 X passes), as achieved HBM GB/s
    against a same-run stream calibration.

    K-step ``lax.scan`` differencing (K_hi vs K_lo evals in one jit call)
    cancels the ~100 ms fixed tunnel dispatch; every figure is a
    median-of-GATE_REPS marginal with [min, max] spread.
    """
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import LabeledPointBatch
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.ops.objective import GLMObjective

    from photon_ml_tpu.data.game_data import build_game_dataset
    from photon_ml_tpu.io.data_reader import (
        FeatureShardConfiguration,
        shard_np_dtypes,
    )
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.parallel.sharded_dense import ShardedDenseGLMObjective

    n, d = x.shape
    xbytes = n * d * 4
    batch = LabeledPointBatch.create(jax.device_put(x), jax.device_put(y))
    # bf16 block via the PRODUCT path: FeatureShardConfiguration(dtype=bf16)
    # -> shard_np_dtypes -> build_game_dataset(shard_dtypes=...), the exact
    # chain `--feature-shard-configurations ...,dtype=bf16` drives — so
    # this row measures what the CLI actually feeds the hot loop
    # (VERDICT r4 #3)
    _ds = build_game_dataset(
        labels=y, feature_shards={"global": x},
        shard_dtypes=shard_np_dtypes(
            {"global": FeatureShardConfiguration(("f",), dtype="bfloat16")}
        ),
    )
    batch_bf16 = LabeledPointBatch.create(
        _ds.feature_shards["global"], jax.device_put(y)
    )
    assert batch_bf16.features.dtype == jnp.bfloat16
    del _ds
    # wide K spread: per-call tunnel dispatch jitters by tens of ms, so the
    # K_hi-K_lo device-time delta must dwarf it (BENCH_r03 saw a 80-eval
    # spread produce a NEGATIVE marginal under dispatch noise)
    k_lo, k_hi = 16, 256
    rng = np.random.default_rng(7)

    def marginal_of(step_fn, b):
        return scan_step_marginal(
            step_fn, b, d, k_lo=k_lo, k_hi=k_hi, reps=GATE_REPS, rng=rng
        )

    # Same-run stream calibration (one X read per step): the tunnel pool's
    # chips vary run to run (567-747 GB/s across rounds of one process), so
    # fractions are only meaningful against THIS run's chip. Note the probe
    # is an XLA matvec and slightly UNDERESTIMATES peak (the r4 kernel
    # sustains ~1.1x it), so fractions >1.0 are real.
    cal = stream_calibration(
        batch.features, k_lo=k_lo, k_hi=k_hi, reps=GATE_REPS, rng=rng
    )
    stream_gbps = cal["gbps"]
    out = [_row(
        "fe_hot_loop_stream_gbps",
        round(stream_gbps, 1),
        [round(s, 1) for s in cal["spread_gbps"]],
        _unit_stream(),
    )]
    # prose for each row lives in HOT_LOOP_NOTES + BASELINE.md (the r4
    # kernel study); bf16 rides the reader's dtype=bf16 product cast so
    # this measures what the CLI actually feeds the hot loop (VERDICT r4
    # #3); mesh1 = the same kernel inside shard_map (parallel/
    # sharded_dense.py, the multi-chip path — parity means the wrapper is
    # free, VERDICT r4 #1)
    for label, obj, b, nbytes in (
        ("autodiff_xla",
         GLMObjective(LogisticLoss(), l2_weight=0.5, use_pallas=False),
         batch, xbytes),
        ("pallas_kernel",
         GLMObjective(LogisticLoss(), l2_weight=0.5, use_pallas=True),
         batch, xbytes),
        ("pallas_bf16",
         GLMObjective(LogisticLoss(), l2_weight=0.5, use_pallas=True),
         batch_bf16, xbytes // 2),
        ("pallas_shardmap_mesh1",
         ShardedDenseGLMObjective(LogisticLoss(), make_mesh(data=1, model=1),
                                  l2_weight=0.5, use_pallas=True),
         batch, xbytes),
    ):
        def step(w, bb, _obj=obj):
            v, g = _obj.value_and_gradient(w, bb)
            return w - 1e-4 * g, v

        m, sp = marginal_of(step, b)
        out.append(_row(
            f"fe_hot_loop_hbm_gbps_{label}",
            round(nbytes / m / 1e9, 1),
            [round(nbytes / s / 1e9, 1) for s in sp[::-1]],
            _unit_hot_loop(
                HOT_LOOP_NOTES[label],
                xbytes / m / 1e9 / stream_gbps,
            ),
        ))
    return out


def bench_game_sweep() -> list[dict]:
    """The flagship workload (SURVEY §3.1): one fused GAME CD sweep — FE +
    2 RE coordinates + rescoring — as marginal ms/sweep (sweep-count
    differencing cancels dispatch + input-layout fixed costs).

    Two rows: the historical metric (10 LBFGS iters/coordinate, unchanged
    definition since r1) and the same sweep with the RE coordinates on the
    r5 batched-Newton solver (optim/newton.py). The r5 decomposition
    (experiments/sweep_decompose_r5.log) attributed ~87% of the sweep to
    the two vmapped RE LBFGS solves (~2 ms per coordinate-iteration,
    op-count-bound at ~40x the bucket's streaming cost); Newton does the
    same per-entity convergence in ~4 fused ops per iteration and
    converges small-d GLMs quadratically."""
    import jax

    from photon_ml_tpu.data.game_data import (
        build_game_dataset,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.optim.optimizer import OptimizerConfig, OptimizerType
    from photon_ml_tpu.parallel.distributed import (
        FixedEffectStepSpec,
        GameTrainProgram,
        GameTrainState,
        RandomEffectStepSpec,
    )
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(0)
    n, d_fe, d_re = 1 << 17, 256, 16
    n_users, n_items = 2000, 1500
    users = np.array([f"u{i}" for i in rng.integers(0, n_users, size=n)])
    items = np.array([f"i{i}" for i in rng.integers(0, n_items, size=n)])
    x_fe = rng.normal(size=(n, d_fe)).astype(np.float32)
    x_re = rng.normal(size=(n, d_re)).astype(np.float32)
    y = (x_fe @ rng.normal(size=d_fe).astype(np.float32) / np.sqrt(d_fe)
         + rng.normal(size=n).astype(np.float32))
    dataset = build_game_dataset(
        labels=y,
        feature_shards={"global": x_fe, "per_entity": x_re},
        entity_keys={"user": users, "item": items},
        dtype=np.float32,
    )
    re_datasets = {
        t: build_random_effect_dataset(dataset, t, "per_entity",
                                       bucket_sizes=(128,))
        for t in ("user", "item")
    }
    from photon_ml_tpu.optim.optimizer import LaneSchedulerConfig

    opt = OptimizerConfig(optimizer_type=OptimizerType.LBFGS, max_iterations=10)
    newton = OptimizerConfig(optimizer_type=OptimizerType.NEWTON,
                             max_iterations=10)
    # probe/rescue lane scheduling (algorithm/lane_scheduler.py) + the live
    # function-decrease stop: the same 10-iteration LBFGS budget, but lanes
    # that converge in the 2-iteration probe never pay the rest. Compare
    # against fused_game_sweep_ms from the SAME run per the calibration
    # discipline — the scheduled step's host reads ride the marginal.
    scheduled = OptimizerConfig(
        optimizer_type=OptimizerType.LBFGS, max_iterations=10,
        rel_function_tolerance=1e-6,
        scheduler=LaneSchedulerConfig(probe_iterations=2),
    )

    def make_program(re_opt):
        return GameTrainProgram(
            TaskType.LINEAR_REGRESSION,
            FixedEffectStepSpec(feature_shard_id="global", optimizer=opt,
                                l2_weight=1.0),
            (
                RandomEffectStepSpec("user", "per_entity", re_opt, l2_weight=1.0),
                RandomEffectStepSpec("item", "per_entity", re_opt, l2_weight=1.0),
            ),
            use_pallas_fe=True,  # single chip: the FE solve takes the kernel
        )

    def measure(program, step_fn=None):
        return _sweep_marginal(program, dataset, re_datasets,
                               step_fn=step_fn)

    per_sweep, sp = measure(make_program(opt))
    newton_sweep, newton_sp = measure(make_program(newton))

    sched_program = make_program(scheduled)
    from photon_ml_tpu.algorithm.lane_scheduler import LaneScheduler

    schedulers = {
        s.re_type: LaneScheduler(s.optimizer.scheduler)
        for s in sched_program.re_specs
    }

    def sched_step(data, buckets, state):
        return sched_program.step_scheduled(
            data, buckets, state, schedulers=schedulers
        )

    sched_sweep, sched_sp = measure(sched_program, step_fn=sched_step)
    return [
        _row(
            "fused_game_sweep_ms",
            round(per_sweep * 1e3, 1),
            [round(s * 1e3, 1) for s in sp],
            _unit_sweep(newton=False),
        ),
        _row(
            "fused_game_sweep_newton_ms",
            round(newton_sweep * 1e3, 1),
            [round(s * 1e3, 1) for s in newton_sp],
            _unit_sweep(newton=True),
        ),
        _row(
            "fused_game_sweep_scheduled_ms",
            round(sched_sweep * 1e3, 1),
            [round(s * 1e3, 1) for s in sched_sp],
            _unit_sweep_scheduled(),
        ),
    ]


def _sweep_marginal(program, dataset, re_datasets, step_fn=None):
    """Marginal seconds per fused GAME sweep (K-sweep differencing, fresh
    perturbed warm starts per rep — the fused-sweep discipline shared by
    bench_game_sweep and bench_game_sweep_composed). Returns (median,
    spread) like MarginalTimer."""
    import jax

    from photon_ml_tpu.parallel.distributed import GameTrainState

    step = step_fn if step_fn is not None else program.step
    data, buckets = program.prepare_inputs(dataset, re_datasets, None)
    base_state = program.init_state(dataset, re_datasets, None)

    def perturbed(seed):
        # fresh warm start per rep: identical repeat executions can be
        # served from a backend cache (see module docstring)
        key = jax.random.PRNGKey(seed)
        keys = jax.random.split(key, 1 + len(base_state.re_tables))
        return GameTrainState(
            fe_coefficients=base_state.fe_coefficients
            + 1e-3 * jax.random.normal(keys[0], base_state.fe_coefficients.shape),
            re_tables={
                t: tab + 1e-3 * jax.random.normal(k, tab.shape)
                for k, (t, tab) in zip(keys[1:], base_state.re_tables.items())
            },
            mf_rows=dict(base_state.mf_rows),
            mf_cols=dict(base_state.mf_cols),
        )

    def timed(k, seed):
        # k dispatches enqueue asynchronously (no host read between
        # sweeps), so per-call dispatch overlaps device execution and
        # the K-step differencing isolates true per-sweep device time
        state = perturbed(seed)
        t0 = time.perf_counter()
        for _ in range(k):
            state, loss = step(data, buckets, state)
        read_scalar(state.fe_coefficients)  # host read: hard sync
        return time.perf_counter() - t0

    timed(1, 0)  # compile + sync
    seed = [0]

    def timed_k(k):
        # two fresh-seed attempts per K, keep the best (dispatch noise)
        s0 = seed[0]
        seed[0] += 5
        return min(timed(k, s0 + s) for s in (1, 2))

    result = MarginalTimer(k_lo=1, k_hi=5, reps=GATE_REPS).measure(timed_k)
    return result.median, result.spread


def bench_game_sweep_composed() -> dict:
    """The composed configuration's device cost (ISSUE 6): ONE Zipfian
    sparse-FE GAME dataset, two configurations of the same fused sweep
    measured back to back in THIS process — (a) ELL layout + unscheduled
    RE solves (the r5-era shape) embedded in the unit, (b) hybrid hot-256
    head + probe2/rescue-scheduled RE solves, the row value. Fractional
    same-run comparison per the calibration discipline.

    The multi-host seams (partitioned ingest, SPMD rescue blocks) are
    host-side and pinned on the CPU mesh (tests/test_composed_path.py);
    what this row prices is the composed DEVICE path: hybrid margins/
    gradients inside the fused FE solve + scheduler-driven probe/rescue
    blocks for the vmapped RE solves, composing the r6 layout win with
    the r8 scheduling win on one workload."""
    import dataclasses as _dc

    from photon_ml_tpu.algorithm.lane_scheduler import LaneScheduler
    from photon_ml_tpu.data.game_data import (
        build_game_dataset,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.data.sparse_batch import HybridPolicy, SparseShard
    from photon_ml_tpu.optim.optimizer import (
        LaneSchedulerConfig,
        OptimizerConfig,
        OptimizerType,
    )
    from photon_ml_tpu.parallel.distributed import (
        FixedEffectStepSpec,
        GameTrainProgram,
        RandomEffectStepSpec,
    )
    from photon_ml_tpu.telemetry import default_registry
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(13)
    n, d, per_row, k_hot, d_re = 1 << 16, 1_000_000, 16, 256, 16
    rows = np.repeat(np.arange(n), per_row)
    cols = _zipf_cols(rng, n * per_row, d)
    vals = (rng.normal(size=n * per_row) / np.sqrt(per_row)).astype(np.float32)
    y = vals.reshape(n, per_row).sum(axis=1) + 0.1 * rng.normal(
        size=n
    ).astype(np.float32)
    users = np.array([f"u{i}" for i in rng.integers(0, 2000, size=n)])
    x_re = rng.normal(size=(n, d_re)).astype(np.float32)
    shard = SparseShard(
        rows=rows.astype(np.int64), cols=cols.astype(np.int64), vals=vals,
        num_samples=n, feature_dim=d,
    )
    hyb_shard = _dc.replace(
        shard,
        hybrid_policy=HybridPolicy(hot_cols=k_hot, label="bench_composed"),
    )

    def make_dataset(fe_shard):
        return build_game_dataset(
            labels=y,
            feature_shards={"global": fe_shard, "per_entity": x_re},
            entity_keys={"user": users},
            dtype=np.float32,
        )

    opt = OptimizerConfig(optimizer_type=OptimizerType.LBFGS,
                          max_iterations=10)
    re_sched = OptimizerConfig(
        optimizer_type=OptimizerType.LBFGS, max_iterations=10,
        rel_function_tolerance=1e-6,
        scheduler=LaneSchedulerConfig(probe_iterations=2),
    )

    def make_program(re_opt):
        return GameTrainProgram(
            TaskType.LINEAR_REGRESSION,
            FixedEffectStepSpec(feature_shard_id="global", optimizer=opt,
                                l2_weight=1.0),
            (RandomEffectStepSpec("user", "per_entity", re_opt,
                                  l2_weight=1.0),),
        )

    ell_dataset = make_dataset(shard)
    ell_res = build_random_effect_dataset(ell_dataset, "user", "per_entity",
                                          bucket_sizes=(128,))
    ell_sweep, _ = _sweep_marginal(make_program(opt), ell_dataset,
                                   {"user": ell_res})

    hyb_dataset = make_dataset(hyb_shard)
    hyb_res = build_random_effect_dataset(hyb_dataset, "user", "per_entity",
                                          bucket_sizes=(128,))
    program = make_program(re_sched)
    schedulers = {
        s.re_type: LaneScheduler(s.optimizer.scheduler)
        for s in program.re_specs if s.optimizer.scheduler is not None
    }

    def sched_step(data, buckets, state):
        return program.step_scheduled(data, buckets, state,
                                      schedulers=schedulers)

    composed, sp = _sweep_marginal(program, hyb_dataset, {"user": hyb_res},
                                   step_fn=sched_step)
    cov = (default_registry().gauge("layout/bench_composed/hot_coverage")
           .value or 0.0)
    return _row(
        "sparse_giant_fe_composed",
        round(composed * 1e3, 1),
        [round(s * 1e3, 1) for s in sp],
        _unit_sweep_composed(ell_sweep * 1e3, cov),
    )


def _lbfgs_iter_marginal(obj, batch, d: int, k_lo: int = 4, k_hi: int = 16):
    """Median-of-GATE_REPS marginal seconds per extra L-BFGS iteration over
    one sparse batch (fresh-PRNG warm starts, k_hi-vs-k_lo differencing —
    the sparse-row discipline since r3). The batch rides as a jit ARGUMENT:
    closing over it would embed the entry arrays as constants in the
    remote-compile request (HTTP 413 over the tunnel — the real cause of
    r2's "compile service drops")."""
    import jax
    import jax.numpy as jnp

    from functools import partial

    from photon_ml_tpu.optim.lbfgs import minimize_lbfgs

    @partial(jax.jit, static_argnums=(2,))
    def run(w0, b, iters):
        r = minimize_lbfgs(obj.bind(b).value_and_grad, w0, max_iter=iters,
                           tolerance=0.0)
        return r.value + r.coefficients[0]

    def timed(iters, seed):
        key = jax.random.PRNGKey(seed)
        w0 = 1e-3 * jax.random.normal(key, (d,), jnp.float32)
        float(run(w0, batch, iters))  # compile + sync
        best = None
        for s in range(2):
            w0 = 1e-3 * jax.random.normal(jax.random.PRNGKey(seed + s + 1), (d,))
            t0 = time.perf_counter()
            float(run(w0.astype(jnp.float32), batch, iters))
            el = time.perf_counter() - t0
            best = el if best is None or el < best else best
        return best

    seed = [0]

    def once():
        s0 = seed[0]
        seed[0] += 1000
        return max(
            (timed(k_hi, s0) - timed(k_lo, s0 + 100)) / (k_hi - k_lo), 1e-6
        )

    return median_spread(once)


def _zipf_cols(rng, size: int, d: int, gamma: float = 24.0) -> np.ndarray:
    """Bounded power-law column ids (top-k nnz share (k/d)^(1/gamma)),
    scattered over [0, d) by an odd multiplicative bijection so the hot set
    is NOT contiguous — Photon's name-term bags are power-law distributed;
    this is the regime the hybrid layout exists for."""
    raw = (rng.random(size) ** gamma * d).astype(np.int64)
    return (raw * 2654435761) % d  # odd, not divisible by 5: bijective mod 10^k


def bench_sparse_fe() -> dict:
    """Giant-d sparse fixed effect on hardware: d=10⁷ logistic L-BFGS over
    flat-COO data (dense [n, d] would be n·d·4 ≈ 21 TB — the path the
    reference's 'hundreds of billions of coefficients' claim needs).
    Reported as entry-iterations/sec, marginal over extra iterations."""
    from photon_ml_tpu.data.sparse_batch import SparseLabeledPointBatch
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.ops.sparse_objective import SparseGLMObjective

    rng = np.random.default_rng(3)
    n, d, per_row = 1 << 19, 10_000_000, 32
    rows = np.repeat(np.arange(n), per_row)
    cols = rng.integers(0, d, size=n * per_row)
    vals = rng.normal(size=n * per_row).astype(np.float32)
    support = rng.choice(d, size=256, replace=False)
    w_true = np.zeros(d, dtype=np.float32)
    w_true[support] = rng.normal(size=256).astype(np.float32)
    sig = rng.integers(0, 256, size=(n, 4))
    sig_vals = rng.normal(size=(n, 4)).astype(np.float32)
    logits = (sig_vals * w_true[support][sig]).sum(axis=1)
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
    rows = np.concatenate([rows, np.repeat(np.arange(n), 4)])
    cols = np.concatenate([cols, support[sig].ravel()])
    vals = np.concatenate([vals, sig_vals.ravel()])
    nnz = len(vals)
    # default ELL layout: dense row-sum margins + broadcast dz (measured
    # 330 ms/iter vs 644 flat-COO vs 733 in r2 — BASELINE.md r3 study; the
    # remaining cost is the w-gather at ~7 ns/index and the transpose
    # scatter, both per-index-rate-bound on v5e)
    batch = SparseLabeledPointBatch.from_coo(rows, cols, vals, y, dim=d,
                                             dtype=np.float32)
    obj = SparseGLMObjective(LogisticLoss(), l2_weight=0.1)
    marginal, sp = _lbfgs_iter_marginal(obj, batch, d)
    return _row(
        "sparse_giant_fe_entry_iters_per_sec",
        round(nnz / marginal, 1),
        [round(nnz / s, 1) for s in sp[::-1]],
        _unit_sparse_1e7(marginal * 1e3),
    )


def bench_sparse_fe_hybrid() -> dict:
    """Same-run hybrid-vs-ELL comparison on Zipfian-column synthetic data
    (ISSUE 5): ONE dataset, two layouts of it, both L-BFGS-iteration
    marginals measured in THIS process back to back — the fractional
    comparison the calibration discipline requires (chip-lottery pool;
    never compare absolute ms across runs).

    The hybrid view trains the 256 nnz-hottest columns (~0.6 of nonzeros
    at gamma=24) as one dense [n, 256] MXU block — ZERO per-entry index
    ops for covered entries — while the ELL tail shrinks to the cold
    residual; the expected win is index-op removal proportional to hot
    coverage (BASELINE.md r6 methodology)."""
    from photon_ml_tpu.data.sparse_batch import (
        HybridPolicy,
        SparseLabeledPointBatch,
    )
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.ops.sparse_objective import SparseGLMObjective
    from photon_ml_tpu.telemetry import default_registry

    rng = np.random.default_rng(11)
    n, d, per_row, k_hot = 1 << 19, 10_000_000, 32, 256
    rows = np.repeat(np.arange(n), per_row)
    cols = _zipf_cols(rng, n * per_row, d)
    vals = rng.normal(size=n * per_row).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    nnz = len(vals)
    common = dict(dim=d, dtype=np.float32)
    ell_batch = SparseLabeledPointBatch.from_coo(rows, cols, vals, y, **common)
    hyb_batch = SparseLabeledPointBatch.from_coo(
        rows, cols, vals, y,
        hybrid=HybridPolicy(hot_cols=k_hot, label="bench_1e7"), **common,
    )
    cov = default_registry().gauge("layout/bench_1e7/hot_coverage").value or 0.0
    obj = SparseGLMObjective(LogisticLoss(), l2_weight=0.1)
    ell_marginal, _ = _lbfgs_iter_marginal(obj, ell_batch, d)
    hyb_marginal, hyb_sp = _lbfgs_iter_marginal(obj, hyb_batch, d)
    return _row(
        "sparse_giant_fe_hybrid",
        round(hyb_marginal * 1e3, 1),
        [round(s * 1e3, 1) for s in hyb_sp],
        _unit_sparse_hybrid(ell_marginal * 1e3, cov, k_hot),
    )


def bench_sparse_fe_1e8() -> dict:
    """d=10⁸ sparse FE via TRON (VERDICT r2 #5: a step toward the
    reference's 'hundreds of billions of coefficients', README.md:77).
    TRON holds O(1) work vectors of size d where LBFGS history is 2·m·d —
    the survey's hard-parts recipe (SURVEY.md §7). Since r6 the columns are
    Zipfian (the realistic name-term regime) and the batch rides the hybrid
    layout, so TRON's CG inner loop takes the split hessian_vector: the hot
    head's forward AND transpose are dense matmuls, only the cold tail pays
    per-entry index ops (ISSUE 5 — what moves this row)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.sparse_batch import (
        HybridPolicy,
        SparseLabeledPointBatch,
    )
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.ops.sparse_objective import SparseGLMObjective
    from photon_ml_tpu.optim.tron import minimize_tron

    from functools import partial

    rng = np.random.default_rng(5)
    n, d, per_row = 1 << 18, 100_000_000, 16
    rows = np.repeat(np.arange(n), per_row)
    cols = _zipf_cols(rng, n * per_row, d)
    vals = rng.normal(size=n * per_row).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    nnz = len(vals)
    batch = SparseLabeledPointBatch.from_coo(
        rows, cols, vals, y, dim=d, dtype=np.float32,
        hybrid=HybridPolicy(hot_cols=512, label="bench_1e8"),
    )
    obj = SparseGLMObjective(LogisticLoss(), l2_weight=0.1)

    @partial(jax.jit, static_argnums=(2,))
    def run(w0, b, iters):
        bound = obj.bind(b)
        r = minimize_tron(bound.value_and_grad, bound.hessian_vector, w0,
                          max_iter=iters, max_cg_iter=2, tolerance=0.0)
        return r.value + r.coefficients[0]

    def timed(iters, seed):
        w0 = 1e-3 * jax.random.normal(jax.random.PRNGKey(seed), (d,), jnp.float32)
        float(run(w0, batch, iters))  # compile + sync
        best = None
        for s in range(2):
            w0 = 1e-3 * jax.random.normal(jax.random.PRNGKey(seed + s + 1),
                                          (d,), jnp.float32)
            t0 = time.perf_counter()
            float(run(w0, batch, iters))
            el = time.perf_counter() - t0
            best = el if best is None or el < best else best
        return best

    k_lo, k_hi = 2, 8
    seed = [0]

    def once():
        s0 = seed[0]
        seed[0] += 1000
        return max(
            (timed(k_hi, s0) - timed(k_lo, s0 + 100)) / (k_hi - k_lo), 1e-6
        )

    marginal, sp = median_spread(once)
    return _row(
        "sparse_1e8_fe_tron_ms_per_iter",
        round(marginal * 1e3, 1),
        [round(s * 1e3, 1) for s in sp],
        _unit_sparse_1e8(nnz / marginal / 1e6),
    )


def bench_stream_fe_chunked() -> dict:
    """Out-of-core chunked epoch, prefetch ON vs OFF back to back in THIS
    process (ISSUE 7). One synthetic d=512 dense dataset streams as 16
    fixed-shape chunks; every load pays a REAL host decode (zlib inflate
    of a 1/8-chunk deflate payload — the Avro block-decompress stand-in,
    scaled down to keep the bench inside the driver budget)
    before the device accumulates value+grad through the one module-level
    jit signature (chunks as ARGUMENTS; the 413 rule). Row value is the
    prefetch-ON ms/epoch; the same-run OFF ms/epoch and the epoch overlap
    fraction ride the unit — the win is decode hidden behind device
    compute, bounded by the decode/compute ratio, never comparable across
    runs (chip-lottery pool; BASELINE.md streaming methodology)."""
    import zlib

    import jax.numpy as jnp

    from photon_ml_tpu.algorithm.streaming import StreamingGLMObjective
    from photon_ml_tpu.io.stream_reader import ArrayChunkSource
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.telemetry import stream_counters

    n, chunk_rows = 1 << 17, 1 << 14  # 8 chunks/epoch, n >> chunk budget
    x, y = _make_data(n, D, seed=5)
    # the decode stand-in has BOTH host costs of a real Avro chunk: a
    # storage-latency wait (sleep — not CPU; this is what hides behind
    # compute even on the 1-core CPU mesh) and a CPU decompress (zlib
    # inflate of a 1/8-chunk deflate payload — scaled down for bench
    # budget, so the CPU cost class is PRESENT but smaller than a real
    # chunk's; hides only when compute runs off-host, i.e. on the TPU)
    blob = zlib.compress(x[: chunk_rows // 8].tobytes(), 1)

    def decode():
        time.sleep(0.008)
        np.frombuffer(zlib.decompress(blob), dtype=np.float32)

    source = ArrayChunkSource(x, y, chunk_rows=chunk_rows, decode_hook=decode)
    w = jnp.zeros((D,), jnp.float32)
    loss = LogisticLoss()

    def epoch_ms(prefetch: bool):
        obj = StreamingGLMObjective(
            source, loss, l2_weight=0.1, prefetch=prefetch
        )
        read_scalar(obj.value_and_grad(w)[0])  # warm the one jit signature

        def once():
            t0 = time.perf_counter()
            read_scalar(obj.value_and_grad(w)[0])
            return (time.perf_counter() - t0) * 1e3

        return median_spread(once)

    off_ms, _off_sp = epoch_ms(False)
    on_ms, on_sp = epoch_ms(True)  # overlap gauge left by the last ON epoch
    return _row(
        "stream_fe_chunked",
        round(on_ms, 1),
        [round(s, 1) for s in on_sp],
        _unit_stream_chunked(
            off_ms, stream_counters.overlap_fraction(), source.num_chunks
        ),
    )


def bench_stream_game_duhl() -> dict:
    """Streamed GAME with the DuHL importance-ordered chunk schedule vs
    uniform sweeps, back to back in THIS process (ISSUE 11). One
    gap-skewed synthetic GAME dataset (hot entities coupled to the FE
    signal, cold entities decoupled — the data shape DuHL exists for)
    streams as entity-clustered chunks with a real per-load host decode
    (sleep + zlib inflate, the Avro stand-in); both modes train to the
    SAME loss-plateau tolerance. Row value is the DuHL prefetch-ON
    ms/sweep; the unit embeds the acceptance evidence — RE chunk visits
    to tolerance ordered vs uniform (same run) and the same-run
    prefetch-OFF ms/sweep. Chunk-visit counts are deterministic; ms/sweep
    is chip-lottery-sensitive and only comparable within the run."""
    import time as _time
    import zlib

    from photon_ml_tpu.algorithm.streaming_game import (
        DuHLChunkSchedule,
        DuHLScheduleConfig,
        StreamingGameProgram,
    )
    from photon_ml_tpu.io.stream_reader import GameArrayChunkSource
    from photon_ml_tpu.optim.optimizer import OptimizerConfig
    from photon_ml_tpu.parallel.distributed import (
        FixedEffectStepSpec,
        RandomEffectStepSpec,
    )
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(13)
    d_fe, d_re = 32, 8
    hot_rows, cold_rows = 512, 1536
    n = hot_rows + cold_rows
    ents = np.concatenate([
        np.repeat(np.arange(4), hot_rows // 4),
        4 + np.arange(cold_rows) // 16,
    ]).astype(np.int32)
    x_fe = rng.normal(size=(n, d_fe)).astype(np.float32)
    x_fe[hot_rows:] = 0.0
    x_re = rng.normal(size=(n, d_re)).astype(np.float32)
    w_fe = rng.normal(size=d_fe).astype(np.float32)
    w_re = 0.5 * rng.normal(size=(int(ents.max()) + 1, d_re))
    w_re[:4] *= 6.0
    y = (
        x_fe @ w_fe + (x_re * w_re[ents]).sum(1)
        + 0.05 * rng.normal(size=n)
    ).astype(np.float32)
    blob = zlib.compress(x_fe[:128].tobytes(), 1)

    def decode():
        _time.sleep(0.002)
        np.frombuffer(zlib.decompress(blob), dtype=np.float32)

    def source(hook=decode):
        return GameArrayChunkSource(
            features={"g": x_fe, "p": x_re}, labels=y,
            entity_idx={"user": ents}, chunk_records=128,
            cluster_by="user", decode_hook=hook,
        )

    opt = OptimizerConfig(max_iterations=4)

    def run(schedule_budget, prefetch=True, hook=decode):
        src = source(hook)
        schedule = (
            DuHLChunkSchedule(
                DuHLScheduleConfig(working_set_chunks=schedule_budget,
                                   tail_chunks_per_sweep=1),
                src.num_chunks,
            )
            if schedule_budget else None
        )
        program = StreamingGameProgram(
            TaskType.LINEAR_REGRESSION, src,
            FixedEffectStepSpec("g", opt, l2_weight=0.1),
            (RandomEffectStepSpec("user", "p", opt, l2_weight=1.0),),
            schedule=schedule, prefetch=prefetch,
        )
        t0 = time.perf_counter()
        result = program.train(num_sweeps=8, tolerance=1e-4)
        return result, (time.perf_counter() - t0) * 1e3

    run(4, hook=None)  # warm every jit signature outside the timings
    uniform, _ = run(None)
    _, off_total = run(4, prefetch=False)
    results = []

    def once():
        result, total_ms = run(4)
        results.append(result)
        return total_ms / max(result.sweeps, 1)

    on_ms, on_sp = median_spread(once)
    duhl = results[-1]
    off_ms = off_total / max(duhl.sweeps, 1)
    return _row(
        "stream_game_duhl",
        round(on_ms, 1),
        [round(s, 1) for s in on_sp],
        _unit_stream_game(
            duhl.chunk_visits, uniform.chunk_visits,
            duhl.sweeps, uniform.sweeps, off_ms,
        ),
    )


def bench_stream_game_ranks() -> dict:
    """Multi-rank partitioned streamed GAME (ISSUE 17): two virtual ranks
    (threads + InProcessExchange) agree one entity-granular chunk plan over
    the exchange, then run the composed per-rank sweep — FE partial sums
    combined in rank order, rank-local RE bucket solves, post-sweep table
    sync. Row value is the two-rank wall ms/sweep, but on virtual ranks the
    threads serialize on one host so wall-clock is NOT the win criterion:
    the unit embeds the deterministic partitioned-read evidence — max
    per-rank decoded payload bytes vs the global input bytes (rb pair;
    each rank must decode STRICTLY less than the whole input) — plus the
    same-run single-rank streamed sweep ms for scale."""
    import tempfile
    import threading

    from photon_ml_tpu.algorithm.streaming_game import StreamingGameProgram
    from photon_ml_tpu.io import avro as avro_io
    from photon_ml_tpu.io.data_reader import FeatureShardConfiguration
    from photon_ml_tpu.io.stream_reader import (
        GameAvroChunkSource,
        plan_partitioned_game_stream,
        scan_game_stream,
    )
    from photon_ml_tpu.optim.optimizer import OptimizerConfig
    from photon_ml_tpu.parallel.distributed import (
        FixedEffectStepSpec,
        RandomEffectStepSpec,
    )
    from photon_ml_tpu.parallel.multihost import InProcessExchange
    from photon_ml_tpu.types import TaskType

    num_ranks, chunk_records, sweeps = 2, 64, 2
    rng = np.random.default_rng(29)
    n, d, n_users = 512, 8, 16
    users = np.sort(rng.integers(0, n_users, size=n))
    schema = {
        "type": "record", "name": "TrainingExampleAvro",
        "fields": [
            {"name": "label", "type": "double"},
            {"name": "userId", "type": ["string", "null"], "default": None},
            {"name": "features", "type": {"type": "array", "items": {
                "type": "record", "name": "FeatureAvro", "fields": [
                    {"name": "name", "type": "string"},
                    {"name": "term", "type": ["string", "null"],
                     "default": None},
                    {"name": "value", "type": "double"},
                ]}}},
        ],
    }
    records = []
    for i in range(n):
        x = rng.normal(size=d)
        records.append({
            "label": float(x.sum() + 0.1 * rng.normal()),
            "userId": f"u{users[i]:02d}",
            "features": [
                {"name": f"f{j}", "term": "", "value": float(x[j])}
                for j in range(d)
            ],
        })
    tmp = tempfile.mkdtemp(prefix="bench_ranks_")
    avro_io.write_container(
        os.path.join(tmp, "part-00000.avro"), schema, records,
        block_records=32,
    )
    cfg = {"global": FeatureShardConfiguration(feature_bags=("features",))}
    opt = OptimizerConfig(max_iterations=4)

    def program(source, vocabs, *, partition=None, exchange=None):
        return StreamingGameProgram(
            TaskType.LINEAR_REGRESSION, source,
            FixedEffectStepSpec("global", opt, l2_weight=0.1),
            (RandomEffectStepSpec("userId", "global", opt, l2_weight=1.0),),
            num_entities={"userId": len(vocabs["userId"])},
            exchange=exchange, partition=partition,
        )

    # same-run single-rank streamed baseline (the pre-ISSUE-17 path)
    files = avro_io.list_avro_files(tmp)
    maps, vocabs, keys, indexes, _scalars = scan_game_stream(
        files, cfg, ("userId",), cluster_by="userId"
    )

    def single_source():
        return GameAvroChunkSource(
            files, cfg, maps, chunk_records=chunk_records,
            random_effect_id_columns=("userId",), entity_vocabs=vocabs,
            cluster_by="userId", cluster_keys=keys, indexes=indexes,
        )

    program(single_source(), vocabs).train(num_sweeps=1)  # warm signatures
    t0 = time.perf_counter()
    program(single_source(), vocabs).train(num_sweeps=sweeps)
    one_rank_ms = (time.perf_counter() - t0) * 1e3 / sweeps

    partitions = [None] * num_ranks

    def rank_run(group, r):
        source, _maps, vocs, part = plan_partitioned_game_stream(
            tmp, cfg, ("userId",), exchange=group[r],
            chunk_records=chunk_records, cluster_by="userId",
        )
        partitions[r] = part
        program(source, vocs, partition=part,
                exchange=group[r]).train(num_sweeps=sweeps)

    def once():
        group = InProcessExchange.create_group(num_ranks, timeout=120.0)
        errs = [None] * num_ranks

        def work(r):
            try:
                rank_run(group, r)
            except Exception as e:
                errs[r] = e
                raise

        threads = [threading.Thread(target=work, args=(r,), daemon=True)
                   for r in range(num_ranks)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300.0)
        if any(t.is_alive() for t in threads) or any(errs):
            raise RuntimeError(f"partitioned rank failure: {errs}")
        return (time.perf_counter() - t0) * 1e3 / sweeps

    once()  # warm the partitioned signatures outside the timings
    ms, sp = median_spread(once)
    part = partitions[0]
    return _row(
        "stream_game_ranks", round(ms, 1), [round(s, 1) for s in sp],
        _unit_stream_game_ranks(
            max(part.payload_bytes) / 1e6, part.input_bytes / 1e6,
            one_rank_ms,
        ),
    )


def bench_serve_microbatch() -> dict:
    """Resident-scorer serving throughput (ISSUE 10): scores/sec through
    the micro-batching loop at the replay's p95 request latency, with the
    same-run ONE-REQUEST-PER-DISPATCH rate embedded in the unit — on this
    platform a dispatch is ~80-110 ms of tunnel, so requests-per-dispatch
    is the entire game and the unbatched rate is the honest baseline a
    naive online scorer would ship. One synthetic GAME model (dense FE +
    one RE table) is placed ONCE; 96 four-row requests replay closed-loop
    through shapes (128, 512); the batched rate is a median-of-GATE_REPS
    over full replays (each replay re-submits every request)."""
    from photon_ml_tpu.data.game_data import (
        build_game_dataset,
        slice_game_dataset,
    )
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_ml_tpu.models.glm import GeneralizedLinearModel
    from photon_ml_tpu.serving import MicroBatchServer, ResidentScorer
    from photon_ml_tpu.telemetry import serving_counters
    from photon_ml_tpu.types import TaskType
    import jax.numpy as jnp

    rng = np.random.default_rng(17)
    n_req, req_rows, d_fe, d_re, n_ent = 96, 4, 256, 8, 512
    n = n_req * req_rows
    users = np.array([f"u{i}" for i in rng.integers(0, n_ent, size=n)])
    dataset = build_game_dataset(
        labels=rng.normal(size=n).astype(np.float32),
        feature_shards={
            "global": rng.normal(size=(n, d_fe)).astype(np.float32),
            "per_entity": rng.normal(size=(n, d_re)).astype(np.float32),
        },
        entity_keys={"user": users},
        offsets=rng.normal(scale=0.1, size=n).astype(np.float32),
    )
    model = GameModel(models={
        "fe": FixedEffectModel(
            glm=GeneralizedLinearModel(
                Coefficients(means=jnp.asarray(
                    rng.normal(size=d_fe).astype(np.float32)
                )),
                TaskType.LINEAR_REGRESSION,
            ),
            feature_shard_id="global",
        ),
        "re": RandomEffectModel(
            coefficients=jnp.asarray(
                rng.normal(size=(n_ent, d_re)).astype(np.float32)
            ),
            entity_keys=dataset.entity_vocabs["user"],
            random_effect_type="user",
            feature_shard_id="per_entity",
            task=TaskType.LINEAR_REGRESSION,
        ),
    })
    requests = [
        slice_game_dataset(dataset, lo, lo + req_rows)
        for lo in range(0, n, req_rows)
    ]
    scorer = ResidentScorer(model, shapes=(128, 512))
    scorer.warm(requests[0])

    # same-run baseline: one request per dispatch, no queue
    t0 = time.perf_counter()
    for r in requests:
        scorer.score(r)
    unbatched_rate = n / max(time.perf_counter() - t0, 1e-9)

    serving_counters.reset_serving_metrics()

    def one_replay() -> float:
        with MicroBatchServer(scorer, max_wait_ms=3.0) as server:
            t0 = time.perf_counter()
            futures = [server.submit(r) for r in requests]
            for f in futures:
                f.result()
            return n / max(time.perf_counter() - t0, 1e-9)

    rate, spread = median_spread(one_replay)
    p95 = serving_counters.latency_summary()["p95"]
    return _row(
        "serve_microbatch",
        rate,
        list(spread),
        _unit_serve(p95, unbatched_rate),
    )


def bench_refresh_incremental() -> dict:
    """Incremental GAME retrain vs full retrain, back to back in THIS
    process (ISSUE 14). One synthetic GAME dataset (dense FE + one
    IDENTITY RE) trains a resident model; a few entities' labels then
    change, and the SAME updated dataset retrains both ways: the full
    warm-started fit (the honest baseline — it too starts from the
    resident model) and the incremental refresh (gradient-screened
    selection, frozen residuals, compacted selected-lane solve). Row value
    is the refresh ms (median-of-GATE_REPS); the unit embeds the
    acceptance evidence — RE lane-solves refresh/full and the same-run
    full-retrain ms. Lane counts are deterministic; ms compares within the
    run only (chip lottery)."""
    from photon_ml_tpu.algorithm.coordinates import (
        CoordinateOptimizationConfig,
    )
    from photon_ml_tpu.algorithm.refresh import RefreshPolicy
    from photon_ml_tpu.data.game_data import build_game_dataset
    from photon_ml_tpu.estimators import (
        FixedEffectCoordinateConfig,
        GameEstimator,
        RandomEffectCoordinateConfig,
    )
    from photon_ml_tpu.optim.optimizer import OptimizerConfig
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(23)
    n, d_fe, d_re, n_ent, n_changed = 4096, 64, 8, 256, 8
    users = np.array([f"u{i:04d}" for i in rng.integers(0, n_ent, size=n)])
    ent = np.array([int(u[1:]) for u in users])
    x_fe = rng.normal(size=(n, d_fe)).astype(np.float32)
    x_re = rng.normal(size=(n, d_re)).astype(np.float32)
    w_fe = rng.normal(size=d_fe).astype(np.float32)
    w_re = rng.normal(size=(n_ent, d_re)).astype(np.float32)

    noise = 0.05 * rng.normal(size=n)

    def labels(w_tab):
        # FIXED noise: unchanged entities' rows are IDENTICAL across the
        # resident and refresh datasets, so only real change moves the
        # gradient screen
        return (
            x_fe @ w_fe + (x_re * w_tab[ent]).sum(1) + noise
        ).astype(np.float32)

    def dataset(y):
        return build_game_dataset(
            labels=y,
            feature_shards={"g": x_fe, "u": x_re},
            entity_keys={"userId": users},
        )

    opt = CoordinateOptimizationConfig(
        optimizer=OptimizerConfig(max_iterations=24), l2_weight=1.0
    )
    estimator = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinate_configs={
            "fe": FixedEffectCoordinateConfig(
                feature_shard_id="g", optimization=opt
            ),
            "re": RandomEffectCoordinateConfig(
                random_effect_type="userId", feature_shard_id="u",
                optimization=opt,
            ),
        },
        num_iterations=1,
    )
    ds0 = dataset(labels(w_re))
    resident = estimator.fit(ds0).model

    w_re2 = w_re.copy()
    changed_rows = rng.choice(n_ent, size=n_changed, replace=False)
    w_re2[changed_rows] *= -2.0
    ds1 = dataset(labels(w_re2))

    policy = RefreshPolicy(gradient_tolerance=1e-1)
    # warm every jit signature (solvers + grad screen + compacted solve)
    # outside the timings — both sides below dispatch warm programs
    estimator.fit(ds1, initial_model=resident)
    estimator.refresh(ds1, resident, policy)

    # same-run full-retrain baseline: warm-started from the resident
    # model, like the refresh — the comparison isolates the selection win
    t0 = time.perf_counter()
    estimator.fit(ds1, initial_model=resident)
    full_ms = (time.perf_counter() - t0) * 1e3

    results = []

    def once() -> float:
        t0 = time.perf_counter()
        results.append(estimator.refresh(ds1, resident, policy))
        return (time.perf_counter() - t0) * 1e3

    refresh_ms, spread = median_spread(once)
    last = results[-1]
    # lanes_total = every valid RE lane — exactly what the full sweep solves
    return _row(
        "refresh_incremental",
        round(refresh_ms, 1),
        [round(s, 1) for s in spread],
        _unit_refresh(last.lanes_solved, last.lanes_total, full_ms),
    )


def bench_search_throughput() -> dict:
    """GP-tournament model search vs one-config-per-solve, back to back in
    THIS process (ISSUE 20). One synthetic logistic dataset; the tournament
    pushes rounds x lane_budget hyperparameter configs through vmapped lane
    solves (GP ask/tell overlapped with the device work), while the
    sequential baseline pushes the SAME number of configs through the same
    driver one lane at a time (Sobol asks — no GP fits charged to it, so
    the comparison isolates dispatch granularity, the vmapped-lane lever).
    Row value is tournament configs/sec (median-of-GATE_REPS); the unit
    embeds the same-run sequential rate. Rates compare within the run only
    (chip lottery)."""
    import jax

    from photon_ml_tpu.data.batch import LabeledPointBatch
    from photon_ml_tpu.hyperparameter.search_driver import (
        parse_search_space,
        run_model_search,
    )
    from photon_ml_tpu.optim.optimizer import OptimizerConfig
    from photon_ml_tpu.types import TaskType

    rounds, lanes = 3, 8
    n_cfg = rounds * lanes
    x, y = _make_data(2048, 32, seed=29)
    xv, yv = _make_data(1024, 32, seed=31)
    batch = LabeledPointBatch.create(jax.device_put(x), jax.device_put(y))
    val = LabeledPointBatch.create(jax.device_put(xv), jax.device_put(yv))
    space = parse_search_space("lambda=1e-3:1e2:log,alpha=0:1")
    opt = OptimizerConfig(max_iterations=16)

    def tournament() -> None:
        run_model_search(
            batch, val, TaskType.LOGISTIC_REGRESSION, space,
            rounds=rounds, lane_budget=lanes, optimizer=opt,
            seed=5, searcher="gp", evaluator="AUC",
        )

    def sequential() -> None:
        run_model_search(
            batch, val, TaskType.LOGISTIC_REGRESSION, space,
            rounds=n_cfg, lane_budget=1, optimizer=opt,
            seed=5, searcher="sobol", evaluator="AUC",
        )

    # warm both lane-width signatures (L=8 and L=1 solve + metric programs)
    # outside the timings
    tournament()
    sequential()

    t0 = time.perf_counter()
    sequential()
    seq_rate = n_cfg / (time.perf_counter() - t0)

    def once() -> float:
        t0 = time.perf_counter()
        tournament()
        return n_cfg / (time.perf_counter() - t0)

    rate, spread = median_spread(once)
    return _row(
        "search_throughput",
        round(rate, 1),
        [round(s, 1) for s in spread],
        _unit_search(seq_rate),
    )


def bench_cpu_scipy(x, y) -> float:
    """scipy L-BFGS-B example-iters/sec over the same λ grid, sequential.
    Iteration-normalized so vs_baseline compares per-unit-work throughput —
    the two solvers terminate after different iteration counts (the TPU
    lanes stop when line search stalls at the optimum; scipy honors
    maxiter), and raw wall-clock would conflate that with hardware speed."""
    from scipy.optimize import minimize

    x64, y64 = x.astype(np.float64), y.astype(np.float64)

    def run_one(lam: float) -> int:
        def f(w):
            m = x64 @ w
            val = np.sum(np.logaddexp(0.0, m) - y64 * m) + 0.5 * lam * np.dot(w, w)
            p = 1.0 / (1.0 + np.exp(-m))
            g = x64.T @ (p - y64) + lam * w
            return val, g

        res = minimize(f, np.zeros(x.shape[1]), jac=True, method="L-BFGS-B",
                       options={"maxiter": MAX_ITER, "ftol": 0.0, "gtol": 0.0})
        return max(int(res.nit), 1)

    t0 = time.perf_counter()
    total_iters = sum(run_one(lam) for lam in _grid(GRID))
    elapsed = time.perf_counter() - t0
    return len(x64) * total_iters / elapsed


def main():
    x, y = _make_data(N, D)

    tpu_time, tpu_spread, lane_iters = bench_tpu(x, y)
    extra = bench_hot_loop_bandwidth(x[: 1 << 17], y[: 1 << 17])
    extra.extend(bench_game_sweep())
    extra.append(bench_sparse_fe())
    extra.append(bench_sparse_fe_hybrid())
    extra.append(bench_game_sweep_composed())
    extra.append(bench_sparse_fe_1e8())
    extra.append(bench_stream_fe_chunked())
    extra.append(bench_stream_game_duhl())
    extra.append(bench_stream_game_ranks())
    extra.append(bench_serve_microbatch())
    extra.append(bench_refresh_incremental())
    extra.append(bench_search_throughput())
    cpu_rate = bench_cpu_scipy(x[:CPU_SUBSAMPLE], y[:CPU_SUBSAMPLE])

    rate = N * lane_iters / tpu_time
    report = _row(
        "glm_lambda_grid_example_iters_per_sec",
        round(rate, 1),
        [round(N * lane_iters / s, 1) for s in tpu_spread[::-1]],
        _unit_primary(lane_iters, tpu_time),
    )
    report["vs_baseline"] = round(rate / cpu_rate, 2)
    report["extra_metrics"] = extra
    # optional structured journal (stdout contract unchanged: ONE JSON line).
    # Calibration rows are chip-lottery-sensitive — compare fractions of the
    # same-run stream probe, never absolute GB/s across journals.
    telemetry_dir = os.environ.get("PHOTON_TELEMETRY_DIR")
    if telemetry_dir:
        from photon_ml_tpu.telemetry import RunJournal

        # the full unslimmed report rides a sidecar the doctor prefers
        # over the tail-captured line (ISSUE 12)
        write_sidecar(
            report, telemetry_dir,
            config={"n": N, "d": D, "grid": GRID, "max_iter": MAX_ITER},
        )
        with RunJournal(telemetry_dir, filename="bench-journal.jsonl") as journal:
            journal.record("config", n=N, d=D, grid=GRID, max_iter=MAX_ITER)
            for row in extra:
                kind = (
                    "calibration" if "stream" in row["metric"] else "bench_metric"
                )
                journal.record(kind, **row)
            journal.record("bench_metric", **{
                k: v for k, v in report.items() if k != "extra_metrics"
            })
    line = render_report(report)
    # the driver tails 2,000 bytes; an over-budget line would lose the
    # primary metric from the official record (BENCH_r04/r05 regression).
    # A hard raise, not an assert — `python -O` must not strip the guard.
    if len(line.encode()) >= MAX_LINE_BYTES:
        raise RuntimeError(
            f"bench JSON line is {len(line.encode())} bytes "
            f"(>= {MAX_LINE_BYTES}); slim the unit builders"
        )
    print(line)


if __name__ == "__main__":
    main()
