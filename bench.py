"""Benchmark: vmapped λ-grid logistic-regression training on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload: the reference's hot loop (SURVEY.md §3.4) folded over a
32-point regularization grid — the λ-grid expansion of GameTrainingDriver
(:612-621) that the Spark reference trains sequentially, one L-BFGS run per
λ. Here the whole grid trains *simultaneously* (photon_ml_tpu
train_glm_grid): vmapped L-BFGS lanes share every read of the [n, d]
feature block, so per-lane margins become one X @ W matmul on the MXU, and
measured wall-clock is nearly flat in the number of lanes (extra λs are
almost free). ``vs_baseline`` is the ratio of example-iteration throughput
(examples x L-BFGS iterations per second) against scipy's Fortran L-BFGS-B
solving the same grid sequentially on the host CPU — iteration-normalized
because the two solvers terminate after different iteration counts
(stand-in for the reference's single-executor Breeze/JVM path; the
reference publishes no benchmark numbers, see BASELINE.md).

Measurement notes (tunneled/remote TPU backends):
- The whole grid is ONE jit call, timed end-to-end (min of 3 reps) with a
  host read as the synchronization point — block_until_ready alone does not
  synchronize on all remote platforms, and per-call tunnel latency (~80 ms
  here) is honestly included in the reported wall-clock.
- Each rep perturbs the warm starts from a fresh PRNG seed so no two
  executions are identical (some backends cache repeat executions).
- The CPU baseline runs on an n/8 subsample; both sides are expressed as
  example-iterations/sec, which is size-invariant (per-iteration cost is
  linear in n at fixed d).
"""

from __future__ import annotations

import json
import time

import numpy as np

N, D, MAX_ITER, GRID = 1 << 18, 512, 30, 32
CPU_SUBSAMPLE = 1 << 15


def _make_data(n: int, d: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(d,)).astype(np.float32) / np.sqrt(d)
    x = rng.normal(size=(n, d)).astype(np.float32)
    logits = x @ w_true
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
    return x, y


def _grid(k: int) -> np.ndarray:
    return np.logspace(-2, 2, k)


def bench_tpu(x, y) -> tuple[float, int]:
    """Returns (grid_wall_clock_sec, total_lane_iters) for one 32-λ grid."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import LabeledPointBatch
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.optim.lbfgs import minimize_lbfgs

    n, d = x.shape
    batch = LabeledPointBatch.create(jax.device_put(x), jax.device_put(y))
    objective = GLMObjective(LogisticLoss(), l2_weight=0.0)

    # The same vmapped-lane program train_glm_grid compiles, inlined so the
    # bench can read per-lane iteration counts and sync on a scalar.
    @jax.jit
    def run_grid(b, l2v, seed):
        bound = objective.bind(b)

        def solve_one(l2, key):
            def vg(w):
                v, g = bound.value_and_grad(w)
                return v + 0.5 * l2 * jnp.vdot(w, w), g + l2 * w

            w0 = 1e-4 * jax.random.normal(key, (d,), jnp.float32)
            return minimize_lbfgs(vg, w0, max_iter=MAX_ITER, tolerance=0.0)

        keys = jax.random.split(jax.random.PRNGKey(seed), l2v.shape[0])
        rs = jax.vmap(solve_one)(l2v, keys)
        return rs.iterations.sum(), rs.value.sum()

    l2v = jnp.asarray(_grid(GRID), jnp.float32)
    float(run_grid(batch, l2v, 0)[1])  # compile + sync
    best = None
    for rep in range(3):
        t0 = time.perf_counter()
        iters, checksum = run_grid(batch, l2v, rep + 1)
        iters = int(iters)
        float(checksum)  # host read: hard sync
        elapsed = time.perf_counter() - t0
        if best is None or elapsed < best[0]:
            best = (elapsed, iters)
    return best


def bench_cpu_scipy(x, y) -> float:
    """scipy L-BFGS-B example-iters/sec over the same λ grid, sequential.
    Iteration-normalized so vs_baseline compares per-unit-work throughput —
    the two solvers terminate after different iteration counts (the TPU
    lanes stop when line search stalls at the optimum; scipy honors
    maxiter), and raw wall-clock would conflate that with hardware speed."""
    from scipy.optimize import minimize

    x64, y64 = x.astype(np.float64), y.astype(np.float64)

    def run_one(lam: float) -> int:
        def f(w):
            m = x64 @ w
            val = np.sum(np.logaddexp(0.0, m) - y64 * m) + 0.5 * lam * np.dot(w, w)
            p = 1.0 / (1.0 + np.exp(-m))
            g = x64.T @ (p - y64) + lam * w
            return val, g

        res = minimize(f, np.zeros(x.shape[1]), jac=True, method="L-BFGS-B",
                       options={"maxiter": MAX_ITER, "ftol": 0.0, "gtol": 0.0})
        return max(int(res.nit), 1)

    t0 = time.perf_counter()
    total_iters = sum(run_one(lam) for lam in _grid(GRID))
    elapsed = time.perf_counter() - t0
    return len(x64) * total_iters / elapsed


def main():
    x, y = _make_data(N, D)

    tpu_time, lane_iters = bench_tpu(x, y)
    cpu_rate = bench_cpu_scipy(x[:CPU_SUBSAMPLE], y[:CPU_SUBSAMPLE])

    rate = N * lane_iters / tpu_time
    print(json.dumps({
        "metric": "glm_lambda_grid_example_iters_per_sec",
        "value": round(rate, 1),
        "unit": (
            f"examples x L-BFGS-iters/sec over a {GRID}-lane vmapped "
            f"lambda grid (n={N}, d={D}, logistic, {lane_iters} lane-iters "
            f"in {tpu_time:.3f}s incl. dispatch latency; vs_baseline is "
            "iteration-normalized against scipy L-BFGS-B on the same grid)"
        ),
        "vs_baseline": round(rate / cpu_rate, 2),
    }))


if __name__ == "__main__":
    main()
