#!/usr/bin/env bash
# Full GAME training + scoring workflow on synthetic recommender data
# (the analogue of the reference's examples/run_photon_ml_driver.sh).
set -euo pipefail

DATA=${DATA:-/tmp/photon-tpu-recsys}
OUT=${OUT:-/tmp/photon-tpu-out}

python examples/generate_recsys_data.py --output-dir "$DATA"

python -m photon_ml_tpu.cli.game_training_driver \
  --input-data-path "$DATA/train" \
  --validation-data-path "$DATA/val" \
  --root-output-dir "$OUT/train" \
  --task-type LINEAR_REGRESSION \
  --feature-shard-configurations "name=global,feature.bags=features,intercept=true" \
  --feature-shard-configurations "name=userShard,feature.bags=userFeatures,intercept=false" \
  --feature-shard-configurations "name=itemShard,feature.bags=itemFeatures,intercept=false" \
  --coordinate-configurations "name=fe,feature.shard=global,reg.weights=0.01|1" \
  --coordinate-configurations "name=per-user,feature.shard=userShard,random.effect.type=userId,reg.weights=1,optimizer=NEWTON" \
  --coordinate-configurations "name=per-item,feature.shard=itemShard,random.effect.type=itemId,reg.weights=1,optimizer=NEWTON" \
  --coordinate-configurations "name=mf,mf.row.effect.type=userId,mf.col.effect.type=itemId,mf.latent.factors=4,reg.weights=0.01" \
  --coordinate-descent-iterations 3 \
  --evaluators "RMSE,RMSE:queryId" \
  --checkpoint-dir "$OUT/ckpt"

python -m photon_ml_tpu.cli.game_scoring_driver \
  --input-data-path "$DATA/val" \
  --model-input-dir "$OUT/train/best" \
  --index-maps-dir "$OUT/train/index-maps" \
  --output-dir "$OUT/scores" \
  --evaluators RMSE \
  --feature-shard-configurations "name=global,feature.bags=features,intercept=true" \
  --feature-shard-configurations "name=userShard,feature.bags=userFeatures,intercept=false" \
  --feature-shard-configurations "name=itemShard,feature.bags=itemFeatures,intercept=false"

echo "training summary: $OUT/train/training-summary.json"
echo "scores:           $OUT/scores"
