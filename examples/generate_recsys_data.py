"""Generate a synthetic MovieLens-style recommender dataset in
TrainingExampleAvro layout (multi-bag: features / userFeatures /
itemFeatures, entity ids in metadataMap).

Usage:
    python examples/generate_recsys_data.py --output-dir /tmp/recsys \
        --num-train 20000 --num-val 5000
"""

from __future__ import annotations

import argparse
import os

import numpy as np

from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import photon_schemas as schemas

SCHEMA = {
    "name": "RecsysTrainingExampleAvro",
    "type": "record",
    "fields": [
        {"name": "uid", "type": ["string", "null"]},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": schemas.FEATURE_AVRO}},
        {"name": "userFeatures", "type": {"type": "array", "items": "FeatureAvro"}},
        {"name": "itemFeatures", "type": {"type": "array", "items": "FeatureAvro"}},
        {"name": "weight", "type": ["double", "null"], "default": None},
        {"name": "offset", "type": ["double", "null"], "default": None},
        {"name": "metadataMap", "type": [{"type": "map", "values": "string"}, "null"],
         "default": None},
    ],
}


def generate(out_dir: str, num_train: int, num_val: int, *,
             d_global: int = 10, d_entity: int = 6, n_users: int = 200,
             n_items: int = 120, n_latent: int = 4, seed: int = 0) -> None:
    truth = np.random.default_rng(seed)
    w = truth.normal(size=d_global)
    user_w = truth.normal(scale=0.6, size=(n_users, d_entity))
    item_w = truth.normal(scale=0.4, size=(n_items, d_entity))
    u_lat = truth.normal(scale=0.5, size=(n_users, n_latent))
    i_lat = truth.normal(scale=0.5, size=(n_items, n_latent))

    for split, n, split_seed in (("train", num_train, 1), ("val", num_val, 2)):
        rng = np.random.default_rng(split_seed)
        records = []
        for i in range(n):
            ui = int(rng.integers(0, n_users))
            vi = int(rng.integers(0, n_items))
            xg = rng.normal(size=d_global)
            xu = rng.normal(size=d_entity)
            xi = rng.normal(size=d_entity)
            y = (xg @ w + xu @ user_w[ui] + xi @ item_w[vi]
                 + u_lat[ui] @ i_lat[vi] + 0.1 * rng.normal())
            records.append({
                "uid": str(i),
                "label": float(y),
                "features": [{"name": f"g{j}", "term": "", "value": float(v)}
                             for j, v in enumerate(xg)],
                "userFeatures": [{"name": f"u{j}", "term": "", "value": float(v)}
                                 for j, v in enumerate(xu)],
                "itemFeatures": [{"name": f"i{j}", "term": "", "value": float(v)}
                                 for j, v in enumerate(xi)],
                "weight": 1.0,
                "offset": 0.0,
                "metadataMap": {"userId": f"user{ui}", "itemId": f"item{vi}",
                                "queryId": f"q{i % 31}"},
            })
        os.makedirs(os.path.join(out_dir, split), exist_ok=True)
        avro_io.write_container(
            os.path.join(out_dir, split, "part-00000.avro"), SCHEMA, records
        )
        print(f"wrote {n} records to {out_dir}/{split}")


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--num-train", type=int, default=20000)
    p.add_argument("--num-val", type=int, default=5000)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    generate(args.output_dir, args.num_train, args.num_val, seed=args.seed)


if __name__ == "__main__":
    main()
