"""r5 probe: the single-pass Pallas kernel inside shard_map on the real TPU.

Checks (1) Mosaic compiles/runs under a 1-device-mesh shard_map, (2) the
wrapper's marginal per-eval cost matches the direct kernel (differenced
K-step scan, same method as bench.py), (3) numerics agree.

Run from the repo root on the TPU env: python experiments/shardmap_kernel_probe.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import LabeledPointBatch
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.parallel.sharded_dense import ShardedDenseGLMObjective

    print("backend:", jax.default_backend(), jax.devices())
    n, d = 1 << 17, 512
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.uniform(size=n) < 0.5).astype(np.float32)
    batch = LabeledPointBatch.create(jax.device_put(x), jax.device_put(y))
    xbytes = n * d * 4

    mesh = make_mesh(data=1, model=1)
    direct = GLMObjective(LogisticLoss(), l2_weight=0.5, use_pallas=True)
    wrapped = ShardedDenseGLMObjective(
        LogisticLoss(), mesh, l2_weight=0.5, use_pallas=True
    )

    w = jnp.asarray(rng.normal(size=d).astype(np.float32)) * 0.01
    v1, g1 = jax.jit(direct.value_and_gradient)(w, batch)
    v2, g2 = jax.jit(wrapped.value_and_gradient)(w, batch)
    dv = abs(float(v1) - float(v2))
    dg = float(jnp.max(jnp.abs(g1 - g2)))
    print(f"numerics: |dv|={dv:.3e} max|dg|={dg:.3e}")
    assert dv < 1e-2 and dg < 1e-3

    def marginal_of(obj):
        def step(w_, b):
            v, g = obj.value_and_gradient(w_, b)
            return w_ - 1e-4 * g, v

        def timed(k):
            @jax.jit
            def run(w0, bb):
                wk, vs = jax.lax.scan(
                    lambda w_, _: step(w_, bb), w0, None, length=k
                )
                return vs.sum() + wk.sum()

            float(run(jnp.zeros(d, jnp.float32), batch))
            best = None
            for _ in range(4):
                w0 = jnp.asarray(rng.normal(size=d).astype(np.float32)) * 0.01
                t0 = time.perf_counter()
                float(run(w0, batch))
                el = time.perf_counter() - t0
                best = el if best is None or el < best else best
            return best

        k_lo, k_hi = 16, 256
        vals = []
        for _ in range(3):
            vals.append(max((timed(k_hi) - timed(k_lo)) / (k_hi - k_lo), 1e-6))
        vals.sort()
        return vals[1], vals

    m_direct, vd = marginal_of(direct)
    m_wrapped, vw = marginal_of(wrapped)
    print(f"direct  kernel: {m_direct*1e3:.3f} ms/eval "
          f"({xbytes/m_direct/1e9:.1f} GB/s) spread={[f'{v*1e3:.3f}' for v in vd]}")
    print(f"shardmap kernel: {m_wrapped*1e3:.3f} ms/eval "
          f"({xbytes/m_wrapped/1e9:.1f} GB/s) spread={[f'{v*1e3:.3f}' for v in vw]}")
    print(f"ratio wrapped/direct: {m_wrapped/m_direct:.3f}")


if __name__ == "__main__":
    main()
