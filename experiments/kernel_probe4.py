"""Probe 4: the rewritten ops/pallas_glm.py measured through the repo path.

Run from anywhere: python experiments/kernel_probe4.py
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

N, D = 1 << 17, 512
K_LO, K_HI = 16, 512


def measure(step_fn, d, batch, reps=4):
    def timed(k):
        @jax.jit
        def run(w0, b):
            w, vs = jax.lax.scan(lambda w, _: step_fn(w, b), w0, None, length=k)
            return vs.sum() + w.sum()

        float(run(jnp.zeros(d, jnp.float32), batch))
        best = None
        rng = np.random.default_rng(0)
        for _ in range(reps):
            w0 = jnp.asarray(rng.normal(size=d).astype(np.float32)) * 0.01
            t0 = time.perf_counter()
            float(run(w0, batch))
            el = time.perf_counter() - t0
            best = el if best is None or el < best else best
        return best

    return max((timed(K_HI) - timed(K_LO)) / (K_HI - K_LO), 1e-9)


def main():
    from photon_ml_tpu.data.batch import LabeledPointBatch
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.ops.objective import GLMObjective

    rng = np.random.default_rng(0)
    x = rng.normal(size=(N, D)).astype(np.float32)
    w_true = rng.normal(size=D).astype(np.float32) / np.sqrt(D)
    y = (rng.uniform(size=N) < 1 / (1 + np.exp(-(x @ w_true)))).astype(np.float32)
    xbytes = N * D * 4

    b32 = LabeledPointBatch.create(jax.device_put(jnp.asarray(x)),
                                   jax.device_put(jnp.asarray(y)))
    bbf = LabeledPointBatch.create(jax.device_put(jnp.asarray(x, jnp.bfloat16)),
                                   jax.device_put(jnp.asarray(y)))

    def stream_step(w, b):
        return w + jnp.sum(b.features.astype(jnp.float32) @ w) * 1e-30, jnp.float32(0)

    m = measure(stream_step, D, b32)
    stream = xbytes / m / 1e9
    print(f"stream: {m*1e3:.3f} ms/step  {stream:.1f} GB/s", flush=True)

    # correctness cross-check vs autodiff (f32)
    obj_k = GLMObjective(LogisticLoss(), l2_weight=0.5, use_pallas=True)
    obj_a = GLMObjective(LogisticLoss(), l2_weight=0.5, use_pallas=False)
    w0 = jnp.asarray((rng.normal(size=D) * 0.01).astype(np.float32))
    vk, gk = jax.jit(obj_k.value_and_gradient)(w0, b32)
    va, ga = jax.jit(obj_a.value_and_gradient)(w0, b32)
    print(f"f32 parity: dv={abs(float(vk)-float(va))/abs(float(va)):.1e} "
          f"dg={float(jnp.max(jnp.abs(gk-ga))/jnp.max(jnp.abs(ga))):.1e}",
          flush=True)
    vb, gb = jax.jit(obj_k.value_and_gradient)(w0, bbf)
    print(f"bf16 parity: dv={abs(float(vb)-float(va))/abs(float(va)):.1e} "
          f"dg={float(jnp.max(jnp.abs(gb-ga))/jnp.max(jnp.abs(ga))):.1e}",
          flush=True)

    for label, obj, batch, nbytes in (
        ("kernel f32", obj_k, b32, xbytes),
        ("kernel bf16", obj_k, bbf, xbytes // 2),
        ("autodiff f32", obj_a, b32, xbytes),
    ):
        def step(w, b, _o=obj):
            v, g = _o.value_and_gradient(w, b)
            return w - 1e-4 * g, v

        m = measure(step, D, batch)
        print(f"{label}: {m*1e3:.3f} ms/step  {nbytes/m/1e9:.1f} GB/s(actual)  "
              f"eff-vs-one-f32-pass={xbytes/m/1e9/stream:.2f}", flush=True)


if __name__ == "__main__":
    main()
