"""Verification drive: r4 batch 2 (distributed scoring, INDEX_MAP
normalization+variances, bf16 batch creation) through the product surface.

Run: PYTHONPATH=/root/repo PALLAS_AXON_POOL_IPS= python experiments/drive_r4_batch2.py
"""
import os
import tempfile

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import jax.numpy as jnp

from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import photon_schemas as schemas

# --- 1. train via the CLI driver, then score via the CLI scoring driver in
# BOTH modes; distributed scores must match single-device bit-for-bit-ish.
schema = {
    "name": "DriveExampleAvro", "type": "record",
    "fields": [
        {"name": "uid", "type": ["string", "null"]},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": schemas.FEATURE_AVRO}},
        {"name": "userFeatures", "type": {"type": "array", "items": "FeatureAvro"}},
        {"name": "weight", "type": ["double", "null"], "default": None},
        {"name": "offset", "type": ["double", "null"], "default": None},
        {"name": "metadataMap",
         "type": [{"type": "map", "values": "string"}, "null"], "default": None},
    ],
}

def records(n, seed):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        xg, xu = rng.normal(size=5), rng.normal(size=3)
        out.append({
            "uid": str(i),
            "label": float(xg.sum() + 0.5 * xu.sum() + 0.1 * rng.normal()),
            "features": [{"name": f"g{j}", "term": "", "value": float(xg[j])} for j in range(5)],
            "userFeatures": [{"name": f"u{j}", "term": "", "value": float(xu[j])} for j in range(3)],
            "weight": 1.0, "offset": 0.0,
            "metadataMap": {"userId": f"user{int(rng.integers(0, 9))}"},
        })
    return out

from photon_ml_tpu.cli.game_training_driver import parse_args, run as train_run
from photon_ml_tpu.cli import game_scoring_driver

with tempfile.TemporaryDirectory() as tmp:
    for split, n, seed in (("train", 400, 1), ("score", 175, 2)):
        os.makedirs(os.path.join(tmp, split), exist_ok=True)
        avro_io.write_container(
            os.path.join(tmp, split, "part-00000.avro"), schema, records(n, seed)
        )
    train_run(parse_args([
        "--input-data-path", os.path.join(tmp, "train"),
        "--root-output-dir", os.path.join(tmp, "out"),
        "--task-type", "LINEAR_REGRESSION",
        "--feature-shard-configurations", "name=global,feature.bags=features,intercept=true",
        "--feature-shard-configurations", "name=perUser,feature.bags=userFeatures,intercept=false",
        "--coordinate-configurations", "name=fe,feature.shard=global,reg.weights=1,max.iter=20",
        "--coordinate-configurations",
        "name=per-user,feature.shard=perUser,random.effect.type=userId,reg.weights=1,max.iter=20",
        "--coordinate-descent-iterations", "2",
    ]))
    model_dir = os.path.join(tmp, "out", "best")
    outs = {}
    for mode, extra in (("single", []), ("dist", ["--mesh", "data=4,model=2"])):
        summary = game_scoring_driver.main([
            "--input-data-path", os.path.join(tmp, "score"),
            "--model-input-dir", model_dir,
            "--output-dir", os.path.join(tmp, f"scored-{mode}"),
            "--evaluators", "RMSE",
            "--feature-shard-configurations", "name=global,feature.bags=features,intercept=true",
            "--feature-shard-configurations", "name=perUser,feature.bags=userFeatures,intercept=false",
        ] + extra)
        outs[mode] = summary
        # scores written to disk
        from photon_ml_tpu.io.model_io import read_scores
        recs = read_scores(os.path.join(tmp, f"scored-{mode}", "scores"))
        recs.sort(key=lambda r: int(r["uid"]))
        outs[mode + "_scores"] = np.asarray([r["predictionScore"] for r in recs])
    print("single RMSE:", outs["single"]["evaluations"]["RMSE"])
    print("dist   RMSE:", outs["dist"]["evaluations"]["RMSE"])
    np.testing.assert_allclose(
        outs["dist_scores"], outs["single_scores"], rtol=1e-5, atol=1e-5
    )
    assert abs(outs["dist"]["evaluations"]["RMSE"] - outs["single"]["evaluations"]["RMSE"]) < 1e-6
    assert outs["single"]["evaluations"]["RMSE"] < 0.5
    print("CLI distributed scoring drive OK")

# --- 2. INDEX_MAP + normalization + variances through GameEstimator
from photon_ml_tpu.algorithm.coordinates import CoordinateOptimizationConfig
from photon_ml_tpu.data.game_data import build_game_dataset
from photon_ml_tpu.estimators import GameEstimator, RandomEffectCoordinateConfig
from photon_ml_tpu.optim.optimizer import OptimizerConfig
from photon_ml_tpu.ops.normalization import NormalizationType
from photon_ml_tpu.projector.projectors import ProjectorType
from photon_ml_tpu.types import TaskType

rng = np.random.default_rng(0)
n, d, E = 600, 40, 15
users = np.array([f"u{i}" for i in rng.integers(0, E, size=n)])
x = np.zeros((n, d), np.float32)
y = np.zeros(n, np.float32)
sup = {e: rng.choice(d, 6, replace=False) for e in range(E)}
wt = {e: rng.normal(size=6) for e in range(E)}
for i in range(n):
    e = int(users[i][1:])
    x[i, sup[e]] = 3.0 * rng.normal(size=6)  # non-unit scale: normalization matters
    y[i] = x[i, sup[e]] @ wt[e] + 0.05 * rng.normal()
ds = build_game_dataset(labels=y, feature_shards={"s": x}, entity_keys={"e": users})
est = GameEstimator(
    task=TaskType.LINEAR_REGRESSION,
    coordinate_configs={
        "re": RandomEffectCoordinateConfig(
            "e", "s",
            CoordinateOptimizationConfig(
                optimizer=OptimizerConfig(max_iterations=50), l2_weight=0.1,
                compute_variance=True,
            ),
            projector_type=ProjectorType.INDEX_MAP,
        )
    },
    normalization=NormalizationType.SCALE_WITH_STANDARD_DEVIATION,
    num_iterations=1,
)
res = est.fit(ds)
m = res.model.get("re")
scores = np.asarray(m.score_dataset(ds))
rmse = float(np.sqrt(np.mean((scores - y) ** 2)))
v = np.asarray(m.variances)
finite = np.isfinite(v)
print(f"INDEX_MAP+norm+variance: rmse={rmse:.4f} "
      f"finite-var frac={finite.mean():.3f} min={v[finite].min():.2e}")
assert rmse < 0.3
assert finite.any() and (v[finite] > 0).all()

# --- 3. bf16 feature block through the public batch+train path (CPU)
from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.estimators import train_glm

xb = rng.normal(size=(500, 16)).astype(np.float32)
yb = (xb.sum(axis=1) + 0.1 * rng.normal(size=500)).astype(np.float32)
m32 = train_glm(LabeledPointBatch.create(xb, yb), TaskType.LINEAR_REGRESSION,
                regularization_weights=[1.0])[1.0]
mbf = train_glm(LabeledPointBatch.create(jnp.asarray(xb, jnp.bfloat16), yb),
                TaskType.LINEAR_REGRESSION, regularization_weights=[1.0])[1.0]
w32 = np.asarray(m32.coefficients.means)
wbf = np.asarray(mbf.coefficients.means)
assert wbf.dtype == np.float32
rel = np.linalg.norm(wbf - w32) / np.linalg.norm(w32)
print(f"bf16 train_glm rel dw = {rel:.2e}")
assert rel < 0.02
print("DRIVE OK")
