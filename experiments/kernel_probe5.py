"""Probe 5: bisect the repo-wrapper slowdown.

probe2 kernel (no offsets/rsum, direct operands): 0.373 ms
repo path (offsets+rsum, col()/pad wrapper, nested jit): 0.777 ms

Variants:
  a) repo _fused_padded called directly on prepadded operands (keeps the
     nested jit + offsets + rsum)
  b) same kernel via a LOCAL pallas_call (no nested jit), same operands
  c) b) without the offsets input
  d) b) without the rsum output
  e) full fused_value_and_gradient (reference point)

Run: python experiments/kernel_probe5.py
"""
from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, D = 1 << 17, 512
K_LO, K_HI = 16, 512


def measure(step_fn, d, batch, reps=4):
    def timed(k):
        @jax.jit
        def run(w0, b):
            w, vs = jax.lax.scan(lambda w, _: step_fn(w, b), w0, None, length=k)
            return vs.sum() + w.sum()

        float(run(jnp.zeros(d, jnp.float32), batch))
        best = None
        rng = np.random.default_rng(0)
        for _ in range(reps):
            w0 = jnp.asarray(rng.normal(size=d).astype(np.float32)) * 0.01
            t0 = time.perf_counter()
            float(run(w0, batch))
            el = time.perf_counter() - t0
            best = el if best is None or el < best else best
        return best

    return max((timed(K_HI) - timed(K_LO)) / (K_HI - K_LO), 1e-9)


def local_kernel(with_o, with_rsum, x_ref, y_ref, *rest):
    if with_o:
        o_ref, ws_ref, w_ref = rest[0], rest[1], rest[2]
        outs = rest[3:]
    else:
        ws_ref, w_ref = rest[0], rest[1]
        o_ref = None
        outs = rest[2:]
    if with_rsum:
        val_ref, grad_ref, rsum_ref = outs
    else:
        val_ref, grad_ref = outs
        rsum_ref = None

    @pl.when(pl.program_id(0) == 0)
    def _init():
        val_ref[0, 0] = jnp.float32(0.0)
        grad_ref[:] = jnp.zeros_like(grad_ref)
        if rsum_ref is not None:
            rsum_ref[0, 0] = jnp.float32(0.0)

    x = x_ref[:]
    w = w_ref[:]
    margins = jnp.dot(x, w.reshape(-1, 1), preferred_element_type=jnp.float32)
    if o_ref is not None:
        margins = margins + o_ref[:]
    l = jnp.logaddexp(0.0, margins) - y_ref[:] * margins
    dz = jax.nn.sigmoid(margins) - y_ref[:]
    ws = ws_ref[:]
    r = ws * dz
    val_ref[0, 0] += jnp.sum(ws * l)
    if rsum_ref is not None:
        rsum_ref[0, 0] += jnp.sum(r)
    g = jax.lax.dot_general(r, x, (((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    grad_ref[:] = grad_ref[:] + g


def local_fused(with_o, with_rsum, tile, x, y, o, ws, w):
    n_pad, d_pad = x.shape
    vmem = dict(memory_space=pltpu.VMEM)
    smem = dict(memory_space=pltpu.SMEM)
    in_specs = [
        pl.BlockSpec((tile, d_pad), lambda i: (i, 0), **vmem),
        pl.BlockSpec((tile, 1), lambda i: (i, 0), **vmem),
    ]
    args = [x, y]
    if with_o:
        in_specs.append(pl.BlockSpec((tile, 1), lambda i: (i, 0), **vmem))
        args.append(o)
    in_specs.append(pl.BlockSpec((tile, 1), lambda i: (i, 0), **vmem))
    args.append(ws)
    in_specs.append(pl.BlockSpec((1, d_pad), lambda i: (0, 0), **vmem))
    args.append(w.reshape(1, d_pad))
    out_specs = [
        pl.BlockSpec((1, 1), lambda i: (0, 0), **smem),
        pl.BlockSpec((1, d_pad), lambda i: (0, 0), **vmem),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((1, 1), jnp.float32),
        jax.ShapeDtypeStruct((1, d_pad), jnp.float32),
    ]
    if with_rsum:
        out_specs.append(pl.BlockSpec((1, 1), lambda i: (0, 0), **smem))
        out_shape.append(jax.ShapeDtypeStruct((1, 1), jnp.float32))
    outs = pl.pallas_call(
        functools.partial(local_kernel, with_o, with_rsum),
        grid=(n_pad // tile,),
        in_specs=in_specs, out_specs=out_specs, out_shape=out_shape,
    )(*args)
    return outs[0][0, 0], outs[1][0]


def main():
    from photon_ml_tpu.data.batch import LabeledPointBatch
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.ops import pallas_glm

    rng = np.random.default_rng(0)
    x = rng.normal(size=(N, D)).astype(np.float32)
    w_true = rng.normal(size=D).astype(np.float32) / np.sqrt(D)
    y = (rng.uniform(size=N) < 1 / (1 + np.exp(-(x @ w_true)))).astype(np.float32)
    xbytes = N * D * 4

    xd = jax.device_put(jnp.asarray(x))
    col = lambda v: jax.device_put(jnp.asarray(v, jnp.float32).reshape(-1, 1))
    batch = {
        "x": xd, "y": col(y), "o": col(np.zeros(N)), "ws": col(np.ones(N)),
    }
    lb = LabeledPointBatch.create(xd, jnp.asarray(y))
    loss = LogisticLoss()

    def stream_step(w, b):
        return w + jnp.sum(b["x"] @ w) * 1e-30, jnp.float32(0)

    m = measure(stream_step, D, batch)
    stream = xbytes / m / 1e9
    print(f"stream: {m*1e3:.3f} ms/step  {stream:.1f} GB/s", flush=True)

    def report(name, m):
        print(f"{name}: {m*1e3:.3f} ms/step  {xbytes/m/1e9:.1f} GB/s  "
              f"frac={xbytes/m/1e9/stream:.2f}", flush=True)

    # a) repo _fused_padded directly (nested jit + o + rsum)
    def step_a(w, b):
        v, g, _ = pallas_glm._fused_padded(
            loss, b["x"], b["y"], b["o"], b["ws"], False, w
        )
        return w - 1e-4 * g[:D], v

    report("a) repo _fused_padded direct", measure(step_a, D, batch))

    # b) local pallas_call, o + rsum, no nested jit
    def step_b(w, b):
        v, g = local_fused(True, True, 1024, b["x"], b["y"], b["o"], b["ws"], w)
        return w - 1e-4 * g[:D], v

    report("b) local o+rsum", measure(step_b, D, batch))

    # c) local, no offsets input
    def step_c(w, b):
        v, g = local_fused(False, True, 1024, b["x"], b["y"], None, b["ws"], w)
        return w - 1e-4 * g[:D], v

    report("c) local rsum only", measure(step_c, D, batch))

    # d) local, no rsum output
    def step_d(w, b):
        v, g = local_fused(True, False, 1024, b["x"], b["y"], b["o"], b["ws"], w)
        return w - 1e-4 * g[:D], v

    report("d) local o only", measure(step_d, D, batch))

    # e) full wrapper (reference point)
    def step_e(w, b):
        v, g = pallas_glm.fused_value_and_gradient(loss, w, b)
        return w - 1e-4 * g, v

    report("e) full wrapper", measure(step_e, D, lb))


if __name__ == "__main__":
    main()
