"""r5: what bounds the vmapped λ-grid (the PRIMARY bench metric)?

The r4 bf16 probe (grid_bf16_probe.py) found halving X bytes gains only
1.09x per grid — so the grid is not X-bandwidth-bound and a one-pass
multi-lane kernel (2x fewer X bytes) would be building the wrong thing.
This probe separates the grid's per-lane-iteration cost into:

1. raw vmapped value+grad eval over the 32 lanes (K-scan differenced);
2. a value-only eval (the line search's extra evaluations are value+grad
   here too — LBFGS calls vg everywhere — so (1) is the eval unit);
3. the full vmapped-LBFGS grid marginal per lockstep iteration
   (max_iter-differenced: 30 vs 10 iters, tolerance=0 so every lane runs
   exactly max_iter outer iterations);
4. (3) with history=5 vs 10 — is the two-loop recursion visible?

solver-per-iter minus (evals-per-iter x eval cost) = line-search lockstep +
two-loop + bookkeeping overhead. Decides whether the next grid attack is a
lane kernel (eval-bound) or solver-shape work (overhead-bound).
"""

import os
import statistics
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import LabeledPointBatch
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.optim.lbfgs import minimize_lbfgs

    print(f"backend={jax.default_backend()}")
    rng = np.random.default_rng(0)
    n, d, L = 1 << 18, 512, 32
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32) / np.sqrt(d)
    logits = x @ w_true
    y = (rng.uniform(size=n) < 1.0 / (1.0 + np.exp(-logits))).astype(np.float32)
    batch = LabeledPointBatch.create(jax.device_put(x), jax.device_put(y))
    objective = GLMObjective(LogisticLoss(), l2_weight=0.0, use_pallas=False)
    bound = objective.bind(batch)
    l2v = jnp.asarray(np.logspace(-2, 2, L), jnp.float32)
    xbytes = n * d * 4

    # --- 1. raw vmapped value+grad eval rate (K-scan differenced) --------
    @partial(jax.jit, static_argnums=(2,))
    def eval_scan(w0s, b, k):
        def step(ws, _):
            def one(w, l2):
                v, g = objective.value_and_gradient(w, b)
                return w - 1e-6 * (g + l2 * w), v
            ws, vs = jax.vmap(one)(ws, l2v)
            return ws, vs.sum()
        ws, vs = jax.lax.scan(step, w0s, None, length=k)
        return ws.sum() + vs.sum()

    def timed_scan(fn, k, *args):
        float(fn(*args, k))
        best = None
        for _ in range(3):
            t0 = time.perf_counter()
            float(fn(*args, k))
            el = time.perf_counter() - t0
            best = el if best is None or el < best else best
        return best

    w0s = jnp.asarray(rng.normal(size=(L, d)).astype(np.float32)) * 1e-3

    def once_eval():
        lo = timed_scan(eval_scan, 8, w0s, batch)
        hi = timed_scan(eval_scan, 64, w0s, batch)
        return max((hi - lo) / 56, 1e-9)

    ev = [once_eval() for _ in range(3)]
    ev_med = statistics.median(ev)
    print(f"vmapped 32-lane value+grad eval: {ev_med * 1e3:.2f} ms "
          f"[{min(ev) * 1e3:.2f}, {max(ev) * 1e3:.2f}] "
          f"({2 * xbytes / ev_med / 1e9:.0f} GB/s two-X-pass-equivalent)")

    # --- 2. full grid marginal per lockstep iteration --------------------
    # batch rides as a jit ARGUMENT — closing over it serializes 537 MB of
    # constants into the remote-compile request (the CLAUDE.md HTTP-413
    # landmine; the first cut of this probe broke the tunnel exactly so)
    @partial(jax.jit, static_argnums=(2, 3))
    def run_grid(seed, b, iters, history):
        bnd = objective.bind(b)

        def solve_one(l2, key):
            def vg(w):
                v, g = bnd.value_and_grad(w)
                return v + 0.5 * l2 * jnp.vdot(w, w), g + l2 * w
            w0 = 1e-4 * jax.random.normal(key, (d,), jnp.float32)
            return minimize_lbfgs(vg, w0, max_iter=iters, history=history,
                                  tolerance=0.0)
        keys = jax.random.split(jax.random.PRNGKey(seed), L)
        rs = jax.vmap(solve_one)(l2v, keys)
        return rs.iterations.sum(), rs.value.sum()

    def timed_grid(iters, history, seed):
        float(run_grid(seed, batch, iters, history)[1])
        best = None
        best_iters = 0
        for s in range(3):
            t0 = time.perf_counter()
            it, v = run_grid(seed + s + 1, batch, iters, history)
            float(v)
            el = time.perf_counter() - t0
            if best is None or el < best:
                best, best_iters = el, int(it)
        return best, best_iters

    for history in (10, 5):
        seed = [history * 1000]

        def once():
            s0 = seed[0]
            seed[0] += 10
            lo, it_lo = timed_grid(10, history, s0)
            hi, it_hi = timed_grid(30, history, s0 + 5)
            # lockstep: every lane runs exactly max_iter outer iterations
            return max((hi - lo) / 20, 1e-9), (it_hi - it_lo) / 20

        rs = [once() for _ in range(3)]
        per_iter = statistics.median([r[0] for r in rs])
        lane_iters = statistics.median([r[1] for r in rs])
        print(f"grid per lockstep iter (history={history}): "
              f"{per_iter * 1e3:.2f} ms "
              f"[{min(r[0] for r in rs) * 1e3:.2f}, "
              f"{max(r[0] for r in rs) * 1e3:.2f}] "
              f"(~{lane_iters:.1f} lane-iters per lockstep iter)")
    print(f"\neval is the unit above; solver-per-iter / eval = evals+overhead")


if __name__ == "__main__":
    main()
