"""Decompose the fused GAME sweep's ~45 ms (VERDICT r4 "what's weak" #1 /
next-round task #2).

The r4 single-pass kernel doubled the FE hot-loop rate but fused_game_sweep_ms
did not move — so the FE value+grad is not the sweep's dominant term, and
nobody measured where the 45 ms actually go. This script applies the in-run
interleaved-differencing technique that settled the r3 bandwidth
contradiction (BASELINE.md:128-159) to PER-COORDINATE variants of the exact
bench workload (bench.py::bench_game_sweep — n=2^17, FE d=256, user/item REs
d=16 with 2000/1500 entities, 10 LBFGS iters per coordinate):

- fe_only_10 / fe_only_1:   FE coordinate alone at 10 vs 1 LBFGS iters
                            -> FE per-iter solve cost (slope) and the
                            FE-coordinate fixed cost (intercept)
- fe_user_10:               + user RE (2000 entities) -> that coordinate's
                            full marginal (solve + residual-offset gathers +
                            rescoring scatter)
- full_10:                  + item RE (1500 entities) == the bench metric
- full_re1:                 both REs at 1 iter -> RE per-iter solve slope
- full_fe1:                 FE at 1 iter -> FE slope inside the full sweep
- all_1:                    everything at 1 iter -> the sweep's
                            iteration-independent floor (rescoring, gathers,
                            bookkeeping)

All variants interleave round-robin in ONE process (median-of-3 marginals,
5-vs-1 sweep differencing, host-read sync) with a same-run stream probe so
fractions survive the chip lottery. Results -> sweep_decompose_r5.log,
summarized in BASELINE.md.

Run from the repo root on the TPU (no PYTHONPATH), nothing else on the host.
"""

import json
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.game_data import (
        build_game_dataset,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.optim.optimizer import OptimizerConfig, OptimizerType
    from photon_ml_tpu.parallel.distributed import (
        FixedEffectStepSpec,
        GameTrainProgram,
        GameTrainState,
        RandomEffectStepSpec,
    )
    from photon_ml_tpu.types import TaskType

    print(f"backend={jax.default_backend()} devices={jax.devices()}")

    rng = np.random.default_rng(0)
    n, d_fe, d_re = 1 << 17, 256, 16
    n_users, n_items = 2000, 1500
    users = np.array([f"u{i}" for i in rng.integers(0, n_users, size=n)])
    items = np.array([f"i{i}" for i in rng.integers(0, n_items, size=n)])
    x_fe = rng.normal(size=(n, d_fe)).astype(np.float32)
    x_re = rng.normal(size=(n, d_re)).astype(np.float32)
    y = (x_fe @ rng.normal(size=d_fe).astype(np.float32) / np.sqrt(d_fe)
         + rng.normal(size=n).astype(np.float32))
    dataset = build_game_dataset(
        labels=y,
        feature_shards={"global": x_fe, "per_entity": x_re},
        entity_keys={"user": users, "item": items},
        dtype=np.float32,
    )
    re_datasets = {
        t: build_random_effect_dataset(dataset, t, "per_entity",
                                       bucket_sizes=(128,))
        for t in ("user", "item")
    }

    def make(fe_iters, re_iters, res):
        fe = FixedEffectStepSpec(
            feature_shard_id="global",
            optimizer=OptimizerConfig(optimizer_type=OptimizerType.LBFGS,
                                      max_iterations=fe_iters),
            l2_weight=1.0,
        )
        specs = tuple(
            RandomEffectStepSpec(
                t, "per_entity",
                OptimizerConfig(optimizer_type=OptimizerType.LBFGS,
                                max_iterations=re_iters),
                l2_weight=1.0,
            )
            for t in res
        )
        program = GameTrainProgram(TaskType.LINEAR_REGRESSION, fe, specs,
                                   use_pallas_fe=True)
        rds = {t: re_datasets[t] for t in res}
        data, buckets = program.prepare_inputs(dataset, rds, None)
        base = program.init_state(dataset, rds, None)
        return program, data, buckets, base

    variants = {
        "fe_only_1": make(1, 10, ()),
        "fe_only_10": make(10, 10, ()),
        "fe_user_10": make(10, 10, ("user",)),
        "full_10": make(10, 10, ("user", "item")),
        "full_re1": make(10, 1, ("user", "item")),
        "full_fe1": make(1, 10, ("user", "item")),
        "all_1": make(1, 1, ("user", "item")),
    }

    def perturbed(base, seed):
        key = jax.random.PRNGKey(seed)
        keys = jax.random.split(key, 1 + max(len(base.re_tables), 1))
        return GameTrainState(
            fe_coefficients=base.fe_coefficients
            + 1e-3 * jax.random.normal(keys[0], base.fe_coefficients.shape),
            re_tables={
                t: tab + 1e-3 * jax.random.normal(k, tab.shape)
                for k, (t, tab) in zip(keys[1:], base.re_tables.items())
            },
            mf_rows=dict(base.mf_rows),
            mf_cols=dict(base.mf_cols),
        )

    def timed(v, k, seed):
        program, data, buckets, base = variants[v]
        state = perturbed(base, seed)
        t0 = time.perf_counter()
        for _ in range(k):
            state, loss = program.step(data, buckets, state)
        float(np.asarray(state.fe_coefficients)[0])  # host read: hard sync
        return time.perf_counter() - t0

    seed = [0]

    def once(v):
        s0 = seed[0]
        seed[0] += 10
        lo = min(timed(v, 1, s0 + s) for s in (1, 2))
        hi = min(timed(v, 5, s0 + s) for s in (3, 4))
        return max((hi - lo) / 4, 1e-6)

    # same-run stream calibration: one [n, d_fe] X read per scan step
    xbytes = n * d_fe * 4

    from functools import partial

    @partial(jax.jit, static_argnums=(2,))
    def stream_run(w0, xx, k):
        w, _ = jax.lax.scan(
            lambda w, _: (w + jnp.sum(xx @ w) * 1e-30, 0.0), w0, None,
            length=k,
        )
        return w.sum()

    x_dev = jax.device_put(x_fe)

    def stream_once():
        k_lo, k_hi = 16, 256

        def t(k):
            w0 = jnp.full((d_fe,), 1e-3, jnp.float32)
            float(stream_run(w0, x_dev, k))  # compile+sync
            best = None
            for _ in range(3):
                t0 = time.perf_counter()
                float(stream_run(w0, x_dev, k))
                el = time.perf_counter() - t0
                best = el if best is None or el < best else best
            return best

        return max((t(k_hi) - t(k_lo)) / (k_hi - k_lo), 1e-9)

    # compile everything first (one pass), then interleave measurements
    for v in variants:
        timed(v, 1, 0)
        print(f"compiled {v}")

    reps = {v: [] for v in variants}
    stream = []
    for r in range(3):
        stream.append(stream_once())
        for v in variants:
            reps[v].append(once(v))
        print(f"rep {r}: stream={xbytes / stream[-1] / 1e9:.0f} GB/s " +
              " ".join(f"{v}={reps[v][-1] * 1e3:.1f}ms" for v in variants),
              flush=True)

    med = {v: statistics.median(reps[v]) * 1e3 for v in reps}
    sp = {v: [min(reps[v]) * 1e3, max(reps[v]) * 1e3] for v in reps}
    stream_gbps = xbytes / statistics.median(stream) / 1e9

    print("\n=== medians (ms/sweep, spread=[min,max]) ===")
    for v in med:
        print(f"{v:12s} {med[v]:7.1f}  {sp[v][0]:7.1f} .. {sp[v][1]:7.1f}")
    print(f"stream calibration: {stream_gbps:.0f} GB/s")

    print("\n=== decomposition ===")
    fe_slope = (med["fe_only_10"] - med["fe_only_1"]) / 9
    fe_slope_full = (med["full_10"] - med["full_fe1"]) / 9
    re_slope = (med["full_10"] - med["full_re1"]) / 9
    user_total = med["fe_user_10"] - med["fe_only_10"]
    item_total = med["full_10"] - med["fe_user_10"]
    print(f"FE per-LBFGS-iter (alone):      {fe_slope:6.2f} ms")
    print(f"FE per-LBFGS-iter (in full):    {fe_slope_full:6.2f} ms")
    print(f"both-RE per-LBFGS-iter:         {re_slope:6.2f} ms")
    print(f"user RE coordinate total:       {user_total:6.2f} ms")
    print(f"item RE coordinate total:       {item_total:6.2f} ms")
    print(f"FE-only fixed (1-iter sweep):   {med['fe_only_1']:6.2f} ms")
    print(f"full 1-iter floor (all_1):      {med['all_1']:6.2f} ms")
    print(json.dumps({"medians_ms": med, "spread_ms": sp,
                      "stream_gbps": round(stream_gbps, 1)}))


if __name__ == "__main__":
    main()
