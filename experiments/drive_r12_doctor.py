"""Verify drive for the run-doctor PR: user-style, end to end.

A: doctor over the repo's checked-in BENCH history (CLI, exit 0, named
   historical verdicts).
B: doctor over a synthetic regression round (exit 1 naming row + rule).
C: GLM driver streaming run with --telemetry-dir: journal heartbeats with
   epoch cursors land, the journal finalizes, the doctor reads it clean.
D: the SAME driver run SIGKILL'd mid-train: the crash-durable .partial
   stage survives with heartbeats, and `doctor --live` names the cursor
   and the never-finalized warning.
E: bench sidecar preferred by the doctor over BENCH artifacts in the dir.
"""
import json
import os
import signal
import subprocess
import sys
import time

REPO = "/root/repo"
sys.path.insert(0, REPO)
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from photon_ml_tpu.io import avro as avro_io  # noqa: E402

SCHEMA = {
    "type": "record", "name": "TrainingExampleAvro",
    "fields": [
        {"name": "uid", "type": ["string", "null"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": {
            "type": "record", "name": "FeatureAvro", "fields": [
                {"name": "name", "type": "string"},
                {"name": "term", "type": ["string", "null"], "default": None},
                {"name": "value", "type": "double"},
            ]}}},
        {"name": "weight", "type": ["double", "null"], "default": None},
        {"name": "offset", "type": ["double", "null"], "default": None},
    ],
}


def make_avro(root, n=240, d=5, seed=7):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=d)
    recs = []
    for i in range(n):
        x = rng.normal(size=d)
        y = 1.0 if rng.random() < 1 / (1 + np.exp(-3 * float(x @ w))) else 0.0
        recs.append({
            "uid": str(i), "label": y,
            "features": [{"name": f"f{j}", "term": "", "value": float(x[j])}
                         for j in range(d)],
            "weight": 1.0, "offset": 0.0,
        })
    os.makedirs(root, exist_ok=True)
    avro_io.write_container(os.path.join(root, "part-00000.avro"), SCHEMA,
                            recs, block_records=24)
    return root


def doctor(args):
    return subprocess.run(
        [sys.executable, "-m", "dev.doctor", *args],
        cwd=REPO, capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def main():
    import tempfile

    tmp = tempfile.mkdtemp(prefix="drive-doctor-")

    # -- A: checked-in history ------------------------------------------
    p = doctor([REPO])
    assert p.returncode == 0, p.stdout + p.stderr
    for needle in ("2.95x", "parsed:null", "plateau",
                   "REGRESSIONS: none"):
        assert needle in p.stdout, f"missing {needle!r}\n{p.stdout}"
    print("A ok: doctor reproduces the checked-in history, exit 0")

    # -- B: synthetic regression ----------------------------------------
    bdir = os.path.join(tmp, "reg")
    os.makedirs(bdir)
    report = {"metric": "glm_lambda_grid_example_iters_per_sec",
              "value": 6e8, "spread": [], "unit": "ex*it/s",
              "vs_baseline": 200.0,
              "extra_metrics": [{
                  "metric": "sparse_giant_fe_hybrid", "value": 800.0,
                  "spread": [],
                  "unit": "ms/it d=1e7 zipf 17M hot256 cov0.62 ELLsr 644"}]}
    with open(os.path.join(bdir, "BENCH_r06.json"), "w") as f:
        json.dump({"n": 6, "rc": 0, "tail": json.dumps(report),
                   "parsed": report}, f)
    p = doctor([bdir])
    assert p.returncode == 1, p.stdout
    assert "sparse_giant_fe_hybrid" in p.stdout
    assert "hybrid-beats-ell" in p.stdout
    print("B ok: synthetic regression exits 1 naming row + rule")

    # -- C: driver streaming run, telemetry journal, doctor reads it ----
    data = make_avro(os.path.join(tmp, "train"))
    tel = os.path.join(tmp, "tel")
    from photon_ml_tpu.cli import glm_driver

    glm_driver.main([
        "--input-data-path", data, "--output-dir", os.path.join(tmp, "out"),
        "--task-type", "LOGISTIC_REGRESSION",
        "--regularization-weights", "0.1,1.0",
        "--max-iterations", "12",
        "--streaming-chunks", "60",
        "--telemetry-dir", tel,
    ])
    rows = []
    with open(os.path.join(tel, "run-journal.jsonl")) as f:
        rows = [json.loads(l) for l in f if l.strip()]
    beats = [r for r in rows if r["kind"] == "heartbeat"]
    assert beats and beats[-1]["stage"] == "glm_streaming", beats[:2]
    assert beats[-1]["epochs"] >= 1 and beats[-1]["lam_index"] == 1
    assert any("counter_deltas" in b for b in beats)
    assert not os.path.exists(
        os.path.join(tel, "run-journal.jsonl.partial"))  # published
    assert rows[-1]["kind"] == "journal_close"
    p = doctor([tel])
    assert p.returncode == 0, p.stdout
    assert "last heartbeat" in p.stdout and "glm_streaming" in p.stdout
    print(f"C ok: {len(beats)} heartbeats, journal finalized, doctor clean")

    # -- D: SIGKILL mid-run; doctor --live tails the stage --------------
    kdata = make_avro(os.path.join(tmp, "ktrain"), n=400, d=6, seed=11)
    ktel = os.path.join(tmp, "ktel")
    script = (
        "import sys, time\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import photon_ml_tpu.io.stream_reader as sr\n"
        "_real = sr.ChunkPrefetcher._load_timed\n"
        "def slow(self, spec):\n"
        "    time.sleep(0.35)\n"  # stretch the run so the kill lands mid-train
        "    return _real(self, spec)\n"
        "sr.ChunkPrefetcher._load_timed = slow\n"
        "from photon_ml_tpu.cli import glm_driver\n"
        "glm_driver.main([\n"
        f"    '--input-data-path', {kdata!r},\n"
        f"    '--output-dir', {os.path.join(tmp, 'kout')!r},\n"
        "    '--task-type', 'LOGISTIC_REGRESSION',\n"
        "    '--regularization-weights', '0.1,0.5,1.0',\n"
        "    '--max-iterations', '40',\n"
        "    '--streaming-chunks', '40',\n"
        "    '--no-streaming-prefetch',\n"  # inline decode: sleep paces epochs
        f"    '--telemetry-dir', {ktel!r},\n"
        "])\n"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", script],
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PALLAS_AXON_POOL_IPS": ""},
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    partial = os.path.join(ktel, "run-journal.jsonl.partial")
    deadline = time.monotonic() + 300
    seen_beat = False
    try:
        while time.monotonic() < deadline:
            if os.path.exists(partial):
                with open(partial) as f:
                    if any('"kind": "heartbeat"' in l for l in f):
                        seen_beat = True
                        break
            if proc.poll() is not None:
                break
            time.sleep(0.3)
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=60)
    assert seen_beat, "driver subprocess never heartbeat within deadline"
    assert os.path.exists(partial), "stage file vanished"
    assert not os.path.exists(os.path.join(ktel, "run-journal.jsonl"))
    p = doctor([ktel, "--live"])
    assert p.returncode == 0, p.stdout
    assert "journal never finalized" in p.stdout
    assert "last heartbeat" in p.stdout and "glm_streaming" in p.stdout
    print("D ok: SIGKILL'd driver left a readable stage; --live names it")

    # -- E: sidecar preferred -------------------------------------------
    sys.path.insert(0, REPO)
    import bench

    sdir = os.path.join(tmp, "side")
    os.makedirs(sdir)
    # a BENCH artifact AND a sidecar: doctor must judge the sidecar
    with open(os.path.join(sdir, "BENCH_r06.json"), "w") as f:
        json.dump({"n": 6, "rc": 0, "tail": "", "parsed": None}, f)
    report = bench.sample_report()
    bench.write_sidecar(report, sdir, config={"drive": True})
    p = doctor([sdir])
    assert "sidecar" in p.stdout and "preferred" in p.stdout, p.stdout
    print("E ok: doctor prefers the bench-report.json sidecar")

    print("DRIVE PASSED")


if __name__ == "__main__":
    main()
