"""Probe 2: Pallas scalar-loop gather with 2-D VMEM layout.

w lives as [d/128, 128] in VMEM; index j decomposes to (j>>7, j&127) and
each entry does a scalar w_ref[hi, lo] load in a fori_loop.
Run: python experiments/sparse_gather_probe2.py
"""
from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NNZ = 1 << 22  # 4.2M (keep compile fast; per-idx rate is what matters)
K_LO, K_HI = 2, 10


def measure(step_fn, carry0, batch, reps=3):
    def timed(k):
        @jax.jit
        def run(c, b):
            c, _ = jax.lax.scan(lambda c, _: (step_fn(c, b), 0.0), c, None,
                                length=k)
            return c

        float(run(carry0, batch).sum())
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            float(run(carry0, batch).sum())
            el = time.perf_counter() - t0
            best = el if best is None or el < best else best
        return best

    return max((timed(K_HI) - timed(K_LO)) / (K_HI - K_LO), 1e-9)


def gather_kernel(block, idx_ref, val_ref, w_ref, out_ref):
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[0, 0] = jnp.float32(0.0)

    def body(i, acc):
        j = idx_ref[0, i]
        return acc + val_ref[0, i] * w_ref[j >> 7, j & 127]

    out_ref[0, 0] += jax.lax.fori_loop(0, block, body, jnp.float32(0.0))


def pallas_gather_sum(idx, vals, w2d, block):
    nnz = idx.shape[1]
    rows = w2d.shape[0]
    (out,) = pl.pallas_call(
        functools.partial(gather_kernel, block),
        grid=(nnz // block,),
        in_specs=[
            pl.BlockSpec((1, block), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, 128), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0),
                                memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((1, 1), jnp.float32)],
    )(idx, vals, w2d)
    return out[0, 0]


def main():
    rng = np.random.default_rng(0)
    d = 1 << 21  # 8 MB in VMEM
    idx = rng.integers(0, d, size=NNZ).astype(np.int32)
    vals = rng.normal(size=NNZ).astype(np.float32)
    batch = {
        "idx": jax.device_put(jnp.asarray(idx)),
        "vals": jax.device_put(jnp.asarray(vals)),
        "idx2": jax.device_put(jnp.asarray(idx).reshape(1, -1)),
        "vals2": jax.device_put(jnp.asarray(vals).reshape(1, -1)),
    }
    w0 = jnp.asarray(rng.normal(size=d).astype(np.float32))

    def xla_gather(w, b):
        s = jnp.sum(b["vals"] * w[b["idx"]])
        return w + s * 1e-30

    m = measure(xla_gather, w0, batch)
    print(f"XLA gather {m/NNZ*1e9:.2f} ns/idx ({m*1e3:.1f} ms)", flush=True)

    for block in (1 << 12, 1 << 15):
        def pstep(w, b, _blk=block):
            s = pallas_gather_sum(b["idx2"], b["vals2"],
                                  w.reshape(-1, 128), _blk)
            return w + s * 1e-30

        try:
            m = measure(pstep, w0, batch)
        except Exception as e:  # noqa: BLE001
            print(f"pallas blk={block} FAILED {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)
            continue
        print(f"pallas scalar-loop blk={block} {m/NNZ*1e9:.2f} ns/idx "
              f"({m*1e3:.1f} ms)", flush=True)


if __name__ == "__main__":
    main()
