"""r5 verification drive: mesh scoring placement refactor + NEWTON solver paths (user-style, 8-device virtual CPU mesh)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from photon_ml_tpu.algorithm.coordinates import CoordinateOptimizationConfig
from photon_ml_tpu.data.game_data import build_game_dataset
from photon_ml_tpu.estimators import (
    FixedEffectCoordinateConfig,
    GameEstimator,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.optim.optimizer import OptimizerConfig
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.parallel.scoring import DistributedScorer
from photon_ml_tpu.transformers import GameTransformer
from photon_ml_tpu.types import TaskType

assert len(jax.devices()) == 8, jax.devices()
rng = np.random.default_rng(11)
n = 777  # deliberately not divisible by 8
users = np.array([f"u{i}" for i in rng.integers(0, 20, size=n)])
queries = np.array([f"q{i}" for i in rng.integers(0, 9, size=n)])
xg = rng.normal(size=(n, 6)).astype(np.float32)
xu = rng.normal(size=(n, 3)).astype(np.float32)
y = (xg.sum(1) + 0.2 * rng.normal(size=n)).astype(np.float32)


def ds(seed, vocabs=None):
    r = np.random.default_rng(seed)
    m = 301
    return build_game_dataset(
        labels=r.normal(size=m).astype(np.float32),
        feature_shards={
            "g": r.normal(size=(m, 6)).astype(np.float32),
            "u": r.normal(size=(m, 3)).astype(np.float32),
        },
        entity_keys={"userId": np.array([f"u{i}" for i in r.integers(0, 20, size=m)])},
        ids={"queryId": np.array([f"q{i}" for i in r.integers(0, 9, size=m)])},
        entity_vocabs=vocabs,
    )


train = build_game_dataset(
    labels=y, feature_shards={"g": xg, "u": xu},
    entity_keys={"userId": users}, ids={"queryId": queries},
)
opt = CoordinateOptimizationConfig(
    optimizer=OptimizerConfig(max_iterations=20), l2_weight=0.5
)
est = GameEstimator(
    task=TaskType.LINEAR_REGRESSION,
    coordinate_configs={
        "fe": FixedEffectCoordinateConfig("g", opt),
        "per-user": RandomEffectCoordinateConfig("userId", "u", opt),
    },
    num_iterations=2,
)
model = est.fit(train).model
val = ds(5, vocabs=train.entity_vocabs)

# 1) transformer: single-device vs mesh — identical scores + evaluations
ref = GameTransformer(model=model, evaluator_specs=("RMSE", "RMSE:queryId")).transform(val)
got = GameTransformer(
    model=model, evaluator_specs=("RMSE", "RMSE:queryId"), mesh=make_mesh()
).transform(val)
np.testing.assert_allclose(got.scores, ref.scores, rtol=1e-5, atol=1e-5)
for k in ref.evaluations:
    assert abs(got.evaluations[k] - ref.evaluations[k]) < 1e-6 * max(
        1, abs(ref.evaluations[k])
    ), (k, got.evaluations[k], ref.evaluations[k])
print("transform mesh==single ok:", {k: round(v, 5) for k, v in got.evaluations.items()})

# 2) scorer-side on-mesh evaluation matches host evaluators
mesh_scorer = DistributedScorer(model, make_mesh())
ev = mesh_scorer.evaluate_dataset(val, ("RMSE", "MAE", "RMSE:queryId"))
host = DistributedScorer(model, None).evaluate_dataset(val, ("RMSE", "MAE", "RMSE:queryId"))
for k in host:
    assert abs(ev[k] - host[k]) < 1e-5 * max(1, abs(host[k])), (k, ev[k], host[k])
print("on-mesh evaluate_dataset ok:", {k: round(v, 5) for k, v in ev.items()})

# 3) negative probe: fe_feature_sharded without a mesh must raise
try:
    DistributedScorer(model, None, fe_feature_sharded=True)
except ValueError as e:
    print("fe_feature_sharded w/o mesh raises ok:", e)
else:
    raise SystemExit("expected ValueError")

# 4) unseen-entity scoring stays finite / RE contributes 0
val2 = ds(6, vocabs=train.entity_vocabs)
s2 = mesh_scorer.score_dataset(val2)
assert np.isfinite(s2).all() and s2.shape == (301,)
print("unseen-entity mesh scoring ok; all checks passed")

# 5) NEWTON solver user-style: estimator RE coordinate, CD + fused mesh.
# The LBFGS baseline scores come from the `model` fit above (mesh-less).
from photon_ml_tpu.optim.optimizer import OptimizerType

nopt = CoordinateOptimizationConfig(
    optimizer=OptimizerConfig(optimizer_type=OptimizerType.NEWTON,
                              max_iterations=10), l2_weight=0.5
)
sl = GameTransformer(model=model).transform(train).scores
scale = float(np.std(sl))
for mesh in (None, make_mesh()):
    est_n = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinate_configs={
            "fe": FixedEffectCoordinateConfig("g", opt),
            "per-user": RandomEffectCoordinateConfig("userId", "u", nopt),
        },
        num_iterations=2, mesh=mesh,
    )
    rn = est_n.fit(train)
    # compare final models' training-set scores against the LBFGS baseline
    sn = GameTransformer(model=rn.model).transform(train).scores
    rmse = float(np.sqrt(np.mean((sn - sl) ** 2)))
    assert rmse < 2e-2 * scale, (rmse, scale)
    print(f"newton mesh={'8dev' if mesh is not None else None}: "
          f"score agreement vs lbfgs rmse={rmse:.2e} (scale {scale:.2f}) ok")
print("newton drive ok")
