"""r5 probe: fused sweep with Newton RE solves vs the LBFGS-10 baseline.

Same workload and interleaved marginal methodology as sweep_decompose_r5.py;
answers "did the batched-Newton solver (optim/newton.py) collapse the RE
coordinates' ~43 ms?" before the full bench run. Also cross-checks the two
programs' converged states agree (same subproblems, different solver).
"""

import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    from photon_ml_tpu.data.game_data import (
        build_game_dataset,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.optim.optimizer import OptimizerConfig, OptimizerType
    from photon_ml_tpu.parallel.distributed import (
        FixedEffectStepSpec,
        GameTrainProgram,
        GameTrainState,
        RandomEffectStepSpec,
    )
    from photon_ml_tpu.types import TaskType

    print(f"backend={jax.default_backend()}")
    rng = np.random.default_rng(0)
    n, d_fe, d_re = 1 << 17, 256, 16
    n_users, n_items = 2000, 1500
    users = np.array([f"u{i}" for i in rng.integers(0, n_users, size=n)])
    items = np.array([f"i{i}" for i in rng.integers(0, n_items, size=n)])
    x_fe = rng.normal(size=(n, d_fe)).astype(np.float32)
    x_re = rng.normal(size=(n, d_re)).astype(np.float32)
    y = (x_fe @ rng.normal(size=d_fe).astype(np.float32) / np.sqrt(d_fe)
         + rng.normal(size=n).astype(np.float32))
    dataset = build_game_dataset(
        labels=y,
        feature_shards={"global": x_fe, "per_entity": x_re},
        entity_keys={"user": users, "item": items},
        dtype=np.float32,
    )
    re_datasets = {
        t: build_random_effect_dataset(dataset, t, "per_entity",
                                       bucket_sizes=(128,))
        for t in ("user", "item")
    }
    opt = OptimizerConfig(optimizer_type=OptimizerType.LBFGS, max_iterations=10)
    newton = OptimizerConfig(optimizer_type=OptimizerType.NEWTON,
                             max_iterations=10)

    def make(re_opt):
        program = GameTrainProgram(
            TaskType.LINEAR_REGRESSION,
            FixedEffectStepSpec(feature_shard_id="global", optimizer=opt,
                                l2_weight=1.0),
            (
                RandomEffectStepSpec("user", "per_entity", re_opt, l2_weight=1.0),
                RandomEffectStepSpec("item", "per_entity", re_opt, l2_weight=1.0),
            ),
            use_pallas_fe=True,
        )
        data, buckets = program.prepare_inputs(dataset, re_datasets, None)
        base = program.init_state(dataset, re_datasets, None)
        return program, data, buckets, base

    variants = {"lbfgs10": make(opt), "newton": make(newton)}

    # numerics cross-check: 3 sweeps from the same init must land both
    # programs on (near-)identical states — same subproblems, solved to
    # (at least) the same quality
    states = {}
    for v, (program, data, buckets, base) in variants.items():
        s = base
        for _ in range(3):
            s, loss = program.step(data, buckets, s)
        states[v] = (np.asarray(s.fe_coefficients),
                     {t: np.asarray(tab) for t, tab in s.re_tables.items()},
                     float(loss))
    fe_d = np.max(np.abs(states["lbfgs10"][0] - states["newton"][0]))
    print(f"after 3 sweeps: loss lbfgs={states['lbfgs10'][2]:.8f} "
          f"newton={states['newton'][2]:.8f}  max|dfe|={fe_d:.2e}")
    for t in states["lbfgs10"][1]:
        d = np.max(np.abs(states["lbfgs10"][1][t] - states["newton"][1][t]))
        print(f"  max|d re[{t}]| = {d:.2e}")

    def perturbed(base, seed):
        key = jax.random.PRNGKey(seed)
        keys = jax.random.split(key, 1 + len(base.re_tables))
        return GameTrainState(
            fe_coefficients=base.fe_coefficients
            + 1e-3 * jax.random.normal(keys[0], base.fe_coefficients.shape),
            re_tables={
                t: tab + 1e-3 * jax.random.normal(k, tab.shape)
                for k, (t, tab) in zip(keys[1:], base.re_tables.items())
            },
            mf_rows=dict(base.mf_rows),
            mf_cols=dict(base.mf_cols),
        )

    def timed(v, k, seed):
        program, data, buckets, base = variants[v]
        state = perturbed(base, seed)
        t0 = time.perf_counter()
        for _ in range(k):
            state, loss = program.step(data, buckets, state)
        float(np.asarray(state.fe_coefficients)[0])
        return time.perf_counter() - t0

    seed = [100]

    def once(v):
        s0 = seed[0]
        seed[0] += 10
        lo = min(timed(v, 1, s0 + s) for s in (1, 2))
        hi = min(timed(v, 5, s0 + s) for s in (3, 4))
        return max((hi - lo) / 4, 1e-6)

    reps = {v: [] for v in variants}
    for r in range(3):
        for v in variants:
            reps[v].append(once(v))
        print(f"rep {r}: " +
              " ".join(f"{v}={reps[v][-1] * 1e3:.1f}ms" for v in variants),
              flush=True)
    for v in reps:
        med = statistics.median(reps[v]) * 1e3
        print(f"{v}: median {med:.1f} ms  "
              f"[{min(reps[v]) * 1e3:.1f}, {max(reps[v]) * 1e3:.1f}]")


if __name__ == "__main__":
    main()
