"""r5b: decompose the NEWTON-RE fused sweep's remaining ~15-20 ms.

Follow-up to sweep_decompose_r5.py (which attributed ~87% of the LBFGS-10
sweep to the vmapped RE solves) after optim/newton.py collapsed those:
where does the Newton sweep spend its time, and what is the next floor?

Variants (same workload, interleaved, marginal 5-vs-1, median-of-3):
- fe_only_1 / fe_only_10: the FE coordinate floor + LBFGS slope (kernel-fed)
- full_newton:  FE LBFGS-10 + both REs on Newton (the bench newton row)
- fe_user_newton: drop the item RE -> one Newton RE coordinate's marginal
- full_newton_fe1: FE at 1 iter -> FE slope inside the Newton sweep
"""

import json
import os
import statistics
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    from photon_ml_tpu.data.game_data import (
        build_game_dataset,
        build_random_effect_dataset,
    )
    from photon_ml_tpu.optim.optimizer import OptimizerConfig, OptimizerType
    from photon_ml_tpu.parallel.distributed import (
        FixedEffectStepSpec,
        GameTrainProgram,
        GameTrainState,
        RandomEffectStepSpec,
    )
    from photon_ml_tpu.types import TaskType

    print(f"backend={jax.default_backend()}")
    rng = np.random.default_rng(0)
    n, d_fe, d_re = 1 << 17, 256, 16
    n_users, n_items = 2000, 1500
    users = np.array([f"u{i}" for i in rng.integers(0, n_users, size=n)])
    items = np.array([f"i{i}" for i in rng.integers(0, n_items, size=n)])
    x_fe = rng.normal(size=(n, d_fe)).astype(np.float32)
    x_re = rng.normal(size=(n, d_re)).astype(np.float32)
    y = (x_fe @ rng.normal(size=d_fe).astype(np.float32) / np.sqrt(d_fe)
         + rng.normal(size=n).astype(np.float32))
    dataset = build_game_dataset(
        labels=y,
        feature_shards={"global": x_fe, "per_entity": x_re},
        entity_keys={"user": users, "item": items},
        dtype=np.float32,
    )
    re_datasets = {
        t: build_random_effect_dataset(dataset, t, "per_entity",
                                       bucket_sizes=(128,))
        for t in ("user", "item")
    }

    def opt(t, iters):
        return OptimizerConfig(optimizer_type=t, max_iterations=iters)

    LB = OptimizerType.LBFGS
    NT = OptimizerType.NEWTON

    def make(fe_iters, re_opt, res):
        program = GameTrainProgram(
            TaskType.LINEAR_REGRESSION,
            FixedEffectStepSpec(feature_shard_id="global",
                                optimizer=opt(LB, fe_iters), l2_weight=1.0),
            tuple(
                RandomEffectStepSpec(t, "per_entity", re_opt, l2_weight=1.0)
                for t in res
            ),
            use_pallas_fe=True,
        )
        rds = {t: re_datasets[t] for t in res}
        data, buckets = program.prepare_inputs(dataset, rds, None)
        base = program.init_state(dataset, rds, None)
        return program, data, buckets, base

    variants = {
        "fe_only_1": make(1, opt(NT, 10), ()),
        "fe_only_10": make(10, opt(NT, 10), ()),
        "fe_user_newton": make(10, opt(NT, 10), ("user",)),
        "full_newton": make(10, opt(NT, 10), ("user", "item")),
        "full_newton_fe1": make(1, opt(NT, 10), ("user", "item")),
    }

    def perturbed(base, seed):
        key = jax.random.PRNGKey(seed)
        keys = jax.random.split(key, 1 + max(len(base.re_tables), 1))
        return GameTrainState(
            fe_coefficients=base.fe_coefficients
            + 1e-3 * jax.random.normal(keys[0], base.fe_coefficients.shape),
            re_tables={
                t: tab + 1e-3 * jax.random.normal(k, tab.shape)
                for k, (t, tab) in zip(keys[1:], base.re_tables.items())
            },
            mf_rows=dict(base.mf_rows),
            mf_cols=dict(base.mf_cols),
        )

    def timed(v, k, seed):
        program, data, buckets, base = variants[v]
        state = perturbed(base, seed)
        t0 = time.perf_counter()
        for _ in range(k):
            state, loss = program.step(data, buckets, state)
        float(np.asarray(state.fe_coefficients)[0])
        return time.perf_counter() - t0

    seed = [0]

    def once(v):
        s0 = seed[0]
        seed[0] += 10
        lo = min(timed(v, 1, s0 + s) for s in (1, 2))
        hi = min(timed(v, 5, s0 + s) for s in (3, 4))
        return max((hi - lo) / 4, 1e-6)

    for v in variants:
        timed(v, 1, 0)
        print(f"compiled {v}")

    reps = {v: [] for v in variants}
    for r in range(3):
        for v in variants:
            reps[v].append(once(v))
        print(f"rep {r}: " +
              " ".join(f"{v}={reps[v][-1] * 1e3:.1f}ms" for v in variants),
              flush=True)

    med = {v: statistics.median(reps[v]) * 1e3 for v in reps}
    sp = {v: [min(reps[v]) * 1e3, max(reps[v]) * 1e3] for v in reps}
    print("\n=== medians (ms/sweep, spread=[min,max]) ===")
    for v in med:
        print(f"{v:16s} {med[v]:7.1f}  {sp[v][0]:7.1f} .. {sp[v][1]:7.1f}")
    print("\n=== decomposition (medians) ===")
    print(f"FE fixed (1-iter sweep):        {med['fe_only_1']:6.2f} ms")
    print(f"FE LBFGS slope x9:              "
          f"{med['fe_only_10'] - med['fe_only_1']:6.2f} ms")
    print(f"user RE (Newton) marginal:      "
          f"{med['fe_user_newton'] - med['fe_only_10']:6.2f} ms")
    print(f"item RE (Newton) marginal:      "
          f"{med['full_newton'] - med['fe_user_newton']:6.2f} ms")
    print(f"FE slope inside full x9:        "
          f"{med['full_newton'] - med['full_newton_fe1']:6.2f} ms")
    print(json.dumps({"medians_ms": med, "spread_ms": sp}))


if __name__ == "__main__":
    main()
