"""Probe: can a Pallas kernel beat XLA's ~7 ns/index gather floor?

VERDICT r3 #5. XLA's gather/scatter at d=10^7 runs ~7-12 ns/element
(BASELINE.md giant-d study) regardless of sortedness. Ideas probed on
hardware, all same-run calibrated:

  a) XLA gather baseline (w[idx], 16.8M indices, d=2^22 and d=10^7)
  b) XLA scatter-add baseline
  c) Pallas scalar-loop gather from VMEM: w resident in VMEM (16 MB),
     per-entry w_ref[0, idx] scalar loads accumulated via fori_loop
  d) Pallas scalar-loop gather+multiply+accumulate (the real ELL inner op)

Run: python experiments/sparse_gather_probe.py
"""
from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NNZ = 1 << 24  # 16.8M indices
K_LO, K_HI = 2, 10


def measure(step_fn, carry0, batch, reps=3):
    def timed(k):
        @jax.jit
        def run(c, b):
            c, _ = jax.lax.scan(lambda c, _: (step_fn(c, b), 0.0), c, None,
                                length=k)
            return c

        float(run(carry0, batch).sum())
        best = None
        for _ in range(reps):
            t0 = time.perf_counter()
            float(run(carry0, batch).sum())
            el = time.perf_counter() - t0
            best = el if best is None or el < best else best
        return best

    return max((timed(K_HI) - timed(K_LO)) / (K_HI - K_LO), 1e-9)


def gather_kernel(block, idx_ref, val_ref, w_ref, out_ref):
    # idx block [1, block] int32; w [1, d] resident; accumulate sum of
    # val*w[idx] into out [1, 1] (SMEM)
    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[0, 0] = jnp.float32(0.0)

    def body(i, acc):
        j = idx_ref[0, i]
        return acc + val_ref[0, i] * w_ref[0, j]

    out_ref[0, 0] += jax.lax.fori_loop(0, block, body, jnp.float32(0.0))


def pallas_gather_sum(idx, vals, w, block):
    nnz = idx.shape[1]
    (out,) = pl.pallas_call(
        functools.partial(gather_kernel, block),
        grid=(nnz // block,),
        in_specs=[
            pl.BlockSpec((1, block), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, w.shape[1]), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[pl.BlockSpec((1, 1), lambda i: (0, 0),
                                memory_space=pltpu.SMEM)],
        out_shape=[jax.ShapeDtypeStruct((1, 1), jnp.float32)],
    )(idx, vals, w)
    return out[0, 0]


def main():
    rng = np.random.default_rng(0)
    for d in (1 << 22, 10_000_000):
        idx = rng.integers(0, d, size=NNZ).astype(np.int32)
        vals = rng.normal(size=NNZ).astype(np.float32)
        batch = {
            "idx": jax.device_put(jnp.asarray(idx)),
            "vals": jax.device_put(jnp.asarray(vals)),
            "idx2": jax.device_put(jnp.asarray(idx).reshape(1, -1)),
            "vals2": jax.device_put(jnp.asarray(vals).reshape(1, -1)),
        }
        w0 = jnp.asarray(rng.normal(size=d).astype(np.float32))

        # a) XLA gather: sum(vals * w[idx]); consume carry so nothing hoists
        def xla_gather(w, b):
            s = jnp.sum(b["vals"] * w[b["idx"]])
            return w + s * 1e-30

        m = measure(xla_gather, w0, batch)
        print(f"d={d}: XLA gather {m/NNZ*1e9:.2f} ns/idx ({m*1e3:.1f} ms)",
              flush=True)

        # b) XLA scatter-add
        def xla_scatter(w, b):
            return w * 0.999999 + jnp.zeros_like(w).at[b["idx"]].add(b["vals"])

        m = measure(xla_scatter, w0, batch)
        print(f"d={d}: XLA scatter {m/NNZ*1e9:.2f} ns/idx ({m*1e3:.1f} ms)",
              flush=True)

        # c/d) Pallas scalar-loop gather (VMEM-resident w) — only for the
        # VMEM-sized d
        if d <= 1 << 22:
            for block in (1 << 12, 1 << 14):
                def pstep(w, b, _blk=block):
                    s = pallas_gather_sum(b["idx2"], b["vals2"],
                                          w.reshape(1, -1), _blk)
                    return w + s * 1e-30

                try:
                    m = measure(pstep, w0, batch)
                except Exception as e:  # noqa: BLE001
                    print(f"d={d}: pallas blk={block} FAILED "
                          f"{type(e).__name__}: {str(e)[:150]}", flush=True)
                    continue
                print(f"d={d}: pallas scalar-loop blk={block} "
                      f"{m/NNZ*1e9:.2f} ns/idx ({m*1e3:.1f} ms)", flush=True)


if __name__ == "__main__":
    main()
