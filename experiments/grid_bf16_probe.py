"""Probe: bf16 feature block under the vmapped λ-grid (the primary bench
workload). The grid's per-lane margins batch into one [n,d]@[d,L] matmul —
bandwidth-bound, so bf16 X should approach 2x. Checks marginal grid time
f32 vs bf16 and the per-lane solution agreement.

Run: python experiments/grid_bf16_probe.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.ops.losses import LogisticLoss
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim.lbfgs import minimize_lbfgs

N, D, MAX_ITER, GRID = 1 << 18, 512, 30, 32


def main():
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=D).astype(np.float32) / np.sqrt(D)
    x = rng.normal(size=(N, D)).astype(np.float32)
    y = (rng.uniform(size=N) < 1 / (1 + np.exp(-(x @ w_true)))).astype(np.float32)
    l2v = jnp.asarray(np.logspace(-2, 2, GRID), jnp.float32)
    objective = GLMObjective(LogisticLoss(), l2_weight=0.0, use_pallas=False)

    @jax.jit
    def run_grid(b, l2v, seed):
        bound = objective.bind(b)

        def solve_one(l2, key):
            def vg(w):
                v, g = bound.value_and_grad(w)
                return v + 0.5 * l2 * jnp.vdot(w, w), g + l2 * w

            w0 = 1e-4 * jax.random.normal(key, (D,), jnp.float32)
            return minimize_lbfgs(vg, w0, max_iter=MAX_ITER, tolerance=0.0)

        keys = jax.random.split(jax.random.PRNGKey(seed), l2v.shape[0])
        rs = jax.vmap(solve_one)(l2v, keys)
        return rs.iterations.sum(), rs.value.sum(), rs.coefficients

    def marginal(batch):
        def timed(k, seed0):
            t0 = time.perf_counter()
            results = [run_grid(batch, l2v, seed0 + i) for i in range(k)]
            for _, checksum, _ in results:
                float(checksum)
            return time.perf_counter() - t0, sum(int(it) for it, _, _ in results)

        float(run_grid(batch, l2v, 0)[1])  # compile
        vals = []
        iters = 0
        for rep in range(3):
            lo = min(timed(1, 100 * rep + s)[0] for s in (1, 2))
            hi_t, hi_iters = min(
                (timed(3, 100 * rep + s) for s in (10, 20)),
                key=lambda r: r[0],
            )
            vals.append(max((hi_t - lo) / 2, 1e-6))
            iters = hi_iters // 3
        vals.sort()
        return vals[1], vals, iters

    b32 = LabeledPointBatch.create(jax.device_put(jnp.asarray(x)),
                                   jax.device_put(jnp.asarray(y)))
    bbf = LabeledPointBatch.create(jax.device_put(jnp.asarray(x, jnp.bfloat16)),
                                   jax.device_put(jnp.asarray(y)))
    m32, v32, it32 = marginal(b32)
    mbf, vbf, itbf = marginal(bbf)
    print(f"f32 : {m32*1e3:.1f} ms/grid (spread {sorted(v32)}), {it32} lane-iters "
          f"-> {N*it32/m32/1e6:.1f}M ex-iters/s", flush=True)
    print(f"bf16: {mbf*1e3:.1f} ms/grid (spread {sorted(vbf)}), {itbf} lane-iters "
          f"-> {N*itbf/mbf/1e6:.1f}M ex-iters/s", flush=True)
    print(f"speedup {m32/mbf:.2f}x (per-grid), "
          f"{(N*itbf/mbf)/(N*it32/m32):.2f}x (per-iter-rate)", flush=True)

    # solution agreement
    _, _, w_f32 = run_grid(b32, l2v, 7)
    _, _, w_bf = run_grid(bbf, l2v, 7)
    wa, wb = np.asarray(w_f32), np.asarray(w_bf)
    rel = np.linalg.norm(wb - wa, axis=1) / np.linalg.norm(wa, axis=1)
    print(f"per-lane rel dw: max={rel.max():.2e} median={np.median(rel):.2e}",
          flush=True)


if __name__ == "__main__":
    main()
