"""Verify drive for the model-search PR: user-style, end to end.

A: GLM driver end-to-end with --search-rounds over lambda+alpha on a real
   Avro train/validation pair + --telemetry-dir: summary carries the search
   block, the journal carries search_round rows (sources sobol then gp) and
   search_complete, search/* counters land, the doctor reads the dir clean.
B: library uniform tournament is BITWISE == train_glm_grid (the λ-grid pin).
C: run_model_search replays bit-for-bit under one seed; a different seed
   diverges; round sources go sobol → gp.
D: rejection probes through the CLI fail fast naming the alternative
   (no search-space, no validation path, --elastic-net-alpha conflict,
   --grid-parallel conflict) and a box dim without driver bounds raises
   from the library naming box_lower.
"""
import json
import os
import subprocess
import sys
import tempfile

REPO = "/root/repo"
sys.path.insert(0, REPO)
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from photon_ml_tpu.io import avro as avro_io  # noqa: E402

SCHEMA = {
    "type": "record", "name": "TrainingExampleAvro",
    "fields": [
        {"name": "uid", "type": ["string", "null"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": {
            "type": "record", "name": "FeatureAvro", "fields": [
                {"name": "name", "type": "string"},
                {"name": "term", "type": ["string", "null"], "default": None},
                {"name": "value", "type": "double"},
            ]}}},
        {"name": "weight", "type": ["double", "null"], "default": None},
        {"name": "offset", "type": ["double", "null"], "default": None},
    ],
}


def make_avro(root, n, d=6, seed=7):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=d)
    recs = []
    for i in range(n):
        x = rng.normal(size=d)
        y = 1.0 if rng.random() < 1 / (1 + np.exp(-3 * float(x @ w))) else 0.0
        recs.append({
            "uid": str(i), "label": y,
            "features": [{"name": f"f{j}", "term": "", "value": float(x[j])}
                         for j in range(d)],
            "weight": 1.0, "offset": 0.0,
        })
    os.makedirs(root, exist_ok=True)
    avro_io.write_container(os.path.join(root, "part-00000.avro"), SCHEMA,
                            recs, block_records=64)
    return root


def main():
    tmp = tempfile.mkdtemp(prefix="drive-r20-")
    train = make_avro(os.path.join(tmp, "train"), n=400, seed=7)
    val = make_avro(os.path.join(tmp, "val"), n=160, seed=11)
    tel = os.path.join(tmp, "tel")
    out = os.path.join(tmp, "out")

    from photon_ml_tpu.cli import glm_driver

    # -- A: driver end-to-end with search --------------------------------
    glm_driver.main([
        "--input-data-path", train,
        "--validation-data-path", val,
        "--output-dir", out,
        "--task-type", "LOGISTIC_REGRESSION",
        "--max-iterations", "25",
        "--search-rounds", "3",
        "--search-lane-budget", "4",
        "--search-space", "lambda=1e-3:1e2:log,alpha=0:1",
        "--search-seed", "5",
        "--telemetry-dir", tel,
    ])
    with open(os.path.join(out, "glm-summary.json")) as f:
        summary = json.load(f)
    sb = summary["search"]
    assert sb["rounds"] == 3 and sb["configs"] == 12, sb
    assert np.isfinite(sb["best_metric"]), sb
    assert set(sb["best_config"]) >= {"lambda", "alpha"}, sb
    with open(os.path.join(tel, "run-journal.jsonl")) as f:
        rows = [json.loads(l) for l in f if l.strip()]
    rounds = [r for r in rows if r["kind"] == "search_round"]
    assert len(rounds) == 3, [r["kind"] for r in rows]
    assert rounds[0]["source"] == "sobol", rounds[0]
    assert rounds[2]["source"] == "gp", rounds[2]
    assert all(np.isfinite(r["best_metric"]) for r in rounds)
    done = [r for r in rows if r["kind"] == "search_complete"]
    assert len(done) == 1 and done[0]["configs"] == 12, done
    snaps = [r for r in rows if r["kind"] == "metrics"]
    flat = {k: v for r in snaps
            for k, v in r["snapshot"]["counters"].items()}
    assert flat.get("search/rounds") == 3, sorted(flat)
    assert flat.get("search/configs_evaluated") == 12, sorted(flat)
    assert flat.get("search/gp_proposal_rounds", 0) >= 1, sorted(flat)
    p = subprocess.run(
        [sys.executable, "-m", "dev.doctor", tel], cwd=REPO,
        capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 0, p.stdout + p.stderr
    print("A ok: driver search run — summary block, journal rows "
          f"(sources {[r['source'] for r in rounds]}), counters, doctor clean")

    # -- B: uniform tournament bitwise == train_glm_grid -----------------
    from photon_ml_tpu.algorithm.lane_search import LaneConfigs
    from photon_ml_tpu.estimators import train_glm_grid, train_glm_tournament
    from photon_ml_tpu.data.batch import LabeledPointBatch
    from photon_ml_tpu.optim.optimizer import OptimizerConfig
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 8)).astype(np.float32)
    wtrue = rng.normal(size=8).astype(np.float32)
    y = (X @ wtrue + 0.1 * rng.normal(size=200) > 0).astype(np.float32)
    batch = LabeledPointBatch(
        features=X, labels=y,
        offsets=np.zeros(200, np.float32), weights=np.ones(200, np.float32))
    lams = np.array([0.01, 0.1, 1.0, 10.0], np.float32)
    opt = OptimizerConfig(max_iterations=40)
    grid = train_glm_grid(batch, TaskType.LOGISTIC_REGRESSION,
                          optimizer=opt,
                          regularization_weights=[float(l) for l in lams])
    lanes = LaneConfigs(l2=np.asarray(lams, np.float64),
                        l1=np.zeros(4),
                        tolerance=np.full(4, opt.tolerance))
    tour = train_glm_tournament(batch, TaskType.LOGISTIC_REGRESSION,
                                lanes, optimizer=opt)
    for i, lam in enumerate(lams):
        a = np.asarray(grid[float(lam)].coefficients.means)
        b = np.asarray(tour.models[i].coefficients.means)
        assert np.array_equal(a, b), (i, np.max(np.abs(a - b)))
    print("B ok: uniform tournament BITWISE == train_glm_grid (4 lanes)")

    # -- C: seeded replay ------------------------------------------------
    from photon_ml_tpu.hyperparameter.search_driver import (
        parse_search_space, run_model_search)

    vb = LabeledPointBatch(
        features=rng.normal(size=(120, 8)).astype(np.float32),
        labels=(rng.random(120) > 0.5).astype(np.float32),
        offsets=np.zeros(120, np.float32), weights=np.ones(120, np.float32))
    space = parse_search_space("lambda=1e-3:1e2:log,alpha=0:1")

    def search(seed):
        return run_model_search(
            batch, vb, TaskType.LOGISTIC_REGRESSION, space,
            rounds=3, lane_budget=4, evaluator="AUC", seed=seed,
            optimizer=opt, min_observations=3)

    r1, r2, r3 = search(5), search(5), search(6)
    assert r1.best_metric == r2.best_metric
    assert np.array_equal(
        np.asarray(r1.best_model.coefficients.means),
        np.asarray(r2.best_model.coefficients.means))
    assert [v for _, v in r1.observations] == [v for _, v in r2.observations]
    src1 = [t["source"] for t in r1.trajectory]
    assert src1 == [t["source"] for t in r2.trajectory]
    assert src1[0] == "sobol" and src1[2] == "gp"
    assert [v for _, v in r1.observations] != [v for _, v in r3.observations]
    print(f"C ok: seed 5 replays bit-for-bit (sources {src1}); "
          "seed 6 diverges")

    # -- D: rejection probes ---------------------------------------------
    def expect(args, needle):
        try:
            glm_driver.main(args)
        except ValueError as e:
            assert needle in str(e), (needle, str(e))
            return
        raise AssertionError(f"no error for {needle!r}")

    base = ["--input-data-path", train, "--output-dir",
            os.path.join(tmp, "out2"), "--task-type", "LOGISTIC_REGRESSION",
            "--search-rounds", "2"]
    expect(base, "--search-space")
    expect(base + ["--search-space", "lambda=1e-3:1e2:log"],
           "--validation-data-path")
    expect(base + ["--search-space", "lambda=1e-3:1e2:log,alpha=0:1",
                   "--validation-data-path", val,
                   "--elastic-net-alpha", "0.5"], "alpha=0:1")
    expect(base + ["--search-space", "lambda=1e-3:1e2:log",
                   "--validation-data-path", val,
                   "--grid-parallel"], "--grid-parallel")
    try:
        run_model_search(
            batch, vb, TaskType.LOGISTIC_REGRESSION,
            parse_search_space("lambda=1e-3:1e2:log,box=0:1:int"),
            rounds=1, lane_budget=2, evaluator="AUC", seed=0,
            optimizer=opt)
    except ValueError as e:
        assert "box_lower" in str(e), str(e)
    else:
        raise AssertionError("box dim without bounds did not raise")
    print("D ok: CLI + library rejections fail fast naming the alternative")

    print("\nALL DRIVE CHECKS PASSED")


if __name__ == "__main__":
    main()
