"""bf16 feature-block accuracy study (VERDICT r4 item 2 done-criterion).

Trains the same logistic problem with f32 vs bf16 X through train_glm
(sequential path -> Pallas kernel on TPU) across a λ grid; reports frozen
train-loss / AUC / coefficient deltas. Run on the TPU from repo root.
"""
import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.estimators import train_glm
from photon_ml_tpu.evaluation.local_metrics import area_under_roc_curve
from photon_ml_tpu.types import TaskType

rng = np.random.default_rng(0)
n, d = 1 << 16, 512
w_true = rng.normal(size=d).astype(np.float32) / np.sqrt(d)
x = rng.normal(size=(n, d)).astype(np.float32)
y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(x @ w_true)))).astype(np.float32)
xh, yh = x[: n // 2], y[: n // 2]
xv, yv = x[n // 2:], y[n // 2:]

for lam in (0.1, 1.0, 10.0):
    out = {}
    for tag, xd in (("f32", xh), ("bf16", jnp.asarray(xh, jnp.bfloat16))):
        b = LabeledPointBatch.create(jax.device_put(jnp.asarray(xd)),
                                     jax.device_put(jnp.asarray(yh)))
        m = train_glm(b, TaskType.LOGISTIC_REGRESSION,
                      regularization_weights=[lam])[lam]
        w = np.asarray(m.coefficients.means, np.float32)
        margins = xv @ w
        loss = float(np.mean(np.logaddexp(0, margins) - yv * margins))
        auc = float(area_under_roc_curve(margins, yv, np.ones_like(yv)))
        out[tag] = (w, loss, auc)
    wf, lf, af = out["f32"]
    wb, lb, ab = out["bf16"]
    print(f"lam={lam}: f32 loss={lf:.6f} auc={af:.6f} | "
          f"bf16 loss={lb:.6f} auc={ab:.6f} | "
          f"dloss={abs(lb-lf):.2e} dauc={abs(ab-af):.2e} "
          f"max|dw|={np.max(np.abs(wb-wf)):.2e} "
          f"rel|dw|={np.linalg.norm(wb-wf)/np.linalg.norm(wf):.2e}",
          flush=True)
