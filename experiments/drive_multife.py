"""Verification drive: multi-FE + configured sweep order via the fused path.

User-style drive of the VERDICT r3 #4 capability (no test harness):
a 2-FE + RE GAME model trained through GameEstimator on the 8-device CPU
mesh, in a non-default update sequence, vs the CD path; then scored through
GameTransformer.

Run: PYTHONPATH=/root/repo PALLAS_AXON_POOL_IPS= python experiments/drive_multife.py
"""
import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from photon_ml_tpu.algorithm.coordinates import CoordinateOptimizationConfig
from photon_ml_tpu.data.game_data import build_game_dataset
from photon_ml_tpu.estimators import (
    FixedEffectCoordinateConfig,
    GameEstimator,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.optim.optimizer import OptimizerConfig
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.transformers import GameTransformer
from photon_ml_tpu.types import TaskType

r = np.random.default_rng(0)
n = 3001  # not divisible by 8: exercises mesh padding
users = np.array([f"u{i}" for i in r.integers(0, 40, size=n)])
x_global = r.normal(size=(n, 8)).astype(np.float32)
x_ctx = r.normal(size=(n, 5)).astype(np.float32)
x_user = r.normal(size=(n, 3)).astype(np.float32)
truth = np.random.default_rng(1)
wg, wc = truth.normal(size=8), truth.normal(size=5)
wu = truth.normal(size=(40, 3))
ui = np.array([int(u[1:]) for u in users])
y = (x_global @ wg + x_ctx @ wc + np.einsum("nd,nd->n", x_user, wu[ui])
     + 0.1 * r.normal(size=n)).astype(np.float32)

def make_ds(sl):
    return build_game_dataset(
        labels=y[sl],
        feature_shards={"g": x_global[sl], "c": x_ctx[sl], "u": x_user[sl]},
        entity_keys={"userId": users[sl]},
        ids={"queryId": users[sl]},
    )

train, val = make_ds(slice(0, 2400)), make_ds(slice(2400, None))
opt = CoordinateOptimizationConfig(
    optimizer=OptimizerConfig(max_iterations=20), l2_weight=0.5
)
configs = {
    "ctx": FixedEffectCoordinateConfig("c", opt),       # extra FE... listed first
    "fixed": FixedEffectCoordinateConfig("g", opt),
    "per-user": RandomEffectCoordinateConfig("userId", "u", opt),
}
seq = ("per-user", "ctx", "fixed")  # RE first, then the two FEs

results = {}
for name, mesh in (("cd", None), ("fused", make_mesh())):
    est = GameEstimator(
        task=TaskType.LINEAR_REGRESSION,
        coordinate_configs=configs,
        update_sequence=seq,
        num_iterations=3,
        validation_evaluators=("RMSE", "RMSE:queryId"),
        mesh=mesh,
    )
    res = est.fit(train, validation_dataset=val)
    results[name] = res
    losses = [h for h in res.metric_history]
    print(f"{name}: best_metric={res.best_metric:.5f} "
          f"model coords={list(res.model.models)}")
    print(f"   history[0]={losses[0] if losses else None}")

cd, fu = results["cd"], results["fused"]
assert list(fu.model.models) == list(cd.model.models) == list(seq), \
    (list(fu.model.models), list(seq))
rel = abs(fu.best_metric - cd.best_metric) / cd.best_metric
print(f"best_metric rel diff fused-vs-cd: {rel:.2e}")
assert rel < 5e-3, rel
for cid in ("ctx", "fixed"):
    a = np.asarray(fu.model.get(cid).glm.coefficients.means)
    b = np.asarray(cd.model.get(cid).glm.coefficients.means)
    print(f"{cid}: max|fused-cd|={np.max(np.abs(a - b)):.2e}")
    assert np.max(np.abs(a - b)) < 1e-2

# the trained FEs recover the truth directions
a = np.asarray(fu.model.get("fixed").glm.coefficients.means)
cos = a @ wg / np.linalg.norm(a) / np.linalg.norm(wg)
print(f"fixed-vs-truth cosine: {cos:.4f}")
assert cos > 0.99

# score the fused-trained model through the standard transformer
tr = GameTransformer(model=fu.best_model or fu.model,
                     evaluator_specs=("RMSE",))
out = tr.transform(val)
print(f"transform RMSE={out.evaluations['RMSE']:.4f}")
assert out.evaluations["RMSE"] < 0.5 * float(np.std(y))
print("DRIVE OK")
