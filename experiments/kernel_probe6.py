"""Probe 6: aux-column packing layouts for the GLM kernel.

probe5 showed each separate [n,1] input stream costs ~0.07 ms/eval
(narrow DMA) and the wrapper's in-jit col() construction costs ~0.25 ms.
Variants:
  v1) aux packed [n, 3] (y,o,ws), single input, prebuilt on device
  v2) aux packed [n, 3] built IN-JIT from three [n] args via jnp.stack
  v3) x passed through an in-jit zero-amount jnp.pad (elision check)
  v4) aux [n, 3] + x zero-pad (full wrapper realism)
  v5) v1 without rsum
Run: python experiments/kernel_probe6.py
"""
from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, D = 1 << 17, 512
K_LO, K_HI = 16, 512


def measure(step_fn, d, batch, reps=4):
    def timed(k):
        @jax.jit
        def run(w0, b):
            w, vs = jax.lax.scan(lambda w, _: step_fn(w, b), w0, None, length=k)
            return vs.sum() + w.sum()

        float(run(jnp.zeros(d, jnp.float32), batch))
        best = None
        rng = np.random.default_rng(0)
        for _ in range(reps):
            w0 = jnp.asarray(rng.normal(size=d).astype(np.float32)) * 0.01
            t0 = time.perf_counter()
            float(run(w0, batch))
            el = time.perf_counter() - t0
            best = el if best is None or el < best else best
        return best

    return max((timed(K_HI) - timed(K_LO)) / (K_HI - K_LO), 1e-9)


def kernel(with_rsum, x_ref, aux_ref, w_ref, *outs):
    if with_rsum:
        val_ref, grad_ref, rsum_ref = outs
    else:
        val_ref, grad_ref = outs
        rsum_ref = None

    @pl.when(pl.program_id(0) == 0)
    def _init():
        val_ref[0, 0] = jnp.float32(0.0)
        grad_ref[:] = jnp.zeros_like(grad_ref)
        if rsum_ref is not None:
            rsum_ref[0, 0] = jnp.float32(0.0)

    x = x_ref[:]
    w = w_ref[:]
    aux = aux_ref[:]  # [tile, 3]: y | o | ws
    y, o, ws = aux[:, 0:1], aux[:, 1:2], aux[:, 2:3]
    margins = jnp.dot(x, w.reshape(-1, 1), preferred_element_type=jnp.float32)
    margins = margins + o
    l = jnp.logaddexp(0.0, margins) - y * margins
    dz = jax.nn.sigmoid(margins) - y
    r = ws * dz
    val_ref[0, 0] += jnp.sum(ws * l)
    if rsum_ref is not None:
        rsum_ref[0, 0] += jnp.sum(r)
    g = jax.lax.dot_general(r, x, (((0,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    grad_ref[:] = grad_ref[:] + g


def fused(with_rsum, tile, x, aux, w):
    n_pad, d_pad = x.shape
    vmem = dict(memory_space=pltpu.VMEM)
    smem = dict(memory_space=pltpu.SMEM)
    out_specs = [
        pl.BlockSpec((1, 1), lambda i: (0, 0), **smem),
        pl.BlockSpec((1, d_pad), lambda i: (0, 0), **vmem),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((1, 1), jnp.float32),
        jax.ShapeDtypeStruct((1, d_pad), jnp.float32),
    ]
    if with_rsum:
        out_specs.append(pl.BlockSpec((1, 1), lambda i: (0, 0), **smem))
        out_shape.append(jax.ShapeDtypeStruct((1, 1), jnp.float32))
    outs = pl.pallas_call(
        functools.partial(kernel, with_rsum),
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, d_pad), lambda i: (i, 0), **vmem),
            pl.BlockSpec((tile, 3), lambda i: (i, 0), **vmem),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0), **vmem),
        ],
        out_specs=out_specs, out_shape=out_shape,
    )(x, aux, w.reshape(1, d_pad))
    return outs[0][0, 0], outs[1][0]


def main():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(N, D)).astype(np.float32)
    w_true = rng.normal(size=D).astype(np.float32) / np.sqrt(D)
    y = (rng.uniform(size=N) < 1 / (1 + np.exp(-(x @ w_true)))).astype(np.float32)
    xbytes = N * D * 4

    xd = jax.device_put(jnp.asarray(x))
    aux = jax.device_put(jnp.stack(
        [jnp.asarray(y), jnp.zeros(N), jnp.ones(N)], axis=1).astype(jnp.float32))
    batch = {
        "x": xd, "aux": aux,
        "y": jax.device_put(jnp.asarray(y)),
        "o": jax.device_put(jnp.zeros(N, jnp.float32)),
        "ws": jax.device_put(jnp.ones(N, jnp.float32)),
    }

    def stream_step(w, b):
        return w + jnp.sum(b["x"] @ w) * 1e-30, jnp.float32(0)

    m = measure(stream_step, D, batch)
    stream = xbytes / m / 1e9
    print(f"stream: {m*1e3:.3f} ms/step  {stream:.1f} GB/s", flush=True)

    def report(name, m):
        print(f"{name}: {m*1e3:.3f} ms/step  {xbytes/m/1e9:.1f} GB/s  "
              f"frac={xbytes/m/1e9/stream:.2f}", flush=True)

    def step_v1(w, b):
        v, g = fused(True, 1024, b["x"], b["aux"], w)
        return w - 1e-4 * g[:D], v

    report("v1 packed aux prebuilt", measure(step_v1, D, batch))

    def step_v2(w, b):
        a = jnp.stack([b["y"], b["o"], b["ws"]], axis=1)
        v, g = fused(True, 1024, b["x"], a, w)
        return w - 1e-4 * g[:D], v

    report("v2 packed aux in-jit stack", measure(step_v2, D, batch))

    def step_v3(w, b):
        xp = jnp.pad(b["x"], ((0, 0), (0, 0)))
        v, g = fused(True, 1024, xp, b["aux"], w)
        return w - 1e-4 * g[:D], v

    report("v3 x zero-pad in-jit", measure(step_v3, D, batch))

    def step_v4(w, b):
        xp = jnp.pad(b["x"], ((0, 0), (0, 0)))
        a = jnp.stack([b["y"], b["o"], b["ws"]], axis=1)
        v, g = fused(True, 1024, xp, a, w)
        return w - 1e-4 * g[:D], v

    report("v4 both in-jit", measure(step_v4, D, batch))

    def step_v5(w, b):
        v, g = fused(False, 1024, b["x"], b["aux"], w)
        return w - 1e-4 * g[:D], v

    report("v5 packed aux no rsum", measure(step_v5, D, batch))


if __name__ == "__main__":
    main()
