"""r5: where does a batched-Newton RE iteration spend its time on TPU?

Pieces, each K-differenced inside one jit (lax.scan, carry-dependent so
nothing hoists): batched 16x16 Cholesky+solve, LU solve, hand-rolled
Gauss elimination, the Hessian einsum, one bucket value pass, and
minimize_newton at fixed iteration counts. Decides whether the 81 ms
newton sweep (newton_sweep_probe_r5.log) is solver-algebra-bound or
no-early-exit-bound.
"""

import os
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    print(f"backend={jax.default_backend()}")
    rng = np.random.default_rng(0)
    e, cap, d = 2000, 128, 16
    x = rng.normal(size=(e, cap, d)).astype(np.float32)
    yv = rng.normal(size=(e, cap)).astype(np.float32)
    h0 = np.einsum("ncd,nce->nde", x, x).astype(np.float32)
    h0 += np.eye(d, dtype=np.float32)[None] * cap  # well-conditioned PD
    g0 = rng.normal(size=(e, d)).astype(np.float32)

    def timed(fn, *args, k_lo=8, k_hi=64):
        @partial(jax.jit, static_argnums=(0,))
        def run(k, *a):
            def step(carry, _):
                out = fn(carry, *a)
                return out, 0.0
            c, _ = jax.lax.scan(step, jnp.zeros((e, d), jnp.float32), None,
                                length=k)
            return c.sum()

        float(run(k_lo, *args)); float(run(k_hi, *args))  # compile
        best = {}
        for k in (k_lo, k_hi):
            vals = []
            for _ in range(3):
                t0 = time.perf_counter()
                float(run(k, *args))
                vals.append(time.perf_counter() - t0)
            best[k] = min(vals)
        return max((best[k_hi] - best[k_lo]) / (k_hi - k_lo), 1e-9)

    h_d, g_d, x_d, y_d = map(jnp.asarray, (h0, g0, x, yv))

    # 1. batched cholesky + cho_solve (carry-coupled so it can't hoist)
    def chol_solve(carry, h, g):
        gg = g + carry * 1e-30
        l_ = jnp.linalg.cholesky(h)
        return jax.scipy.linalg.cho_solve((l_, True), gg)

    # 2. batched LU solve
    def lu_solve(carry, h, g):
        return jnp.linalg.solve(h, (g + carry * 1e-30)[..., None])[..., 0]

    # 3. hand-rolled Gauss-Jordan elimination (vectorized over e, fori over d)
    def gauss(carry, h, g):
        gg = g + carry * 1e-30
        a = jnp.concatenate([h, gg[:, :, None]], axis=2)  # [e, d, d+1]

        def elim(i, a):
            piv = a[:, i, :] / a[:, i, i][:, None]  # [e, d+1]
            factors = a[:, :, i]  # [e, d]
            a = a - factors[:, :, None] * piv[:, None, :]
            a = a.at[:, i, :].set(piv)
            return a

        a = jax.lax.fori_loop(0, d, elim, a)
        return a[:, :, d]

    # 4. hessian einsum
    def hess(carry, x_, y_):
        w = carry * 1e-30
        m = jnp.einsum("ecd,ed->ec", x_, w + 1.0)
        dz = m - y_
        return jnp.einsum("ec,ecd->ed", dz, x_)  # grad-ish pass

    def hess_full(carry, x_):
        h = jnp.einsum("ncd,nce->nde", x_ + carry[:, None, :] * 1e-30, x_)
        return h[:, :, 0]

    for name, fn, args in (
        ("cholesky+cho_solve [e,16,16]", chol_solve, (h_d, g_d)),
        ("lu jnp.linalg.solve", lu_solve, (h_d, g_d)),
        ("hand gauss-jordan", gauss, (h_d, g_d)),
        ("value/grad bucket pass", hess, (x_d, y_d)),
        ("hessian einsum", hess_full, (x_d,)),
    ):
        t = timed(fn, *args)
        print(f"{name:32s} {t * 1e3:8.3f} ms/call")

    # 6. minimize_newton at pinned iteration counts on a real bucket solve
    from photon_ml_tpu.data.batch import LabeledPointBatch
    from photon_ml_tpu.ops.losses import SquaredLoss
    from photon_ml_tpu.ops.objective import GLMObjective
    from photon_ml_tpu.optim.newton import minimize_newton
    from photon_ml_tpu.optim.lbfgs import minimize_lbfgs

    obj = GLMObjective(SquaredLoss(), l2_weight=1.0)
    w8 = jnp.asarray(rng.uniform(0.5, 1.0, size=(e, cap)).astype(np.float32))
    off = jnp.zeros((e, cap), jnp.float32)

    def newton_k(iters):
        def solve_one(f, l, o, wt, w0, tol):
            b = LabeledPointBatch(features=f, labels=l, offsets=o, weights=wt)
            bound = obj.bind(b)
            return minimize_newton(bound.value_and_grad, bound.hessian_matrix,
                                   w0, value_fn=bound.value, max_iter=iters,
                                   tolerance=tol).coefficients

        def fn(carry, x_, y_, o_, w_):
            w0 = carry * 1e-3
            return jax.vmap(solve_one, in_axes=(0, 0, 0, 0, 0, None))(
                x_, y_, o_, w_, w0, 0.0)

        return fn

    def lbfgs_k(iters):
        def solve_one(f, l, o, wt, w0):
            b = LabeledPointBatch(features=f, labels=l, offsets=o, weights=wt)
            bound = obj.bind(b)
            return minimize_lbfgs(bound.value_and_grad, w0, max_iter=iters,
                                  tolerance=0.0).coefficients

        def fn(carry, x_, y_, o_, w_):
            w0 = carry * 1e-3
            return jax.vmap(solve_one)(x_, y_, o_, w_, w0)

        return fn

    for name, fn in (
        ("newton 1 iter", newton_k(1)),
        ("newton 2 iters", newton_k(2)),
        ("newton 10 iters", newton_k(10)),
        ("lbfgs 1 iter", lbfgs_k(1)),
        ("lbfgs 10 iters", lbfgs_k(10)),
    ):
        t = timed(fn, x_d, y_d, off, w8, k_lo=4, k_hi=16)
        print(f"bucket solve {name:20s} {t * 1e3:8.3f} ms/call")


if __name__ == "__main__":
    main()
