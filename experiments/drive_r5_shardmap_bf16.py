"""r5 verification drive: sharded one-pass kernel + bf16 product path.

User-style end-to-end (not tests): on the 8-device virtual CPU mesh,
1. GameEstimator CD vs distributed-with-kernel-forced agreement;
2. read_merged with dtype=bf16 (libsvm) -> estimator -> metrics vs f32;
3. negative probes (bad dtype spec, sparse+bf16).

Run: PYTHONPATH=/root/repo PALLAS_AXON_POOL_IPS= \
     XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
     python experiments/drive_r5_shardmap_bf16.py
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")


def main():
    from photon_ml_tpu.algorithm.coordinates import CoordinateOptimizationConfig
    from photon_ml_tpu.data.game_data import build_game_dataset
    from photon_ml_tpu.estimators import (
        FixedEffectCoordinateConfig,
        GameEstimator,
        RandomEffectCoordinateConfig,
    )
    from photon_ml_tpu.optim.optimizer import OptimizerConfig
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.types import TaskType

    assert len(jax.devices()) == 8, jax.devices()
    rng = np.random.default_rng(42)
    n, d_fe, d_re = 999, 12, 4  # deliberately NOT divisible by 8
    user_ids = rng.integers(0, 30, size=n)
    users = np.array([f"u{i}" for i in user_ids])
    x_fe = rng.normal(size=(n, d_fe)).astype(np.float32)
    x_re = rng.normal(size=(n, d_re)).astype(np.float32)
    w_true = rng.normal(size=d_fe)
    # real per-user signal so the RE coordinate IMPROVES validation: the CD
    # path validates after every coordinate update while the fused path
    # validates per sweep, so best_metric only matches when the last
    # coordinate helps (same reason the music fixture has entity signal)
    w_user = rng.normal(scale=0.8, size=(30, d_re))
    y = (
        x_fe @ w_true
        + np.einsum("nd,nd->n", x_re, w_user[user_ids])
        + 0.3 * rng.normal(size=n)
    ).astype(np.float32)

    def dataset():
        return build_game_dataset(
            labels=y, feature_shards={"global": x_fe, "per": x_re},
            entity_keys={"user": users},
        )

    def estimator(mesh=None, use_pallas=None):
        return GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinate_configs={
                "fe": FixedEffectCoordinateConfig(
                    "global",
                    CoordinateOptimizationConfig(
                        optimizer=OptimizerConfig(max_iterations=20),
                        l2_weight=0.5,
                    ),
                ),
                "per-user": RandomEffectCoordinateConfig(
                    "user", "per",
                    CoordinateOptimizationConfig(
                        optimizer=OptimizerConfig(max_iterations=10),
                        l2_weight=1.0,
                    ),
                ),
            },
            num_iterations=2,
            validation_evaluators=("RMSE",),
            mesh=mesh,
            use_pallas=use_pallas,
        )

    # 1. CD (no mesh) vs distributed with the per-device kernel FORCED
    yv = (
        x_fe @ w_true
        + np.einsum("nd,nd->n", x_re, w_user[user_ids])
        + 0.3 * rng.normal(size=n)
    ).astype(np.float32)[:256]
    val = build_game_dataset(
        labels=yv,
        feature_shards={"global": x_fe[:256], "per": x_re[:256]},
        entity_keys={"user": users[:256]},
    )
    r_cd = estimator().fit(dataset(), validation_dataset=val)
    mesh = make_mesh(data=8, model=1)
    r_mesh = estimator(mesh=mesh, use_pallas=True).fit(
        dataset(), validation_dataset=val
    )
    m_cd, m_mesh = r_cd.best_metric, r_mesh.best_metric
    rel = abs(m_mesh - m_cd) / abs(m_cd)
    print(f"1. CD RMSE={m_cd:.6f}  mesh+kernel RMSE={m_mesh:.6f}  rel={rel:.2e}")
    assert rel < 5e-3, (m_cd, m_mesh)

    # confirm the program actually held a sharded-kernel objective
    # (estimator internals: rebuild the program the same way)
    est = estimator(mesh=mesh, use_pallas=True)
    # quick structural check through a program the same ctor args produce
    from photon_ml_tpu.parallel.distributed import (
        FixedEffectStepSpec, GameTrainProgram,
    )
    p = GameTrainProgram(
        TaskType.LINEAR_REGRESSION,
        FixedEffectStepSpec("global", OptimizerConfig(max_iterations=2)),
        (), mesh=mesh, use_pallas_fe=True,
    )
    assert p._fe_sharded_objective is not None
    print("   sharded-kernel objective present on multi-device program: ok")

    # 2. bf16 through the product reader: libsvm + dtype=bf16
    import jax.numpy as jnp

    from photon_ml_tpu.io.data_reader import (
        FeatureShardConfiguration, read_merged,
    )

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "part-0.libsvm")
        with open(path, "w") as f:
            for i in range(512):
                pairs = " ".join(
                    f"{j + 1}:{x_fe[i, j]:.5f}" for j in range(d_fe)
                )
                f.write(f"{y[i]:.5f} {pairs}\n")

        def read(dtype):
            return read_merged(
                path,
                {"g": FeatureShardConfiguration(("features",), sparse=False,
                                                has_intercept=False,
                                                dtype=dtype)},
                fmt="libsvm",
            )

        res32 = read("float32")
        res16 = read("bfloat16")
        sh32 = res32.dataset.feature_shards["g"]
        sh16 = res16.dataset.feature_shards["g"]
        assert sh16.dtype == jnp.bfloat16, sh16.dtype
        assert sh32.dtype == jnp.float32, sh32.dtype
        # the bf16 block is the f32 block rounded once
        np.testing.assert_allclose(
            np.asarray(sh16, dtype=np.float32), np.asarray(sh32),
            rtol=1e-2, atol=1e-2,
        )
        # train on both, metrics agree to bf16 accuracy
        def fit(res):
            ds = res.dataset
            est = GameEstimator(
                task=TaskType.LINEAR_REGRESSION,
                coordinate_configs={
                    "fe": FixedEffectCoordinateConfig(
                        "g",
                        CoordinateOptimizationConfig(
                            optimizer=OptimizerConfig(max_iterations=20),
                            l2_weight=0.5,
                        ),
                    )
                },
                num_iterations=1,
            )
            r = est.fit(ds)
            w = np.asarray(
                r.model.models["fe"].glm.coefficients.means, dtype=np.float64
            )
            return w

        w32, w16 = fit(res32), fit(res16)
        assert w16.dtype == np.float64 and np.isfinite(w16).all()
        relw = np.linalg.norm(w16 - w32) / np.linalg.norm(w32)
        print(f"2. bf16-product-path rel ||dw|| vs f32: {relw:.2e}")
        assert relw < 5e-2, relw

    # 4. device-side evaluation + ring RE scoring (VERDICT r4 #4/#6):
    # a user scores + evaluates a model with a big dense RE table over the
    # mesh; metrics must match the host evaluators and the table must stay
    # entity-sharded (ring rotation, no all-gather)
    from photon_ml_tpu.models.game import GameModel, RandomEffectModel
    from photon_ml_tpu.parallel.scoring import DistributedScorer

    e_big, d_re2, n2 = 4096, 8, 800
    vocab = np.array(sorted({f"u{i}" for i in range(e_big)}))
    table = rng.normal(size=(e_big, d_re2)).astype(np.float32)
    u2 = rng.integers(0, e_big, size=n2)
    x2 = rng.normal(size=(n2, d_re2)).astype(np.float32)
    q2 = np.array([f"q{i}" for i in rng.integers(0, 17, size=n2)])
    ds2 = build_game_dataset(
        labels=(rng.random(n2) < 0.5).astype(np.float32),
        feature_shards={"u": x2},
        entity_keys={"user": u2.astype(str)},
        entity_vocabs={"user": vocab},
        ids={"queryId": q2},
    )
    big_model = GameModel(models={
        "per-user": RandomEffectModel(
            coefficients=table,
            entity_keys=vocab,
            random_effect_type="user",
            feature_shard_id="u",
            task=TaskType.LOGISTIC_REGRESSION,
        )
    })
    mesh8 = make_mesh(data=8, model=1)
    ref_scores = DistributedScorer(big_model, None).score_dataset(ds2)
    ring_scores = DistributedScorer(big_model, mesh8).score_dataset(ds2)
    np.testing.assert_allclose(ring_scores, ref_scores, rtol=1e-5, atol=1e-5)

    from photon_ml_tpu.evaluation.evaluators import (
        EvaluationData, parse_evaluator,
    )

    specs = ("RMSE", "AUC", "AUC:queryId", "PRECISION@3:queryId", "AUPR")
    got = DistributedScorer(big_model, mesh8).evaluate_dataset(ds2, specs)
    host_data = EvaluationData(
        labels=np.asarray(ds2.host_array("labels"), np.float64),
        offsets=np.zeros(n2), weights=np.ones(n2),
        ids={"queryId": q2},
    )
    for s in specs:
        ev = parse_evaluator(s)
        want = ev.evaluate(ref_scores, host_data)
        tol = 5e-3 if ev.name == "AUC" else 1e-6
        assert abs(got[ev.name] - want) <= tol * max(1.0, abs(want)), (
            s, got[ev.name], want
        )
    print(f"4. ring RE scoring + device evaluation over {len(specs)} "
          f"metrics (E={e_big} dense table, 8-device mesh): ok")

    # 3. negative probes
    from photon_ml_tpu.cli.configs import parse_feature_shard_config

    for spec, msg in (
        ("name=g,feature.bags=f,dtype=int8", "unknown feature shard dtype"),
        ("name=g,feature.bags=f,sparse=true,dtype=bf16", "dense"),
    ):
        try:
            parse_feature_shard_config(spec)
            raise AssertionError(f"{spec} should have raised")
        except ValueError as e:
            assert msg in str(e), (spec, e)
    print("3. negative probes: ok")
    print("DRIVE_OK")


if __name__ == "__main__":
    main()
