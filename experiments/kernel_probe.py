"""Hardware probe: GLM value+grad Pallas kernel variants vs stream rate.

VERDICT r3 item 1: the r3 kernel achieved 0.45x the same-run stream rate
despite being single-pass. This probe measures, IN ONE PROCESS on one chip
assignment, a same-run stream calibration plus kernel variants that move the
margin matvec and the gradient accumulation onto the MXU, sweep row-tile
sizes, and try bf16 X storage.

Run from repo root on the TPU (no PYTHONPATH):  python experiments/kernel_probe.py
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, D = 1 << 17, 512
K_LO, K_HI = 16, 512


def _round_up(x, m):
    return ((x + m - 1) // m) * m


def loss_and_dz(margins, y):
    # logistic: log(1+e^m) - y*m ; dz = sigmoid(m) - y
    l = jnp.logaddexp(0.0, margins) - y * margins
    dz = jax.nn.sigmoid(margins) - y
    return l, dz


def make_kernel(margin_mode, grad_mode):
    def kernel(x_ref, y_ref, ws_ref, w_ref, val_ref, grad_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            val_ref[0, 0] = jnp.float32(0.0)
            grad_ref[:] = jnp.zeros_like(grad_ref)

        x = x_ref[:]
        w = w_ref[:]
        if margin_mode == "vpu":
            margins = jnp.sum(x.astype(jnp.float32) * w, axis=1, keepdims=True)
        else:  # mxu
            margins = jax.lax.dot_general(
                x, w, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
        l, dz = loss_and_dz(margins, y_ref[:])
        r = ws_ref[:] * dz
        val_ref[0, 0] += jnp.sum(ws_ref[:] * l)
        if grad_mode == "vpu":
            g = jnp.sum(r * x.astype(jnp.float32), axis=0, keepdims=True)
        else:  # mxu
            g = jax.lax.dot_general(
                r.astype(x.dtype), x, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        grad_ref[:] = grad_ref[:] + g

    return kernel


def fused(margin_mode, grad_mode, tile, x, y, ws, w, semantics=None):
    n_pad, d_pad = x.shape
    grid = (n_pad // tile,)
    params = {}
    if semantics is not None:
        params["compiler_params"] = pltpu.CompilerParams(
            dimension_semantics=(semantics,))
    value, grad = pl.pallas_call(
        make_kernel(margin_mode, grad_mode),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile, d_pad), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, d_pad), jnp.float32),
        ],
        **params,
    )(x, y, ws, w.reshape(1, d_pad))
    return value[0, 0], grad[0]


def measure(step_fn, d, batch, reps=4):
    """Marginal seconds per step via K_hi-vs-K_lo scan differencing."""
    def timed(k):
        @jax.jit
        def run(w0, b):
            w, vs = jax.lax.scan(lambda w, _: step_fn(w, b), w0, None, length=k)
            return vs.sum() + w.sum()

        float(run(jnp.zeros(d, jnp.float32), batch))  # compile+sync
        best = None
        rng = np.random.default_rng(0)
        for _ in range(reps):
            w0 = jnp.asarray(rng.normal(size=d).astype(np.float32)) * 0.01
            t0 = time.perf_counter()
            float(run(w0, batch))
            el = time.perf_counter() - t0
            best = el if best is None or el < best else best
        return best

    return max((timed(K_HI) - timed(K_LO)) / (K_HI - K_LO), 1e-9)


def main():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(N, D)).astype(np.float32)
    w_true = rng.normal(size=D).astype(np.float32) / np.sqrt(D)
    y = (rng.uniform(size=N) < 1 / (1 + np.exp(-(x @ w_true)))).astype(np.float32)
    xbytes = N * D * 4

    xd = jax.device_put(jnp.asarray(x))
    xbf = jax.device_put(jnp.asarray(x, jnp.bfloat16))
    yc = jax.device_put(jnp.asarray(y).reshape(-1, 1))
    wsc = jax.device_put(jnp.ones((N, 1), jnp.float32))
    batch = {"x": xd, "xbf": xbf, "y": yc, "ws": wsc}

    # stream calibration: one X read per step, consumes carry
    def stream_step(w, b):
        return w + jnp.sum(b["x"] @ w) * 1e-30, jnp.float32(0)

    m = measure(stream_step, D, batch)
    stream = xbytes / m / 1e9
    print(f"stream: {m*1e3:.3f} ms/step  {stream:.1f} GB/s", flush=True)

    # autodiff 2-pass for reference
    def autodiff_step(w, b):
        def val(w):
            margins = b["x"] @ w
            l, _ = loss_and_dz(margins[:, None], b["y"])
            return jnp.sum(b["ws"] * l)
        v, g = jax.value_and_grad(val)(w)
        return w - 1e-4 * g, v

    m = measure(autodiff_step, D, batch)
    print(f"autodiff: {m*1e3:.3f} ms/step  {xbytes/m/1e9:.1f} GB/s(1-read)  "
          f"frac={xbytes/m/1e9/stream:.2f}", flush=True)

    variants = [
        ("vpu/vpu t1024 f32", "vpu", "vpu", 1024, "x", None),
        ("mxu/vpu t1024 f32", "mxu", "vpu", 1024, "x", None),
        ("vpu/mxu t1024 f32", "vpu", "mxu", 1024, "x", None),
        ("mxu/mxu t1024 f32", "mxu", "mxu", 1024, "x", None),
        ("mxu/mxu t512  f32", "mxu", "mxu", 512, "x", None),
        ("mxu/mxu t2048 f32", "mxu", "mxu", 2048, "x", None),
        ("mxu/mxu t256  f32", "mxu", "mxu", 256, "x", None),
        ("mxu/mxu t1024 f32 arb", "mxu", "mxu", 1024, "x", "arbitrary"),
        ("mxu/mxu t1024 bf16", "mxu", "mxu", 1024, "xbf", None),
        ("mxu/mxu t2048 bf16", "mxu", "mxu", 2048, "xbf", None),
        ("vpu/vpu t1024 bf16", "vpu", "vpu", 1024, "xbf", None),
    ]
    for name, mm, gm, tile, xkey, sem in variants:
        nb = (2 if xkey == "xbf" else 4) * N * D

        def kstep(w, b, _mm=mm, _gm=gm, _tile=tile, _xk=xkey, _sem=sem):
            v, g = fused(_mm, _gm, _tile, b[_xk], b["y"], b["ws"], w, _sem)
            return w - 1e-4 * g, v

        try:
            m = measure(kstep, D, batch)
        except Exception as e:  # noqa: BLE001
            print(f"{name}: FAILED {type(e).__name__}: {str(e)[:200]}", flush=True)
            continue
        gbps = nb / m / 1e9
        print(f"{name}: {m*1e3:.3f} ms/step  {gbps:.1f} GB/s(actual)  "
              f"eff-frac-of-stream={xbytes/m/1e9/stream:.2f} "
              f"actual-frac={gbps/stream:.2f}", flush=True)


if __name__ == "__main__":
    main()
