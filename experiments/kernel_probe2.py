"""Probe 2: bf16 MXU workarounds + the repo's own kernel path.

The direct bf16 dot_general with [1,d]/[tile,1] operands trips a Mosaic
verification bug ('vector.broadcast'). Workarounds tried here:
  - standard-layout [tile,d]@[d,1] matmul for margins
  - 128-replicated-column dots (W128 / R128) so M/N are MXU-native
Also measures the repo's fused_value_and_gradient (objective path,
use_pallas=True) to explain BENCH_r03's 0.45 frac.

Run from repo root:  python experiments/kernel_probe2.py
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, D = 1 << 17, 512
K_LO, K_HI = 16, 512


def loss_and_dz(margins, y):
    l = jnp.logaddexp(0.0, margins) - y * margins
    dz = jax.nn.sigmoid(margins) - y
    return l, dz


def make_kernel(margin_mode, grad_mode):
    def kernel(x_ref, y_ref, ws_ref, w_ref, val_ref, grad_ref):
        @pl.when(pl.program_id(0) == 0)
        def _init():
            val_ref[0, 0] = jnp.float32(0.0)
            grad_ref[:] = jnp.zeros_like(grad_ref)

        x = x_ref[:]          # [tile, d] (maybe bf16)
        w = w_ref[:]          # [1, d] f32 or bf16 (same dtype as x)
        if margin_mode == "vpu":
            margins = jnp.sum(x.astype(jnp.float32) * w.astype(jnp.float32),
                              axis=1, keepdims=True)
        elif margin_mode == "mxu_col":  # [tile,d]@[d,1] standard layout
            margins = jnp.dot(x, w.reshape(-1, 1),
                              preferred_element_type=jnp.float32)
        elif margin_mode == "mxu_w128":  # replicate w into 128 columns
            w128 = jnp.broadcast_to(w.reshape(-1, 1), (w.shape[1], 128))
            margins = jnp.dot(x, w128,
                              preferred_element_type=jnp.float32)[:, :1]
        l, dz = loss_and_dz(margins, y_ref[:])
        r = ws_ref[:] * dz    # [tile, 1] f32
        val_ref[0, 0] += jnp.sum(ws_ref[:] * l)
        if grad_mode == "vpu":
            g = jnp.sum(r * x.astype(jnp.float32), axis=0, keepdims=True)
        elif grad_mode == "mxu":
            g = jax.lax.dot_general(
                r.astype(x.dtype), x, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        elif grad_mode == "mxu_r128":
            r128 = jnp.broadcast_to(r.astype(x.dtype), (r.shape[0], 128))
            g = jax.lax.dot_general(
                r128, x, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)[:1]
        grad_ref[:] = grad_ref[:] + g

    return kernel


def fused(margin_mode, grad_mode, tile, x, y, ws, w):
    n_pad, d_pad = x.shape
    value, grad = pl.pallas_call(
        make_kernel(margin_mode, grad_mode),
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, d_pad), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0), memory_space=pltpu.SMEM),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, d_pad), jnp.float32),
        ],
    )(x, y, ws, w.reshape(1, d_pad).astype(x.dtype))
    return value[0, 0], grad[0]


def measure(step_fn, d, batch, reps=4):
    def timed(k):
        @jax.jit
        def run(w0, b):
            w, vs = jax.lax.scan(lambda w, _: step_fn(w, b), w0, None, length=k)
            return vs.sum() + w.sum()

        float(run(jnp.zeros(d, jnp.float32), batch))
        best = None
        rng = np.random.default_rng(0)
        for _ in range(reps):
            w0 = jnp.asarray(rng.normal(size=d).astype(np.float32)) * 0.01
            t0 = time.perf_counter()
            float(run(w0, batch))
            el = time.perf_counter() - t0
            best = el if best is None or el < best else best
        return best

    return max((timed(K_HI) - timed(K_LO)) / (K_HI - K_LO), 1e-9)


def main():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(N, D)).astype(np.float32)
    w_true = rng.normal(size=D).astype(np.float32) / np.sqrt(D)
    y = (rng.uniform(size=N) < 1 / (1 + np.exp(-(x @ w_true)))).astype(np.float32)
    xbytes = N * D * 4

    xd = jax.device_put(jnp.asarray(x))
    xbf = jax.device_put(jnp.asarray(x, jnp.bfloat16))
    yc = jax.device_put(jnp.asarray(y).reshape(-1, 1))
    wsc = jax.device_put(jnp.ones((N, 1), jnp.float32))
    batch = {"x": xd, "xbf": xbf, "y": yc, "ws": wsc}

    def stream_step(w, b):
        return w + jnp.sum(b["x"] @ w) * 1e-30, jnp.float32(0)

    m = measure(stream_step, D, batch)
    stream = xbytes / m / 1e9
    print(f"stream: {m*1e3:.3f} ms/step  {stream:.1f} GB/s", flush=True)

    # correctness reference
    def ref_vg(w, xk):
        margins = (np.asarray(batch[xk], np.float32) @ np.asarray(w))[:, None]
        l, dz = (np.logaddexp(0.0, margins) - y[:, None] * margins,
                 1 / (1 + np.exp(-margins)) - y[:, None])
        return l.sum(), (dz * np.asarray(batch[xk], np.float32)).sum(axis=0)

    variants = [
        ("mxu_col/mxu  t1024 f32", "mxu_col", "mxu", 1024, "x"),
        ("mxu_col/mxu  t1024 bf16", "mxu_col", "mxu", 1024, "xbf"),
        ("mxu_w128/vpu t1024 bf16", "mxu_w128", "vpu", 1024, "xbf"),
        ("mxu_w128/mxu_r128 t1024 bf16", "mxu_w128", "mxu_r128", 1024, "xbf"),
        ("vpu/mxu_r128 t1024 bf16", "vpu", "mxu_r128", 1024, "xbf"),
        ("mxu_w128/mxu_r128 t2048 bf16", "mxu_w128", "mxu_r128", 2048, "xbf"),
        ("vpu/vpu t512 bf16", "vpu", "vpu", 512, "xbf"),
        ("vpu/vpu t2048 bf16", "vpu", "vpu", 2048, "xbf"),
    ]
    w0 = (rng.normal(size=D) * 0.01).astype(np.float32)
    for name, mm, gm, tile, xkey in variants:
        nb = (2 if xkey == "xbf" else 4) * N * D

        # correctness first
        try:
            v, g = jax.jit(lambda w, b: fused(mm, gm, tile, b[xkey], b["y"],
                                              b["ws"], w))(jnp.asarray(w0), batch)
            rv, rg = ref_vg(w0, xkey)
            verr = abs(float(v) - rv) / max(abs(rv), 1)
            gerr = float(np.max(np.abs(np.asarray(g) - rg)) /
                         max(np.max(np.abs(rg)), 1))
        except Exception as e:  # noqa: BLE001
            print(f"{name}: FAILED {type(e).__name__}: {str(e)[:160]}", flush=True)
            continue

        def kstep(w, b, _mm=mm, _gm=gm, _tile=tile, _xk=xkey):
            v, g = fused(_mm, _gm, _tile, b[_xk], b["y"], b["ws"], w)
            return w - 1e-4 * g, v

        m = measure(kstep, D, batch)
        gbps = nb / m / 1e9
        print(f"{name}: {m*1e3:.3f} ms/step  {gbps:.1f} GB/s(actual)  "
              f"eff={xbytes/m/1e9/stream:.2f} actual={gbps/stream:.2f} "
              f"verr={verr:.1e} gerr={gerr:.1e}", flush=True)

    # the repo's own kernel path (objective-level, use_pallas=True) — does it
    # reproduce BENCH_r03's 0.45 or probe 1's 0.91?
    from photon_ml_tpu.data.batch import LabeledPointBatch
    from photon_ml_tpu.ops.losses import LogisticLoss
    from photon_ml_tpu.ops.objective import GLMObjective

    lb = LabeledPointBatch.create(xd, jnp.asarray(y))
    obj = GLMObjective(LogisticLoss(), l2_weight=0.5, use_pallas=True)

    def repo_step(w, b):
        v, g = obj.value_and_gradient(w, b)
        return w - 1e-4 * g, v

    m = measure(repo_step, D, lb)
    print(f"repo use_pallas=True: {m*1e3:.3f} ms/step  "
          f"{xbytes/m/1e9:.1f} GB/s  frac={xbytes/m/1e9/stream:.2f}", flush=True)

    obj2 = GLMObjective(LogisticLoss(), l2_weight=0.5, use_pallas=False)

    def repo_auto(w, b):
        v, g = obj2.value_and_gradient(w, b)
        return w - 1e-4 * g, v

    m = measure(repo_auto, D, lb)
    print(f"repo autodiff:        {m*1e3:.3f} ms/step  "
          f"{xbytes/m/1e9:.1f} GB/s  frac={xbytes/m/1e9/stream:.2f}", flush=True)


if __name__ == "__main__":
    main()
