"""Verification drive: SCALE-normalized compact (sparse giant-d_re) random
effects through the public estimator surface, CD and fused mesh paths.

Run: PYTHONPATH=/root/repo PALLAS_AXON_POOL_IPS= python experiments/drive_compact_norm.py
"""
import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from photon_ml_tpu.algorithm.coordinates import CoordinateOptimizationConfig
from photon_ml_tpu.data.game_data import build_game_dataset
from photon_ml_tpu.data.sparse_batch import SparseShard
from photon_ml_tpu.estimators import GameEstimator, RandomEffectCoordinateConfig
from photon_ml_tpu.optim.optimizer import OptimizerConfig
from photon_ml_tpu.ops.normalization import NormalizationType
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.transformers import GameTransformer
from photon_ml_tpu.types import TaskType

# giant-d_re sparse shard with WILDLY different column scales — the case
# normalization exists for
rng = np.random.default_rng(0)
n, d_re, E, support = 900, 50_000, 20, 6
users = np.array([f"u{i}" for i in rng.integers(0, E, size=n)])
ui = np.array([int(u[1:]) for u in users])
ent_cols = {e: np.sort(rng.choice(d_re, support, replace=False)) for e in range(E)}
w_true = {e: rng.normal(size=support) for e in range(E)}
col_scale = 10.0 ** rng.uniform(-2, 2, size=d_re)  # 4 decades of scale spread
rows, cols, vals = [], [], []
y = np.zeros(n, np.float32)
for i in range(n):
    e = ui[i]
    xv = rng.normal(size=support)
    rows += [i] * support
    cols += list(ent_cols[e])
    vals += list(xv * col_scale[ent_cols[e]])
    # truth lives in the SCALED data space
    y[i] = (xv * col_scale[ent_cols[e]]) @ (
        w_true[e] / col_scale[ent_cols[e]]
    ) + 0.05 * rng.normal()
shard = SparseShard(rows=np.array(rows), cols=np.array(cols),
                    vals=np.array(vals, np.float64), num_samples=n,
                    feature_dim=d_re)
ds = build_game_dataset(labels=y, feature_shards={"re": shard},
                        entity_keys={"userId": users}, dtype=np.float64)

opt = CoordinateOptimizationConfig(
    optimizer=OptimizerConfig(max_iterations=40), l2_weight=1e-3
)
results = {}
for name, mesh in (("cd", None), ("fused", make_mesh())):
    for norm in (NormalizationType.NONE,
                 NormalizationType.SCALE_WITH_STANDARD_DEVIATION):
        est = GameEstimator(
            task=TaskType.LINEAR_REGRESSION,
            coordinate_configs={
                "per-user": RandomEffectCoordinateConfig("userId", "re", opt)
            },
            normalization=norm, num_iterations=1, mesh=mesh,
        )
        model = est.fit(ds).model
        scores = GameTransformer(model=model).transform(ds).scores
        rmse = float(np.sqrt(np.mean((scores - y) ** 2)))
        results[(name, norm.name)] = (model, rmse)
        print(f"{name:5s} norm={norm.name:30s} rmse={rmse:.4f}")

# normalized fits must work and agree across paths; models in ORIGINAL space
for norm in ("NONE", "SCALE_WITH_STANDARD_DEVIATION"):
    m_cd, r_cd = results[("cd", norm)]
    m_fu, r_fu = results[("fused", norm)]
    np.testing.assert_allclose(
        np.asarray(m_fu.get("per-user").coefficients),
        np.asarray(m_cd.get("per-user").coefficients),
        atol=5e-3,
    )
    assert abs(r_cd - r_fu) < 1e-3
# normalization is the difference between stalling and fitting on
# ill-scaled columns (4 decades of spread, 40 L-BFGS iters)
r_raw = results[("cd", "NONE")][1]
r_norm = results[("cd", "SCALE_WITH_STANDARD_DEVIATION")][1]
assert r_norm < 0.15, r_norm
assert r_norm < 0.25 * r_raw, (r_norm, r_raw)
# the normalized model still scores the RAW data correctly (original space)
m = results[("cd", "SCALE_WITH_STANDARD_DEVIATION")][0].get("per-user")
assert m.is_compact
print("DRIVE OK")
