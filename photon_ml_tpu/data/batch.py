"""Dense batched training data: the TPU-native LabeledPoint.

Reference parity: photon-lib data/LabeledPoint.scala — per-sample
(label, features, offset, weight). On TPU the unit is not one sample but a
dense [n, d] block: the MXU wants large batched matmuls, so sparse per-sample
vectors become padded dense rows (feature shards are domain-limited, see
SURVEY.md §7 "Sparse features on TPU").

``weights`` double as the padding mask: padded rows carry weight 0 and
therefore contribute nothing to any weighted aggregate — value, gradient,
Hessian-vector, or evaluator. This is how fixed-shape jit programs coexist
with ragged real-world data.
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


@flax.struct.dataclass
class LabeledPointBatch:
    """A dense block of labeled samples.

    features: [n, d] float array
    labels:   [n] float array
    offsets:  [n] float array — prior/residual scores added to the margin
              (the residual mechanism of coordinate descent,
              reference data/DataSet.scala addScoresToOffsets)
    weights:  [n] float array — sample weights; 0 marks padding
    """

    features: Array
    labels: Array
    offsets: Array
    weights: Array

    @property
    def num_samples(self) -> int:
        return self.features.shape[0]

    @property
    def dim(self) -> int:
        return self.features.shape[1]

    @property
    def dtype(self):
        return self.features.dtype

    @property
    def solve_dtype(self):
        """Dtype for coefficients/optimizer state: bf16 feature blocks
        (half the HBM traffic on the hot loop) still solve in f32 — only
        the per-product operand is bf16; accumulation, coefficients, and
        every aux column stay f32 (CLAUDE.md: a bf16 block is a no-op
        unless the whole read path is bf16; the solve path must NOT be)."""
        import jax.numpy as _jnp

        return _jnp.float32 if self.features.dtype == _jnp.bfloat16 else self.features.dtype

    def with_offsets(self, offsets: Array) -> "LabeledPointBatch":
        return self.replace(offsets=offsets)

    def add_scores_to_offsets(self, scores: Array) -> "LabeledPointBatch":
        """Residual update used by coordinate descent (DataSet.addScoresToOffsets)."""
        return self.replace(offsets=self.offsets + scores)

    @classmethod
    def create(
        cls,
        features,
        labels,
        offsets=None,
        weights=None,
        dtype=None,
    ) -> "LabeledPointBatch":
        """Build a batch. ``dtype=None`` preserves the input float dtype
        (float64 in x64 test mode, float32 in production)."""
        features = jnp.asarray(features, dtype=dtype)
        if dtype is None:
            dtype = features.dtype
        if dtype == jnp.bfloat16:
            # bf16 applies to the FEATURE BLOCK only; labels/offsets/weights
            # stay f32 (loss math and accumulation are f32 throughout)
            dtype = jnp.float32
        labels = jnp.asarray(labels, dtype=dtype)
        n = features.shape[0]
        if offsets is None:
            offsets = jnp.zeros((n,), dtype=dtype)
        else:
            offsets = jnp.asarray(offsets, dtype=dtype)
        if weights is None:
            weights = jnp.ones((n,), dtype=dtype)
        else:
            weights = jnp.asarray(weights, dtype=dtype)
        return cls(features=features, labels=labels, offsets=offsets, weights=weights)

    def pad_to(self, n: int) -> "LabeledPointBatch":
        """Pad to n rows with zero-weight rows (fixed shapes for jit)."""
        cur = self.num_samples
        if cur == n:
            return self
        if cur > n:
            raise ValueError(f"cannot pad {cur} rows down to {n}")
        pad = n - cur
        return LabeledPointBatch(
            features=jnp.pad(self.features, ((0, pad), (0, 0))),
            labels=jnp.pad(self.labels, (0, pad)),
            offsets=jnp.pad(self.offsets, (0, pad)),
            weights=jnp.pad(self.weights, (0, pad)),
        )


def solve_dtype_of(feature_dtype) -> jnp.dtype:
    """Coefficient/optimizer-state dtype for a feature-block dtype: bf16
    blocks still solve in f32 (see LabeledPointBatch.solve_dtype)."""
    return (
        jnp.float32 if jnp.dtype(feature_dtype) == jnp.bfloat16
        else jnp.dtype(feature_dtype)
    )


def compute_margins(batch: LabeledPointBatch, coefficients: Array) -> Array:
    """margin_i = x_i . w + offset_i (reference DataPoint.computeMargin)."""
    return batch.features @ coefficients + batch.offsets


def summarize(features: np.ndarray, weights: np.ndarray | None = None) -> dict:
    """Weighted feature summary (reference stat/BasicStatisticalSummary.scala).

    Returns mean, variance (unbiased, weighted), max, min, max_magnitude,
    norm_l1, norm_l2, num_nonzeros per feature column — the statistics the
    reference gets from Spark MLLIB's MultivariateStatisticalSummary.
    """
    features = np.asarray(features, dtype=np.float64)
    n = features.shape[0]
    if weights is None:
        weights = np.ones((n,), dtype=np.float64)
    else:
        weights = np.asarray(weights, dtype=np.float64)
    wsum = weights.sum()
    mean = (weights[:, None] * features).sum(axis=0) / wsum
    centered = features - mean
    var = (weights[:, None] * centered * centered).sum(axis=0) / np.maximum(wsum - 1.0, 1.0)
    return {
        "count": n,
        "weight_sum": wsum,
        "mean": mean,
        "variance": var,
        "max": features.max(axis=0) if n else np.zeros(features.shape[1]),
        "min": features.min(axis=0) if n else np.zeros(features.shape[1]),
        "max_magnitude": np.abs(features).max(axis=0) if n else np.zeros(features.shape[1]),
        "norm_l1": np.abs(features).sum(axis=0),
        "norm_l2": np.sqrt((features * features).sum(axis=0)),
        "num_nonzeros": (features != 0).sum(axis=0).astype(np.float64),
    }
