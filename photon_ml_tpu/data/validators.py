"""Input-data sanity validation.

Reference parity: photon-client data/DataValidators.scala — per-row checks
(finite label/offset/weight/features; binary labels for logistic; non-negative
labels for Poisson) with DataValidationType {VALIDATE_FULL, VALIDATE_SAMPLE,
VALIDATE_DISABLED}; validation failures abort training with a summary of
every failed check.

TPU-native: checks are vectorized numpy reductions over the host-side
columns of a GameDataset (or raw arrays) instead of per-row RDD filters —
one pass, no Python loop.
"""

from __future__ import annotations

import enum
import logging
from typing import Mapping, Sequence

import numpy as np

from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)


class DataValidationType(enum.Enum):
    """Reference: DataValidationType.scala."""

    VALIDATE_FULL = "VALIDATE_FULL"
    VALIDATE_SAMPLE = "VALIDATE_SAMPLE"
    VALIDATE_DISABLED = "VALIDATE_DISABLED"


class DataValidationError(ValueError):
    """Raised when validation fails; message lists every failed check."""


_SAMPLE_FRACTION = 0.1  # reference samples 10% for VALIDATE_SAMPLE
_MIN_SAMPLE = 1024


def _subsample(n: int, validation_type: DataValidationType) -> np.ndarray | slice:
    if validation_type == DataValidationType.VALIDATE_SAMPLE and n > _MIN_SAMPLE:
        k = max(_MIN_SAMPLE, int(n * _SAMPLE_FRACTION))
        # deterministic evenly-spaced subsample
        return np.linspace(0, n - 1, k).astype(np.intp)
    return slice(None)


def validate_arrays(
    *,
    labels: np.ndarray,
    task: TaskType,
    offsets: np.ndarray | None = None,
    weights: np.ndarray | None = None,
    feature_shards: Mapping[str, np.ndarray] | None = None,
    validation_type: DataValidationType = DataValidationType.VALIDATE_FULL,
    extra_failures: Sequence[str] = (),
) -> None:
    """Run the reference's sanityCheckData checks; raise DataValidationError
    listing all failures (DataValidators.scala aggregates before throwing).
    extra_failures: pre-computed failure strings (e.g. sparse-shard checks)
    aggregated into the same report."""
    if validation_type == DataValidationType.VALIDATE_DISABLED:
        return

    labels = np.asarray(labels)
    sel = _subsample(len(labels), validation_type)
    labels = labels[sel]
    failures: list[str] = list(extra_failures)

    if not np.all(np.isfinite(labels)):
        failures.append("labels contain NaN/Inf")
    if task in (TaskType.LOGISTIC_REGRESSION, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM):
        if not np.all((labels == 0.0) | (labels == 1.0)):
            failures.append(f"{task.name} requires binary labels in {{0, 1}}")
    if task == TaskType.POISSON_REGRESSION and np.any(labels < 0.0):
        failures.append("POISSON_REGRESSION requires non-negative labels")

    if offsets is not None:
        offsets = np.asarray(offsets)[sel]
        if not np.all(np.isfinite(offsets)):
            failures.append("offsets contain NaN/Inf")
    if weights is not None:
        weights = np.asarray(weights)[sel]
        if not np.all(np.isfinite(weights)):
            failures.append("weights contain NaN/Inf")
        elif np.any(weights < 0.0):
            failures.append("weights contain negative values")
    for shard_id, features in (feature_shards or {}).items():
        if not np.all(np.isfinite(np.asarray(features)[sel])):
            failures.append(f"feature shard '{shard_id}' contains NaN/Inf")

    if failures:
        raise DataValidationError(
            "input data failed validation: " + "; ".join(failures)
        )
    logger.debug("data validation passed (%s)", validation_type.value)


def validate_game_dataset(
    dataset,
    task: TaskType,
    validation_type: DataValidationType = DataValidationType.VALIDATE_FULL,
) -> None:
    """Validate a GameDataset (reference sanityCheckDataFrameForTraining,
    GameTrainingDriver.scala:400-417)."""
    from photon_ml_tpu.data.sparse_batch import SparseShard

    if validation_type == DataValidationType.VALIDATE_DISABLED:
        return
    dense_shards: dict = {}
    sparse_failures: list[str] = []
    for k, v in dataset.feature_shards.items():
        if isinstance(v, SparseShard):
            # COO values are the entire feature content; O(nnz) full check
            # regardless of sample-level validation mode
            if not np.all(np.isfinite(v.vals)):
                sparse_failures.append(
                    f"feature shard '{k}' contains NaN/Inf"
                )
        else:
            dense_shards[k] = np.asarray(v)
    validate_arrays(
        labels=np.asarray(dataset.labels),
        task=task,
        offsets=np.asarray(dataset.offsets),
        weights=np.asarray(dataset.weights),
        feature_shards=dense_shards,
        validation_type=validation_type,
        extra_failures=sparse_failures,
    )
