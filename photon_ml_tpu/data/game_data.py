"""GAME dataset: the TPU-native GameDatum collection.

Reference parity: photon-api data/GameDatum.scala (response/offset/weight +
per-shard features + id tags), data/FixedEffectDataSet.scala,
data/RandomEffectDataSet.scala (grouping per entity with reservoir caps,
lower bounds, active/passive split), data/LocalDataSet.scala (per-entity
Pearson feature selection), data/RandomEffectDataSetPartitioner.scala.

TPU-native redesign (SURVEY.md §7):

- The dataset is column-oriented: one dense [n, d_shard] feature block per
  feature shard, plus [n] labels/offsets/weights and per-RE-type [n] entity
  index arrays. The sample axis shards over the mesh's "data" axis.
- Random-effect *training* data is materialized as size-bucketed padded
  blocks: entities bucketed by sample count, each bucket a
  [entities, cap, d] tensor that a vmapped local solver consumes. This
  replaces the reference's groupByKey + per-entity RDD records.
- There is no passive/active score split: scoring always runs over the full
  sample axis via an entity-indexed gather (models/game.py), so samples
  dropped from training (reservoir cap, lower bound) are still scored —
  the same semantics as active+passive scoring in the reference
  (RandomEffectDataSet.scala:433-478).
- Reservoir sampling is keyed on stable sample ids, fixing the recompute
  instability documented at RandomEffectDataSet.scala:389-395.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.data.sparse_batch import SparseLabeledPointBatch, SparseShard
from photon_ml_tpu.projector.projectors import (
    ProjectorType,
    RandomProjectionMatrix,
)
from photon_ml_tpu.sampling.down_sampler import stable_uniform

Array = jax.Array


@dataclasses.dataclass
class GameDataset:
    """Column-oriented GAME data. Host-built once, then device-resident.

    feature_shards: shard id -> [n, d_shard] (np or jax array)
    entity_idx:     RE type -> [n] int32 (row in that type's entity vocab,
                    -1 for entities absent from the vocab)
    entity_vocabs:  RE type -> [num_entities] key array (host)
    ids:            eval id columns (e.g. queryId) -> [n] host array
    """

    unique_ids: np.ndarray
    labels: Array
    offsets: Array
    weights: Array
    feature_shards: dict[str, Array]
    entity_idx: dict[str, Array]
    entity_vocabs: dict[str, np.ndarray]
    ids: dict[str, np.ndarray] = dataclasses.field(default_factory=dict)
    #: host-side copies kept by build_game_dataset so bucketing never pulls
    #: device arrays back through a (possibly remote) transfer path
    host_cache: dict[str, np.ndarray] = dataclasses.field(default_factory=dict)

    def host_array(self, name: str) -> np.ndarray:
        """Host copy of a namespaced array: 'labels'/'weights'/'offsets',
        'shard/<shard_id>', or 'entity_idx/<re_type>'. Shard ids and RE types
        are caller-chosen strings, hence the prefixes — a shard named
        'labels' must not collide with the label vector."""
        if name in self.host_cache:
            return self.host_cache[name]
        if name in ("labels", "weights", "offsets"):
            value = np.asarray(getattr(self, name))
        elif name.startswith("shard/"):
            shard = self.feature_shards[name[len("shard/"):]]
            if isinstance(shard, SparseShard):
                raise TypeError(
                    f"feature shard '{name[len('shard/'):]}' is sparse "
                    "(giant-d); dense host materialization would defeat it. "
                    "Random-effect coordinates and other dense consumers "
                    "need a dense shard."
                )
            value = np.asarray(shard)
        elif name.startswith("entity_idx/"):
            value = np.asarray(self.entity_idx[name[len("entity_idx/"):]])
        else:
            raise KeyError(name)
        self.host_cache[name] = value
        return value

    @property
    def num_samples(self) -> int:
        return int(self.labels.shape[0])

    def shard_features(self, shard_id: str) -> Array:
        return self.feature_shards[shard_id]

    def entity_indices(self, re_type: str) -> Array:
        return self.entity_idx[re_type]

    def fixed_effect_batch(
        self, shard_id: str, extra_offsets: Array | None = None
    ) -> LabeledPointBatch | SparseLabeledPointBatch:
        offsets = self.offsets if extra_offsets is None else self.offsets + extra_offsets
        shard = self.feature_shards[shard_id]
        if isinstance(shard, SparseShard):
            return SparseLabeledPointBatch.from_shard(
                shard, self.labels, offsets, self.weights
            )
        return LabeledPointBatch(
            features=jnp.asarray(shard),
            labels=jnp.asarray(self.labels),
            offsets=jnp.asarray(offsets),
            weights=jnp.asarray(self.weights),
        )


def pad_game_dataset(dataset: GameDataset, multiple: int) -> tuple[GameDataset, int]:
    """Pad the sample axis with zero-weight rows to a multiple of ``multiple``.

    Mesh sharding wants the sample axis divisible by the mesh "data" axis
    (parallel/mesh.py). Padding rows carry weight 0 (they contribute nothing
    to any weighted aggregate), entity index -1 (scored as 0 by
    score_random_effect), offset/label 0, zero feature rows, and fresh
    negative unique ids (so stable-id hashing never collides with real
    rows). Sparse shards pad by bumping ``num_samples`` only — no new
    entries. Entity buckets built from the unpadded dataset stay valid:
    their ``sample_rows`` indices are unchanged by appending rows.

    Returns (padded dataset, original sample count); the original object is
    returned untouched when already divisible.
    """
    n = dataset.num_samples
    pad = (-n) % max(1, int(multiple))
    return _pad_game_dataset_rows(dataset, pad), n


def pad_game_dataset_to(dataset: GameDataset, length: int) -> tuple[GameDataset, int]:
    """Pad the sample axis with zero-weight rows to EXACTLY ``length`` rows
    (same padding contract as :func:`pad_game_dataset`). The partitioned
    ingestion path uses this to make every rank's local block the agreed
    common length — including ranks that decoded zero rows."""
    n = dataset.num_samples
    if length < n:
        raise ValueError(
            f"cannot pad a {n}-row dataset down to {length} rows"
        )
    return _pad_game_dataset_rows(dataset, length - n), n


def _pad_game_dataset_rows(dataset: GameDataset, pad: int) -> GameDataset:
    n = dataset.num_samples
    if pad == 0:
        return dataset

    def padded_vec(name: str) -> tuple[np.ndarray, Array]:
        arr = dataset.host_array(name)
        out = np.concatenate([arr, np.zeros(pad, dtype=arr.dtype)])
        return out, jnp.asarray(out)

    labels_h, labels_d = padded_vec("labels")
    offsets_h, offsets_d = padded_vec("offsets")
    # weights pad with zeros — the whole point
    weights_h, weights_d = padded_vec("weights")

    shards: dict[str, object] = {}
    host_cache = {"labels": labels_h, "offsets": offsets_h, "weights": weights_h}
    for k, v in dataset.feature_shards.items():
        if isinstance(v, SparseShard):
            # _coalesced survives (entries unchanged) but the hybrid split
            # caches a dense [n, k_hot] head whose n is now stale
            shards[k] = dataclasses.replace(
                v, num_samples=v.num_samples + pad, _device=None,
                _hybrid_cache=None,
            )
        else:
            arr = np.asarray(v)
            arr = np.concatenate(
                [arr, np.zeros((pad, arr.shape[1]), dtype=arr.dtype)]
            )
            shards[k] = jnp.asarray(arr)
            host_cache[f"shard/{k}"] = arr

    entity_idx: dict[str, Array] = {}
    for t, idx in dataset.entity_idx.items():
        arr = np.concatenate(
            [np.asarray(idx), np.full(pad, -1, dtype=np.int32)]
        ).astype(np.int32)
        entity_idx[t] = jnp.asarray(arr)
        host_cache[f"entity_idx/{t}"] = arr

    ids = {
        k: np.concatenate([np.asarray(v), np.zeros(pad, np.asarray(v).dtype)])
        for k, v in dataset.ids.items()
    }
    unique_ids = np.concatenate(
        [np.asarray(dataset.unique_ids),
         -(np.arange(pad, dtype=np.int64) + 1 + np.abs(dataset.unique_ids).max(initial=0))]
    )
    return dataclasses.replace(
        dataset,
        unique_ids=unique_ids,
        labels=labels_d,
        offsets=offsets_d,
        weights=weights_d,
        feature_shards=shards,
        entity_idx=entity_idx,
        ids=ids,
        host_cache=host_cache,
    )


def slice_game_dataset(dataset: GameDataset, lo: int, hi: int) -> GameDataset:
    """Row-range view [lo, hi) of a GameDataset as a NEW dataset (host-side
    vectorized; entity vocabs are shared, not copied). Sparse shards slice
    their coalesced triples by a searchsorted range (they are row-major
    sorted) with rows shifted to the slice origin. The serving layer uses
    this to split replay data into requests and to split an over-sized
    request across micro-batches."""
    n = dataset.num_samples
    if not (0 <= lo < hi <= n):
        raise ValueError(f"slice [{lo}, {hi}) out of range for {n} samples")

    def vec(name: str) -> np.ndarray:
        return dataset.host_array(name)[lo:hi]

    labels_h, offsets_h, weights_h = vec("labels"), vec("offsets"), vec("weights")
    host_cache = {"labels": labels_h, "offsets": offsets_h,
                  "weights": weights_h}
    shards: dict[str, object] = {}
    for k, v in dataset.feature_shards.items():
        if isinstance(v, SparseShard):
            rows, cols, vals = v.coalesced()
            a, b = np.searchsorted(rows, [lo, hi])
            shards[k] = dataclasses.replace(
                v,
                rows=(rows[a:b] - lo).astype(rows.dtype),
                cols=np.array(cols[a:b]),
                vals=np.array(vals[a:b]),
                num_samples=hi - lo,
                _device=None, _coalesced=None, _hybrid_cache=None,
            )
        else:
            arr = dataset.host_array(f"shard/{k}")[lo:hi]
            shards[k] = jnp.asarray(arr)
            host_cache[f"shard/{k}"] = arr
    entity_idx: dict[str, Array] = {}
    for t in dataset.entity_idx:
        arr = dataset.host_array(f"entity_idx/{t}")[lo:hi]
        entity_idx[t] = jnp.asarray(arr)
        host_cache[f"entity_idx/{t}"] = arr
    return GameDataset(
        unique_ids=np.asarray(dataset.unique_ids)[lo:hi],
        labels=jnp.asarray(labels_h),
        offsets=jnp.asarray(offsets_h),
        weights=jnp.asarray(weights_h),
        feature_shards=shards,
        entity_idx=entity_idx,
        entity_vocabs=dataset.entity_vocabs,
        ids={k: np.asarray(v)[lo:hi] for k, v in dataset.ids.items()},
        host_cache=host_cache,
    )


def concat_game_datasets(datasets: "Sequence[GameDataset]") -> GameDataset:
    """Row-wise concatenation of GameDatasets built against the SAME
    schema: shard ids/widths, entity types AND vocabs, and id columns must
    agree (a vocab mismatch would silently misalign one part's entity rows,
    so it is validated, not assumed). Sparse shards concatenate coalesced
    triples with rows shifted into the merged sample axis — parts are
    row-sorted and appended in order, so the result keeps the row-major
    promise the scoring segment-sum relies on. The serving micro-batcher
    uses this to coalesce queued requests into one device dispatch."""
    datasets = list(datasets)
    if not datasets:
        raise ValueError("concat_game_datasets needs at least one dataset")
    if len(datasets) == 1:
        return datasets[0]
    base = datasets[0]
    for d in datasets[1:]:
        for attr in ("feature_shards", "entity_idx", "ids"):
            if set(getattr(d, attr)) != set(getattr(base, attr)):
                raise ValueError(
                    f"datasets disagree on {attr} keys: "
                    f"{sorted(getattr(base, attr))} vs "
                    f"{sorted(getattr(d, attr))}"
                )
        for t, vocab in base.entity_vocabs.items():
            other = d.entity_vocabs.get(t)
            if other is not vocab and not np.array_equal(
                np.asarray(other), np.asarray(vocab)
            ):
                raise ValueError(
                    f"datasets disagree on the '{t}' entity vocab "
                    f"({len(np.asarray(vocab))} vs "
                    f"{0 if other is None else len(np.asarray(other))} keys)"
                )

    def cat(name: str) -> np.ndarray:
        return np.concatenate([d.host_array(name) for d in datasets])

    labels_h, offsets_h, weights_h = cat("labels"), cat("offsets"), cat("weights")
    host_cache = {"labels": labels_h, "offsets": offsets_h,
                  "weights": weights_h}
    starts = np.cumsum([0] + [d.num_samples for d in datasets])
    n_total = int(starts[-1])
    shards: dict[str, object] = {}
    for k, v in base.feature_shards.items():
        if isinstance(v, SparseShard):
            rows_parts, cols_parts, vals_parts = [], [], []
            for d, start in zip(datasets, starts):
                shard = d.feature_shards[k]
                if not isinstance(shard, SparseShard):
                    raise ValueError(
                        f"shard '{k}' is sparse in one dataset and dense "
                        "in another"
                    )
                if shard.feature_dim != v.feature_dim:
                    raise ValueError(
                        f"shard '{k}' feature_dim mismatch: "
                        f"{v.feature_dim} vs {shard.feature_dim}"
                    )
                r, c, vv = shard.coalesced()
                rows_parts.append(np.asarray(r, np.int64) + int(start))
                cols_parts.append(c)
                vals_parts.append(vv)
            shards[k] = dataclasses.replace(
                v,
                rows=np.concatenate(rows_parts),
                cols=np.concatenate(cols_parts),
                vals=np.concatenate(vals_parts),
                num_samples=n_total,
                _device=None, _coalesced=None, _hybrid_cache=None,
            )
        else:
            arr = np.concatenate(
                [d.host_array(f"shard/{k}") for d in datasets]
            )
            shards[k] = jnp.asarray(arr)
            host_cache[f"shard/{k}"] = arr
    entity_idx: dict[str, Array] = {}
    for t in base.entity_idx:
        arr = np.concatenate(
            [d.host_array(f"entity_idx/{t}") for d in datasets]
        )
        entity_idx[t] = jnp.asarray(arr)
        host_cache[f"entity_idx/{t}"] = arr
    return GameDataset(
        unique_ids=np.concatenate(
            [np.asarray(d.unique_ids) for d in datasets]
        ),
        labels=jnp.asarray(labels_h),
        offsets=jnp.asarray(offsets_h),
        weights=jnp.asarray(weights_h),
        feature_shards=shards,
        entity_idx=entity_idx,
        entity_vocabs=base.entity_vocabs,
        ids={
            k: np.concatenate([np.asarray(d.ids[k]) for d in datasets])
            for k in base.ids
        },
        host_cache=host_cache,
    )


@dataclasses.dataclass
class EntityBucket:
    """One size-bucket of random-effect training data.

    features:    [e, cap, d] — d is the *bucket's* feature dim: the shard
                 width for identity projection, the bucket's max
                 active-column count for index-map projection, or the
                 projected dim for random projection
    labels/offsets/weights: [e, cap] (weight 0 marks padding)
    entity_rows: [e] int32 — row of each entity in the RE type's vocab
    sample_rows: [e, cap] int32 — global sample row of each slot, -1 pad
    col_index:   [e, d] int32 — index-map projection only: original column
                 of each projected slot; padding slots hold ``full_dim``
    """

    features: Array
    labels: Array
    weights: Array
    entity_rows: Array
    sample_rows: Array
    col_index: Array | None = None

    @property
    def num_entities(self) -> int:
        return self.features.shape[0]

    @property
    def capacity(self) -> int:
        return self.features.shape[1]

    def gather_offsets(self, full_offsets: Array) -> Array:
        """Current residual offsets for every slot: [e, cap]."""
        safe = jnp.maximum(self.sample_rows, 0)
        return jnp.where(self.sample_rows >= 0, full_offsets[safe], 0.0)


@dataclasses.dataclass
class RandomEffectDataset:
    """Bucketed per-entity training view for one RE coordinate.

    ``dim`` is always the original shard width (the model table is [E, dim]
    in original space); buckets may carry lower-dimensional features when a
    projector is active.
    """

    random_effect_type: str
    feature_shard_id: str
    buckets: list[EntityBucket]
    num_entities: int  # size of the entity vocab
    dim: int
    projector_type: "ProjectorType" = None  # set in __post_init__
    projection: "RandomProjectionMatrix | None" = None
    #: giant-d_re compact mode (sparse feature shard): [E, K] sorted active
    #: GLOBAL columns per entity (pad = dim); the coefficient table is then
    #: [E, K] over these columns, bucket ``col_index`` holds LOCAL positions
    #: (pad = K), and scoring maps data entries to positions
    #: (models/game.compact_entry_positions). This is the reference's
    #: per-entity projection insight (IndexMapProjectorRDD.scala:218-257)
    #: without ever materializing [E, d_re].
    active_cols: np.ndarray | None = None
    #: True when INDEX_MAP bucket features were rewritten to normalized
    #: space at build time (build_random_effect_dataset(normalization=...));
    #: solvers must then use a PLAIN objective (no context) while table
    #: conversions/scoring keep using the context
    pre_normalized: bool = False

    def __post_init__(self):
        if self.projector_type is None:
            self.projector_type = ProjectorType.IDENTITY

    @property
    def num_trained_entities(self) -> int:
        return sum(b.num_entities for b in self.buckets)

    @property
    def is_compact(self) -> bool:
        return self.active_cols is not None

    @property
    def table_width(self) -> int:
        """Second axis of the coefficient table: K in compact mode, the
        full shard width otherwise."""
        return (
            int(self.active_cols.shape[1]) if self.active_cols is not None
            else self.dim
        )


def _stable_priorities(sample_ids: np.ndarray, seed: int) -> np.ndarray:
    """Deterministic per-sample priorities for reservoir sampling, stable
    under recompute (fixes RandomEffectDataSet.scala:389-395). Vectorized
    via the same splitmix64 keying the down-samplers."""
    return stable_uniform(sample_ids, seed)


def group_entities_into_buckets(
    entity_idx: np.ndarray,
    unique_ids: np.ndarray,
    *,
    bucket_sizes: Sequence[int],
    active_data_upper_bound: int | None = None,
    active_data_lower_bound: int | None = None,
    seed: int = 0,
) -> dict[int, list[tuple[int, np.ndarray]]]:
    """Group sample rows by entity into size buckets.

    Returns {bucket_capacity: [(entity_row, sample_rows), ...]}. Applies the
    per-entity reservoir cap (stable-id keyed, reference
    RandomEffectDataSet.scala:354-420) and the lower-bound filter (:320-341).
    Shared by random-effect and matrix-factorization bucketing.
    """
    valid = entity_idx >= 0
    order = np.argsort(entity_idx[valid], kind="stable")
    rows = np.nonzero(valid)[0][order]
    ents = entity_idx[rows]
    per_bucket: dict[int, list[tuple[int, np.ndarray]]] = {c: [] for c in bucket_sizes}
    if len(ents) == 0:
        return per_bucket
    boundaries = np.concatenate(
        [[0], np.nonzero(ents[1:] != ents[:-1])[0] + 1, [len(ents)]]
    )
    max_bucket = max(bucket_sizes)
    for start, end in zip(boundaries[:-1], boundaries[1:]):
        entity = int(ents[start])
        sample_rows = rows[start:end]
        count = len(sample_rows)
        if active_data_lower_bound is not None and count < active_data_lower_bound:
            continue
        # The largest bucket is an implicit cap: sampling (not head-truncation)
        # applies either way, so the kept subset is unbiased.
        cap = min(active_data_upper_bound or max_bucket, max_bucket)
        if count > cap:
            # stable reservoir: keep the `cap` samples with smallest priority
            prio = _stable_priorities(unique_ids[sample_rows], seed)
            keep = np.argsort(prio, kind="stable")[:cap]
            sample_rows = sample_rows[np.sort(keep)]
            count = cap
        bucket_cap = next(c for c in bucket_sizes if c >= count)
        per_bucket[bucket_cap].append((entity, sample_rows))
    return per_bucket


def _pearson_keep_mask(x: np.ndarray, y: np.ndarray, num_keep: int) -> np.ndarray:
    """Boolean [d] mask of the ``num_keep`` columns of x most correlated
    (|Pearson|) with y. Zero-variance columns (e.g. an intercept) score +inf
    and are always retained — the reference's LocalDataSet Pearson filter
    assigns the intercept a perfect score (LocalDataSet.scala:221-280)."""
    d = x.shape[1]
    if num_keep >= d:
        return np.ones(d, dtype=bool)
    # float64 is the defined semantics for selection scores: float32 inputs
    # must rank identically in the scalar and grouped implementations (exact
    # mathematical ties would otherwise break differently per code path)
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    xc = x - x.mean(axis=0)
    yc = y - y.mean()
    var_x = (xc * xc).sum(axis=0)
    var_y = float(yc @ yc)
    all_zero = ~np.any(x != 0.0, axis=0)
    const_nonzero = (var_x == 0.0) & ~all_zero  # intercept-like
    if var_y == 0.0:
        # constant labels carry no correlation signal; prefer active,
        # high-variance columns rather than degenerating to first-K-by-index
        score = var_x.astype(np.float64)
    else:
        denom = np.sqrt(var_x * var_y)
        with np.errstate(divide="ignore", invalid="ignore"):
            score = np.abs(xc.T @ yc) / denom
        score = np.where(var_x == 0.0, 0.0, score)
    score = np.where(const_nonzero, np.inf, score)  # intercept always kept
    score = np.where(all_zero, -np.inf, score)  # inactive columns rank last
    keep = np.argsort(-_quantize_scores(score), kind="stable")[:num_keep]
    mask = np.zeros(d, dtype=bool)
    mask[keep] = True
    return mask


def _quantize_scores(score: np.ndarray) -> np.ndarray:
    """Round selection scores to 9 decimals before ranking, so columns whose
    scores are mathematically equal (e.g. |corr| = 1 for every doubly-active
    column of a 2-sample entity) tie exactly in BOTH the scalar and grouped
    implementations — their accumulation orders (BLAS vs np.add.at) differ
    at the last ulp, and without quantization stable argsort would pick
    different columns per code path."""
    return np.round(score, 9)


def pack_bucket_lanes(
    members: list[tuple[int, np.ndarray]],
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized lane layout for one bucket's members.

    Returns (entity_rows[e], rows_concat[m], lane[m], slot[m]): sample i of
    entity lane l lands at [lane, slot] in the padded [e, cap] blocks — one
    fancy assignment per array instead of a Python loop per entity. Shared
    by random-effect and matrix-factorization bucket packing.
    """
    e = len(members)
    entity_rows = np.fromiter(
        (ent for ent, _ in members), dtype=np.int32, count=e
    )
    counts = np.fromiter((len(sr) for _, sr in members), dtype=np.intp, count=e)
    rows_concat = np.concatenate([sr for _, sr in members])
    lane = np.repeat(np.arange(e, dtype=np.intp), counts)
    slot = np.arange(len(rows_concat), dtype=np.intp) - np.repeat(
        np.concatenate(([0], np.cumsum(counts[:-1]))), counts
    )
    return entity_rows, rows_concat, lane, slot


def compact_lane_blocks(
    host_blocks: Sequence[Mapping[str, np.ndarray]],
    picks: Sequence[tuple[int, np.ndarray]],
    *,
    pad_to: int,
    sentinel_row: int,
) -> tuple[dict[str, np.ndarray], np.ndarray, np.ndarray]:
    """Gather selected lanes of same-(cap, d) host bucket blocks into ONE
    padded block — the lane-compaction counterpart of
    :func:`pack_bucket_lanes`'s slot packing, used by the probe/rescue lane
    scheduler (algorithm/lane_scheduler.py) to re-run only unconverged
    entity solves.

    picks: [(block_index, lane_indices), ...] — every named block must share
        capacity and feature width (the caller groups by (cap, d)).
    pad_to: lane count of the output block (power-of-two padded, so rescue
        jit signatures stay bounded across sweeps).
    sentinel_row: ``entity_rows`` value for padding lanes — out of range for
        any coefficient table, so gathers clamp (junk warm starts on
        all-zero-weight lanes are harmless) and scatters drop.

    Returns (fields, src_block, src_lane): the padded field dict (weights 0 /
    sample_rows -1 / entity_rows sentinel on padding lanes) plus the source
    (block, lane) of each REAL lane for trace scatter-back.
    """
    src_block = np.concatenate(
        [np.full(len(lanes), b, dtype=np.int32) for b, lanes in picks]
    )
    src_lane = np.concatenate(
        [np.asarray(lanes, dtype=np.int64) for _, lanes in picks]
    )
    m = len(src_lane)
    if not 0 < m <= pad_to:
        raise ValueError(f"{m} picked lanes do not fit pad_to={pad_to}")
    pad = pad_to - m
    out: dict[str, np.ndarray] = {}
    first = host_blocks[picks[0][0]]
    for key in ("features", "labels", "weights", "sample_rows", "col_index"):
        if first.get(key) is None:
            continue
        arr = np.concatenate(
            [host_blocks[b][key][lanes] for b, lanes in picks], axis=0
        )
        if pad:
            pad_block = np.zeros((pad,) + arr.shape[1:], dtype=arr.dtype)
            if key == "sample_rows":
                pad_block[...] = -1
            arr = np.concatenate([arr, pad_block], axis=0)
        out[key] = arr
    rows = np.concatenate(
        [np.asarray(host_blocks[b]["entity_rows"][lanes]) for b, lanes in picks]
    ).astype(np.int32)
    if pad:
        rows = np.concatenate([rows, np.full(pad, sentinel_row, np.int32)])
    out["entity_rows"] = rows
    return out, src_block, src_lane


def build_random_effect_dataset(
    dataset: GameDataset,
    re_type: str,
    shard_id: str,
    *,
    active_data_upper_bound: int | None = None,
    active_data_lower_bound: int | None = None,
    bucket_sizes: Sequence[int] = (8, 32, 128, 512, 2048),
    seed: int = 0,
    projector_type: ProjectorType = ProjectorType.IDENTITY,
    projected_dim: int | None = None,
    features_to_samples_ratio: float | None = None,
    normalization=None,
) -> RandomEffectDataset:
    """Group samples by entity into padded, size-bucketed blocks.

    - upper bound: per-entity reservoir cap (stable-id keyed sampling),
      reference RandomEffectDataSet.scala:354-420 / MinHeapWithFixedCapacity.
    - lower bound: entities with fewer samples are excluded from training
      (still scored via the gather path), reference :320-341.
    - buckets: entities padded to the smallest bucket capacity >= their
      (capped) sample count; per-bucket tensors keep padding waste bounded
      while giving the vmapped solver fixed shapes.
    - projector (reference projector/*.scala): INDEX_MAP bakes per-entity
      active-column gathers into the buckets; RANDOM applies one shared
      Gaussian [dim, projected_dim] matrix.
    - features_to_samples_ratio: per-entity Pearson feature selection
      (reference RandomEffectDataSetPartitioner's
      numFeaturesToSamplesRatioUpperBound + LocalDataSet Pearson filter,
      LocalDataSet.scala:221-280): an entity with c samples keeps only its
      ceil(ratio * c) best features by |Pearson corr| with the label;
      dropped columns are zeroed in its block (and therefore excluded from
      INDEX_MAP active columns).
    - normalization (INDEX_MAP only): an ops.normalization
      NormalizationContext projected into each entity's active columns at
      build time — the gathered [e, cap, k] blocks are rewritten to
      x' = (x - shift)*factor so the per-entity solves run in normalized
      space without a per-entity context object (reference
      IndexMapProjectorRDD.projectNormalizationRDD:134-147 builds the
      per-entity projected contexts; here the blocks are already dense
      per-coordinate copies, so the rewrite is free). The scratch column
      (pad slots) keeps factor 1 / shift 0, so padding stays zero.
    """
    shard = dataset.feature_shards[shard_id]
    if (
        normalization is not None
        and projector_type == ProjectorType.IDENTITY
        and not isinstance(shard, SparseShard)  # sparse coerces to INDEX_MAP
    ):
        raise ValueError(
            "build_random_effect_dataset(normalization=...) pre-normalizes "
            "PROJECTED entity blocks (INDEX_MAP/RANDOM/compact); IDENTITY "
            "coordinates normalize through the objective's context"
        )
    if isinstance(shard, SparseShard):
        if normalization is not None and normalization.shifts is not None:
            raise ValueError(
                "sparse (compact) random-effect shards support SCALE-only "
                "normalization; mean shifts (STANDARDIZATION) would densify "
                "the feature space"
            )
        # giant-d_re path: per-entity observed-column blocks from the COO
        # triples, compact [E, K] coefficient table — never densify
        if projector_type not in (ProjectorType.IDENTITY, ProjectorType.INDEX_MAP):
            raise ValueError(
                f"sparse random-effect shard '{shard_id}': only "
                "IDENTITY/INDEX_MAP projectors are supported (the compact "
                "representation IS an index-map projection)"
            )
        if features_to_samples_ratio is not None:
            raise ValueError(
                "features_to_samples_ratio (Pearson selection) is not "
                "supported on sparse random-effect shards"
            )
        return _build_sparse_random_effect_dataset(
            dataset, re_type, shard_id, shard,
            active_data_upper_bound=active_data_upper_bound,
            active_data_lower_bound=active_data_lower_bound,
            bucket_sizes=bucket_sizes,
            seed=seed,
            normalization=normalization,
        )

    entity_idx = dataset.host_array(f"entity_idx/{re_type}")
    features = dataset.host_array(f"shard/{shard_id}")
    labels = dataset.host_array("labels")
    weights = dataset.host_array("weights")
    unique_ids = np.asarray(dataset.unique_ids)
    dim = features.shape[1]
    num_entities = len(dataset.entity_vocabs[re_type])

    projection = None
    if projector_type == ProjectorType.RANDOM:
        if projected_dim is None:
            raise ValueError("RANDOM projection requires projected_dim")
        projection = RandomProjectionMatrix.create(dim, projected_dim, seed)
        if normalization is not None:
            # normalize BEFORE sketching: x' = (x - shift)*factor, then
            # project — exact, unlike the reference's projection OF the
            # context (ProjectionMatrixBroadcast.projectNormalizationContext
            # maps factor/shift vectors through the Gaussian sketch, which
            # does not commute with per-feature scaling). Solves then run
            # plain; the back-projected [E, d] tables are normalized-space
            # coefficients and convert through the standard context algebra.
            from photon_ml_tpu.ops.normalization import (
                host_factors,
                host_shifts,
            )

            features = np.asarray(features)
            shifts = host_shifts(normalization)
            if shifts is not None:
                features = features - shifts.astype(features.dtype)
            factors = host_factors(normalization)
            if factors is not None:
                features = features * factors.astype(features.dtype)
        features = projection.project_features(features).astype(features.dtype)

    per_bucket = group_entities_into_buckets(
        entity_idx,
        unique_ids,
        bucket_sizes=bucket_sizes,
        active_data_upper_bound=active_data_upper_bound,
        active_data_lower_bound=active_data_lower_bound,
        seed=seed,
    )

    if features_to_samples_ratio is not None and projector_type == ProjectorType.RANDOM:
        raise ValueError(
            "features_to_samples_ratio (Pearson selection) operates on "
            "original feature columns and cannot combine with RANDOM "
            "projection; use IDENTITY or INDEX_MAP"
        )

    index_projected = projector_type == ProjectorType.INDEX_MAP
    buckets: list[EntityBucket] = []
    for cap, members in per_bucket.items():
        if not members:
            continue
        e = len(members)
        be, rows_concat, lane, slot = pack_bucket_lanes(members)
        bl = np.zeros((e, cap), dtype=labels.dtype)
        bw = np.zeros((e, cap), dtype=weights.dtype)
        bs = np.full((e, cap), -1, dtype=np.int32)
        bl[lane, slot] = labels[rows_concat]
        bw[lane, slot] = weights[rows_concat]
        bs[lane, slot] = rows_concat

        # one gather of the bucket's samples; every per-entity computation
        # below (Pearson masks, active columns) is a vectorized grouped
        # reduction over `lane` — no Python loop over entities
        x = features[rows_concat]
        if features_to_samples_ratio is not None:
            keep = _pearson_keep_masks_grouped(
                x, labels[rows_concat], lane, e, features_to_samples_ratio
            )
            x = x * keep[lane]

        bc = None
        if index_projected:
            bf, bc = _pack_index_projected(x, lane, slot, e, cap, dim)
            if normalization is not None:
                bf = _normalize_projected_block(
                    bf, bc, bs, normalization, dim
                )
        else:
            bf = np.zeros((e, cap, x.shape[1]), dtype=features.dtype)
            bf[lane, slot] = x
        buckets.append(
            EntityBucket(
                features=jnp.asarray(bf),
                labels=jnp.asarray(bl),
                weights=jnp.asarray(bw),
                entity_rows=jnp.asarray(be),
                sample_rows=jnp.asarray(bs),
                col_index=None if bc is None else jnp.asarray(bc),
            )
        )

    return RandomEffectDataset(
        random_effect_type=re_type,
        feature_shard_id=shard_id,
        buckets=buckets,
        num_entities=num_entities,
        dim=dim,
        projector_type=projector_type,
        projection=projection,
        pre_normalized=normalization is not None,
    )


def build_random_effect_dataset_partitioned(
    dataset: GameDataset,
    re_type: str,
    shard_id: str,
    *,
    partition,
    exchange,
    active_data_upper_bound: int | None = None,
    active_data_lower_bound: int | None = None,
    bucket_sizes: Sequence[int] = (8, 32, 128, 512, 2048),
    seed: int = 0,
    lane_multiple: int = 1,
    entity_rank_presence: np.ndarray | None = None,
    tag: str | None = None,
) -> RandomEffectDataset:
    """Rank-local random-effect view over a partitioned ingest.

    ``dataset`` is this rank's LOCAL padded block from
    io/partitioned_reader.py (entity indices already in the GLOBAL vocab;
    padding rows carry entity -1 and are excluded here as everywhere).
    Buckets are built from the local samples only; global consistency
    comes from ONE small metadata allgather of per-capacity entity counts
    (the entity ids + counts themselves were exchanged by the reader) —
    never from re-reading other ranks' bytes:

    - every rank agrees on the bucket-capacity list and pads its per-
      capacity entity block to the common lane count (padding lanes carry
      weight 0 and an out-of-range entity row — the established scatter-
      drop convention), so the concatenation of rank blocks is one global
      bucket tensor each rank can feed as its addressable shard;
    - ``sample_rows`` are shifted by the rank's base row, so in-step
      residual gathers index the GLOBAL sample axis.

    Semantics note (the partitioned deviation): an entity whose samples
    span ranks gets one lane PER rank, each solving on that rank's samples
    only — the later block's solve wins the table row, unlike the
    full-read path where all its samples share one lane. Entity-clustered
    inputs (the layout the reference's partitioner produces,
    RandomEffectDataSetPartitioner.scala) keep every entity on one rank
    and match the full read exactly; ``entity_rank_presence`` (from the
    reader) triggers a warning when that does not hold. Dense IDENTITY
    coordinates only — projected/compact coordinates read full.
    """
    shard = dataset.feature_shards[shard_id]
    if isinstance(shard, SparseShard):
        raise ValueError(
            f"random-effect coordinate '{re_type}': sparse (compact) "
            "shards are not supported by the partitioned path; use the "
            "full reader"
        )
    if entity_rank_presence is not None:
        spanning = int(np.sum(np.asarray(entity_rank_presence) > 1))
        if spanning:
            import logging

            logging.getLogger(__name__).warning(
                "random-effect coordinate '%s': %d entities have samples "
                "on multiple ranks; their per-rank partial solves deviate "
                "from the full-read result (entity-cluster the input for "
                "exact parity)", re_type, spanning,
            )

    local = build_random_effect_dataset(
        dataset, re_type, shard_id,
        active_data_upper_bound=active_data_upper_bound,
        active_data_lower_bound=active_data_lower_bound,
        bucket_sizes=bucket_sizes,
        seed=seed,
    )
    by_cap = {b.capacity: b for b in local.buckets}
    payload = {str(cap): b.num_entities for cap, b in by_cap.items()}
    gathered = exchange.allgather(
        f"re_partitioned/{tag or re_type}", payload
    )
    all_caps = sorted(
        {int(c) for g in gathered for c in g},
        key=lambda c: (list(bucket_sizes).index(c)
                       if c in bucket_sizes else len(bucket_sizes), c),
    )
    dim = local.dim
    base_row = partition.base_row
    oob_entity = np.iinfo(np.int32).max
    labels_dtype = np.asarray(dataset.host_array("labels")).dtype
    weights_dtype = np.asarray(dataset.host_array("weights")).dtype
    feat_dtype = np.asarray(dataset.host_array(f"shard/{shard_id}")).dtype

    buckets: list[EntityBucket] = []
    for cap in all_caps:
        e_max = max(int(g.get(str(cap), 0)) for g in gathered)
        e_pad = -(-e_max // max(1, lane_multiple)) * max(1, lane_multiple)
        b = by_cap.get(cap)
        e_local = 0 if b is None else b.num_entities
        if b is not None:
            bf = np.asarray(b.features)
            bl = np.asarray(b.labels)
            bw = np.asarray(b.weights)
            bs = np.asarray(b.sample_rows)
            be = np.asarray(b.entity_rows)
        else:
            bf = np.zeros((0, cap, dim), dtype=feat_dtype)
            bl = np.zeros((0, cap), dtype=labels_dtype)
            bw = np.zeros((0, cap), dtype=weights_dtype)
            bs = np.full((0, cap), -1, dtype=np.int32)
            be = np.zeros((0,), dtype=np.int32)
        pad = e_pad - e_local
        if pad:
            bf = np.concatenate([bf, np.zeros((pad, cap, dim), bf.dtype)])
            bl = np.concatenate([bl, np.zeros((pad, cap), bl.dtype)])
            bw = np.concatenate([bw, np.zeros((pad, cap), bw.dtype)])
            bs = np.concatenate([bs, np.full((pad, cap), -1, np.int32)])
            be = np.concatenate([be, np.full(pad, oob_entity, np.int32)])
        # local -> global sample rows (padding slots stay -1)
        bs = np.where(bs >= 0, bs + base_row, -1).astype(np.int32)
        buckets.append(EntityBucket(
            features=bf, labels=bl, weights=bw,
            entity_rows=be, sample_rows=bs,
        ))
    return RandomEffectDataset(
        random_effect_type=re_type,
        feature_shard_id=shard_id,
        buckets=buckets,
        num_entities=local.num_entities,
        dim=dim,
        projector_type=ProjectorType.IDENTITY,
    )


def _normalize_projected_block(bf, bc, bs, normalization, dim):
    """Rewrite an index-projected [e, cap, k] block to normalized space:
    x' = (x - shift)*factor over each entity's gathered columns. Valid
    sample slots only (bs >= 0); the scratch column (bc == dim) maps to
    factor 1 / shift 0 so padding slots stay exactly zero."""
    from photon_ml_tpu.ops.normalization import host_factors, host_shifts

    out = bf
    valid = (bs >= 0)[:, :, None]
    shifts = host_shifts(normalization)
    if shifts is not None:
        shift_ext = np.append(shifts.astype(bf.dtype), bf.dtype.type(0))
        out = out - shift_ext[bc][:, None, :] * valid
    factors = host_factors(normalization)
    if factors is not None:
        fac_ext = np.append(factors.astype(bf.dtype), bf.dtype.type(1))
        out = out * fac_ext[bc][:, None, :]
    return out


def _build_sparse_random_effect_dataset(
    dataset: GameDataset,
    re_type: str,
    shard_id: str,
    shard: SparseShard,
    *,
    active_data_upper_bound: int | None,
    active_data_lower_bound: int | None,
    bucket_sizes: Sequence[int],
    seed: int,
    normalization=None,
) -> RandomEffectDataset:
    """Compact per-entity blocks from a sparse (giant-d_re) shard.

    The reference trains each entity on its OBSERVED feature support
    (IndexMapProjectorRDD.scala:218-257, LocalDataSet.scala:36-173). Here:
    each entity's active columns = the union of nonzero columns across its
    kept samples (small, even when d_re is 10⁶+); its dense training block
    is [cap, bdim] over those columns; the coefficient table is [E, K]
    compact. Bucket ``col_index`` holds LOCAL table positions (pad = K), so
    the existing INDEX_MAP bucket solver runs unchanged with a [E, K+1]
    scratch-column table.
    """
    entity_idx = dataset.host_array(f"entity_idx/{re_type}")
    labels = dataset.host_array("labels")
    weights = dataset.host_array("weights")
    unique_ids = np.asarray(dataset.unique_ids)
    n = dataset.num_samples
    dim = int(shard.feature_dim)
    num_entities = len(dataset.entity_vocabs[re_type])

    rows_s, cols_s, vals_s = shard.coalesced()
    rows_s = np.asarray(rows_s)
    cols_s = np.asarray(cols_s)
    vals_s = np.asarray(vals_s)
    if normalization is not None and normalization.factors is not None:
        # pre-normalize at build time: x' = x * factor[col] (SCALE-only —
        # shifts rejected by the dispatcher); solves then run on a plain
        # objective and tables convert via the *_compact context methods
        from photon_ml_tpu.ops.normalization import host_factors

        vals_s = vals_s * host_factors(normalization).astype(vals_s.dtype)[cols_s]
    row_ptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(np.bincount(rows_s, minlength=n), out=row_ptr[1:])

    per_bucket = group_entities_into_buckets(
        entity_idx,
        unique_ids,
        bucket_sizes=bucket_sizes,
        active_data_upper_bound=active_data_upper_bound,
        active_data_lower_bound=active_data_lower_bound,
        seed=seed,
    )

    # pass 1: per-bucket entry expansion + per-entity active columns
    staged = []
    for cap, members in per_bucket.items():
        if not members:
            continue
        e = len(members)
        be, rows_concat, lane, slot = pack_bucket_lanes(members)
        bl = np.zeros((e, cap), dtype=labels.dtype)
        bw = np.zeros((e, cap), dtype=weights.dtype)
        bs = np.full((e, cap), -1, dtype=np.int32)
        bl[lane, slot] = labels[rows_concat]
        bw[lane, slot] = weights[rows_concat]
        bs[lane, slot] = rows_concat

        # expand the kept samples' COO entries (vectorized CSR slicing)
        cnt = row_ptr[rows_concat + 1] - row_ptr[rows_concat]
        total = int(cnt.sum())
        if total:
            base = np.repeat(row_ptr[rows_concat], cnt)
            offs = np.arange(total) - np.repeat(np.cumsum(cnt) - cnt, cnt)
            eidx = base + offs
            ecol = cols_s[eidx]
            evals = vals_s[eidx]
            elane = np.repeat(lane, cnt)
            eslot = np.repeat(slot, cnt)
        else:
            ecol = np.zeros(0, np.int64)
            evals = np.zeros(0, vals_s.dtype)
            elane = np.zeros(0, np.int64)
            eslot = np.zeros(0, np.int64)

        # per-lane sorted unique active columns
        key = elane * (dim + 1) + ecol
        uniq = np.unique(key)
        ulane, ucol = uniq // (dim + 1), uniq % (dim + 1)
        counts = np.bincount(ulane, minlength=e)
        bdim = max(int(counts.max(initial=0)), 1)
        starts = np.zeros(e + 1, dtype=np.int64)
        np.cumsum(counts, out=starts[1:])
        pos_of_uniq = np.arange(len(uniq)) - starts[ulane]
        bc = np.full((e, bdim), dim, dtype=np.int32)  # pad = dim (global)
        bc[ulane, pos_of_uniq] = ucol
        # entry -> position in its lane's active list (uniq is sorted, so
        # searchsorted over the flat unique keys localizes each entry)
        epos = np.searchsorted(uniq, key)
        epos = epos - starts[elane]

        bf = np.zeros((e, cap, bdim), dtype=vals_s.dtype)
        bf[elane, eslot, epos] = evals
        staged.append((cap, e, be, bl, bw, bs, bc, bf, bdim))

    k_width = max((bdim for *_, bdim in staged), default=1)
    active_cols = np.full((num_entities, k_width), dim, dtype=np.int32)
    buckets: list[EntityBucket] = []
    for cap, e, be, bl, bw, bs, bc, bf, bdim in staged:
        active_cols[be, :bdim] = bc
        # local table positions: the canonical active list IS this bucket's
        # bc row (entities live in exactly one bucket), so position p maps
        # to table slot p; pads point at the scratch column K
        local = np.broadcast_to(
            np.arange(bdim, dtype=np.int32), (e, bdim)
        ).copy()
        local[bc >= dim] = k_width
        buckets.append(EntityBucket(
            features=jnp.asarray(bf),
            labels=jnp.asarray(bl),
            weights=jnp.asarray(bw),
            entity_rows=jnp.asarray(be),
            sample_rows=jnp.asarray(bs),
            col_index=jnp.asarray(local),
        ))

    return RandomEffectDataset(
        random_effect_type=re_type,
        feature_shard_id=shard_id,
        buckets=buckets,
        num_entities=num_entities,
        dim=dim,
        projector_type=ProjectorType.INDEX_MAP,
        active_cols=active_cols,
        pre_normalized=normalization is not None,
    )


def _pearson_keep_masks_grouped(
    x: np.ndarray,  # [T, d] gathered bucket samples
    y: np.ndarray,  # [T]
    lane: np.ndarray,  # [T] entity lane of each sample
    e: int,
    ratio: float,
) -> np.ndarray:
    """Vectorized per-entity Pearson selection: [e, d] boolean keep masks.

    Same semantics as :func:`_pearson_keep_mask` applied per entity (the
    scalar function stays as the tested reference), but computed as grouped
    reductions over ``lane`` — the host-side bucketing cost is O(T·d) numpy
    instead of a Python loop over entities (VERDICT r1 weak #4).
    """
    d = x.shape[1]
    counts = np.bincount(lane, minlength=e).astype(np.float64)
    num_keep = np.maximum(1, np.ceil(ratio * counts)).astype(np.int64)

    # float64 scores: the defined tie-breaking semantics (see
    # _pearson_keep_mask, which upcasts the same way)
    x = np.asarray(x, dtype=np.float64)
    y = np.asarray(y, dtype=np.float64)
    sum_x = np.zeros((e, d))
    np.add.at(sum_x, lane, x)
    mean_x = sum_x / counts[:, None]
    xc = x - mean_x[lane]
    mean_y = np.bincount(lane, weights=y, minlength=e) / counts
    yc = y - mean_y[lane]
    var_x = np.zeros((e, d))
    np.add.at(var_x, lane, xc * xc)
    var_y = np.bincount(lane, weights=yc * yc, minlength=e)
    cov = np.zeros((e, d))
    np.add.at(cov, lane, xc * yc[:, None])
    any_nonzero = _grouped_active_mask(x, lane, e, d)

    all_zero = ~any_nonzero
    const_nonzero = (var_x == 0.0) & ~all_zero  # intercept-like
    with np.errstate(divide="ignore", invalid="ignore"):
        corr = np.abs(cov) / np.sqrt(var_x * var_y[:, None])
    score = np.where(var_x == 0.0, 0.0, corr)
    # constant labels carry no correlation signal; prefer active,
    # high-variance columns (same rule as the scalar function)
    score = np.where((var_y == 0.0)[:, None], var_x, score)
    score = np.where(const_nonzero, np.inf, score)
    score = np.where(all_zero, -np.inf, score)

    order = np.argsort(-_quantize_scores(score), axis=1, kind="stable")
    ranked_keep = np.arange(d)[None, :] < num_keep[:, None]
    keep = np.zeros((e, d), dtype=bool)
    np.put_along_axis(keep, order, ranked_keep, axis=1)
    return keep


def _grouped_active_mask(x: np.ndarray, lane: np.ndarray, e: int, d: int) -> np.ndarray:
    """[e, d] boolean: does entity (lane) have any nonzero in column j."""
    mask = np.zeros((e, d), dtype=bool)
    t_idx, col = np.nonzero(x)
    mask[lane[t_idx], col] = True
    return mask


def _pack_index_projected(
    x: np.ndarray,  # [T, d] gathered (possibly Pearson-zeroed) samples
    lane: np.ndarray,  # [T]
    slot: np.ndarray,  # [T]
    e: int,
    cap: int,
    dim: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized index-map projection packing: each entity's active columns
    compacted to the left, padding slots holding ``dim`` (the scratch
    column). Returns (bf [e, cap, bdim], bc [e, bdim])."""
    any_nonzero = _grouped_active_mask(x, lane, e, dim)
    # entity with no active column: keep column 0 (a zero column, solved to
    # ~0 by regularization — the projector module's documented fallback)
    empty = ~any_nonzero.any(axis=1)
    any_nonzero[empty, 0] = True

    counts = any_nonzero.sum(axis=1)
    bdim = int(counts.max())
    le, ce = np.nonzero(any_nonzero)  # lane-major, column-ascending
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pos = np.arange(len(le)) - starts[le]
    bc = np.full((e, bdim), dim, dtype=np.int32)
    bc[le, pos] = ce

    safe = np.minimum(bc, dim - 1)
    vals = x[np.arange(x.shape[0])[:, None], safe[lane]]  # [T, bdim]
    vals = vals * (bc[lane] < dim)
    bf = np.zeros((e, cap, bdim), dtype=x.dtype)
    bf[lane, slot] = vals
    return bf, bc


def build_game_dataset(
    *,
    labels,
    feature_shards: Mapping[str, np.ndarray],
    entity_keys: Mapping[str, np.ndarray] | None = None,
    offsets=None,
    weights=None,
    unique_ids=None,
    ids: Mapping[str, np.ndarray] | None = None,
    entity_vocabs: Mapping[str, np.ndarray] | None = None,
    dtype=np.float32,
    shard_dtypes: Mapping[str, object] | None = None,
) -> GameDataset:
    """Assemble a GameDataset from host arrays (reference GameConverters).

    entity_keys: RE type -> [n] per-sample entity key array; vocabs are built
    from the observed keys unless provided (warm-start scoring needs the
    training vocab, reference GameEstimator.getInitialModel).

    shard_dtypes: per-shard storage-dtype overrides (e.g. ml_dtypes.bfloat16
    for a dtype=bf16 FeatureShardConfiguration) — applied at assembly so a
    bf16 block is cast ONCE on host and transferred once, never staged
    through a full-size f32 device array.
    """
    labels = np.asarray(labels, dtype=dtype)
    n = len(labels)
    offsets = np.zeros(n, dtype) if offsets is None else np.asarray(offsets, dtype)
    weights = np.ones(n, dtype) if weights is None else np.asarray(weights, dtype)
    unique_ids = np.arange(n, dtype=np.int64) if unique_ids is None else np.asarray(unique_ids)

    entity_keys = entity_keys or {}
    vocabs: dict[str, np.ndarray] = {}
    entity_idx: dict[str, Array] = {}
    host_idx: dict[str, np.ndarray] = {}
    for re_type, keys in entity_keys.items():
        # Entity keys are canonically strings (they round-trip through Avro
        # model files as modelId strings, io/model_io.py); coerce here so an
        # int-keyed dataset still matches a loaded model's vocab.
        keys = np.asarray(keys).astype(str)
        if entity_vocabs is not None and re_type in entity_vocabs:
            vocab = np.asarray(entity_vocabs[re_type]).astype(str)
            if len(vocab) == 0:
                idx = np.full(len(keys), -1, dtype=np.int32)
            else:
                # vectorized lookup: position in sorted vocab, -1 for misses
                order = np.argsort(vocab, kind="stable")
                sorted_vocab = vocab[order]
                pos = np.minimum(
                    np.searchsorted(sorted_vocab, keys), len(vocab) - 1
                )
                idx = np.where(
                    sorted_vocab[pos] == keys, order[pos], -1
                ).astype(np.int32)
        else:
            vocab, inverse = np.unique(keys, return_inverse=True)
            idx = inverse.astype(np.int32)
        vocabs[re_type] = vocab
        entity_idx[re_type] = jnp.asarray(idx)
        host_idx[re_type] = idx

    # SparseShard values pass through untouched (giant-d shards never
    # densify — not on host, not on device)
    host_shards = {
        k: v for k, v in feature_shards.items()
        if not isinstance(v, SparseShard)
    }
    host_shards = {
        k: np.asarray(v, dtype=(shard_dtypes or {}).get(k, dtype))
        for k, v in host_shards.items()
    }
    device_shards: dict[str, object] = {
        k: (v if isinstance(v, SparseShard) else None)
        for k, v in feature_shards.items()
    }
    for k, v in host_shards.items():
        device_shards[k] = jnp.asarray(v)
    return GameDataset(
        unique_ids=unique_ids,
        labels=jnp.asarray(labels),
        offsets=jnp.asarray(offsets),
        weights=jnp.asarray(weights),
        feature_shards=device_shards,
        entity_idx=entity_idx,
        entity_vocabs=vocabs,
        ids=dict(ids or {}),
        host_cache={"labels": labels, "offsets": offsets, "weights": weights,
                    **{f"shard/{k}": v for k, v in host_shards.items()},
                    **{f"entity_idx/{t}": v for t, v in host_idx.items()}},
    )
