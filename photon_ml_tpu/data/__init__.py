from photon_ml_tpu.data.batch import LabeledPointBatch  # noqa: F401
