"""Legacy single-GLM training driver with staged pipeline + diagnostics.

Reference parity: photon-client Driver.scala — staged pipeline
INIT -> PREPROCESSED -> TRAINED -> VALIDATED -> DIAGNOSED (:158-218), train
via ModelTraining over the λ grid with warm starts (:334-368), validation
metrics + best-model selection (:373-450, ModelSelection.scala), diagnostics
+ HTML report (:608-635, 719-739), text model output (IOUtils
writeModelsInText, :211-215).
"""

from __future__ import annotations

import argparse
import dataclasses
import enum
import json
import logging
import os
from typing import Sequence

import numpy as np

from photon_ml_tpu.data.batch import LabeledPointBatch, summarize
from photon_ml_tpu.data.validators import DataValidationType, validate_arrays
from photon_ml_tpu.diagnostics.metrics import METRIC_DIRECTIONS, evaluate_model
from photon_ml_tpu.diagnostics.report_builder import build_diagnostic_report
from photon_ml_tpu.diagnostics.reporting import render_html, render_text
from photon_ml_tpu.estimators import train_glm, train_glm_grid
from photon_ml_tpu.io.data_reader import FeatureShardConfiguration
from photon_ml_tpu.io.partitioned_reader import read_partitioned
from photon_ml_tpu.io.model_io import write_glm_text
from photon_ml_tpu.ops.normalization import NormalizationType, build_normalization
from photon_ml_tpu.optim.optimizer import OptimizerConfig, OptimizerType
from photon_ml_tpu.resilience import run_with_recovery
from photon_ml_tpu.telemetry import io_counters
from photon_ml_tpu.telemetry import RunJournal, SolverTelemetry, default_registry
from photon_ml_tpu.telemetry.layout import reset_layout_metrics
from photon_ml_tpu.telemetry.resilience_counters import reset_resilience_metrics
from photon_ml_tpu.telemetry.stream_counters import reset_stream_metrics
from photon_ml_tpu.telemetry.probes import CompileMonitor
from photon_ml_tpu.telemetry.solver_trace import reset_solver_metrics
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.util import (
    EventEmitter,
    PhotonLogger,
    SetupEvent,
    Timed,
    TrainingFinishEvent,
    TrainingStartEvent,
)
from photon_ml_tpu.util.timed import reset_timings, timing_summary

logger = logging.getLogger(__name__)

#: process-wide emitter; external telemetry registers listeners here — the
#: reference emitted PhotonSetupEvent/TrainingStart/Finish and per-update
#: PhotonOptimizationLogEvents from Driver.scala:120-393, which this driver
#: previously had no wiring for (only the GAME driver did)
events = EventEmitter()


class DriverStage(enum.Enum):
    """Reference: DriverStage.scala."""

    INIT = 0
    PREPROCESSED = 1
    TRAINED = 2
    VALIDATED = 3
    DIAGNOSED = 4


#: model selection metric per task (reference ModelSelection.scala:
#: best AUC for classification, best RMSE for regression)
_SELECTION_METRIC = {
    TaskType.LOGISTIC_REGRESSION: "AUC",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: "AUC",
    TaskType.LINEAR_REGRESSION: "RMSE",
    TaskType.POISSON_REGRESSION: "POISSON_LOSS",
}


@dataclasses.dataclass
class GLMDriverParams:
    input_data_path: str
    output_dir: str
    task_type: TaskType
    validation_data_path: str | None = None
    regularization_weights: tuple[float, ...] = (0.0,)
    elastic_net_alpha: float = 0.0
    optimizer: OptimizerType = OptimizerType.LBFGS
    max_iterations: int = 100
    tolerance: float = 1e-7
    normalization: NormalizationType = NormalizationType.NONE
    data_validation: DataValidationType = DataValidationType.VALIDATE_DISABLED
    enable_diagnostics: bool = False
    num_bootstraps: int = 0
    compute_variance: bool = False
    #: train the whole λ grid simultaneously as vmapped solver lanes
    #: (train_glm_grid) instead of the sequential warm-start fold; LBFGS/
    #: OWLQN only — see estimators.train_glm_grid
    grid_parallel: bool = False
    #: JSON constraint list (reference Params.constraintString): maps with
    #: name/term (+ optional lowerBound/upperBound), "*" wildcards allowed
    coefficient_box_constraints: str | None = None
    input_format: str = "avro"
    #: structured-telemetry output dir: a JSONL run journal (phase timings,
    #: per-λ convergence rows, compile-count gauge) finalized on completion;
    #: None = disabled
    telemetry_dir: str | None = None
    #: run-trace output dir (telemetry/tracing.py): host-side span timeline
    #: exported as Chrome-trace JSON (``trace-00000.json``, open in
    #: Perfetto) + a straggler report journaled next to it — flushed on
    #: success AND failure paths. None = disabled (zero overhead).
    trace_dir: str | None = None
    #: corrupt-input handling for Avro ingestion: "raise" (strict,
    #: default) or "quarantine" (skip-and-count corrupt container blocks;
    #: io/avro.py + resilience layer)
    on_corrupt: str = "raise"
    #: out-of-core streaming epochs: records per chunk (> 0 opts in). The
    #: training data is never materialized in core — each solver objective
    #: evaluation is one exact chunked epoch with host Avro decode
    #: double-buffered behind device accumulation
    #: (io/stream_reader.py + algorithm/streaming.py). 0 = off (default),
    #: byte-identical to the in-core path.
    streaming_chunks: int = 0
    #: disable the background prefetch thread (chunks decode inline) — the
    #: same-run OFF baseline for overlap measurements; streaming mode only
    streaming_prefetch: bool = True
    #: crash-safe resume for streaming solves (io/checkpoint.
    #: SolverCheckpointer): optimizer state + λ-grid position + epoch
    #: cursor persist at every epoch boundary; a restarted run
    #: fast-forwards past completed λs and resumes mid-solve. Requires
    #: --streaming-chunks (the in-core solve has no epoch-granular state
    #: to persist). None = disabled.
    checkpoint_dir: str | None = None
    #: iteration cadence for mid-solve snapshots (λ-boundary snapshots
    #: always save): the solver state is model-sized, so giant-d runs
    #: widen this instead of paying a blocking save every iteration
    checkpoint_every: int = 1
    #: crash-safe recovery budget (resilience/recovery.py): a classified-
    #: transient failure (incl. device-loss/pool-preemption shapes)
    #: restarts the run — resuming from the latest intact checkpoint when
    #: --checkpoint-dir is set — up to this many times. 0 disables.
    max_restarts: int = 2
    #: GP-driven model search (hyperparameter/search_driver.py): > 0 opts
    #: in — each round trains --search-lane-budget configs as ONE vmapped
    #: tournament, evaluated on-mesh by the task's selection metric, with
    #: the GP fit overlapping the next round's device solve. Replaces the
    #: --regularization-weights grid; requires --validation-data-path and
    #: --search-space.
    search_rounds: int = 0
    #: configs per tournament round (vmapped solver lanes)
    search_lane_budget: int = 8
    #: search-space grammar, e.g. "lambda=1e-4:1e2:log,alpha=0:1,
    #: tolerance=1e-9:1e-5:log" (see search_driver.parse_search_space)
    search_space: str | None = None
    #: one SeedSequence threads Sobol + the GP slice sampler — a search
    #: trajectory replays deterministically under a fixed seed
    search_seed: int = 0


@dataclasses.dataclass
class GLMDriverResult:
    stage: DriverStage
    models: dict
    best_lambda: float | None
    validation_metrics: dict
    summary_path: str


def _read_batch(path: str, fmt: str, shard_cfg, index_maps=None,
                on_corrupt: str = "raise"):
    # the single-GLM driver is a one-process tool: read through the
    # ingestion dispatcher with the trivial exchange (identical bytes to
    # the old direct read; the lint bans direct read_merged in cli/),
    # wrapped in the transient-I/O retry policy (non-collective read)
    from photon_ml_tpu.parallel.multihost import SingleProcessExchange
    from photon_ml_tpu.resilience import default_io_policy

    result = default_io_policy().call(
        lambda: read_partitioned(
            path, shard_cfg, exchange=SingleProcessExchange(),
            index_maps=index_maps, fmt=fmt, on_corrupt=on_corrupt,
        ),
        description=f"read {path}",
    ).result
    ds = result.dataset
    batch = LabeledPointBatch(
        features=ds.feature_shards["features"],
        labels=ds.labels,
        offsets=ds.offsets,
        weights=ds.weights,
    )
    return batch, result.index_maps, result.intercept_indices.get("features")


def _check_streaming_supported(params: "GLMDriverParams") -> None:
    """Fail fast, with the alternative named, before any data is read:
    the streaming path never materializes the full batch, so stages that
    re-fit or decompose on the in-core batch cannot ride it."""
    if params.input_format != "avro":
        raise ValueError(
            "--streaming-chunks streams Avro container blocks; for "
            "libsvm inputs drop --streaming-chunks (or convert with "
            "cli/libsvm_to_avro.py and stream the result)"
        )
    if params.grid_parallel:
        raise ValueError(
            "--streaming-chunks trains the λ grid sequentially with warm "
            "starts (vmapped grid lanes need the in-core batch); drop "
            "--grid-parallel"
        )
    if params.enable_diagnostics or params.num_bootstraps:
        raise ValueError(
            "diagnostics re-fit on the in-core batch; drop "
            "--enable-diagnostics/--num-bootstraps or run without "
            "--streaming-chunks"
        )
    if params.compute_variance:
        raise ValueError(
            "coefficient variances decompose the in-core Hessian; drop "
            "--compute-variance or run without --streaming-chunks"
        )
    if params.optimizer == OptimizerType.NEWTON:
        raise ValueError(
            "NEWTON needs the dense [d, d] Hessian; use --optimizer TRON "
            "for streamed second-order solves"
        )


def _check_search_supported(params: "GLMDriverParams") -> None:
    """Fail fast, naming the alternative, before any data is read."""
    if not params.search_space:
        raise ValueError(
            "--search-rounds needs --search-space (grammar: "
            "name=low:high[:log][:int], comma-separated; e.g. "
            "'lambda=1e-4:1e2:log,alpha=0:1')"
        )
    if not params.validation_data_path:
        raise ValueError(
            "--search-rounds selects by the validation metric; pass "
            "--validation-data-path"
        )
    if params.streaming_chunks > 0:
        raise ValueError(
            "--search-rounds trains vmapped tournament lanes on the "
            "in-core batch; drop --streaming-chunks (stream-compose the "
            "winning config afterwards instead)"
        )
    if params.grid_parallel:
        raise ValueError(
            "--search-rounds replaces the λ grid (tournament lanes ARE "
            "the grid generalization); drop --grid-parallel"
        )
    if params.elastic_net_alpha:
        raise ValueError(
            "the elastic-net mix is a search dimension — add 'alpha=0:1' "
            "to --search-space instead of --elastic-net-alpha"
        )
    if params.enable_diagnostics or params.num_bootstraps:
        raise ValueError(
            "diagnostics re-fit the λ grid; run them on the winning "
            "config without --search-rounds"
        )
    if params.compute_variance:
        raise ValueError(
            "coefficient variances are not computed per tournament lane; "
            "re-fit the winning config with --compute-variance"
        )


def _check_checkpoint_supported(params: "GLMDriverParams") -> None:
    if params.checkpoint_dir and params.streaming_chunks <= 0:
        raise ValueError(
            "--checkpoint-dir resumes STREAMING solves (epoch-granular "
            "solver state; io/checkpoint.SolverCheckpointer) — pass "
            "--streaming-chunks N to opt in, or drop --checkpoint-dir "
            "(the in-core path re-runs from scratch under --max-restarts)"
        )
    if params.max_restarts < 0:
        raise ValueError("--max-restarts must be >= 0")
    if params.checkpoint_every < 1:
        raise ValueError("--checkpoint-every must be >= 1")


def run(params: GLMDriverParams) -> GLMDriverResult:
    if params.streaming_chunks > 0:
        _check_streaming_supported(params)
    if params.search_rounds > 0:
        _check_search_supported(params)
    _check_checkpoint_supported(params)
    if (
        params.coefficient_box_constraints
        and params.normalization != NormalizationType.NONE
    ):
        # bounds are stated in original feature space; the solvers work in
        # normalized space (reference Params.scala:219). Checked before any
        # data is read.
        raise ValueError(
            "coefficient box constraints cannot combine with feature "
            "normalization (bounds are stated in original feature space; "
            "the solvers work in normalized space) — drop "
            "normalization.type or the box constraints"
        )
    os.makedirs(params.output_dir, exist_ok=True)
    # per-run phase timings + solver/layout/stream tallies (sweeps may call
    # run() repeatedly)
    reset_timings()
    reset_solver_metrics()
    reset_layout_metrics()
    reset_stream_metrics()
    reset_resilience_metrics()
    journal = (
        RunJournal(params.telemetry_dir) if params.telemetry_dir else None
    )
    # program ledger rides --telemetry-dir (ISSUE 13): labeled jit sites
    # journal per-program compile/cost/signature rows with recompile
    # attribution; inert (null-object) without it
    ledger = None
    if journal is not None:
        from photon_ml_tpu.telemetry.program_ledger import (
            ProgramLedger,
            install_ledger,
        )

        ledger = install_ledger(ProgramLedger(journal=journal))
    # journal + registry are opt-in via --telemetry-dir; the emitter rides
    # along unconditionally (per-λ OptimizationLogEvents for any registered
    # listener). SolverTelemetry builds nothing — paying no host reads —
    # unless one of those sinks would actually consume the record.
    telemetry = SolverTelemetry(
        journal=journal,
        emitter=events,
        registry=default_registry() if journal and journal.active else None,
    )
    config_summary = {
        "task_type": params.task_type.name,
        "optimizer": params.optimizer.name,
        "regularization_weights": list(params.regularization_weights),
        "grid_parallel": params.grid_parallel,
        "max_iterations": params.max_iterations,
        "tolerance": params.tolerance,
        "normalization": params.normalization.name,
        "streaming_chunks": params.streaming_chunks,
        "streaming_prefetch": params.streaming_prefetch,
        "search_rounds": params.search_rounds,
        "search_lane_budget": params.search_lane_budget,
        "search_space": params.search_space,
        "checkpoint_dir": params.checkpoint_dir,
        "max_restarts": params.max_restarts,
        "trace_dir": params.trace_dir,
    }
    events.send(SetupEvent(config_summary=json.dumps(config_summary)))
    events.send(TrainingStartEvent(job_name="glm-training"))
    if journal is not None:
        journal.record("config", **config_summary)
    compiles = CompileMonitor()
    # crash-safe recovery (resilience/recovery.py — today GAME-only, now
    # here too): a classified-transient failure (dropped tunnel, device
    # loss/preemption) restarts the stages up to --max-restarts times; with
    # --checkpoint-dir the streaming solve resumes from the latest intact
    # epoch-boundary snapshot instead of from scratch
    checkpointer = None
    if params.checkpoint_dir:
        from photon_ml_tpu.io.checkpoint import SolverCheckpointer

        checkpointer = SolverCheckpointer(
            params.checkpoint_dir, save_every=params.checkpoint_every
        )
    # NO coordinator here (ISSUE 15): coordinated recovery requires the
    # run's hot path to ride a fenced MetadataExchange — the GLM streaming
    # path performs no exchange ops, so peers would never observe an abort
    # marker and a rank-local transient failure (which the detached
    # restart below genuinely recovers) would instead deadline out at the
    # restart rendezvous and kill the job. Attach one when a multi-rank
    # streamed-GLM surface (exchange-coordinated) lands.
    coordinator = None
    # span tracing is opt-in via --trace-dir; installed IMMEDIATELY before
    # the try whose finally uninstalls it (an exception in between would
    # leak the process-global tracer into the next run), early enough that
    # a failure mid-read still leaves a timeline
    tracer = None
    if params.trace_dir:
        from photon_ml_tpu.telemetry.tracing import Tracer, install_tracer

        tracer = install_tracer(Tracer())
    try:
        with compiles:
            result = run_with_recovery(
                lambda restart: _run_stages(
                    params, telemetry, checkpointer=checkpointer
                ),
                max_restarts=params.max_restarts,
                checkpointer=checkpointer,
                journal=journal,
                description="glm training",
                coordinator=coordinator,
            )
        events.send(TrainingFinishEvent(job_name="glm-training", succeeded=True))
        return result
    except Exception:
        events.send(TrainingFinishEvent(job_name="glm-training", succeeded=False))
        raise
    finally:
        # traces flush FIRST (before the failure journal) so a crash leaves
        # a readable timeline even if journaling itself fails; best-effort —
        # a trace-publication error never masks the run's own outcome
        if tracer is not None:
            from photon_ml_tpu.telemetry.tracing import (
                flush_trace_best_effort,
                uninstall_tracer,
            )

            try:
                flush_trace_best_effort(
                    tracer, params.trace_dir, journal=journal
                )
            finally:
                uninstall_tracer()
        if ledger is not None:
            from photon_ml_tpu.telemetry.program_ledger import uninstall_ledger

            uninstall_ledger()
        # journal phase timings / gauges on failure too — a failed run's
        # journal is the one that most needs them (the registry snapshot
        # carries the resilience/* counters)
        if journal is not None:
            from photon_ml_tpu.telemetry import resilience_counters

            for event in resilience_counters.drain_quarantine_events():
                journal.record("quarantined_block", **event)
            journal.record_timings(timing_summary())
            journal.record_gauge("jax/backend_compile_count", compiles.count)
            journal.record_metrics(default_registry().snapshot())
            journal.close()


def _prepare_streaming(params: GLMDriverParams, shard_cfg):
    """Streaming PREPROCESS: global index maps from one discarding vocab
    pass, the chunked epoch source over the block plan, per-chunk
    validation, and (when requested) normalization statistics from one
    streaming summary pass — the full batch is never materialized."""
    from photon_ml_tpu.algorithm.streaming import streaming_summarize
    from photon_ml_tpu.io.avro import list_avro_files
    from photon_ml_tpu.io.index_map import INTERCEPT_KEY
    from photon_ml_tpu.io.stream_reader import (
        AvroChunkSource,
        ChunkPrefetcher,
        DenseRecordAssembler,
        build_streaming_index_maps,
    )
    from photon_ml_tpu.resilience import default_io_policy

    cfg = shard_cfg["features"]
    files = list_avro_files(params.input_data_path)
    # same journal evidence as the full-read path (read_partitioned sets
    # it there; plan_partitioned_stream on the multi-process path)
    io_counters.set_input_bytes_total(
        sum(int(os.path.getsize(f)) for f in files)
    )
    index_maps = default_io_policy().call(
        lambda: build_streaming_index_maps(
            files, shard_cfg, on_corrupt=params.on_corrupt
        ),
        description=f"streaming vocab pass over {params.input_data_path}",
    )
    imap = index_maps["features"]
    intercept_index = imap.get_index(INTERCEPT_KEY)
    if intercept_index < 0:
        intercept_index = None
    source = AvroChunkSource(
        files,
        DenseRecordAssembler(imap, cfg),
        chunk_records=params.streaming_chunks,
        on_corrupt=params.on_corrupt,
    )
    if params.data_validation != DataValidationType.VALIDATE_DISABLED:
        # one inline pass, validating each chunk's TRUE rows (weight-0
        # chunk padding is layout, not data)
        with ChunkPrefetcher(source, prefetch=False) as chunks:
            for batch, spec in zip(chunks, source.specs):
                n = spec.num_records
                validate_arrays(
                    labels=np.asarray(batch.labels)[:n],
                    task=params.task_type,
                    offsets=np.asarray(batch.offsets)[:n],
                    weights=np.asarray(batch.weights)[:n],
                    feature_shards={
                        "features": np.asarray(batch.features)[:n]
                    },
                    validation_type=params.data_validation,
                )
    norm = None
    if params.normalization != NormalizationType.NONE:
        stats = streaming_summarize(
            source, prefetch=params.streaming_prefetch
        )
        import jax.numpy as jnp

        norm = build_normalization(
            params.normalization,
            mean=jnp.asarray(stats["mean"]),
            variance=jnp.asarray(stats["variance"]),
            max_magnitude=jnp.asarray(stats["max_magnitude"]),
            intercept_index=intercept_index,
        )
    return source, index_maps, intercept_index, norm


def _run_stages(params: GLMDriverParams, telemetry: SolverTelemetry,
                checkpointer=None) -> GLMDriverResult:
    stage = DriverStage.INIT
    shard_cfg = {"features": FeatureShardConfiguration(feature_bags=("features",))}
    streaming = params.streaming_chunks > 0

    with PhotonLogger(os.path.join(params.output_dir, "driver.log")) as job_log:
        # PREPROCESS
        batch = None
        with Timed("glm preprocess"):
            if streaming:
                source, index_maps, intercept_index, norm = (
                    _prepare_streaming(params, shard_cfg)
                )
            else:
                batch, index_maps, intercept_index = _read_batch(
                    params.input_data_path, params.input_format, shard_cfg,
                    on_corrupt=params.on_corrupt,
                )
                validate_arrays(
                    labels=np.asarray(batch.labels),
                    task=params.task_type,
                    offsets=np.asarray(batch.offsets),
                    weights=np.asarray(batch.weights),
                    feature_shards={"features": np.asarray(batch.features)},
                    validation_type=params.data_validation,
                )
                norm = None
                if params.normalization != NormalizationType.NONE:
                    stats = summarize(np.asarray(batch.features), np.asarray(batch.weights))
                    import jax.numpy as jnp

                    norm = build_normalization(
                        params.normalization,
                        mean=jnp.asarray(stats["mean"]),
                        variance=jnp.asarray(stats["variance"]),
                        max_magnitude=jnp.asarray(stats["max_magnitude"]),
                        intercept_index=intercept_index,
                    )
        stage = DriverStage.PREPROCESSED
        if streaming:
            job_log.info(
                "preprocessed %d samples, %d features (streaming: %d "
                "chunks of <=%d records)",
                source.total_records, source.dim, source.num_chunks,
                params.streaming_chunks,
            )
        else:
            job_log.info("preprocessed %d samples, %d features", batch.num_samples, batch.dim)

        # TRAIN
        opt = OptimizerConfig(
            optimizer_type=params.optimizer,
            max_iterations=params.max_iterations,
            tolerance=params.tolerance,
        )

        lower_bounds = upper_bounds = None
        if params.coefficient_box_constraints:
            from photon_ml_tpu.io.constraints import build_bound_arrays

            lower_bounds, upper_bounds = build_bound_arrays(
                params.coefficient_box_constraints, index_maps["features"]
            )

        def fit(b: LabeledPointBatch, lams, tel=None) -> dict:
            trainer = train_glm_grid if params.grid_parallel else train_glm
            return trainer(
                b,
                params.task_type,
                optimizer=opt,
                regularization_weights=lams,
                elastic_net_alpha=params.elastic_net_alpha,
                normalization=norm,
                intercept_index=intercept_index,
                compute_variance=params.compute_variance,
                lower_bounds=lower_bounds,
                upper_bounds=upper_bounds,
                telemetry=tel,
            )

        val_batch = None
        search_outcome = None
        with Timed("glm train"):
            if params.search_rounds > 0:
                from photon_ml_tpu.hyperparameter.search_driver import (
                    parse_search_space,
                    run_model_search,
                )

                # the validation batch doubles as the tournament metric
                # input; read it here (VALIDATE below reuses it)
                val_batch, _, _ = _read_batch(
                    params.validation_data_path, params.input_format,
                    shard_cfg, index_maps, on_corrupt=params.on_corrupt,
                )
                space = parse_search_space(params.search_space)
                search_outcome = run_model_search(
                    batch, val_batch, params.task_type, space,
                    rounds=params.search_rounds,
                    lane_budget=params.search_lane_budget,
                    optimizer=opt,
                    seed=params.search_seed,
                    evaluator=_SELECTION_METRIC[params.task_type],
                    normalization=norm,
                    intercept_index=intercept_index,
                    box_lower=lower_bounds,
                    box_upper=upper_bounds,
                    journal=telemetry.journal,
                    telemetry=telemetry,
                )
                models = {
                    search_outcome.best_config["lambda"]:
                        search_outcome.best_model
                }
                job_log.info(
                    "search best %s=%s config=%s (%d configs over %d rounds)",
                    search_outcome.evaluator_name,
                    search_outcome.best_metric,
                    search_outcome.best_config,
                    params.search_rounds * params.search_lane_budget,
                    params.search_rounds,
                )
            elif streaming:
                from photon_ml_tpu.estimators import train_glm_streaming

                models = train_glm_streaming(
                    source,
                    params.task_type,
                    optimizer=opt,
                    regularization_weights=params.regularization_weights,
                    elastic_net_alpha=params.elastic_net_alpha,
                    normalization=norm,
                    intercept_index=intercept_index,
                    telemetry=telemetry,
                    prefetch=params.streaming_prefetch,
                    lower_bounds=lower_bounds,
                    upper_bounds=upper_bounds,
                    checkpointer=checkpointer,
                )
            else:
                # telemetry only on the primary grid: diagnostics re-fits
                # below would repeat per-λ convergence rows
                models = fit(batch, params.regularization_weights, tel=telemetry)
        stage = DriverStage.TRAINED
        write_glm_text(
            os.path.join(params.output_dir, "models-text"),
            models,
            index_maps["features"],
        )

        # VALIDATE
        best_lambda = None
        validation_metrics: dict = {}
        if params.validation_data_path:
            with Timed("glm validate"):
                if val_batch is None:
                    val_batch, _, _ = _read_batch(
                        params.validation_data_path, params.input_format,
                        shard_cfg, index_maps, on_corrupt=params.on_corrupt,
                    )
                metric = _SELECTION_METRIC[params.task_type]
                larger = METRIC_DIRECTIONS[metric]
                best_value = None
                for lam, model in sorted(models.items()):
                    m = evaluate_model(model, val_batch)
                    validation_metrics[lam] = m
                    value = m[metric]
                    if np.isnan(value):  # a diverged model never wins
                        continue
                    if best_value is None or (value > best_value) == larger:
                        best_value, best_lambda = value, lam
            stage = DriverStage.VALIDATED
            job_log.info("best λ=%s by %s=%s", best_lambda, metric, best_value)

        # DIAGNOSE
        if params.enable_diagnostics:
            if val_batch is None:
                raise ValueError("diagnostics require --validation-data-path")
            if best_lambda is None:
                raise ValueError(
                    "no model produced a finite validation metric; nothing to diagnose"
                )
            with Timed("glm diagnose"):
                report = build_diagnostic_report(
                    models,
                    batch,
                    val_batch,
                    task=params.task_type,
                    train_fn_for_lambda=lambda lam: (
                        lambda b: fit(b, (lam,))[lam]
                    ),
                    best_lambda=best_lambda,
                    index_map=index_maps["features"],
                    num_bootstraps=params.num_bootstraps,
                    validation_metrics=validation_metrics,
                )
                with open(
                    os.path.join(params.output_dir, "diagnostic-report.html"), "w"
                ) as f:
                    f.write(render_html(report))
                with open(
                    os.path.join(params.output_dir, "diagnostic-report.txt"), "w"
                ) as f:
                    f.write(render_text(report))
            stage = DriverStage.DIAGNOSED

    summary_path = os.path.join(params.output_dir, "glm-summary.json")
    summary = {
        "stage": stage.name,
        "lambdas": sorted(models),
        "best_lambda": best_lambda,
        "validation_metrics": {
            str(k): v for k, v in validation_metrics.items()
        },
    }
    if search_outcome is not None:
        summary["search"] = {
            "best_config": search_outcome.best_config,
            "best_metric": search_outcome.best_metric,
            "metric": search_outcome.evaluator_name,
            "rounds": len(search_outcome.trajectory),
            "configs": len(search_outcome.observations),
        }
    with open(summary_path, "w") as f:
        json.dump(summary, f, indent=2, default=float)
    return GLMDriverResult(
        stage=stage,
        models=models,
        best_lambda=best_lambda,
        validation_metrics=validation_metrics,
        summary_path=summary_path,
    )


def main(argv: Sequence[str] | None = None) -> GLMDriverResult:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(prog="glm_driver", description=__doc__.split("\n")[0])
    p.add_argument("--input-data-path", required=True)
    p.add_argument("--validation-data-path")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--task-type", required=True,
                   choices=[t.name for t in TaskType if t != TaskType.NONE])
    p.add_argument("--regularization-weights", default="0",
                   help="comma-separated λ grid")
    p.add_argument("--elastic-net-alpha", type=float, default=0.0)
    p.add_argument("--optimizer", default="LBFGS",
                   choices=[o.name for o in OptimizerType])
    p.add_argument("--max-iterations", type=int, default=100)
    p.add_argument("--tolerance", type=float, default=1e-7)
    p.add_argument("--normalization", default="NONE",
                   choices=[n.name for n in NormalizationType])
    p.add_argument("--data-validation", default="VALIDATE_DISABLED",
                   choices=[v.name for v in DataValidationType])
    p.add_argument("--enable-diagnostics", action="store_true")
    p.add_argument("--num-bootstraps", type=int, default=0)
    p.add_argument("--compute-variance", action="store_true")
    p.add_argument("--grid-parallel", action="store_true",
                   help="train all regularization weights simultaneously as "
                        "vmapped solver lanes (LBFGS/OWLQN only)")
    p.add_argument("--coefficient-box-constraints",
                   help='JSON constraint list, e.g. \'[{"name": "f0", '
                        '"term": "", "lowerBound": 0}]\'; "*" wildcards '
                        "match all features / all terms of a name")
    p.add_argument("--input-format", default="avro", choices=["avro", "libsvm"])
    p.add_argument("--telemetry-dir",
                   help="write a JSONL run journal (phase timings, per-λ "
                        "convergence rows, compile counts) here")
    p.add_argument("--trace-dir",
                   help="write a Chrome-trace span timeline "
                        "(trace-00000.json, open in Perfetto) + straggler "
                        "report here; flushed on success and failure")
    p.add_argument("--on-corrupt", default="raise",
                   choices=["raise", "quarantine"],
                   help="corrupt Avro blocks: 'raise' (strict, default) "
                        "or 'quarantine' (skip-and-count)")
    p.add_argument("--streaming-chunks", type=int, default=0,
                   help="out-of-core streaming epochs: records per chunk "
                        "(> 0 opts in; the training data never "
                        "materializes in core — host Avro decode is "
                        "double-buffered behind device accumulation). "
                        "0 = off (default, byte-identical in-core path)")
    p.add_argument("--no-streaming-prefetch", action="store_true",
                   help="decode chunks inline instead of on the "
                        "background prefetch thread (the same-run OFF "
                        "baseline for overlap measurements)")
    p.add_argument("--checkpoint-dir",
                   help="crash-safe resume for --streaming-chunks runs: "
                        "solver state + λ-grid position + epoch cursor "
                        "persist at epoch boundaries; a restarted run "
                        "fast-forwards past completed λs and resumes "
                        "mid-solve")
    p.add_argument("--checkpoint-every", type=int, default=1,
                   help="save the mid-solve snapshot every N solver "
                        "iterations (λ-boundary snapshots always save; "
                        "widen for giant-d runs where the state is large)")
    p.add_argument("--max-restarts", type=int, default=2,
                   help="recovery budget: restart after a classified-"
                        "transient failure (incl. device-loss/preemption "
                        "shapes) up to N times, resuming from the latest "
                        "intact checkpoint when --checkpoint-dir is set "
                        "(0 disables)")
    p.add_argument("--search-rounds", type=int, default=0,
                   help="GP-driven model search: rounds of vmapped config "
                        "tournaments (> 0 opts in; replaces "
                        "--regularization-weights; requires "
                        "--validation-data-path and --search-space)")
    p.add_argument("--search-lane-budget", type=int, default=8,
                   help="configs per tournament round (vmapped solver "
                        "lanes sharing one feature-block read)")
    p.add_argument("--search-space",
                   help="search-space grammar: name=low:high[:log][:int], "
                        "comma-separated; dims: lambda (required), alpha, "
                        "tolerance, box — e.g. "
                        "'lambda=1e-4:1e2:log,alpha=0:1'")
    p.add_argument("--search-seed", type=int, default=0,
                   help="one SeedSequence threads Sobol + the GP slice "
                        "sampler; a trajectory replays deterministically "
                        "under a fixed seed")
    args = p.parse_args(argv)
    return run(
        GLMDriverParams(
            input_data_path=args.input_data_path,
            validation_data_path=args.validation_data_path,
            output_dir=args.output_dir,
            task_type=TaskType[args.task_type],
            regularization_weights=tuple(
                float(x) for x in args.regularization_weights.split(",") if x
            ),
            elastic_net_alpha=args.elastic_net_alpha,
            optimizer=OptimizerType[args.optimizer],
            max_iterations=args.max_iterations,
            tolerance=args.tolerance,
            normalization=NormalizationType[args.normalization],
            data_validation=DataValidationType[args.data_validation],
            enable_diagnostics=args.enable_diagnostics,
            num_bootstraps=args.num_bootstraps,
            compute_variance=args.compute_variance,
            grid_parallel=args.grid_parallel,
            coefficient_box_constraints=args.coefficient_box_constraints,
            input_format=args.input_format,
            telemetry_dir=args.telemetry_dir,
            trace_dir=args.trace_dir,
            on_corrupt=args.on_corrupt,
            streaming_chunks=args.streaming_chunks,
            streaming_prefetch=not args.no_streaming_prefetch,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            max_restarts=args.max_restarts,
            search_rounds=args.search_rounds,
            search_lane_budget=args.search_lane_budget,
            search_space=args.search_space,
            search_seed=args.search_seed,
        )
    )


if __name__ == "__main__":
    main()
