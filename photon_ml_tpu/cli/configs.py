"""Typed driver configuration + the k=v,k=v CLI grammar.

Reference parity: photon-client io/scopt/ScoptParserHelpers.scala:43-101,
155-200 — composite key-value grammar for coordinate and feature-shard
configurations ("name=X,feature.shard=Y,reg.weights=0.1|1|10"), photon-client
io/CoordinateConfiguration.scala (data config + opt config + reg-weight
grid, expandOptimizationConfigurations), io/FeatureShardConfiguration.scala,
and ModelOutputMode {NONE, BEST, EXPLICIT, TUNED, ALL}.

The reference wraps spark.ml Params in scopt; here plain dataclasses +
argparse carry the same nouns, and `expand_reg_weight_grid` reproduces the
cartesian grid fold of GameTrainingDriver.scala:612-621.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
from typing import Mapping, Sequence

from photon_ml_tpu.algorithm.coordinates import CoordinateOptimizationConfig
from photon_ml_tpu.estimators import (
    FixedEffectCoordinateConfig,
    MatrixFactorizationCoordinateConfig,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.io.data_reader import FeatureShardConfiguration
from photon_ml_tpu.ops.variance import validate_variance_mode
from photon_ml_tpu.optim.optimizer import (
    LaneSchedulerConfig,
    OptimizerConfig,
    OptimizerType,
)
from photon_ml_tpu.projector.projectors import ProjectorType


class ModelOutputMode(enum.Enum):
    """Reference: io/ModelOutputMode.scala — NONE (logs only), BEST (best
    model only), EXPLICIT (best + the explicit λ-grid models), TUNED (best +
    hyperparameter-tuning models), ALL (everything)."""

    NONE = "NONE"
    BEST = "BEST"
    EXPLICIT = "EXPLICIT"
    TUNED = "TUNED"
    ALL = "ALL"


LIST_SEP = "|"


def parse_kv_list(spec: str) -> dict[str, str]:
    """Parse "k1=v1,k2=v2" into a dict (list values use '|' separators,
    reference ScoptParserHelpers' composite grammar)."""
    out: dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, eq, value = part.partition("=")
        if not eq:
            raise ValueError(f"expected key=value, got {part!r} in {spec!r}")
        key = key.strip()
        if key in out:
            raise ValueError(f"duplicate key {key!r} in {spec!r}")
        out[key] = value.strip()
    return out


def _bool(s: str) -> bool:
    if s.lower() in ("true", "1", "yes"):
        return True
    if s.lower() in ("false", "0", "no"):
        return False
    raise ValueError(f"expected boolean, got {s!r}")


def parse_feature_shard_config(spec: str) -> tuple[str, FeatureShardConfiguration]:
    """"name=global,feature.bags=features|userFeatures,intercept=true"."""
    kv = parse_kv_list(spec)
    try:
        name = kv.pop("name")
        bags = tuple(b for b in kv.pop("feature.bags").split(LIST_SEP) if b)
    except KeyError as e:
        raise ValueError(f"feature shard config missing {e} in {spec!r}") from None
    intercept = _bool(kv.pop("intercept", "true"))
    sparse = _bool(kv.pop("sparse", "false"))
    pre_indexed = _bool(kv.pop("pre.indexed", "false"))
    dimension = kv.pop("dimension", None)
    # hybrid dense-head/sparse-tail layout (sparse shards only): the
    # nnz-hottest columns train on a dense MXU block, the cold residual on
    # the ELL tail (data/sparse_batch.HybridPolicy; BASELINE.md r6)
    hybrid = _bool(kv.pop("hybrid", "false"))
    hybrid_hot_cols = kv.pop("hybrid.hot.cols", None)
    hybrid_coverage = kv.pop("hybrid.coverage", None)
    # dtype=bf16 halves the dense block's HBM footprint/traffic (hot loop
    # at ~1.2-1.4x, BASELINE.md r4 bf16 study); accepted aliases follow
    # common usage
    dtype_aliases = {
        "f32": "float32", "float32": "float32", "fp32": "float32",
        "bf16": "bfloat16", "bfloat16": "bfloat16",
    }
    raw_dtype = kv.pop("dtype", "float32").lower()
    if raw_dtype not in dtype_aliases:
        raise ValueError(
            f"unknown feature shard dtype {raw_dtype!r} in {spec!r} "
            f"(expected one of {sorted(dtype_aliases)})"
        )
    if kv:
        raise ValueError(f"unknown feature shard keys {sorted(kv)} in {spec!r}")
    if pre_indexed and dimension is None:
        raise ValueError(
            f"pre.indexed=true requires dimension=N in {spec!r}"
        )
    return name, FeatureShardConfiguration(
        feature_bags=bags, has_intercept=intercept, sparse=sparse,
        pre_indexed=pre_indexed,
        dimension=None if dimension is None else int(dimension),
        dtype=dtype_aliases[raw_dtype],
        hybrid=hybrid,
        hybrid_hot_cols=(
            None if hybrid_hot_cols is None else int(hybrid_hot_cols)
        ),
        hybrid_coverage=(
            None if hybrid_coverage is None else float(hybrid_coverage)
        ),
    )


@dataclasses.dataclass(frozen=True)
class CoordinateCliConfig:
    """One coordinate's full CLI configuration (reference
    io/CoordinateConfiguration.scala: data config + opt config + λ grid)."""

    name: str
    feature_shard: str
    #: LBFGS (default) | OWLQN | LBFGSB | TRON (the reference's set,
    #: OptimizerType.scala) | NEWTON (TPU-first batched small-d solver,
    #: optim/newton.py — the fast choice for RE/MF coordinates)
    optimizer: OptimizerType = OptimizerType.LBFGS
    max_iterations: int = 100
    tolerance: float = 1e-7
    #: live function-decrease stop (optim/common.check_convergence): the
    #: knob that lets warm-started vmapped lanes exit before max_iter.
    #: None keeps the reference behavior (the plain tolerance)
    rel_function_tolerance: float | None = None
    #: TRON inner CG cap (giant-d solves budget device time with a short
    #: CG ladder; ignored by other optimizers)
    max_cg_iterations: int = 20
    reg_weights: tuple[float, ...] = (0.0,)
    reg_alpha: float = 0.0  # elastic-net: fraction of λ on L1
    down_sampling_rate: float = 1.0
    compute_variance: bool = False
    variance_mode: str = "auto"  # "auto" | "full" | "diagonal"
    # random-effect only
    random_effect_type: str | None = None
    active_data_lower_bound: int | None = None
    active_data_upper_bound: int | None = None
    projector: ProjectorType = ProjectorType.IDENTITY
    projected_dim: int | None = None
    features_to_samples_ratio: float | None = None
    #: probe/rescue lane scheduling for the vmapped per-entity solves
    #: (algorithm/lane_scheduler.py); strictly opt-in — off is
    #: bitwise-identical to the unscheduled path
    scheduler: bool = False
    scheduler_probe_iterations: int = 2
    #: cross-sweep active sets: entities whose relative coefficient delta
    #: AND gradient norm fall below these after a sweep are frozen (skipped
    #: by later sweeps, still rescored; final sweep runs everyone). Both
    #: must be > 0 to freeze anything.
    scheduler_freeze_tolerance: float = 0.0
    scheduler_freeze_gradient: float = 0.0
    # matrix-factorization only (feature_shard is unused: the "features" of
    # an MF coordinate are the other side's latent factors)
    mf_row_effect_type: str | None = None
    mf_col_effect_type: str | None = None
    mf_latent_factors: int = 0
    mf_alternations: int = 2

    @property
    def is_random_effect(self) -> bool:
        return self.random_effect_type is not None

    @property
    def is_matrix_factorization(self) -> bool:
        return self.mf_row_effect_type is not None

    def optimization_config(self, reg_weight: float) -> CoordinateOptimizationConfig:
        l1 = self.reg_alpha * reg_weight
        l2 = (1.0 - self.reg_alpha) * reg_weight
        return CoordinateOptimizationConfig(
            optimizer=OptimizerConfig(
                optimizer_type=self.optimizer,
                max_iterations=self.max_iterations,
                tolerance=self.tolerance,
                rel_function_tolerance=self.rel_function_tolerance,
                max_cg_iterations=self.max_cg_iterations,
                scheduler=LaneSchedulerConfig(
                    probe_iterations=self.scheduler_probe_iterations,
                    freeze_coefficient_tolerance=self.scheduler_freeze_tolerance,
                    freeze_gradient_tolerance=self.scheduler_freeze_gradient,
                ) if self.scheduler else None,
            ),
            l2_weight=l2,
            l1_weight=l1,
            compute_variance=self.compute_variance,
            variance_mode=self.variance_mode,
            down_sampling_rate=self.down_sampling_rate,
        )

    def estimator_config(self, reg_weight: float):
        if self.is_matrix_factorization:
            return MatrixFactorizationCoordinateConfig(
                row_effect_type=self.mf_row_effect_type,
                col_effect_type=self.mf_col_effect_type,
                num_latent_factors=self.mf_latent_factors,
                optimization=self.optimization_config(reg_weight),
                num_alternations=self.mf_alternations,
                active_data_upper_bound=self.active_data_upper_bound,
            )
        if self.is_random_effect:
            return RandomEffectCoordinateConfig(
                random_effect_type=self.random_effect_type,
                feature_shard_id=self.feature_shard,
                optimization=self.optimization_config(reg_weight),
                active_data_lower_bound=self.active_data_lower_bound,
                active_data_upper_bound=self.active_data_upper_bound,
                projector_type=self.projector,
                projected_dim=self.projected_dim,
                features_to_samples_ratio=self.features_to_samples_ratio,
            )
        return FixedEffectCoordinateConfig(
            feature_shard_id=self.feature_shard,
            optimization=self.optimization_config(reg_weight),
        )


_CLI_DEFAULTS = {
    f.name: f.default for f in dataclasses.fields(CoordinateCliConfig)
}


def format_coordinate_config(cfg: CoordinateCliConfig) -> str:
    """Render a config back to its CLI spec string (reference ScoptParameter
    print-round-trip: parse(format(cfg)) == cfg). Only non-default fields
    are emitted; defaults come from the dataclass itself so the round-trip
    stays exact if CoordinateCliConfig's defaults ever change."""
    d = _CLI_DEFAULTS
    parts = [f"name={cfg.name}"]
    if cfg.feature_shard:
        parts.append(f"feature.shard={cfg.feature_shard}")
    if cfg.optimizer != d["optimizer"]:
        parts.append(f"optimizer={cfg.optimizer.value}")
    if cfg.max_iterations != d["max_iterations"]:
        parts.append(f"max.iter={cfg.max_iterations}")
    if cfg.tolerance != d["tolerance"]:
        parts.append(f"tolerance={cfg.tolerance!r}")
    if cfg.rel_function_tolerance is not None:
        parts.append(f"rel.function.tolerance={cfg.rel_function_tolerance!r}")
    if cfg.max_cg_iterations != d["max_cg_iterations"]:
        parts.append(f"max.cg.iter={cfg.max_cg_iterations}")
    if cfg.reg_weights != d["reg_weights"]:
        parts.append(
            "reg.weights=" + LIST_SEP.join(repr(w) for w in cfg.reg_weights)
        )
    if cfg.reg_alpha != d["reg_alpha"]:
        parts.append(f"reg.alpha={cfg.reg_alpha!r}")
    if cfg.down_sampling_rate != d["down_sampling_rate"]:
        parts.append(f"down.sampling.rate={cfg.down_sampling_rate!r}")
    if cfg.compute_variance != d["compute_variance"]:
        parts.append("variance=true")
    if cfg.variance_mode != d["variance_mode"]:
        parts.append(f"variance.mode={cfg.variance_mode}")
    if cfg.random_effect_type:
        parts.append(f"random.effect.type={cfg.random_effect_type}")
    if cfg.active_data_lower_bound is not None:
        parts.append(f"active.data.lower.bound={cfg.active_data_lower_bound}")
    if cfg.active_data_upper_bound is not None:
        parts.append(f"active.data.upper.bound={cfg.active_data_upper_bound}")
    if cfg.projector != d["projector"]:
        parts.append(f"projector={cfg.projector.value}")
    if cfg.projected_dim is not None:
        parts.append(f"projected.dim={cfg.projected_dim}")
    if cfg.features_to_samples_ratio is not None:
        parts.append(f"features.to.samples.ratio={cfg.features_to_samples_ratio!r}")
    if cfg.scheduler != d["scheduler"]:
        parts.append("scheduler=true")
    if cfg.scheduler_probe_iterations != d["scheduler_probe_iterations"]:
        parts.append(f"scheduler.probe.iter={cfg.scheduler_probe_iterations}")
    if cfg.scheduler_freeze_tolerance != d["scheduler_freeze_tolerance"]:
        parts.append(
            f"scheduler.freeze.tolerance={cfg.scheduler_freeze_tolerance!r}"
        )
    if cfg.scheduler_freeze_gradient != d["scheduler_freeze_gradient"]:
        parts.append(
            f"scheduler.freeze.gradient={cfg.scheduler_freeze_gradient!r}"
        )
    if cfg.mf_row_effect_type:
        parts.append(f"mf.row.effect.type={cfg.mf_row_effect_type}")
        parts.append(f"mf.col.effect.type={cfg.mf_col_effect_type}")
        parts.append(f"mf.latent.factors={cfg.mf_latent_factors}")
        if cfg.mf_alternations != d["mf_alternations"]:
            parts.append(f"mf.alternations={cfg.mf_alternations}")
    return ",".join(parts)


def parse_coordinate_config(spec: str) -> CoordinateCliConfig:
    """Parse one --coordinate-configurations value, e.g.
    "name=per-user,random.effect.type=userId,feature.shard=user,
     optimizer=TRON,reg.weights=0.1|1|10,active.data.upper.bound=4096"."""
    kv = parse_kv_list(spec)
    try:
        name = kv.pop("name")
        # MF coordinates take no feature shard (their features are the other
        # side's latent factors); everything else requires one.
        if "mf.row.effect.type" in kv:
            shard = kv.pop("feature.shard", "")
        else:
            shard = kv.pop("feature.shard")
    except KeyError as e:
        raise ValueError(f"coordinate config missing {e} in {spec!r}") from None

    def pop(key, default=None):
        return kv.pop(key, default)

    mf_keys_given = sorted(k for k in kv if k.startswith("mf."))

    cfg = CoordinateCliConfig(
        name=name,
        feature_shard=shard,
        optimizer=OptimizerType(pop("optimizer", "LBFGS").upper()),
        max_iterations=int(pop("max.iter", "100")),
        tolerance=float(pop("tolerance", "1e-7")),
        rel_function_tolerance=(
            float(v) if (v := pop("rel.function.tolerance")) else None
        ),
        max_cg_iterations=int(pop("max.cg.iter", "20")),
        reg_weights=tuple(
            float(w) for w in pop("reg.weights", "0").split(LIST_SEP) if w
        ),
        reg_alpha=float(pop("reg.alpha", "0")),
        down_sampling_rate=float(pop("down.sampling.rate", "1")),
        compute_variance=_bool(pop("variance", "false")),
        variance_mode=validate_variance_mode(pop("variance.mode", "auto").lower()),
        random_effect_type=pop("random.effect.type"),
        active_data_lower_bound=(
            int(v) if (v := pop("active.data.lower.bound")) else None
        ),
        active_data_upper_bound=(
            int(v) if (v := pop("active.data.upper.bound")) else None
        ),
        projector=ProjectorType(pop("projector", "IDENTITY").upper()),
        projected_dim=(int(v) if (v := pop("projected.dim")) else None),
        features_to_samples_ratio=(
            float(v) if (v := pop("features.to.samples.ratio")) else None
        ),
        scheduler=_bool(pop("scheduler", "false")),
        scheduler_probe_iterations=int(pop("scheduler.probe.iter", "2")),
        scheduler_freeze_tolerance=float(pop("scheduler.freeze.tolerance", "0")),
        scheduler_freeze_gradient=float(pop("scheduler.freeze.gradient", "0")),
        mf_row_effect_type=pop("mf.row.effect.type"),
        mf_col_effect_type=pop("mf.col.effect.type"),
        mf_latent_factors=int(pop("mf.latent.factors", "0")),
        mf_alternations=int(pop("mf.alternations", "2")),
    )
    if kv:
        raise ValueError(f"unknown coordinate config keys {sorted(kv)} in {spec!r}")
    if not cfg.reg_weights:
        raise ValueError(f"coordinate {name!r} has an empty reg.weights grid")
    # Any mf.* key makes this an MF coordinate; partial specs (e.g. col+factors
    # without row) must fail loudly, not silently train a fixed effect.
    if mf_keys_given and (
        cfg.mf_row_effect_type is None
        or cfg.mf_col_effect_type is None
        or cfg.mf_latent_factors <= 0
    ):
        raise ValueError(
            f"coordinate {name!r} sets {mf_keys_given} but a matrix-"
            "factorization coordinate requires all of mf.row.effect.type, "
            "mf.col.effect.type, and mf.latent.factors > 0"
        )
    if cfg.features_to_samples_ratio is not None and not cfg.is_random_effect:
        raise ValueError(
            f"coordinate {name!r}: features.to.samples.ratio is per-entity "
            "Pearson selection and only applies to random-effect coordinates"
        )
    if cfg.scheduler and not cfg.is_random_effect:
        raise ValueError(
            f"coordinate {name!r}: scheduler=true is probe/rescue lane "
            "scheduling for VMAPPED per-entity solves and only applies to "
            "random-effect coordinates (fixed effects are a single "
            "un-vmapped solve; use rel.function.tolerance there)"
        )
    if cfg.is_matrix_factorization and cfg.is_random_effect:
        raise ValueError(
            f"coordinate {name!r} sets both random.effect.type and mf.* keys; "
            "a coordinate is either a random effect or a matrix factorization"
        )
    if cfg.is_matrix_factorization and cfg.reg_alpha > 0.0:
        raise ValueError(
            f"MF coordinate {name!r}: L1 (reg.alpha > 0) is not supported on "
            "latent factors; use pure L2"
        )
    return cfg


def expand_reg_weight_grid(
    configs: Mapping[str, CoordinateCliConfig],
) -> list[dict[str, float]]:
    """Cartesian product of each coordinate's λ grid (reference
    GameTrainingDriver.prepareGameOptConfigs:612-621)."""
    names = list(configs.keys())
    grids = [configs[n].reg_weights for n in names]
    return [dict(zip(names, combo)) for combo in itertools.product(*grids)]


def estimator_coordinate_configs(
    configs: Mapping[str, CoordinateCliConfig], reg_weights: Mapping[str, float]
) -> dict:
    return {
        name: cfg.estimator_config(reg_weights[name]) for name, cfg in configs.items()
    }


def evaluation_id_columns(evaluator_specs: Sequence[str]) -> tuple[str, ...]:
    """Id columns needed by per-query evaluator specs ("AUC:queryId")."""
    cols = []
    for spec in evaluator_specs:
        if ":" in spec:
            col = spec.split(":", 1)[1].strip()
            if col and col not in cols:
                cols.append(col)
    return tuple(cols)
