"""GAME training driver: the flagship end-to-end CLI entry point.

Reference parity: photon-client cli/game/training/GameTrainingDriver.scala —
params (:78-166), run() pipeline (:335-471): read + validate data, feature
stats, normalization contexts, λ-grid expansion (:612-621), GameEstimator
fit per configuration warm-starting from the previous (:352-366), optional
hyperparameter tuning (:631-663), model selection (:672-737), model save
(:748-815); shared GameDriver params (cli/game/GameDriver.scala:56-132).

Usage:
    python -m photon_ml_tpu.cli.game_training_driver \
        --input-data-path data/train --validation-data-path data/val \
        --root-output-dir out \
        --feature-shard-configurations name=global,feature.bags=features \
        --coordinate-configurations name=fe,feature.shard=global,reg.weights=0.1|1|10 \
        --task-type LOGISTIC_REGRESSION --evaluators AUC
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
from typing import Sequence

import numpy as np

from photon_ml_tpu.cli.configs import (
    CoordinateCliConfig,
    ModelOutputMode,
    estimator_coordinate_configs,
    evaluation_id_columns,
    expand_reg_weight_grid,
    format_coordinate_config,
    parse_coordinate_config,
    parse_feature_shard_config,
)
from photon_ml_tpu.data.batch import summarize
from photon_ml_tpu.data.sparse_batch import SparseShard
from photon_ml_tpu.data.validators import DataValidationType, validate_game_dataset
from photon_ml_tpu.estimators import GameEstimator
from photon_ml_tpu.evaluation.evaluators import parse_evaluator
from photon_ml_tpu.hyperparameter.game_glue import (
    GameHyperparameterTuner,
    HyperparameterTuningMode,
    load_prior_observations,
    save_tuned_config,
)
from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.io.partitioned_reader import read_partitioned
from photon_ml_tpu.io.model_io import (
    DEFAULT_COMPACT_RE_THRESHOLD,
    load_game_model,
    save_game_model,
    write_feature_stats,
)
from photon_ml_tpu.ops.normalization import NormalizationType
from photon_ml_tpu.optim.optimizer import OptimizerType
from photon_ml_tpu.projector.projectors import ProjectorType
from photon_ml_tpu.telemetry import RunJournal, SolverTelemetry, default_registry
from photon_ml_tpu.telemetry.layout import reset_layout_metrics
from photon_ml_tpu.telemetry.probes import CompileMonitor, live_buffer_bytes
from photon_ml_tpu.telemetry.refresh_counters import reset_refresh_metrics
from photon_ml_tpu.telemetry.resilience_counters import reset_resilience_metrics
from photon_ml_tpu.telemetry.solver_trace import reset_solver_metrics
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.util import (
    EventEmitter,
    PhotonLogger,
    Timed,
    TrainingFinishEvent,
    TrainingStartEvent,
)
from photon_ml_tpu.util.timed import reset_timings, timing_summary

logger = logging.getLogger(__name__)

#: process-wide emitter; external telemetry registers listeners here
#: (reference Driver event emission, Driver.scala:120-393)
events = EventEmitter()


@dataclasses.dataclass
class GameTrainingParams:
    """Validated driver parameters (reference GameTrainingDriver params)."""

    input_data_path: str
    root_output_dir: str
    feature_shards: dict
    coordinates: dict[str, CoordinateCliConfig]
    task_type: TaskType
    validation_data_path: str | None = None
    #: "yyyyMMdd-yyyyMMdd" or "N-M" days-ago; expands the input path into
    #: its <base>/daily/yyyy/MM/dd subdirectories (reference GameDriver
    #: date-range params + IOUtils.getInputPathsWithinDateRange)
    input_date_range: str | None = None
    validation_data_date_range: str | None = None
    update_sequence: tuple[str, ...] = ()
    coordinate_descent_iterations: int = 1
    evaluators: tuple[str, ...] = ()
    normalization: NormalizationType = NormalizationType.NONE
    data_validation: DataValidationType = DataValidationType.VALIDATE_DISABLED
    model_input_dir: str | None = None  # warm start
    partial_retrain_locked_coordinates: tuple[str, ...] = ()
    model_output_mode: ModelOutputMode = ModelOutputMode.ALL
    hyperparameter_tuning: HyperparameterTuningMode = HyperparameterTuningMode.NONE
    hyperparameter_tuning_iter: int = 10
    hyperparameter_tuning_range: tuple[float, float] = (1e-4, 1e4)
    #: tuned-hyperparameters.json from a previous run, used as search priors
    #: (reference HyperparameterSerialization)
    hyperparameter_prior_json: str | None = None
    input_format: str = "avro"
    #: reuse index stores built by feature_indexing_driver (plain .keys or
    #: native off-heap .photonix) instead of scanning the data
    index_maps_dir: str | None = None
    override_output: bool = False
    #: mid-training checkpoint/resume (io/checkpoint.py); one subdirectory
    #: per λ-grid configuration. Empty = disabled.
    checkpoint_dir: str | None = None
    checkpoint_every: int = 1
    resume: bool = True
    #: jax.profiler trace output dir (TensorBoard); empty = disabled
    profile_dir: str | None = None
    #: warm-start models whose RE feature space exceeds this load compact
    compact_random_effect_threshold: int = DEFAULT_COMPACT_RE_THRESHOLD
    #: train through the fused mesh-sharded SPMD program
    #: (parallel/distributed.py) instead of the host-loop CD path — the
    #: cluster-scale mode of the reference driver
    #: (GameTrainingDriver.scala:822-843). ``mesh_shape`` lays the devices
    #: out as {"data": N, "model": M}; empty with distributed=True means all
    #: devices on "data".
    distributed: bool = False
    mesh_shape: dict[str, int] | None = None
    #: structured-telemetry output dir: a rank-0 JSONL run journal (config
    #: summary, phase timings, per-coordinate convergence rows, compile and
    #: HBM gauges) finalized on completion; None = disabled
    telemetry_dir: str | None = None
    #: run-trace output dir (telemetry/tracing.py): EVERY rank exports its
    #: host-side span timeline as Chrome-trace JSON (trace-{rank:05d}.json
    #: — rank-0 mkdir, barrier, per-rank write, the score-writer carve-out)
    #: and a rank-merged straggler report is journaled at run end. Flushed
    #: on success AND failure paths; None = disabled (zero overhead).
    trace_dir: str | None = None
    #: partitioned host I/O (io/partitioned_reader.py): on a multi-process
    #: run each rank decodes only ~1/P of the input bytes and feeds its
    #: local block as addressable shards of the global arrays. Opt-in:
    #: v1 supports dense shards + IDENTITY random effects without
    #: normalization/validation riders, and entities spanning rank
    #: partitions solve per-rank (entity-cluster the input for exact
    #: full-read parity). Single-process runs are unaffected.
    partitioned_io: bool = False
    #: corrupt-input handling for Avro ingestion: "raise" (strict,
    #: default) or "quarantine" (skip-and-count corrupt container blocks;
    #: spans journaled — io/avro.py, resilience layer)
    on_corrupt: str = "raise"
    #: crash-safe recovery budget: a mid-sweep DivergenceError (with a
    #: checkpoint to restore) or classified-transient failure restarts the
    #: configuration — resuming from the latest intact checkpoint — up to
    #: this many times before the error propagates
    #: (resilience/recovery.py). 0 disables recovery.
    max_restarts: int = 2
    #: out-of-core streamed GAME (ISSUE 11): records per chunk (> 0 opts
    #: in). The input streams as entity-clustered fixed-shape chunks
    #: through the one-jitted-step accumulators
    #: (io/stream_reader.GameAvroChunkSource +
    #: algorithm/streaming_game.StreamingGameProgram) — n bounded by disk,
    #: not HBM. Requires an entity-sorted Avro input (sorted by the first
    #: random-effect coordinate's id column). 0 = off (default), the
    #: unchanged in-core path.
    streaming_chunks: int = 0
    #: double-buffered chunk decode; --no-streaming-prefetch is the
    #: same-run OFF baseline for overlap measurements
    streaming_prefetch: bool = True
    #: DuHL importance-ordered chunk schedule (arXiv:1702.07005): > 0 pins
    #: this many gap-hottest chunks resident and streams the cold tail
    #: round-robin. 0 (default) = uniform order, bitwise-identical to the
    #: unscheduled streamed sweep.
    duhl_working_set: int = 0
    #: cold-tail chunks revisited per sweep under the DuHL schedule
    duhl_tail_chunks: int = 1
    #: incremental retrain (ISSUE 14, algorithm/refresh.py): re-solve only
    #: the random-effect entities that saw new data or whose gradient at
    #: the resident solution exceeds tolerance, against frozen residuals
    #: from the resident model's scores — a refresh costs ~the changed
    #: entities' solve time, not a full GAME fit. Strictly opt-in: off is
    #: the unchanged full-fit path. Needs a resident model
    #: (--model-input-dir, or --checkpoint-dir warm-start re-entry).
    incremental_refresh: bool = False
    #: gradient screen: re-solve entities whose solve-space gradient norm
    #: at the resident solution exceeds this (<= 0 disables the screen —
    #: only declared entities re-solve)
    refresh_gradient_tolerance: float = 1e-4
    #: raw "reType=key1|key2" specs: entities DECLARED changed (the ingest
    #: layer's knowledge); the gradient screen catches undeclared drift
    refresh_changed_entities: tuple[str, ...] = ()
    #: also re-solve fixed-effect coordinates (warm-started) — off by
    #: default: the FE is the slow-moving global part a refresh skips
    refresh_fixed_effects: bool = False

    def validate(self) -> None:
        """Cross-parameter checks (reference validateParams:196-298)."""
        problems = []
        if self.on_corrupt not in ("raise", "quarantine"):
            problems.append(
                f"--on-corrupt must be 'raise' or 'quarantine', got "
                f"{self.on_corrupt!r}"
            )
        if self.max_restarts < 0:
            problems.append("--max-restarts must be >= 0")
        # hybrid x --partitioned-io is a SUPPORTED composition since ISSUE
        # 6: the hot-column ranking is a global nnz statistic, so the
        # partitioned reader ships per-rank histograms through the metadata
        # exchange and every rank resolves the SAME head
        # (io/partitioned_reader._resolve_global_sparse_layout)
        sequence = self.update_sequence or tuple(self.coordinates.keys())
        for cid in sequence:
            if cid not in self.coordinates:
                problems.append(f"update sequence names unknown coordinate '{cid}'")
        for cid in self.partial_retrain_locked_coordinates:
            if cid not in sequence:
                problems.append(f"locked coordinate '{cid}' not in update sequence")
        if self.partial_retrain_locked_coordinates and self.model_input_dir is None:
            problems.append("partial retraining requires --model-input-dir")
        for name, cfg in self.coordinates.items():
            if cfg.is_matrix_factorization:
                continue  # MF coordinates take no feature shard
            if cfg.feature_shard not in self.feature_shards:
                problems.append(
                    f"coordinate '{name}' references undefined feature shard "
                    f"'{cfg.feature_shard}'"
                )
        if self.evaluators and self.validation_data_path is None:
            problems.append(
                "--evaluators are validation evaluators and require "
                "--validation-data-path"
            )
        for spec in self.evaluators:
            # fail fast on bad specs, before any data is read
            try:
                parse_evaluator(spec)
            except ValueError as e:
                problems.append(str(e))
        if self.index_maps_dir:
            # typo'd stores dir must fail before the output dir is touched;
            # filenames only — no store is opened/mmapped here
            try:
                found = IndexMap.list_directory(self.index_maps_dir)
                missing = set(self.feature_shards) - set(found)
                if missing:
                    problems.append(
                        f"--index-maps-dir {self.index_maps_dir!r} has no "
                        f"stores for shards {sorted(missing)}"
                    )
            except OSError as e:
                problems.append(
                    f"cannot read --index-maps-dir {self.index_maps_dir!r}: {e}"
                )
        if self.hyperparameter_prior_json:
            # a typo'd priors path must fail now, not after the grid trains
            try:
                load_prior_observations(self.hyperparameter_prior_json)
            except Exception as e:
                problems.append(
                    f"cannot read --hyperparameter-prior-json "
                    f"{self.hyperparameter_prior_json!r}: {e}"
                )
        if (
            self.hyperparameter_tuning != HyperparameterTuningMode.NONE
            and not self.evaluators
        ):
            problems.append("hyperparameter tuning requires --evaluators")
        if self.incremental_refresh:
            self._validate_refresh(problems)
        elif self.refresh_changed_entities or self.refresh_fixed_effects:
            problems.append(
                "--refresh-changed-entities/--refresh-fixed-effects tune "
                "the incremental-refresh policy; pass --incremental-refresh "
                "to opt into the refresh driver mode"
            )
        if self.streaming_chunks > 0:
            self._validate_streaming(problems)
        elif self.duhl_working_set > 0:
            problems.append(
                "--duhl-working-set schedules streamed chunks; pass "
                "--streaming-chunks N to opt into the streamed GAME path"
            )
        if problems:
            raise ValueError("invalid driver parameters: " + "; ".join(problems))

    def _validate_refresh(self, problems: list) -> None:
        """The incremental-refresh surface (ISSUE 14): the single-process
        host CD path, one λ per coordinate, against a resident model.
        Everything outside it fails fast with the composing alternative
        named (lint check 8)."""
        if not self.model_input_dir and not self.checkpoint_dir:
            problems.append(
                "--incremental-refresh needs a resident model: pass "
                "--model-input-dir (a saved model directory) or "
                "--checkpoint-dir (a training run's CD checkpoints — "
                "warm-start re-entry)"
            )
        if self.distributed or self.mesh_shape or self.partitioned_io:
            problems.append(
                "--incremental-refresh is the single-process host path; "
                "drop --distributed/--mesh/--partitioned-io (run the full "
                "fused fit to retrain at mesh scale)"
            )
        if self.streaming_chunks > 0:
            problems.append(
                "--incremental-refresh reads the refresh data in-core; "
                "drop --streaming-chunks (or run the streamed full fit)"
            )
        if self.hyperparameter_tuning != HyperparameterTuningMode.NONE:
            problems.append(
                "--incremental-refresh trains the resident λ; drop "
                "--hyperparameter-tuning (tune on a full fit)"
            )
        if self.validation_data_path or self.evaluators:
            problems.append(
                "--incremental-refresh has no validation pass; drop "
                "--validation-data-path/--evaluators and score with the "
                "scoring driver"
            )
        if self.refresh_gradient_tolerance < 0:
            problems.append("--refresh-gradient-tolerance must be >= 0")
        for name, cfg in self.coordinates.items():
            if len(cfg.reg_weights) != 1:
                problems.append(
                    f"coordinate '{name}': --incremental-refresh trains "
                    "the resident λ; pass a single reg.weights value"
                )
        try:
            _parse_changed_entities(self.refresh_changed_entities)
        except ValueError as e:
            problems.append(str(e))

    def _validate_streaming(self, problems: list) -> None:
        """The streamed-GAME surface (ISSUE 11 + 17): one dense primary FE
        + IDENTITY random effects over an entity-sorted Avro input —
        single-process, or multi-rank via --partitioned-io (the ISSUE 17
        composition). Everything outside it fails fast here with the
        composing alternative named (lint check 8)."""
        if self.input_format != "avro":
            problems.append(
                "--streaming-chunks streams Avro container blocks; for "
                "libsvm inputs drop --streaming-chunks (or convert with "
                "cli.libsvm_to_avro)"
            )
        if self.input_date_range:
            problems.append(
                "--streaming-chunks streams one input directory; drop "
                "--input-date-range (pass the resolved daily dir directly)"
            )
        if self.validation_data_date_range:
            problems.append(
                "--streaming-chunks streams one validation directory; drop "
                "--validation-data-date-range (pass the resolved dir "
                "directly)"
            )
        if self.distributed or self.mesh_shape:
            problems.append(
                "--streaming-chunks is the host-loop out-of-core GAME "
                "path; drop --distributed/--mesh (for multi-process "
                "streamed GAME use --partitioned-io, which partitions "
                "chunks across ranks instead of meshing devices)"
            )
        if self.normalization != NormalizationType.NONE:
            problems.append(
                "--streaming-chunks trains un-normalized; use "
                "--normalization NONE or run in-core"
            )
        for spec in self.evaluators:
            if ":" in str(spec):
                problems.append(
                    f"evaluator '{spec}': per-query evaluators need "
                    "evaluation id columns the chunk stream does not "
                    "decode; use a global evaluator or score with the "
                    "scoring driver"
                )
        if self.hyperparameter_tuning != HyperparameterTuningMode.NONE:
            problems.append(
                "--streaming-chunks trains one configuration; drop "
                "--hyperparameter-tuning"
            )
        if self.data_validation != DataValidationType.VALIDATE_DISABLED:
            problems.append(
                "--streaming-chunks has no chunked validation pass yet; "
                "use --data-validation VALIDATE_DISABLED or run in-core"
            )
        if self.model_input_dir or self.partial_retrain_locked_coordinates:
            problems.append(
                "--streaming-chunks does not warm-start from "
                "--model-input-dir yet; drop it or train in-core"
            )
        if self.duhl_working_set < 0 or self.duhl_tail_chunks < 1:
            problems.append(
                "--duhl-working-set must be >= 0 and --duhl-tail-chunks "
                ">= 1"
            )
        fe_coords = [
            n for n, c in self.coordinates.items()
            if not c.is_random_effect and not c.is_matrix_factorization
        ]
        if len(fe_coords) != 1:
            problems.append(
                "--streaming-chunks needs exactly one fixed-effect "
                f"coordinate (got {fe_coords}); train other layouts in-core"
            )
        sequence = self.update_sequence or tuple(self.coordinates.keys())
        if fe_coords and sequence and sequence[0] != fe_coords[0]:
            problems.append(
                "--streaming-chunks trains the fixed effect first; put "
                f"'{fe_coords[0]}' first in --update-sequence"
            )
        for name, c in self.coordinates.items():
            if c.is_matrix_factorization:
                problems.append(
                    f"coordinate '{name}': matrix factorization does not "
                    "stream; drop --streaming-chunks or the MF coordinate"
                )
            if (
                not c.is_random_effect
                and not c.is_matrix_factorization
                and c.optimizer == OptimizerType.NEWTON
            ):
                problems.append(
                    f"coordinate '{name}': NEWTON cannot stream the fixed "
                    "effect (dense [d, d] Hessian); use TRON or LBFGS"
                )
            if c.is_random_effect and c.projector != ProjectorType.IDENTITY:
                problems.append(
                    f"coordinate '{name}': projector {c.projector.name} "
                    "does not stream; use IDENTITY or train in-core"
                )
            if len(c.reg_weights) != 1:
                problems.append(
                    f"coordinate '{name}': --streaming-chunks trains one "
                    "λ per coordinate; pass a single reg.weights value"
                )
            if c.reg_alpha > 0.0:
                problems.append(
                    f"coordinate '{name}': elastic-net L1 does not stream "
                    "on the GAME path; set reg.alpha=0 or train in-core"
                )
            if c.compute_variance:
                problems.append(
                    f"coordinate '{name}': variances need the in-core "
                    "Hessian path; drop compute.variance or "
                    "--streaming-chunks"
                )
            if c.down_sampling_rate < 1.0:
                problems.append(
                    f"coordinate '{name}': down-sampling does not stream "
                    "yet; use down.sampling.rate=1"
                )
            if (
                c.is_random_effect
                and (c.active_data_lower_bound or c.active_data_upper_bound)
            ):
                problems.append(
                    f"coordinate '{name}': active-data bounds are not "
                    "supported streamed; drop them or train in-core"
                )


def _parse_changed_entities(specs) -> dict:
    """'reType=key1|key2' specs -> {reType: (keys...)} (repeatable,
    same-type specs merge)."""
    out: dict = {}
    for spec in specs:
        typ, sep, keys = str(spec).partition("=")
        typ = typ.strip()
        if not sep or not typ:
            raise ValueError(
                f"bad --refresh-changed-entities {spec!r}; expected "
                "reType=key1|key2"
            )
        out.setdefault(typ, [])
        out[typ] += [k for k in keys.split("|") if k]
    return {k: tuple(v) for k, v in out.items()}


def _trace_exchange():
    """Exchange for run-end trace publication + straggler merge: the
    coordination-service KV transport on multi-process runs (EVERY rank's
    run() reaches this finally, so the collective discipline holds),
    trivial single-process."""
    from photon_ml_tpu.parallel.multihost import default_exchange

    return default_exchange()


def run(params: GameTrainingParams) -> dict:
    """Execute the training pipeline; returns a result summary dict."""
    params.validate()
    import jax

    if jax.process_count() > 1:
        # Multi-process pods: every process executes the same SPMD program
        # (reads the same inputs, joins every collective), but filesystem
        # outputs belong to process 0 — workers write into a scratch
        # subdirectory. The checkpoint directory stays SHARED: all processes
        # restore from it, train_distributed writes it from process 0 only.
        if not (
            params.distributed or params.mesh_shape
            or (params.streaming_chunks > 0 and params.partitioned_io)
        ):
            # the host-loop CD path has no cross-process coordination (every
            # rank would train redundantly and race on the shared
            # checkpoint directory)
            raise ValueError(
                "multi-process runs require --distributed or --mesh "
                "(the fused SPMD training path) or --streaming-chunks with "
                "--partitioned-io (the partitioned streamed GAME path)"
            )
        if jax.process_index() > 0:
            params = dataclasses.replace(
                params,
                root_output_dir=os.path.join(
                    params.root_output_dir, f".worker-{jax.process_index()}"
                ),
                override_output=True,
            )
    out = params.root_output_dir
    # ignore worker scratch dirs: a faster rank may create out/.worker-N
    # before rank 0's emptiness check runs
    existing = (
        [e for e in os.listdir(out) if not e.startswith(".worker-")]
        if os.path.isdir(out) else []
    )
    if existing and not params.override_output:
        raise ValueError(
            f"output dir {out!r} is non-empty (pass --override-output to replace)"
        )
    os.makedirs(out, exist_ok=True)

    # per-run phase timings + solver/layout tallies (a sweep may call run()
    # repeatedly)
    reset_timings()
    reset_solver_metrics()
    reset_layout_metrics()
    reset_resilience_metrics()
    reset_refresh_metrics()
    events.send(TrainingStartEvent(job_name="game-training"))
    job_log = PhotonLogger(os.path.join(out, "driver.log"))
    # rank-gated journal: inert on worker ranks, so telemetry calls below
    # are unconditional (collectives must still run on EVERY rank). The
    # journal + registry sinks are opt-in via --telemetry-dir; the emitter
    # rides along for any registered listener. With no live sink,
    # SolverTelemetry skips row-building entirely, so default runs pay no
    # per-coordinate device-to-host reads (~100 ms dispatch each on the
    # tunneled TPU — CLAUDE.md).
    journal = RunJournal(params.telemetry_dir) if params.telemetry_dir else None
    telemetry = SolverTelemetry(
        journal=journal,
        emitter=events,
        # registry only where the journal will persist it (rank 0): worker
        # ranks would otherwise pay the row-building host reads for metrics
        # nobody reads
        registry=default_registry() if journal and journal.active else None,
    )
    compiles = CompileMonitor()
    # program ledger rides --telemetry-dir (ISSUE 13): labeled jit sites
    # (train/step, coord/*, scheduler/*, score/*) journal per-program
    # compile/cost rows with recompile attribution; inert without it
    ledger = None
    if journal is not None:
        from photon_ml_tpu.telemetry.program_ledger import (
            ProgramLedger,
            install_ledger,
        )

        ledger = install_ledger(ProgramLedger(journal=journal))
    # span tracing is opt-in via --trace-dir; installed before any stage so
    # a failure mid-read still leaves a timeline on every rank
    tracer = None
    if params.trace_dir:
        from photon_ml_tpu.telemetry.tracing import Tracer, install_tracer

        tracer = install_tracer(Tracer())
    succeeded = False
    try:
        from photon_ml_tpu.util.timed import profile_trace

        with profile_trace(params.profile_dir), compiles:
            summary = _run_inner(params, job_log, telemetry)
        succeeded = True
        return summary
    except Exception:
        events.send(TrainingFinishEvent(job_name="game-training", succeeded=False))
        raise
    finally:
        # traces flush FIRST (before the failure journal rows) so a crash
        # leaves a readable per-rank timeline. Success path: the straggler
        # tables merge over the exchange and publication is barriered
        # (rank-0 mkdir, barrier, per-rank write); failure path: no new
        # collectives — local report, unbarriered per-rank write.
        if tracer is not None:
            from photon_ml_tpu.telemetry.tracing import (
                flush_trace_best_effort,
                uninstall_tracer,
            )

            try:
                # best-effort: a publication error or a mixed-outcome
                # straggler-merge timeout never masks the run's own
                # outcome or skips the journal rows below
                flush_trace_best_effort(
                    tracer, params.trace_dir,
                    exchange=_trace_exchange() if succeeded else None,
                    gather=succeeded,
                    journal=journal,
                )
            finally:
                uninstall_tracer()
        if ledger is not None:
            from photon_ml_tpu.telemetry.program_ledger import uninstall_ledger

            uninstall_ledger()
        # journal phase timings / gauges on failure too — a failed run's
        # journal is the one that most needs them. The registry snapshot
        # carries the resilience/* counters (retries, giveups,
        # quarantined_blocks, checkpoint_restores); quarantined block
        # SPANS get one forensic row each.
        if journal is not None:
            from photon_ml_tpu.telemetry import resilience_counters

            for event in resilience_counters.drain_quarantine_events():
                journal.record("quarantined_block", **event)
            journal.record_timings(timing_summary())
            journal.record_gauge("jax/backend_compile_count", compiles.count)
            journal.record_gauge("device/live_buffer_bytes", live_buffer_bytes())
            journal.record_metrics(default_registry().snapshot())
            journal.close()
        job_log.close()


def _run_inner(
    params: GameTrainingParams,
    job_log: PhotonLogger,
    telemetry: SolverTelemetry | None = None,
) -> dict:
    if params.incremental_refresh:
        # the refresh mode reads the data in the RESIDENT model's feature
        # space (its index maps + entity vocabs) — a separate pipeline
        return _run_refresh(params, job_log, telemetry)
    if params.streaming_chunks > 0:
        # the out-of-core path does its own streaming scans — the full
        # read below would materialize exactly what it exists to avoid
        return _run_streaming(params, job_log, telemetry)
    out = params.root_output_dir
    entity_columns = {
        c.random_effect_type
        for c in params.coordinates.values()
        if c.random_effect_type
    }
    for c in params.coordinates.values():
        # MF coordinates consume two entity-id columns (row + col)
        if c.is_matrix_factorization:
            entity_columns.update((c.mf_row_effect_type, c.mf_col_effect_type))
    re_columns = tuple(sorted(entity_columns))
    eval_columns = evaluation_id_columns(params.evaluators)

    def resolve(path, range_spec):
        if not range_spec:
            return path
        from photon_ml_tpu.util.date_range import (
            parse_date_or_days_range,
            resolve_input_paths,
        )

        return resolve_input_paths([path], parse_date_or_days_range(range_spec))

    prebuilt_maps = None
    if params.index_maps_dir:
        # reference GameDriver.prepareFeatureMaps (GameDriver.scala:195-240):
        # reuse stores built by the feature-indexing driver (plain .keys or
        # native off-heap .photonix) instead of scanning the data.
        # validate() already checked existence + shard coverage.
        prebuilt_maps = IndexMap.load_directory(params.index_maps_dir)

    # the mesh exists BEFORE ingestion: partitioned reads align their
    # per-rank blocks with the mesh's addressable shards
    import jax

    mesh = None
    model_axis = 1
    if params.distributed or params.mesh_shape:
        # the multi-chip entry point: one ("data", "model") mesh over all
        # (possibly multi-process) devices, topology-aware across slices
        from photon_ml_tpu.parallel.multihost import make_hybrid_mesh

        shape = dict(params.mesh_shape or {})
        model_axis = int(shape.get("model", 1))
        mesh = make_hybrid_mesh(
            data=shape.get("data"), model=model_axis
        )
        job_log.info(
            "distributed mode: mesh %s over %d devices",
            dict(zip(mesh.axis_names, mesh.devices.shape)), mesh.devices.size,
        )

    # partitioned host I/O: each rank decodes ~1/P of the bytes
    # (io/partitioned_reader.py). exchange/pad_multiple resolve to the
    # trivial single-rank values unless --partitioned-io on a multi-process
    # run, so the single-process path reads byte-identically to before.
    exchange = None
    coordinator = None
    pad_multiple = 1
    if params.partitioned_io and jax.process_count() > 1:
        from photon_ml_tpu.parallel.multihost import default_exchange

        if mesh is None:
            raise ValueError(
                "--partitioned-io requires --distributed or --mesh (the "
                "partitioned blocks feed a mesh's addressable shards)"
            )
        exchange = default_exchange()
        # coordinated multi-rank recovery (ISSUE 15): fence the run's ONE
        # exchange into restart generations and attach the coordinator to
        # every run_with_recovery below — a preempted rank then becomes an
        # attributed all-rank rollback to the last barrier-committed
        # checkpoint instead of a whole-job ExchangeTimeout death. The
        # budget is SHARED across ranks AND grid configs (one job, one
        # budget). Host-side KV only: no device collective is added,
        # skipped, or reordered.
        from photon_ml_tpu.resilience import CoordinatedRecovery

        coordinator = CoordinatedRecovery(
            exchange,
            max_restarts=params.max_restarts,
            journal=telemetry.journal if telemetry is not None else None,
            description="partitioned game train",
        )
        data_axis = int(mesh.shape["data"])
        if data_axis % exchange.num_ranks:
            raise ValueError(
                f"--partitioned-io: mesh data axis {data_axis} must be a "
                f"multiple of the process count {exchange.num_ranks}"
            )
        pad_multiple = data_axis // exchange.num_ranks
        if params.validation_data_path:
            raise ValueError(
                "--partitioned-io does not support validation data yet; "
                "score + evaluate with the partitioned scoring driver"
            )

    # transient-I/O retry for the ingestion boundary — ONLY when the read
    # is not collective: retrying one rank of a partitioned (exchange-
    # coordinated) read would desynchronize the SPMD exchange sequence,
    # so the collective path keeps its deadlines (ExchangeTimeout) instead
    from photon_ml_tpu.resilience import default_io_policy

    def _read(description, fn):
        if exchange is not None:
            return fn()
        return default_io_policy().call(fn, description=description)

    with Timed("read training data"):
        train_part = _read(
            "read training data",
            lambda: read_partitioned(
                resolve(params.input_data_path, params.input_date_range),
                params.feature_shards,
                exchange=exchange,
                index_maps=prebuilt_maps,
                random_effect_id_columns=re_columns,
                evaluation_id_columns=eval_columns,
                fmt=params.input_format,
                pad_multiple=pad_multiple,
                tag="train",
                on_corrupt=params.on_corrupt,
            ),
        )
        train = train_part.result
    partition = train_part.partition
    job_log.info(
        "read %d training samples%s, shards %s",
        train.dataset.num_samples,
        (
            f" (rank {partition.rank}/{partition.num_ranks}, "
            f"{train_part.bytes_decoded}/{train_part.input_bytes_total} "
            "bytes decoded)"
            if partition.num_ranks > 1 else ""
        ),
        {k: v.size for k, v in train.index_maps.items()},
    )

    validation = None
    if params.validation_data_path:
        with Timed("read validation data"):
            validation = _read(
                "read validation data",
                lambda: read_partitioned(
                    resolve(
                        params.validation_data_path,
                        params.validation_data_date_range,
                    ),
                    params.feature_shards,
                    index_maps=train.index_maps,
                    random_effect_id_columns=re_columns,
                    evaluation_id_columns=eval_columns,
                    entity_vocabs=train.dataset.entity_vocabs,
                    fmt=params.input_format,
                    tag="validation",
                    on_corrupt=params.on_corrupt,
                ),
            ).result

    with Timed("validate data"):
        validate_game_dataset(train.dataset, params.task_type, params.data_validation)
        if validation is not None:
            validate_game_dataset(
                validation.dataset, params.task_type, params.data_validation
            )

    with Timed("feature shard stats"):
        from photon_ml_tpu.io.index_map import IdentityIndexMap

        if partition.num_ranks > 1:
            # rank-local rows: a per-rank stats file would summarize 1/P of
            # the data and masquerade as global statistics
            logger.info("partitioned ingest: skipping feature stats "
                        "(rank-local rows)")
        for shard_id, features in (
            {} if partition.num_ranks > 1 else train.dataset.feature_shards
        ).items():
            imap = train.index_maps[shard_id]
            if isinstance(imap, IdentityIndexMap) and imap.size > (1 << 20):
                # pre-indexed giant-d space: a per-column stats file would
                # be d records — skip (stats exist for name-term shards)
                logger.info(
                    "skipping feature stats for pre-indexed shard '%s' "
                    "(d=%d)", shard_id, imap.size,
                )
                continue
            if isinstance(features, SparseShard):
                stats = features.summarize(np.asarray(train.dataset.weights))
            else:
                stats = summarize(np.asarray(features), np.asarray(train.dataset.weights))
            write_feature_stats(
                os.path.join(out, "feature-stats", shard_id, "part-00000.avro"),
                stats,
                imap,
            )

    initial_model = None
    if params.model_input_dir:
        with Timed("load warm-start model"):
            initial_model = load_game_model(
                params.model_input_dir, train.index_maps,
                compact_random_effect_threshold=(
                    params.compact_random_effect_threshold
                ),
            )

    # save index maps next to the models so scoring is self-contained;
    # plain maps (built here OR prebuilt .keys) are cheap to copy, while
    # off-heap stores stay where they are (scoring takes --index-maps-dir)
    for shard_id, imap in train.index_maps.items():
        if isinstance(imap, IndexMap):
            imap.save(os.path.join(out, "index-maps"), shard_id)

    estimator_partition = None
    if partition.num_ranks > 1:
        from photon_ml_tpu.estimators import TrainPartition

        estimator_partition = TrainPartition(
            info=partition,
            exchange=exchange,
            lane_multiple=pad_multiple,
            entity_rank_presence=train_part.entity_rank_presence,
        )

    def make_estimator(
        reg_weights, checkpointer=None, resume=None, resume_step=None
    ) -> GameEstimator:
        return GameEstimator(
            task=params.task_type,
            coordinate_configs=estimator_coordinate_configs(
                params.coordinates, reg_weights
            ),
            update_sequence=params.update_sequence or None,
            num_iterations=params.coordinate_descent_iterations,
            normalization=params.normalization,
            validation_evaluators=params.evaluators,
            locked_coordinates=frozenset(params.partial_retrain_locked_coordinates),
            intercept_indices=train.intercept_indices,
            checkpointer=checkpointer,
            checkpoint_every=params.checkpoint_every,
            resume=params.resume if resume is None else resume,
            resume_step=resume_step,
            mesh=mesh,
            fe_feature_sharded=model_axis > 1,
            telemetry=telemetry,
            partition=estimator_partition,
        )

    def make_checkpointer(config_index: int, reg_weights):
        if not params.checkpoint_dir:
            return None
        import hashlib

        from photon_ml_tpu.io.checkpoint import TrainingCheckpointer

        # key the directory by the configuration CONTENT, not just its grid
        # position — editing the λ grid between runs must not resume a
        # checkpoint trained under different regularization weights
        digest = hashlib.sha256(
            json.dumps(sorted(reg_weights.items()), default=float).encode()
        ).hexdigest()[:12]
        return TrainingCheckpointer(
            os.path.join(params.checkpoint_dir, f"config_{config_index}_{digest}")
        )

    grid = expand_reg_weight_grid(params.coordinates)
    job_log.info("expanded λ grid to %d configurations", len(grid))
    if telemetry is not None and telemetry.journal is not None:
        telemetry.journal.record(
            "config",
            task_type=params.task_type.name,
            distributed=mesh is not None,
            num_configurations=len(grid),
            coordinate_configurations={
                name: format_coordinate_config(cfg)
                for name, cfg in params.coordinates.items()
            },
            update_sequence=list(
                params.update_sequence or params.coordinates.keys()
            ),
            coordinate_descent_iterations=params.coordinate_descent_iterations,
            # lane-scheduled coordinates (algorithm/lane_scheduler.py): the
            # scheduler/* counters + solver/lane_iters histogram land in the
            # registry snapshot journaled on success AND failure paths
            scheduled_coordinates=[
                name for name, cfg in params.coordinates.items() if cfg.scheduler
            ],
        )
    first_evaluator = parse_evaluator(params.evaluators[0]) if params.evaluators else None

    from photon_ml_tpu.resilience import run_with_recovery

    results = []
    warm_model = initial_model
    best_index, best_metric = -1, float("nan")
    for i, reg_weights in enumerate(grid):
        with Timed(f"train config {i}"):
            # crash-safe sweep: a DivergenceError (with a checkpoint to
            # restore) or classified-transient failure restarts this
            # configuration — the re-created estimator resumes from the
            # latest intact checkpoint — instead of aborting the run
            initial = warm_model
            ckpt = make_checkpointer(i, reg_weights)

            def attempt(restart: int, _rw=reg_weights, _ck=ckpt, _init=initial):
                est = make_estimator(
                    _rw,
                    _ck,
                    # restarts must resume even under --no-resume (the
                    # whole point of the restart is the checkpoint)
                    resume=params.resume or restart > 0,
                    # a coordinated restart restores the PUBLISHED step on
                    # every rank, never each rank's own local newest
                    resume_step=(
                        coordinator.resume_step
                        if coordinator is not None else None
                    ),
                )
                return est.fit(
                    train.dataset,
                    validation_dataset=(
                        None if validation is None else validation.dataset
                    ),
                    initial_model=_init,
                )

            if coordinator is not None:
                # the rollback step is resolved against THIS config's
                # checkpoint directory (per-config dirs are content-keyed);
                # rebind also clears any resume step published for the
                # PREVIOUS config's rollback
                coordinator.rebind(ckpt)
            result = run_with_recovery(
                attempt,
                max_restarts=params.max_restarts,
                checkpointer=ckpt,
                journal=telemetry.journal if telemetry is not None else None,
                description=f"train config {i}",
                coordinator=coordinator,
            )
        # warm start the next grid point (reference GameEstimator.fit:352-366)
        warm_model = result.model
        results.append((reg_weights, result))
        metric = result.best_metric
        job_log.info("config %d %s -> metric %s", i, reg_weights, metric)
        if first_evaluator is None:
            if best_index < 0:
                best_index = i
        elif best_index < 0 or first_evaluator.better_than(metric, best_metric):
            best_index, best_metric = i, metric

        if params.model_output_mode in (ModelOutputMode.ALL, ModelOutputMode.EXPLICIT):
            save_game_model(
                os.path.join(out, "models", str(i)),
                result.best_model,
                train.index_maps,
                optimization_configurations={"regWeights": reg_weights},
            )

    summary: dict = {
        "distributed": mesh is not None,
        "num_configurations": len(grid),
        # effective configs in re-runnable CLI form (reference ScoptParameter
        # print-round-trip)
        "effective_coordinate_configurations": {
            name: format_coordinate_config(cfg)
            for name, cfg in params.coordinates.items()
        },
        "best_configuration_index": best_index,
        "best_reg_weights": grid[best_index],
        "best_metric": best_metric,
        "metric_history": [
            {"reg_weights": rw, "metrics": r.metric_history} for rw, r in results
        ],
    }

    # Save the grid best immediately (a later tuning failure must not cost
    # the already-trained model); if a tuned candidate wins the
    # best-over-all selection below it overwrites this directory
    # (reference GameTrainingDriver.selectModels:672-691).
    best_result = results[best_index][1]
    best_reg_weights = grid[best_index]
    if params.model_output_mode != ModelOutputMode.NONE:
        save_game_model(
            os.path.join(out, "best"),
            best_result.best_model,
            train.index_maps,
            optimization_configurations={"regWeights": best_reg_weights},
        )

    if params.hyperparameter_tuning != HyperparameterTuningMode.NONE:
        with Timed("hyperparameter tuning"):
            tunable = {
                name: params.hyperparameter_tuning_range
                for name in params.coordinates
                if name not in params.partial_retrain_locked_coordinates
            }
            tuner = GameHyperparameterTuner(
                estimator=make_estimator(grid[best_index]),
                reg_ranges=tunable,
                mode=params.hyperparameter_tuning,
            )
            priors = [
                (rw, r.best_metric)
                for rw, r in results
                if not np.isnan(r.best_metric)
            ]
            if params.hyperparameter_prior_json:
                priors += load_prior_observations(params.hyperparameter_prior_json)
            tuned = tuner.tune(
                train.dataset,
                validation.dataset,
                num_iterations=params.hyperparameter_tuning_iter,
                prior_observations=priors,
                # only TUNED/ALL need every candidate's model; the winner is
                # tracked O(1) either way (TuningResult.best_result)
                keep_models=params.model_output_mode
                in (ModelOutputMode.ALL, ModelOutputMode.TUNED),
            )
        save_tuned_config(tuned, os.path.join(out, "tuned-hyperparameters.json"))
        summary["tuned_reg_weights"] = tuned.best_reg_weights
        summary["tuned_metric"] = tuned.best_value
        if params.model_output_mode in (ModelOutputMode.ALL, ModelOutputMode.TUNED):
            for j, (reg, r) in enumerate(tuned.tuned_results):
                save_game_model(
                    os.path.join(out, "models-tuned", str(j)),
                    r.best_model,
                    train.index_maps,
                    optimization_configurations={"regWeights": reg},
                )
        # best over explicit + tuned (first evaluator decides)
        if first_evaluator is not None and tuned.best_result is not None:
            reg, r = tuned.best_result
            if not np.isnan(r.best_metric) and first_evaluator.better_than(
                r.best_metric, best_metric
            ):
                best_metric, best_result, best_reg_weights = (
                    r.best_metric, r, reg
                )
                summary["best_metric"] = best_metric
                summary["best_reg_weights"] = best_reg_weights
                # the grid index no longer identifies the winner
                summary["best_configuration_index"] = None
                summary["best_is_tuned"] = True
                if params.model_output_mode != ModelOutputMode.NONE:
                    save_game_model(
                        os.path.join(out, "best"),
                        best_result.best_model,
                        train.index_maps,
                        optimization_configurations={
                            "regWeights": best_reg_weights
                        },
                    )

    summary["timings"] = timing_summary()
    with open(os.path.join(out, "training-summary.json"), "w") as f:
        json.dump(_json_safe(summary), f, indent=2, default=float)
    events.send(TrainingFinishEvent(job_name="game-training", succeeded=True))
    return summary


def _run_streaming(
    params: GameTrainingParams,
    job_log: PhotonLogger,
    telemetry: SolverTelemetry | None = None,
) -> dict:
    """The --streaming-chunks GAME pipeline (ISSUE 11): one streaming scan
    (index maps + entity vocabs + cluster keys, records discarded), an
    entity-clustered chunk source, and StreamingGameProgram sweeps — the
    input never materializes in core, so n is bounded by disk, not HBM.
    With --partitioned-io on a multi-process run (ISSUE 17) the chunk plan
    is agreed over the metadata exchange, each rank streams only its own
    entity-clustered chunk slice, and sweeps recover through the
    coordinated all-rank rollback — n is then bounded by the fleet's
    disks. validate() already restricted the surface (dense single FE +
    IDENTITY REs, one λ)."""
    import jax  # noqa: F401  (platform selection must already be done)

    from photon_ml_tpu.algorithm.streaming_game import (
        DuHLChunkSchedule,
        DuHLScheduleConfig,
        StreamingGameProgram,
        score_game_stream,
    )
    from photon_ml_tpu.io import avro as avro_io
    from photon_ml_tpu.io.checkpoint import TrainingCheckpointer
    from photon_ml_tpu.io.stream_reader import (
        GameAvroChunkSource,
        plan_partitioned_game_stream,
        scan_game_stream,
    )
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_ml_tpu.models.glm import GeneralizedLinearModel
    from photon_ml_tpu.parallel.distributed import (
        FixedEffectStepSpec,
        RandomEffectStepSpec,
    )
    from photon_ml_tpu.resilience import run_with_recovery
    from photon_ml_tpu.telemetry import stream_counters

    out = params.root_output_dir
    sequence = tuple(params.update_sequence or params.coordinates.keys())
    fe_name = next(
        n for n in sequence
        if not params.coordinates[n].is_random_effect
    )
    fe_cfg = params.coordinates[fe_name]
    re_names = [n for n in sequence if n != fe_name]
    cluster_by = (
        params.coordinates[re_names[0]].random_effect_type
        if re_names else None
    )
    shard_ids = {fe_cfg.feature_shard} | {
        params.coordinates[n].feature_shard for n in re_names
    }
    shard_configs = {s: params.feature_shards[s] for s in shard_ids}
    re_columns = tuple(sorted(
        params.coordinates[n].random_effect_type for n in re_names
    ))

    exchange = None
    coordinator = None
    partition = None
    scalars = None
    if params.partitioned_io and jax.process_count() > 1:
        from photon_ml_tpu.parallel.multihost import default_exchange
        from photon_ml_tpu.resilience import CoordinatedRecovery

        if cluster_by is None:
            raise ValueError(
                "--partitioned-io streamed GAME needs at least one random-"
                "effect coordinate (its entities define the chunk "
                "partition); drop --partitioned-io or add one"
            )
        if params.validation_data_path:
            raise ValueError(
                "--partitioned-io streamed GAME has no multi-rank "
                "validation pass; drop --validation-data-path and score "
                "with the scoring driver"
            )
        exchange = default_exchange()
        schedule_budget = (
            {"working_set": params.duhl_working_set,
             "tail_chunks": params.duhl_tail_chunks}
            if params.duhl_working_set > 0 else None
        )
        with Timed("streaming scan"):
            source, index_maps, vocabs, partition = (
                plan_partitioned_game_stream(
                    params.input_data_path, shard_configs, re_columns,
                    exchange=exchange,
                    chunk_records=params.streaming_chunks,
                    cluster_by=cluster_by,
                    schedule_budget=schedule_budget,
                    on_corrupt=params.on_corrupt,
                )
            )
        job_log.info(
            "partitioned streamed plan %s: rank %d/%d holds chunks "
            "[%d, %d) of %d (payload %d/%d input bytes)",
            partition.fingerprint, partition.rank, partition.num_ranks,
            *partition.chunk_range(), partition.num_chunks,
            partition.payload_bytes[partition.rank], partition.input_bytes,
        )
        # coordinated multi-rank recovery (ISSUE 15, applied to the
        # streamed path): fence the run's ONE exchange into restart
        # generations so a preempted rank becomes an attributed all-rank
        # rollback to the last barrier-committed sweep. Host-side KV only.
        coordinator = CoordinatedRecovery(
            exchange,
            max_restarts=params.max_restarts,
            journal=telemetry.journal if telemetry is not None else None,
            description="partitioned streamed game train",
        )
    else:
        files = avro_io.list_avro_files(params.input_data_path)
        with Timed("streaming scan"):
            index_maps, vocabs, cluster_keys, indexes, scalars = (
                scan_game_stream(
                    files, shard_configs, re_columns,
                    cluster_by=cluster_by, on_corrupt=params.on_corrupt,
                )
            )
        source = GameAvroChunkSource(
            files, shard_configs, index_maps,
            chunk_records=params.streaming_chunks,
            random_effect_id_columns=re_columns,
            entity_vocabs=vocabs,
            cluster_by=cluster_by,
            cluster_keys=cluster_keys,
            indexes=indexes,
            on_corrupt=params.on_corrupt,
        )
    job_log.info(
        "streaming scan: %d files, shards %s, entities %s",
        len(source.files), {k: v.size for k, v in index_maps.items()},
        {k: len(v) for k, v in vocabs.items()},
    )
    for shard_id, imap in index_maps.items():
        if isinstance(imap, IndexMap):
            imap.save(os.path.join(out, "index-maps"), shard_id)
    job_log.info(
        "planned %d entity-clustered chunks (<=%d records requested, "
        "chunk_rows=%d)",
        source.num_chunks, params.streaming_chunks, source.chunk_rows,
    )

    def opt_config(cfg):
        return cfg.optimization_config(cfg.reg_weights[0])

    fe_opt = opt_config(fe_cfg)
    fe_spec = FixedEffectStepSpec(
        feature_shard_id=fe_cfg.feature_shard,
        optimizer=fe_opt.optimizer,
        l2_weight=fe_opt.l2_weight,
    )
    re_specs = []
    for n in re_names:
        cfg = params.coordinates[n]
        o = opt_config(cfg)
        re_specs.append(RandomEffectStepSpec(
            re_type=cfg.random_effect_type,
            feature_shard_id=cfg.feature_shard,
            optimizer=o.optimizer,
            l2_weight=o.l2_weight,
        ))

    schedule = None
    if params.duhl_working_set > 0:
        # the schedule spans GLOBAL chunks when partitioned — every rank
        # drives the same schedule from the same allgathered signal
        schedule = DuHLChunkSchedule(
            DuHLScheduleConfig(
                working_set_chunks=params.duhl_working_set,
                tail_chunks_per_sweep=params.duhl_tail_chunks,
            ),
            partition.num_chunks if partition is not None
            else source.num_chunks,
        )
    checkpointer = (
        TrainingCheckpointer(
            os.path.join(params.checkpoint_dir, "streaming-game")
        )
        if params.checkpoint_dir else None
    )

    with Timed("streamed game train"):
        def attempt(restart: int):
            program = StreamingGameProgram(
                params.task_type, source, fe_spec, tuple(re_specs),
                num_entities={t: len(vocabs[t]) for t in re_columns},
                schedule=schedule,
                prefetch=params.streaming_prefetch,
                exchange=exchange,
                partition=partition,
                # the scan pass already collected the [n] scalars — the
                # program skips its decode fallback entirely (partitioned
                # plans collect per-rank scalars in the program's own
                # chunk pass instead)
                scalars=scalars,
            )
            return program.train(
                num_sweeps=params.coordinate_descent_iterations,
                checkpointer=checkpointer,
                resume=params.resume or restart > 0,
                # a coordinated restart restores the PUBLISHED step on
                # every rank, never each rank's own local newest
                resume_step=(
                    coordinator.resume_step
                    if coordinator is not None else None
                ),
                on_sweep=(
                    None if telemetry is None else
                    lambda sweep, total, loss: telemetry.heartbeat(
                        "game_streaming", sweep=sweep, num_sweeps=total,
                        loss=loss,
                    )
                ),
            )

        if coordinator is not None:
            coordinator.rebind(checkpointer)
        result = run_with_recovery(
            attempt,
            max_restarts=params.max_restarts,
            checkpointer=checkpointer,
            journal=telemetry.journal if telemetry is not None else None,
            description="streamed game train",
            coordinator=coordinator,
        )

    state = result.state
    models: dict = {
        fe_name: FixedEffectModel(
            glm=GeneralizedLinearModel(
                Coefficients(means=state.fe_coefficients),
                params.task_type,
            ),
            feature_shard_id=fe_cfg.feature_shard,
        )
    }
    for n, spec in zip(re_names, re_specs):
        models[n] = RandomEffectModel(
            coefficients=state.re_tables[spec.re_type],
            entity_keys=vocabs[spec.re_type],
            random_effect_type=spec.re_type,
            feature_shard_id=spec.feature_shard_id,
            task=params.task_type,
        )
    model = GameModel(models=models)
    if params.model_output_mode != ModelOutputMode.NONE:
        save_game_model(
            os.path.join(out, "best"), model, index_maps,
            optimization_configurations={
                "regWeights": {
                    n: params.coordinates[n].reg_weights[0] for n in sequence
                }
            },
        )

    # streamed validation scoring (ISSUE 17 rider): chunk-wise scores
    # against the streamed model through the SAME jitted steps the sweeps
    # use — pinned == in-core score_dataset + offsets to float round-off
    best_metric = float("nan")
    validation_metrics: dict = {}
    if params.validation_data_path:
        from photon_ml_tpu.evaluation.evaluators import (
            EvaluationData,
            parse_evaluator,
        )

        with Timed("streamed validation scoring"):
            val_source = GameAvroChunkSource(
                avro_io.list_avro_files(params.validation_data_path),
                shard_configs, index_maps,
                chunk_records=params.streaming_chunks,
                random_effect_id_columns=re_columns,
                entity_vocabs=vocabs,
                on_corrupt=params.on_corrupt,
            )
            val_scores, val_scalars = score_game_stream(
                state, val_source, params.task_type, fe_cfg.feature_shard,
                {spec.re_type: spec.feature_shard_id for spec in re_specs},
                prefetch=params.streaming_prefetch,
                return_scalars=True,
            )
        val_data = EvaluationData(
            labels=val_scalars["labels"],
            offsets=val_scalars["offsets"],
            weights=val_scalars["weights"],
            ids={},
        )
        for spec_str in params.evaluators:
            validation_metrics[spec_str] = float(
                parse_evaluator(spec_str).evaluate(val_scores, val_data)
            )
        if params.evaluators:
            best_metric = validation_metrics[params.evaluators[0]]
        job_log.info(
            "streamed validation: %d records, metrics %s",
            val_source.total_records, validation_metrics,
        )

    evidence = stream_counters.game_stream_evidence()
    summary: dict = {
        "distributed": False,
        "streaming": {
            "chunks": (
                partition.num_chunks if partition is not None
                else source.num_chunks
            ),
            "chunk_rows": source.chunk_rows,
            "records": (
                partition.total_records if partition is not None
                else source.total_records
            ),
            "schedule": "duhl" if schedule is not None else "uniform",
            **evidence,
            **(
                {} if partition is None else {
                    "partitioned": {
                        "plan": partition.fingerprint,
                        "rank": partition.rank,
                        "num_ranks": partition.num_ranks,
                        "chunk_range": list(partition.chunk_range()),
                        "rank_records": source.total_records,
                        "bytes_decoded": source.bytes_decoded,
                        "input_bytes": partition.input_bytes,
                    }
                }
            ),
        },
        "num_configurations": 1,
        "effective_coordinate_configurations": {
            name: format_coordinate_config(cfg)
            for name, cfg in params.coordinates.items()
        },
        "best_configuration_index": 0,
        "best_reg_weights": {
            n: params.coordinates[n].reg_weights[0] for n in sequence
        },
        "best_metric": best_metric,
        "validation_metrics": validation_metrics,
        "losses": [float(x) for x in result.losses],
        "metric_history": [],
    }
    if telemetry is not None and telemetry.journal is not None:
        telemetry.journal.record(
            "config",
            task_type=params.task_type.name,
            distributed=False,
            streaming_chunks=params.streaming_chunks,
            duhl_working_set=params.duhl_working_set,
            partitioned_ranks=(
                partition.num_ranks if partition is not None else 1
            ),
            num_configurations=1,
        )
    summary["timings"] = timing_summary()
    with open(os.path.join(out, "training-summary.json"), "w") as f:
        json.dump(_json_safe(summary), f, indent=2, default=float)
    events.send(TrainingFinishEvent(job_name="game-training", succeeded=True))
    return summary


def _run_refresh(
    params: GameTrainingParams,
    job_log: PhotonLogger,
    telemetry: SolverTelemetry | None = None,
) -> dict:
    """The --incremental-refresh pipeline (ISSUE 14, algorithm/refresh.py):
    load the resident model (saved directory, or warm-start re-entry from
    a training run's CD checkpoints), read the refresh data in ITS feature
    space, fingerprint-guard the agreement (layout + λ — a mismatch fails
    fast naming fields), then re-solve only the policy-selected
    random-effect entities against frozen residuals, under
    ``run_with_recovery`` with per-coordinate refresh checkpoints."""
    import jax  # noqa: F401  (platform selection must already be done)

    from photon_ml_tpu.algorithm.refresh import (
        RefreshPolicy,
        check_refresh_fingerprint,
        expected_fingerprint,
        model_fingerprint,
    )
    from photon_ml_tpu.cli.game_scoring_driver import _load_scoring_model
    from photon_ml_tpu.io.checkpoint import (
        TrainingCheckpointer,
        latest_trained_model,
    )
    from photon_ml_tpu.resilience import default_io_policy, run_with_recovery

    out = params.root_output_dir
    reg_weights = {
        name: cfg.reg_weights[0] for name, cfg in params.coordinates.items()
    }

    saved_reg_weights = None
    if params.model_input_dir:
        model, index_maps, feature_shards, entity_vocabs, re_columns = (
            _load_scoring_model(
                model_input_dir=params.model_input_dir,
                index_maps_dir=params.index_maps_dir,
                feature_shards=params.feature_shards,
                compact_random_effect_threshold=(
                    params.compact_random_effect_threshold
                ),
            )
        )
        meta_path = os.path.join(params.model_input_dir, "model-metadata.json")
        if os.path.exists(meta_path):
            with open(meta_path) as f:
                saved = (
                    json.load(f).get("optimizationConfigurations") or {}
                ).get("regWeights")
            if isinstance(saved, dict):
                saved_reg_weights = {k: float(v) for k, v in saved.items()}
    else:
        # warm-start re-entry from PR 8 checkpoint state: the training
        # run's CD checkpoint directory IS the resident model
        with Timed("restore resident model from checkpoint"):
            restored = latest_trained_model(
                TrainingCheckpointer(params.checkpoint_dir)
            )
        if restored is None:
            raise ValueError(
                f"--checkpoint-dir {params.checkpoint_dir!r} holds no "
                "loadable checkpoint; pass --model-input-dir (a saved "
                "model) instead"
            )
        model, step = restored
        job_log.info("resident model restored from checkpoint step %d", step)
        if not params.index_maps_dir:
            raise ValueError(
                "checkpoint warm-start re-entry needs --index-maps-dir "
                "(the training run's saved stores) so the refresh data "
                "reads in the resident model's feature space; or pass "
                "--model-input-dir"
            )
        index_maps = IndexMap.load_directory(params.index_maps_dir)
        feature_shards = params.feature_shards
        entity_vocabs = {}
        re_set = set()
        from photon_ml_tpu.models.game import RandomEffectModel
        from photon_ml_tpu.models.matrix_factorization import (
            MatrixFactorizationModel,
        )

        for m in model.models.values():
            if isinstance(m, RandomEffectModel):
                entity_vocabs[m.random_effect_type] = np.asarray(m.entity_keys)
                re_set.add(m.random_effect_type)
            elif isinstance(m, MatrixFactorizationModel):
                entity_vocabs[m.row_effect_type] = np.asarray(m.row_keys)
                entity_vocabs[m.col_effect_type] = np.asarray(m.col_keys)
                re_set.update((m.row_effect_type, m.col_effect_type))
        re_columns = tuple(sorted(re_set))

    with Timed("read refresh data"):
        part = default_io_policy().call(
            lambda: read_partitioned(
                params.input_data_path,
                feature_shards,
                index_maps=index_maps or None,
                random_effect_id_columns=re_columns,
                evaluation_id_columns=(),
                entity_vocabs=entity_vocabs,
                fmt=params.input_format,
                tag="refresh",
                on_corrupt=params.on_corrupt,
            ),
            description="read refresh data",
        )
        dataset = part.result.dataset
    job_log.info("read %d refresh samples", dataset.num_samples)

    sequence = list(params.update_sequence or params.coordinates.keys())
    coordinate_configs = estimator_coordinate_configs(
        params.coordinates, reg_weights
    )
    # the agreement guard: layout + λ, both sides' differing fields named.
    # λ only cross-checks when the saved model METADATA recorded it (the
    # checkpoint re-entry path has no regWeights record).
    expected = expected_fingerprint(
        dataset, coordinate_configs, sequence,
        reg_weights=reg_weights if saved_reg_weights is not None else None,
    )
    resident_fp = model_fingerprint(
        model, sequence, reg_weights=saved_reg_weights
    )
    check_refresh_fingerprint(resident_fp, expected)

    policy = RefreshPolicy(
        gradient_tolerance=(
            params.refresh_gradient_tolerance
            if params.refresh_gradient_tolerance > 0 else None
        ),
        changed_entities=_parse_changed_entities(
            params.refresh_changed_entities
        ),
        refresh_fixed_effects=params.refresh_fixed_effects,
    )
    refresh_ckpt = None
    if params.checkpoint_dir:
        import shutil

        refresh_dir = os.path.join(params.checkpoint_dir, "refresh")
        if not params.resume and os.path.isdir(refresh_dir):
            # --no-resume: purge stale refresh progress NOW, so a
            # mid-run transient restart (which always resumes — that's
            # what the checkpoint is for) resumes THIS run's steps, never
            # yesterday's completed refresh
            shutil.rmtree(refresh_dir)
        refresh_ckpt = TrainingCheckpointer(refresh_dir)
    estimator = GameEstimator(
        task=params.task_type,
        coordinate_configs=coordinate_configs,
        update_sequence=sequence,
        normalization=params.normalization,
        locked_coordinates=frozenset(params.partial_retrain_locked_coordinates),
        intercept_indices=part.result.intercept_indices,
        telemetry=telemetry,
    )
    if telemetry is not None and telemetry.journal is not None:
        telemetry.journal.record(
            "config",
            task_type=params.task_type.name,
            incremental_refresh=True,
            update_sequence=sequence,
            refresh_gradient_tolerance=params.refresh_gradient_tolerance,
            refresh_changed_entities={
                k: len(v) for k, v in policy.changed_entities.items()
            },
            refresh_fixed_effects=params.refresh_fixed_effects,
        )

    with Timed("incremental refresh"):
        def attempt(restart: int):
            return estimator.refresh(
                dataset, model, policy,
                checkpointer=refresh_ckpt,
                fingerprint=expected,
                # restarts must resume even under --no-resume (the whole
                # point of the restart is the checkpoint)
                resume=params.resume or restart > 0,
            )

        result = run_with_recovery(
            attempt,
            max_restarts=params.max_restarts,
            checkpointer=refresh_ckpt,
            journal=telemetry.journal if telemetry is not None else None,
            description="incremental refresh",
        )

    if params.model_output_mode != ModelOutputMode.NONE:
        save_game_model(
            os.path.join(out, "best"), result.model, index_maps,
            optimization_configurations={"regWeights": reg_weights},
        )
    summary: dict = {
        "distributed": False,
        # ONE source of truth: the RefreshResult (the refresh/* registry
        # counters carry the same numbers into the journal snapshot)
        "incremental_refresh": {
            "lanes_total": result.lanes_total,
            "lanes_solved": result.lanes_solved,
            "lanes_changed": result.lanes_changed,
            "lanes_gradient": result.lanes_gradient,
            "coordinates": result.coordinate_stats,
            "coordinates_refreshed": sum(
                1 for s in result.coordinate_stats.values()
                if s.get("refreshed")
            ),
            "coordinates_carried": sum(
                1 for s in result.coordinate_stats.values()
                if not s.get("refreshed")
            ),
        },
        "num_configurations": 1,
        "effective_coordinate_configurations": {
            name: format_coordinate_config(cfg)
            for name, cfg in params.coordinates.items()
        },
        "best_configuration_index": 0,
        "best_reg_weights": reg_weights,
        "best_metric": float("nan"),
        "metric_history": [],
    }
    summary["timings"] = timing_summary()
    with open(os.path.join(out, "training-summary.json"), "w") as f:
        json.dump(_json_safe(summary), f, indent=2, default=float)
    events.send(TrainingFinishEvent(job_name="game-training", succeeded=True))
    return summary


def _json_safe(obj):
    """NaN/Inf -> None so the summary is strict JSON."""
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    if isinstance(obj, float) and not np.isfinite(obj):
        return None
    return obj


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="game_training_driver", description=__doc__.split("\n")[0]
    )
    p.add_argument("--input-data-path", required=True)
    p.add_argument("--input-date-range",
                   help="yyyyMMdd-yyyyMMdd or N-M days ago: read "
                        "<input>/daily/yyyy/MM/dd dirs in the range")
    p.add_argument("--validation-data-path")
    p.add_argument("--validation-data-date-range")
    p.add_argument("--root-output-dir", required=True)
    p.add_argument(
        "--feature-shard-configurations", action="append", required=True,
        help="name=NAME,feature.bags=BAG|BAG,intercept=true (repeatable)",
    )
    p.add_argument(
        "--coordinate-configurations", action="append", required=True,
        help="name=NAME,feature.shard=SHARD,reg.weights=0.1|1,... (repeatable)",
    )
    p.add_argument("--task-type", required=True,
                   choices=[t.name for t in TaskType if t != TaskType.NONE])
    p.add_argument("--update-sequence", default="",
                   help="comma-separated coordinate order")
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument("--evaluators", default="", help="comma-separated specs")
    p.add_argument("--normalization", default="NONE",
                   choices=[n.name for n in NormalizationType])
    p.add_argument("--data-validation", default="VALIDATE_DISABLED",
                   choices=[v.name for v in DataValidationType])
    p.add_argument("--model-input-dir", help="warm-start model directory")
    p.add_argument("--partial-retrain-locked-coordinates", default="")
    p.add_argument("--model-output-mode", default="ALL",
                   choices=[m.name for m in ModelOutputMode])
    p.add_argument("--hyperparameter-tuning", default="NONE",
                   choices=[m.name for m in HyperparameterTuningMode])
    p.add_argument("--hyperparameter-tuning-iter", type=int, default=10)
    p.add_argument("--hyperparameter-tuning-range", default="1e-4,1e4",
                   help="low,high λ search range (log-scale)")
    p.add_argument("--hyperparameter-prior-json",
                   help="tuned-hyperparameters.json from a previous run, "
                        "used to seed the search")
    p.add_argument("--input-format", default="avro", choices=["avro", "libsvm"])
    p.add_argument("--index-maps-dir",
                   help="reuse index stores built by the feature indexing "
                        "driver (plain .keys or off-heap .photonix)")
    p.add_argument("--override-output", action="store_true")
    p.add_argument("--checkpoint-dir",
                   help="mid-training checkpoint/resume directory")
    p.add_argument("--checkpoint-every", type=int, default=1,
                   help="save every N coordinate updates")
    p.add_argument("--no-resume", action="store_true",
                   help="ignore existing checkpoints (fresh run)")
    p.add_argument("--profile-dir",
                   help="write a jax.profiler (TensorBoard) trace here")
    p.add_argument("--telemetry-dir",
                   help="write a rank-0 JSONL run journal (config, phase "
                        "timings, per-coordinate convergence rows, compile/"
                        "HBM gauges) here")
    p.add_argument("--trace-dir",
                   help="write per-rank Chrome-trace span timelines "
                        "(trace-{rank:05d}.json, open in Perfetto) + a "
                        "rank-merged straggler report here; flushed on "
                        "success and failure")
    p.add_argument("--compact-random-effect-threshold", type=int,
                   default=DEFAULT_COMPACT_RE_THRESHOLD,
                   help="warm-start RE models over this feature-space size "
                        "load as compact per-entity tables")
    p.add_argument("--distributed", action="store_true",
                   help="train through the fused mesh-sharded SPMD program "
                        "over all devices (multi-chip/multi-host path)")
    p.add_argument("--mesh", default="",
                   help="device mesh layout 'data=8,model=1' (implies "
                        "--distributed; model>1 shards the fixed-effect "
                        "feature axis)")
    p.add_argument("--partitioned-io", action="store_true",
                   help="multi-process runs: each rank decodes only ~1/P "
                        "of the input bytes (per-rank partitioned Avro "
                        "ingestion; dense IDENTITY configs, no validation "
                        "riders — see io/partitioned_reader.py)")
    p.add_argument("--on-corrupt", default="raise",
                   choices=["raise", "quarantine"],
                   help="corrupt Avro blocks: 'raise' (strict, default) "
                        "or 'quarantine' (skip-and-count; spans journaled "
                        "via resilience/quarantined_blocks)")
    p.add_argument("--max-restarts", type=int, default=2,
                   help="mid-sweep recovery budget: restore the latest "
                        "intact checkpoint and resume after a divergence/"
                        "transient failure up to N times (0 disables)")
    p.add_argument("--streaming-chunks", type=int, default=0,
                   help="out-of-core streamed GAME: records per chunk "
                        "(> 0 opts in; entity-clustered chunks stream "
                        "through the one-jitted-step accumulators — "
                        "dense single-FE + IDENTITY-RE configs over an "
                        "entity-sorted Avro input)")
    p.add_argument("--no-streaming-prefetch", action="store_true",
                   help="decode chunks inline instead of double-buffered "
                        "(the same-run OFF baseline for overlap evidence)")
    p.add_argument("--duhl-working-set", type=int, default=0,
                   help="DuHL importance-ordered schedule: pin this many "
                        "gap-hottest chunks resident and stream the cold "
                        "tail round-robin (0 = uniform order, bitwise the "
                        "unscheduled streamed sweep)")
    p.add_argument("--duhl-tail-chunks", type=int, default=1,
                   help="cold-tail chunks revisited per sweep under "
                        "--duhl-working-set")
    p.add_argument("--incremental-refresh", action="store_true",
                   help="incremental retrain (ISSUE 14): re-solve only the "
                        "RE entities that saw new data or whose gradient "
                        "at the resident solution exceeds tolerance, "
                        "against frozen residuals — needs --model-input-dir "
                        "or --checkpoint-dir (the resident model)")
    p.add_argument("--refresh-gradient-tolerance", type=float, default=1e-4,
                   help="re-solve entities whose solve-space gradient norm "
                        "at the resident solution exceeds this (0 disables "
                        "the screen: only declared entities re-solve)")
    p.add_argument("--refresh-changed-entities", action="append", default=[],
                   help="reType=key1|key2 — entities DECLARED changed "
                        "(repeatable; the gradient screen catches "
                        "undeclared drift)")
    p.add_argument("--refresh-fixed-effects", action="store_true",
                   help="also re-solve fixed-effect coordinates "
                        "(warm-started) during the refresh")
    return p


def parse_args(argv: Sequence[str] | None = None) -> GameTrainingParams:
    args = build_arg_parser().parse_args(argv)
    shards = dict(
        parse_feature_shard_config(s) for s in args.feature_shard_configurations
    )
    coords = {}
    for spec in args.coordinate_configurations:
        cfg = parse_coordinate_config(spec)
        if cfg.name in coords:
            raise ValueError(f"duplicate coordinate name {cfg.name!r}")
        coords[cfg.name] = cfg
    split = lambda s: tuple(x.strip() for x in s.split(",") if x.strip())
    return GameTrainingParams(
        input_data_path=args.input_data_path,
        input_date_range=args.input_date_range,
        validation_data_path=args.validation_data_path,
        validation_data_date_range=args.validation_data_date_range,
        root_output_dir=args.root_output_dir,
        feature_shards=shards,
        coordinates=coords,
        task_type=TaskType[args.task_type],
        update_sequence=split(args.update_sequence),
        coordinate_descent_iterations=args.coordinate_descent_iterations,
        evaluators=split(args.evaluators),
        normalization=NormalizationType[args.normalization],
        data_validation=DataValidationType[args.data_validation],
        model_input_dir=args.model_input_dir,
        partial_retrain_locked_coordinates=split(
            args.partial_retrain_locked_coordinates
        ),
        model_output_mode=ModelOutputMode[args.model_output_mode],
        hyperparameter_tuning=HyperparameterTuningMode[args.hyperparameter_tuning],
        hyperparameter_tuning_iter=args.hyperparameter_tuning_iter,
        hyperparameter_tuning_range=tuple(
            float(x) for x in args.hyperparameter_tuning_range.split(",")
        ),
        hyperparameter_prior_json=args.hyperparameter_prior_json,
        input_format=args.input_format,
        index_maps_dir=args.index_maps_dir,
        override_output=args.override_output,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_every=args.checkpoint_every,
        resume=not args.no_resume,
        profile_dir=args.profile_dir,
        telemetry_dir=args.telemetry_dir,
        trace_dir=args.trace_dir,
        compact_random_effect_threshold=args.compact_random_effect_threshold,
        distributed=args.distributed or bool(args.mesh),
        mesh_shape=_parse_mesh_shape(args.mesh),
        partitioned_io=args.partitioned_io,
        on_corrupt=args.on_corrupt,
        max_restarts=args.max_restarts,
        streaming_chunks=args.streaming_chunks,
        streaming_prefetch=not args.no_streaming_prefetch,
        duhl_working_set=args.duhl_working_set,
        duhl_tail_chunks=args.duhl_tail_chunks,
        incremental_refresh=args.incremental_refresh,
        refresh_gradient_tolerance=args.refresh_gradient_tolerance,
        refresh_changed_entities=tuple(args.refresh_changed_entities),
        refresh_fixed_effects=args.refresh_fixed_effects,
    )


def _parse_mesh_shape(spec: str) -> dict[str, int] | None:
    """'data=8,model=1' -> {"data": 8, "model": 1}; '' -> None."""
    if not spec:
        return None
    out: dict[str, int] = {}
    for part in spec.split(","):
        if not part.strip():
            continue
        key, _, value = part.partition("=")
        key = key.strip()
        if (
            key not in ("data", "model")
            or not value.strip().isdigit()
            or int(value) < 1
        ):
            raise ValueError(
                f"bad --mesh component {part!r}; expected data=N,model=M "
                "with N,M >= 1"
            )
        out[key] = int(value)
    return out


def main(argv: Sequence[str] | None = None) -> dict:
    logging.basicConfig(level=logging.INFO)
    # Multi-host pods: rendezvous before any jax.devices() call; a no-op for
    # single-process runs (parallel/multihost.py).
    from photon_ml_tpu.parallel import multihost

    multihost.initialize()
    return run(parse_args(argv))


if __name__ == "__main__":
    main()
