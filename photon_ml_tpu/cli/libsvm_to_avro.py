"""LibSVM text -> TrainingExampleAvro converter.

Reference parity: dev-scripts/libsvm_text_to_trainingexample_avro.py — the
reference's only Python tool, converting LibSVM files (e.g. a1a) into the
TrainingExampleAvro container format its drivers consume. Same field
mapping: feature name = str(0-based index), term = "", ±1 labels -> {0, 1}.

Usage:
    python -m photon_ml_tpu.cli.libsvm_to_avro \
        --input a1a --output data/train/part-00000.avro [--zero-based]
"""

from __future__ import annotations

import argparse
import os
from typing import Sequence

from photon_ml_tpu.io import avro as avro_io
from photon_ml_tpu.io import photon_schemas as schemas
from photon_ml_tpu.io.data_reader import read_libsvm


def convert(
    input_path: str | os.PathLike,
    output_path: str | os.PathLike,
    *,
    zero_based: bool = False,
) -> int:
    """Convert one LibSVM file; returns the number of records written.

    The record mapping lives in one place: data_reader.read_libsvm already
    yields TrainingExampleAvro-shaped dicts.
    """
    out_dir = os.path.dirname(str(output_path))
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    return avro_io.write_container(
        output_path,
        schemas.TRAINING_EXAMPLE_AVRO,
        read_libsvm(input_path, zero_based=zero_based),
    )


def main(argv: Sequence[str] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--input", required=True, help="LibSVM text file")
    p.add_argument("--output", required=True, help="output .avro path")
    p.add_argument("--zero-based", action="store_true",
                   help="feature indices in the input are 0-based")
    args = p.parse_args(argv)
    n = convert(args.input, args.output, zero_based=args.zero_based)
    print(f"wrote {n} records to {args.output}")
    return n


if __name__ == "__main__":
    main()
