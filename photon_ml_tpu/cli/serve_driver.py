"""Serve driver: offline replay harness for the resident scoring service.

Reference parity: photon-client cli/game/scoring/GameScoringDriver.scala —
the reference's scoring entry point is a batch job; this driver is the
ONLINE half the ROADMAP's heavy-traffic north star needs, exercised
offline: it loads a GAME model ONCE into a resident scorer
(serving/resident.py), replays an Avro file of scoring records as a stream
of small requests through the micro-batching loop (serving/batching.py),
and reports the latency-SLO evidence — scores/sec, p50/p95 request
latency, pad fraction, compiled-signature count — against an embedded
SAME-RUN one-request-per-dispatch baseline (the calibration discipline:
never compare across runs on the chip-lottery pool).

The replay is deliberately closed-loop (submit as fast as the bounded
queue admits): it measures the service's steady-state ceiling, not an
arrival process.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import threading
import time
from typing import Sequence

from photon_ml_tpu.cli.configs import parse_feature_shard_config
from photon_ml_tpu.io.model_io import DEFAULT_COMPACT_RE_THRESHOLD
from photon_ml_tpu.io.partitioned_reader import read_partitioned
from photon_ml_tpu.util import Timed

logger = logging.getLogger(__name__)

DEFAULT_SHAPES = "64,256,1024"


class _SwapPoller(threading.Thread):
    """Continuous zero-downtime refresh (ROADMAP item 2 rider): watch a
    directory for ATOMICALLY-RENAMED model subdirectories and hot-swap
    each through the guarded ``MicroBatchServer.swap_model`` API while the
    serving loop keeps draining. Appearance == completeness (publishers
    stage under a ``tmp.*``/dot-prefixed sibling and ``os.rename`` into
    place — the checkpoint discipline), so a half-written model is never
    loaded. A rejected swap (``ModelSwapError``: layout change) or an
    unloadable dir journals a typed ``model_swap`` row and serving
    CONTINUES on the resident model — one bad publish never takes the
    service down."""

    def __init__(self, server, watch_dir: str, poll_s: float, *,
                 index_maps, compact_threshold: int, journal=None):
        super().__init__(name="serve-swap-poller", daemon=True)
        self._server = server
        self._watch_dir = watch_dir
        self._poll_s = max(poll_s, 1e-3)
        self._index_maps = index_maps
        self._compact_threshold = compact_threshold
        self._journal = journal
        self._stop_event = threading.Event()
        self._seen: set[str] = set()
        self.polls = 0
        self.applied: list[str] = []
        self.rejected: list[dict] = []

    def stop(self) -> None:
        self._stop_event.set()
        self.join(timeout=10.0)

    def run(self) -> None:
        while not self._stop_event.is_set():
            self.scan_once()
            self._stop_event.wait(self._poll_s)
        # one final scan so a model published just before the replay
        # drained is not silently skipped
        self.scan_once()

    def scan_once(self) -> None:
        from photon_ml_tpu.io.model_io import load_game_model
        from photon_ml_tpu.serving import ModelSwapError

        self.polls += 1
        try:
            names = sorted(os.listdir(self._watch_dir))
        except OSError:
            return  # the watch dir may not exist yet — keep serving
        for name in names:
            # staged (not yet renamed) publishes are invisible by contract
            if name in self._seen or name.startswith((".", "tmp.")):
                continue
            path = os.path.join(self._watch_dir, name)
            if not os.path.isdir(path):
                continue
            self._seen.add(name)
            try:
                model = load_game_model(
                    path, self._index_maps,
                    compact_random_effect_threshold=self._compact_threshold,
                )
                self._server.swap_model(model)
            except Exception as e:  # noqa: BLE001 — thread boundary (below)
                # a bad PUBLISH must never take the poller (and with it,
                # every future refresh) down: a garbled model dir can
                # raise beyond the obvious types (struct/zlib/EOF damage
                # inside an intact-looking dir), and this daemon thread
                # has no caller to re-raise to — so this is a reviewed
                # host-boundary catch (lint check 5 allowlist): every
                # failure is journaled typed and serving continues on the
                # resident model. A FATAL classification (programming
                # error) is additionally logged loudly with the class
                # named, so a systematic bug is not mistaken for bad
                # publishes.
                from photon_ml_tpu.resilience import is_transient

                self.rejected.append({"dir": name, "error": repr(e)})
                log = (
                    logger.warning
                    if isinstance(e, (ModelSwapError, OSError, ValueError,
                                      KeyError)) or is_transient(e)
                    else logger.error
                )
                log("rejected hot swap of %s: %r", path, e)
                if self._journal is not None:
                    self._journal.record(
                        "model_swap", dir=name, applied=False,
                        error=repr(e),
                    )
                continue
            self.applied.append(name)
            logger.info("hot-swapped model from %s", path)
            if self._journal is not None:
                self._journal.record("model_swap", dir=name, applied=True)


def _parse_shapes(spec: str) -> tuple[int, ...]:
    try:
        shapes = tuple(int(s) for s in spec.split(",") if s.strip())
    except ValueError:
        raise ValueError(f"bad --microbatch-shapes {spec!r}") from None
    if not shapes:
        raise ValueError("--microbatch-shapes names no shapes")
    return shapes


def run(
    *,
    requests_avro: str,
    model_input_dir: str,
    output_dir: str,
    feature_shards: dict | None = None,
    index_maps_dir: str | None = None,
    input_format: str = "avro",
    compact_random_effect_threshold: int = DEFAULT_COMPACT_RE_THRESHOLD,
    microbatch_shapes: "tuple[int, ...] | str" = DEFAULT_SHAPES,
    max_wait_ms: float = 2.0,
    queue_depth: int = 1024,
    request_rows: int = 1,
    num_requests: int | None = None,
    bf16: bool = False,
    skip_unbatched_baseline: bool = False,
    swap_model_dir: str | None = None,
    swap_at_request: int | None = None,
    swap_poll_ms: float = 0.0,
    telemetry_dir: str | None = None,
    trace_dir: str | None = None,
) -> dict:
    """Replay ``requests_avro`` as ``request_rows``-row requests through
    the resident micro-batch scorer; writes ``serving-summary.json`` under
    ``output_dir``.

    microbatch_shapes: the bucket set (power-of-two row counts) — the
    bound on compiled program signatures. max_wait_ms/queue_depth: the SLO
    knobs of the micro-batching loop. bf16: opt-in whole-path bf16
    features (not bitwise). skip_unbatched_baseline: drop the embedded
    one-request-per-dispatch comparison (it costs one dispatch per
    request — slow over a ~100 ms tunnel when the replay is long).

    swap_model_dir: zero-downtime refresh rehearsal — a refreshed model
    (e.g. the incremental-refresh driver's output) hot-swapped IN-PLACE
    mid-replay through the guarded swap API while requests keep flowing;
    the summary's ``swap`` block carries the evidence (zero dropped
    requests, ledger-attributed score-program compiles across the swap ==
    0 on a same-layout model). swap_at_request: the submit index the swap
    fires before (default: halfway).

    swap_poll_ms > 0 switches ``swap_model_dir`` into CONTINUOUS mode
    (ROADMAP item 2 rider): the directory is WATCHED — every
    atomically-renamed model subdirectory that appears during the replay
    is loaded and hot-swapped in arrival order through the same guarded
    ``swap_model`` API (appearance == completeness: publishers must write
    to a ``tmp.*``/dot-prefixed sibling and ``os.rename`` into place, the
    checkpoint discipline). A rejected swap (layout change) journals a
    typed ``model_swap`` row and the loop KEEPS SERVING the resident
    model; the summary's ``swap`` block carries applied/rejected counts.

    telemetry_dir: rank-0 JSONL run journal (serve/* counters + latency
    histogram + phase timings) — written on the FAILURE path too.
    trace_dir: per-rank Chrome-trace span timelines; ``serve/`` spans
    observe the batching loop and dispatches, never gate them.
    """
    from photon_ml_tpu.telemetry import RunJournal
    from photon_ml_tpu.telemetry.resilience_counters import (
        reset_resilience_metrics,
    )
    from photon_ml_tpu.telemetry.serving_counters import reset_serving_metrics
    from photon_ml_tpu.util.timed import reset_timings, timing_summary

    # knowable before any load/warm work is paid: the two swap modes take
    # mutually exclusive knobs
    if swap_poll_ms > 0 and swap_at_request is not None:
        raise ValueError(
            "--swap-at-request names a submit index for the ONE-SHOT "
            "rehearsal swap, but --swap-poll-ms selects continuous mode, "
            "where swaps fire when a model dir APPEARS in "
            "--swap-model-dir; drop one of the two flags"
        )
    reset_timings()
    reset_resilience_metrics()
    reset_serving_metrics()
    journal = RunJournal(telemetry_dir) if telemetry_dir else None
    # the program ledger rides --telemetry-dir (ISSUE 13): every labeled
    # jit dispatch journals its compile/signature accounting, so a nonzero
    # replay compile count arrives WITH its attributed cause (the
    # program_recompile row naming the differing signature leaves)
    ledger = None
    if journal is not None:
        from photon_ml_tpu.telemetry.program_ledger import (
            ProgramLedger,
            install_ledger,
        )

        ledger = install_ledger(ProgramLedger(journal=journal))
    tracer = None
    if trace_dir:
        from photon_ml_tpu.telemetry.tracing import Tracer, install_tracer

        tracer = install_tracer(Tracer())
    succeeded = False
    try:
        summary = _run_inner(
            requests_avro=requests_avro,
            model_input_dir=model_input_dir,
            output_dir=output_dir,
            feature_shards=feature_shards,
            index_maps_dir=index_maps_dir,
            input_format=input_format,
            compact_random_effect_threshold=compact_random_effect_threshold,
            microbatch_shapes=microbatch_shapes,
            max_wait_ms=max_wait_ms,
            queue_depth=queue_depth,
            request_rows=request_rows,
            num_requests=num_requests,
            bf16=bf16,
            skip_unbatched_baseline=skip_unbatched_baseline,
            swap_model_dir=swap_model_dir,
            swap_at_request=swap_at_request,
            swap_poll_ms=swap_poll_ms,
            journal=journal,
        )
        succeeded = True
        if journal is not None:
            journal.record("serving_summary", **summary)
        return summary
    finally:
        if ledger is not None:
            from photon_ml_tpu.telemetry.program_ledger import uninstall_ledger

            uninstall_ledger()
        if tracer is not None:
            from photon_ml_tpu.telemetry.tracing import (
                flush_trace_best_effort,
                uninstall_tracer,
            )

            try:
                # best-effort: a publication error never masks the run's
                # own outcome or skips the journal rows below; the serve
                # driver is single-process, so no straggler merge
                flush_trace_best_effort(
                    tracer, trace_dir, exchange=None, gather=False,
                    journal=journal,
                )
            finally:
                uninstall_tracer()
        # failure-path journaling: the serve/* counters and the latency
        # histogram up to the failure are the post-mortem evidence
        if journal is not None:
            from photon_ml_tpu.telemetry import default_registry

            journal.record_timings(timing_summary())
            journal.record_metrics(default_registry().snapshot())
            journal.close()


def _run_inner(
    *,
    requests_avro: str,
    model_input_dir: str,
    output_dir: str,
    feature_shards: dict | None,
    index_maps_dir: str | None,
    input_format: str,
    compact_random_effect_threshold: int,
    microbatch_shapes,
    max_wait_ms: float,
    queue_depth: int,
    request_rows: int,
    num_requests: int | None,
    bf16: bool,
    skip_unbatched_baseline: bool,
    swap_model_dir: str | None = None,
    swap_at_request: int | None = None,
    swap_poll_ms: float = 0.0,
    journal=None,
) -> dict:
    import jax

    from photon_ml_tpu.cli.game_scoring_driver import _load_scoring_model
    from photon_ml_tpu.data.game_data import slice_game_dataset
    from photon_ml_tpu.serving import MicroBatchServer, ResidentScorer
    from photon_ml_tpu.telemetry import serving_counters
    from photon_ml_tpu.telemetry.probes import CompileMonitor

    if jax.process_count() > 1:
        raise ValueError(
            "serve_driver is single-process (one resident service per "
            "host); use game_scoring_driver --partitioned-io for "
            "multi-process batch scoring"
        )
    if request_rows <= 0:
        raise ValueError(f"request_rows must be positive, got {request_rows}")
    shapes = (
        _parse_shapes(microbatch_shapes)
        if isinstance(microbatch_shapes, str) else tuple(microbatch_shapes)
    )
    os.makedirs(output_dir, exist_ok=True)

    with Timed("load model"):
        model, index_maps, feature_shards, entity_vocabs, re_columns = (
            _load_scoring_model(
                model_input_dir=model_input_dir,
                index_maps_dir=index_maps_dir,
                feature_shards=feature_shards,
                compact_random_effect_threshold=(
                    compact_random_effect_threshold
                ),
            )
        )

    with Timed("read replay data"):
        from photon_ml_tpu.resilience import default_io_policy

        part = default_io_policy().call(
            lambda: read_partitioned(
                requests_avro,
                feature_shards,
                index_maps=index_maps or None,
                random_effect_id_columns=re_columns,
                entity_vocabs=entity_vocabs,
                fmt=input_format,
            ),
            description="read replay data",
        )
        dataset = part.result.dataset

    n = dataset.num_samples
    with Timed("slice requests"):
        requests = [
            slice_game_dataset(dataset, lo, min(lo + request_rows, n))
            for lo in range(0, n, request_rows)
        ]
        if num_requests is not None:
            requests = requests[:num_requests]
    total_rows = sum(r.num_samples for r in requests)
    logger.info(
        "replaying %d requests (%d rows) through shapes %s",
        len(requests), total_rows, shapes,
    )

    from photon_ml_tpu.telemetry.program_ledger import current_ledger

    ledger = current_ledger()
    scorer = ResidentScorer(model, shapes=shapes, bf16=bf16)
    if ledger is not None:
        ledger.set_phase("warm")
    with Timed("warm compile"), CompileMonitor() as warm_compiles:
        scorer.warm(requests[0])

    swap_model = None
    if swap_model_dir and swap_poll_ms <= 0:
        from photon_ml_tpu.io.model_io import load_game_model

        with Timed("load swap model"):
            # the SAME index maps as the resident model: an equal layout
            # is the whole point of a hot swap (the guard rejects a
            # mismatch typed, naming the differing leaves)
            swap_model = load_game_model(
                swap_model_dir, index_maps or None,
                compact_random_effect_threshold=(
                    compact_random_effect_threshold
                ),
            )
        if len(requests) < 2:
            raise ValueError(
                f"the replay has {len(requests)} request(s) but the "
                "mid-replay swap fires BETWEEN requests; raise "
                "--num-requests / shrink --request-rows, or drop "
                "--swap-model-dir"
            )
        if swap_at_request is None:
            swap_at_request = max(1, len(requests) // 2)
        # strict upper bound: the swap fires BEFORE submit index i, so
        # len(requests) would silently never fire
        if not 0 < swap_at_request < len(requests):
            raise ValueError(
                f"--swap-at-request {swap_at_request} is outside the "
                f"replay (1..{len(requests) - 1})"
            )

    unbatched_rate = None
    if not skip_unbatched_baseline:
        with Timed("unbatched baseline"):
            # the same-run baseline: one request per dispatch, no queue —
            # what a naive online scorer would do; its rate rides the
            # summary so the batched number is judged against THIS run's
            # chip and tunnel only
            t0 = time.perf_counter()
            for r in requests:
                scorer.score(r)
            unbatched_rate = total_rows / max(
                time.perf_counter() - t0, 1e-9
            )
        # the baseline's latencies/counters are not the service's: reset
        # so the journaled histogram is the batched replay's alone
        from photon_ml_tpu.telemetry.serving_counters import (
            reset_serving_metrics,
        )

        reset_serving_metrics()

    if ledger is not None:
        # replay compiles are the SLO violation serving pins at zero: the
        # phase stamp makes any program_compile row from here on
        # attributable to the replay, not the warm-up
        ledger.set_phase("replay")
    swap_info = None
    with Timed("batched replay"), CompileMonitor() as replay_compiles:
        server = MicroBatchServer(
            scorer,
            max_wait_ms=max_wait_ms,
            queue_depth=queue_depth,
        )
        poller = None
        if swap_model_dir and swap_poll_ms > 0:
            poller = _SwapPoller(
                server, swap_model_dir, swap_poll_ms / 1e3,
                index_maps=index_maps or None,
                compact_threshold=compact_random_effect_threshold,
                journal=journal,
            )
        t0 = time.perf_counter()
        with server:
            if poller is not None:
                poller.start()
            try:
                futures = []
                for i, r in enumerate(requests):
                    if swap_model is not None and i == swap_at_request:
                        # the zero-downtime seam: swap IN-PLACE while the
                        # consumer keeps draining; a same-layout swap must
                        # compile nothing (the ledger delta below proves it)
                        pre = (
                            ledger.snapshot()
                            .get("serve/score", {}).get("compiles", 0)
                            if ledger is not None else None
                        )
                        server.swap_model(swap_model)
                        swap_info = {
                            "performed": True,
                            "at_request": i,
                            "_compiles_before": pre,
                        }
                    futures.append(server.submit(r))
                for f in futures:
                    f.result()
            finally:
                if poller is not None:
                    # stop INSIDE the server context — the final scan's
                    # swap still targets a live loop — and on the failure
                    # path too, so the thread never outlives the server
                    # or writes to a finalized journal
                    poller.stop()
        batched_sec = time.perf_counter() - t0
    batched_rate = total_rows / max(batched_sec, 1e-9)
    if poller is not None:
        swap_info = {
            "mode": "poll",
            "poll_ms": swap_poll_ms,
            "polls": poller.polls,
            "applied": list(poller.applied),
            "rejected": list(poller.rejected),
        }
    if swap_info is not None and "mode" not in swap_info:
        pre = swap_info.pop("_compiles_before")
        swap_info["score_compiles_after_swap"] = (
            None if pre is None else
            ledger.snapshot().get("serve/score", {}).get("compiles", 0) - pre
        )

    latency = serving_counters.latency_summary()
    summary = {
        "num_requests": len(requests),
        "num_rows": total_rows,
        "request_rows": request_rows,
        "microbatch_shapes": list(shapes),
        "max_wait_ms": max_wait_ms,
        "bf16": bf16,
        "scores_per_sec": batched_rate,
        "scores_per_sec_unbatched": unbatched_rate,
        "latency_ms_p50": latency["p50"],
        "latency_ms_p95": latency["p95"],
        "pad_fraction": serving_counters.pad_fraction(),
        "compiled_signatures": len(scorer.signatures),
        "warm_compiles": warm_compiles.count,
        "replay_compiles": replay_compiles.count,
        # mid-replay hot-swap evidence (None without --swap-model-dir):
        # every submitted request resolved above, so zero were dropped
        "swap": swap_info,
        # per-label compile accounting from the program ledger (None when
        # --telemetry-dir is off): the count's attribution lives in the
        # journal's program_compile/program_recompile rows, phase-stamped
        "program_compiles": None if ledger is None else ledger.snapshot(),
    }
    with open(os.path.join(output_dir, "serving-summary.json"), "w") as f:
        from photon_ml_tpu.cli.game_training_driver import _json_safe

        json.dump(_json_safe(summary), f, indent=2, default=float)
    return summary


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="serve_driver")
    p.add_argument("--requests-avro", required=True,
                   help="Avro scoring records replayed as requests")
    p.add_argument("--model-input-dir", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--feature-shard-configurations", action="append",
                   default=None)
    p.add_argument("--index-maps-dir")
    p.add_argument("--input-format", default="avro",
                   choices=["avro", "libsvm"])
    p.add_argument("--compact-random-effect-threshold", type=int,
                   default=DEFAULT_COMPACT_RE_THRESHOLD)
    p.add_argument("--microbatch-shapes", default=DEFAULT_SHAPES,
                   help="comma-separated power-of-two micro-batch row "
                        "buckets — the bound on compiled score-program "
                        "signatures")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="flush deadline: a request waits at most this long "
                        "for batch company before dispatch")
    p.add_argument("--queue-depth", type=int, default=1024,
                   help="bounded request-queue depth (backpressure "
                        "surfaces as a typed submit timeout)")
    p.add_argument("--request-rows", type=int, default=1,
                   help="rows per replayed request")
    p.add_argument("--num-requests", type=int, default=None,
                   help="cap the replay length (default: the whole file)")
    p.add_argument("--bf16", action="store_true",
                   help="whole-path bf16 features+params (not bitwise)")
    p.add_argument("--skip-unbatched-baseline", action="store_true",
                   help="skip the embedded one-request-per-dispatch "
                        "baseline pass")
    p.add_argument("--swap-model-dir",
                   help="hot-swap this refreshed model in-place mid-replay "
                        "(zero-downtime refresh rehearsal; same-layout "
                        "models only — the guard rejects layout changes "
                        "typed)")
    p.add_argument("--swap-at-request", type=int, default=None,
                   help="submit index the swap fires before (default: "
                        "halfway through the replay)")
    p.add_argument("--swap-poll-ms", type=float, default=0.0,
                   help="poll --swap-model-dir every this many ms for "
                        "atomically-renamed model subdirectories and "
                        "hot-swap each continuously through the guarded "
                        "swap API (rejected swaps journal typed and keep "
                        "serving); 0 = the one rehearsed mid-replay swap")
    p.add_argument("--telemetry-dir",
                   help="write a rank-0 JSONL run journal (serve/* "
                        "counters, latency histogram, phase timings) here "
                        "— on the failure path too")
    p.add_argument("--trace-dir",
                   help="write Chrome-trace span timelines here (serve/ "
                        "spans observe the loop; open in Perfetto)")
    return p


def main(argv: Sequence[str] | None = None) -> dict:
    logging.basicConfig(level=logging.INFO)
    args = build_arg_parser().parse_args(argv)
    shards = None
    if args.feature_shard_configurations:
        shards = dict(
            parse_feature_shard_config(s)
            for s in args.feature_shard_configurations
        )
    return run(
        requests_avro=args.requests_avro,
        model_input_dir=args.model_input_dir,
        output_dir=args.output_dir,
        feature_shards=shards,
        index_maps_dir=args.index_maps_dir,
        input_format=args.input_format,
        compact_random_effect_threshold=args.compact_random_effect_threshold,
        microbatch_shapes=args.microbatch_shapes,
        max_wait_ms=args.max_wait_ms,
        queue_depth=args.queue_depth,
        request_rows=args.request_rows,
        num_requests=args.num_requests,
        bf16=args.bf16,
        skip_unbatched_baseline=args.skip_unbatched_baseline,
        swap_model_dir=args.swap_model_dir,
        swap_at_request=args.swap_at_request,
        swap_poll_ms=args.swap_poll_ms,
        telemetry_dir=args.telemetry_dir,
        trace_dir=args.trace_dir,
    )


if __name__ == "__main__":
    main()
