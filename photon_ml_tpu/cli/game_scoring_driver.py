"""GAME scoring driver: load a model, score a dataset, save scores.

Reference parity: photon-client cli/game/scoring/GameScoringDriver.scala —
run() (:133-194): prepare feature maps, read data, load GAME model from the
training output layout, GameTransformer.transform, optional evaluation,
saveScoresToHDFS (:191-253, ScoringResultAvro records).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
from typing import Sequence

import numpy as np

from photon_ml_tpu.cli.configs import (
    evaluation_id_columns,
    parse_feature_shard_config,
)
from photon_ml_tpu.cli.game_training_driver import _parse_mesh_shape
from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.io.partitioned_reader import read_partitioned
from photon_ml_tpu.io.model_io import DEFAULT_COMPACT_RE_THRESHOLD, load_game_model, write_scores
from photon_ml_tpu.models.game import RandomEffectModel
from photon_ml_tpu.models.matrix_factorization import MatrixFactorizationModel
from photon_ml_tpu.transformers import GameTransformer
from photon_ml_tpu.util import Timed

logger = logging.getLogger(__name__)


def run(
    *,
    input_data_path: "str | Sequence[str]",
    model_input_dir: str,
    output_dir: str,
    feature_shards: dict | None = None,
    index_maps_dir: str | None = None,
    evaluators: Sequence[str] = (),
    model_id: str = "",
    input_format: str = "avro",
    compact_random_effect_threshold: int = DEFAULT_COMPACT_RE_THRESHOLD,
    distributed: bool = False,
    mesh_shape: dict | None = None,
    fe_feature_sharded: bool = False,
    partitioned_io: bool = False,
    on_corrupt: str = "raise",
    telemetry_dir: str | None = None,
    trace_dir: str | None = None,
) -> dict:
    """Score ``input_data_path`` with the model at ``model_input_dir``.

    input_data_path: one dataset path, or a sequence of paths scored in
    one run — the model Avro is parsed and its device placement built
    ONCE (the separable-placement API: ``DistributedScorer.
    params_for_layouts`` caches the placed model across datasets), each
    dataset writing under ``output_dir/dataset-NNNN``. A single path keeps
    the historical single-dataset output layout exactly.

    on_corrupt: "raise" (strict, default) or "quarantine" — skip-and-count
    corrupt Avro container blocks during ingestion (io/avro.py); spans and
    the resilience/* counters land in the run journal.

    telemetry_dir: rank-0 JSONL run journal (phase timings, io/resilience
    counters) — written on the FAILURE path too, so a scoring run that
    died mid-read still leaves its retry/quarantine evidence.

    trace_dir: per-rank Chrome-trace span timelines
    (``trace-{rank:05d}.json``; telemetry/tracing.py) + a rank-merged
    straggler report journaled at run end — flushed on success AND
    failure paths, before the failure journal rows.

    Index maps default to the ones the training driver saved next to the
    model (<root>/index-maps); feature shard configs default to one shard
    per saved index map using the bag of the same name.

    distributed/mesh_shape: score through the jitted mesh-sharded SPMD
    program (parallel/scoring.DistributedScorer) over a ("data", "model")
    mesh — the analogue of the reference's executor-distributed scoring
    (GameTransformer.scala:156-203). fe_feature_sharded additionally
    shards the FE coordinate's feature/coefficient axis over "model"
    (mesh model>1 implies it), so column-sharded giant-d models score
    without replicating the coefficient vector.

    partitioned_io: multi-process runs decode only ~1/P of the input per
    rank (io/partitioned_reader.py) and every rank writes its OWN
    part-NNNNN.avro score shard into the shared output directory
    (io/score_writer.ShardedScoreWriter — the reference's per-partition
    ScoreProcessingUtils layout), replacing the process_allgather score
    funnel. ``output_dir`` is then one SHARED directory; evaluators are
    not supported on this path yet. Single-process runs are unaffected.
    """
    import jax

    if on_corrupt not in ("raise", "quarantine"):
        raise ValueError(
            f"on_corrupt must be 'raise' or 'quarantine', got {on_corrupt!r}"
        )
    partitioned = partitioned_io and jax.process_count() > 1
    if partitioned and not (distributed or mesh_shape):
        raise ValueError(
            "--partitioned-io requires --distributed or --mesh (the "
            "partitioned blocks feed a mesh's addressable shards)"
        )
    # hybrid x --partitioned-io composes since ISSUE 6: the partitioned
    # reader resolves one GLOBAL hot head over the metadata exchange, so
    # every rank's layout agrees (io/partitioned_reader.py); scores are
    # layout-independent either way.
    from photon_ml_tpu.telemetry import RunJournal
    from photon_ml_tpu.telemetry.resilience_counters import (
        reset_resilience_metrics,
    )
    from photon_ml_tpu.util.timed import reset_timings, timing_summary

    reset_timings()
    reset_resilience_metrics()
    journal = RunJournal(telemetry_dir) if telemetry_dir else None
    # program ledger rides --telemetry-dir (ISSUE 13): the scoring program
    # (score/score_dataset) journals its compile/cost/signature accounting
    ledger = None
    if journal is not None:
        from photon_ml_tpu.telemetry.program_ledger import (
            ProgramLedger,
            install_ledger,
        )

        ledger = install_ledger(ProgramLedger(journal=journal))
    tracer = None
    if trace_dir:
        from photon_ml_tpu.telemetry.tracing import Tracer, install_tracer

        tracer = install_tracer(Tracer())
    exchange = None
    coordinator = None
    if partitioned:
        from photon_ml_tpu.parallel.multihost import default_exchange
        from photon_ml_tpu.resilience import CoordinatedRecovery

        exchange = default_exchange()
        # scoring has no restart loop, but the coordinator still buys
        # ATTRIBUTION (ISSUE 15): the run's exchange is generation-fenced,
        # and a rank dying of a classified-transient failure posts an
        # abort marker below, so its peers fail fast with a PeerAbort
        # naming it instead of burning the full exchange deadline
        coordinator = CoordinatedRecovery(
            exchange, max_restarts=0, journal=journal,
            description="partitioned scoring",
        )
    succeeded = False
    try:
        summary = _run_inner(
            input_data_path=input_data_path,
            model_input_dir=model_input_dir,
            output_dir=output_dir,
            feature_shards=feature_shards,
            index_maps_dir=index_maps_dir,
            evaluators=evaluators,
            model_id=model_id,
            input_format=input_format,
            compact_random_effect_threshold=compact_random_effect_threshold,
            distributed=distributed,
            mesh_shape=mesh_shape,
            fe_feature_sharded=fe_feature_sharded,
            partitioned=partitioned,
            on_corrupt=on_corrupt,
            journal=journal,
            exchange=exchange,
        )
        succeeded = True
        if journal is not None:
            journal.record("scoring_summary", **summary)
        return summary
    except Exception as e:  # attributed, then re-raised — never swallowed
        from photon_ml_tpu.resilience import is_transient

        if coordinator is not None and is_transient(e):
            coordinator.post_abort(e)
        raise
    finally:
        # traces flush FIRST (before the failure journal rows) so a dead
        # run still leaves a readable per-rank timeline; the straggler
        # merge + barriered publish run collectives only on the success
        # path (every rank's run() reaches this finally)
        if tracer is not None:
            from photon_ml_tpu.parallel.multihost import default_exchange
            from photon_ml_tpu.telemetry.tracing import (
                flush_trace_best_effort,
                uninstall_tracer,
            )

            try:
                # best-effort: a publication error never masks the run's
                # own outcome or skips the journal rows below. The run's
                # (possibly fenced) exchange is reused so the merge rides
                # the same key namespace as the run itself.
                flush_trace_best_effort(
                    tracer, trace_dir,
                    exchange=(
                        (exchange or default_exchange()) if succeeded
                        else None
                    ),
                    gather=succeeded,
                    journal=journal,
                )
            finally:
                uninstall_tracer()
        if ledger is not None:
            from photon_ml_tpu.telemetry.program_ledger import uninstall_ledger

            uninstall_ledger()
        # failure-path journaling too: the resilience/* counters (retries,
        # giveups, quarantined_blocks) and quarantine spans are exactly
        # what a post-mortem of a dead scoring run needs
        if journal is not None:
            from photon_ml_tpu.telemetry import (
                default_registry,
                resilience_counters,
            )

            for event in resilience_counters.drain_quarantine_events():
                journal.record("quarantined_block", **event)
            journal.record_timings(timing_summary())
            journal.record_metrics(default_registry().snapshot())
            journal.close()


def _load_scoring_model(
    *,
    model_input_dir: str,
    index_maps_dir: str | None,
    feature_shards: dict | None,
    compact_random_effect_threshold: int,
):
    """Parse the model Avro + index maps ONCE: (model, index_maps,
    feature_shards, entity_vocabs, re_columns). Hoisted out of the
    per-dataset scoring loop (and reused by cli/serve_driver.py) so a run
    that scores several datasets — or serves requests — never re-parses
    the model."""
    if index_maps_dir is None:
        candidate = os.path.join(os.path.dirname(model_input_dir.rstrip("/")), "index-maps")
        index_maps_dir = candidate if os.path.isdir(candidate) else None
    # both formats: plain .keys and native off-heap .photonix stores
    index_maps = IndexMap.load_directory(index_maps_dir) if index_maps_dir else {}
    if index_maps:
        if feature_shards is None:
            # shard name == bag name is OUR training driver's convention,
            # only trustworthy for maps its stores produced
            from photon_ml_tpu.io.data_reader import FeatureShardConfiguration

            feature_shards = {
                shard: FeatureShardConfiguration(feature_bags=(shard, "features"))
                for shard in index_maps
            }
        with Timed("load model"):
            model = load_game_model(
                model_input_dir, index_maps,
                compact_random_effect_threshold=compact_random_effect_threshold,
            )
    else:
        # no saved stores (e.g. a reference-written model whose index maps
        # are JVM-only PalDB): one pass rebuilds maps from the model's own
        # records while loading. Shard->bag mapping cannot be guessed for a
        # foreign model, so explicit shard configs are required.
        if feature_shards is None:
            raise ValueError(
                "no saved index-map stores next to this model: pass "
                "--feature-shard-configurations mapping each model shard id "
                "to the data's feature bags"
            )
        from photon_ml_tpu.io.model_io import load_game_model_and_index_maps

        logger.info("no index-map stores found; rebuilding from model records")
        with Timed("load model"):
            model, index_maps = load_game_model_and_index_maps(
                model_input_dir,
                compact_random_effect_threshold=compact_random_effect_threshold,
            )
    entity_vocabs: dict[str, np.ndarray] = {}

    def set_vocab(effect_type: str, keys: np.ndarray) -> None:
        keys = np.asarray(keys)
        existing = entity_vocabs.get(effect_type)
        if existing is not None and not np.array_equal(existing, keys):
            # two sub-models disagreeing on a shared entity space would
            # silently misalign one model's table rows
            raise ValueError(
                f"sub-models disagree on entity keys for effect type "
                f"'{effect_type}' ({len(existing)} vs {len(keys)} keys); "
                "cannot build a consistent scoring vocab"
            )
        entity_vocabs[effect_type] = keys

    for m in model.models.values():
        if isinstance(m, RandomEffectModel):
            set_vocab(m.random_effect_type, m.entity_keys)
        elif isinstance(m, MatrixFactorizationModel):
            set_vocab(m.row_effect_type, m.row_keys)
            set_vocab(m.col_effect_type, m.col_keys)
    re_columns = tuple(sorted(entity_vocabs))
    return model, index_maps, feature_shards, entity_vocabs, re_columns


def _run_inner(
    *,
    input_data_path: "str | Sequence[str]",
    model_input_dir: str,
    output_dir: str,
    feature_shards: dict | None,
    index_maps_dir: str | None,
    evaluators: Sequence[str],
    model_id: str,
    input_format: str,
    compact_random_effect_threshold: int,
    distributed: bool,
    mesh_shape: dict | None,
    fe_feature_sharded: bool,
    partitioned: bool,
    on_corrupt: str,
    journal=None,
    exchange=None,
) -> dict:
    import jax
    if partitioned and evaluators:
        raise ValueError(
            "--partitioned-io does not support --evaluators yet; evaluate "
            "through the non-partitioned scoring path"
        )
    from photon_ml_tpu.parallel.multihost import default_exchange

    paths = (
        [input_data_path] if isinstance(input_data_path, (str, os.PathLike))
        else list(input_data_path)
    )
    if not paths:
        raise ValueError("input_data_path names no datasets")
    if exchange is None:
        exchange = default_exchange() if partitioned else None
    if not partitioned or jax.process_index() == 0:
        os.makedirs(output_dir, exist_ok=True)
    if exchange is not None:
        exchange.barrier("scoring/output_dir")
    model, index_maps, feature_shards, entity_vocabs, re_columns = (
        _load_scoring_model(
            model_input_dir=model_input_dir,
            index_maps_dir=index_maps_dir,
            feature_shards=feature_shards,
            compact_random_effect_threshold=compact_random_effect_threshold,
        )
    )

    mesh = None
    if distributed or mesh_shape:
        from photon_ml_tpu.parallel.multihost import make_hybrid_mesh

        shape = dict(mesh_shape or {})
        mesh = make_hybrid_mesh(shape.get("data"), shape.get("model", 1))
        if shape.get("model", 1) > 1:
            fe_feature_sharded = True
        logger.info(
            "distributed scoring: mesh %s over %d devices",
            dict(zip(mesh.axis_names, mesh.devices.shape)), mesh.devices.size,
        )

    pad_multiple = 1
    if exchange is not None:
        data_axis = int(mesh.shape["data"])
        if data_axis % exchange.num_ranks:
            raise ValueError(
                f"--partitioned-io: mesh data axis {data_axis} must be a "
                f"multiple of the process count {exchange.num_ranks}"
            )
        pad_multiple = data_axis // exchange.num_ranks

    # the model half of the scoring work is built ONCE and reused across
    # the per-dataset loop: the transformer keeps its DistributedScorer,
    # whose placed params are cached per layout (params_for_layouts) — a
    # multi-dataset run pays model parse + device placement exactly once
    transformer = GameTransformer(
        model=model, evaluator_specs=tuple(evaluators),
        mesh=mesh, fe_feature_sharded=fe_feature_sharded,
    )
    part_scorer = None
    summaries: list[dict] = []
    for di, path in enumerate(paths):
        ds_output = (
            output_dir if len(paths) == 1
            else os.path.join(output_dir, f"dataset-{di:04d}")
        )
        if ds_output != output_dir:
            if not partitioned or jax.process_index() == 0:
                os.makedirs(ds_output, exist_ok=True)
            if exchange is not None:
                exchange.barrier(f"scoring/output_dir/{di}")

        with Timed("read scoring data"):
            from photon_ml_tpu.resilience import default_io_policy

            def _read(_path=path):
                return read_partitioned(
                    _path,
                    feature_shards,
                    exchange=exchange,
                    index_maps=index_maps or None,
                    random_effect_id_columns=re_columns,
                    evaluation_id_columns=evaluation_id_columns(evaluators),
                    entity_vocabs=entity_vocabs,
                    fmt=input_format,
                    pad_multiple=pad_multiple,
                    on_corrupt=on_corrupt,
                )

            # transient-I/O retry only on the non-collective path: retrying
            # one rank of an exchange-coordinated read would desynchronize
            # the SPMD exchange sequence (the collective path has deadlines
            # instead)
            part = (
                _read() if exchange is not None
                else default_io_policy().call(
                    _read, description="read scoring data"
                )
            )
            data = part.result
        partition = part.partition

        if partition.num_ranks > 1:
            # partitioned scoring: the [n] score vector stays mesh-sharded
            # end to end; each rank device-gets only its rows and writes
            # its own part file — no process_allgather funnel, no rank-0
            # encode of the full output (ScoreProcessingUtils.scala
            # per-partition layout)
            from photon_ml_tpu.io.score_writer import ShardedScoreWriter
            from photon_ml_tpu.parallel.scoring import DistributedScorer

            with Timed("score"):
                if part_scorer is None:
                    part_scorer = DistributedScorer(
                        model, mesh, fe_feature_sharded=fe_feature_sharded
                    )
                local_scores = part_scorer.score_partitioned(
                    {partition.rank: data.dataset}, partition,
                    exchange=exchange,
                )[partition.rank]
            n_local = partition.local_n
            with Timed("save scores"):
                ShardedScoreWriter(
                    os.path.join(ds_output, "scores"), exchange=exchange
                ).write(
                    local_scores,
                    model_id=model_id,
                    uids=np.asarray(data.dataset.unique_ids)[:n_local],
                    labels=np.asarray(
                        data.dataset.host_array("labels")
                    )[:n_local],
                    weights=np.asarray(
                        data.dataset.host_array("weights")
                    )[:n_local],
                )
            summary = {
                "num_scored": partition.total_true_rows,
                "num_scored_local": n_local,
                "bytes_decoded_local": part.bytes_decoded,
                "input_bytes_total": part.input_bytes_total,
                "evaluations": {},
            }
        else:
            with Timed("score"):
                from photon_ml_tpu.resilience import default_dispatch_policy

                # the remote-compile/dispatch boundary: retry classified-
                # transient tunnel failures, single-process only (a multi-
                # process transform joins cross-process collectives — one
                # rank retrying desyncs them)
                if jax.process_count() == 1:
                    scored = default_dispatch_policy().call(
                        transformer.transform, data.dataset,
                        description="score",
                    )
                else:
                    scored = transformer.transform(data.dataset)

            summary = {
                "num_scored": int(len(scored.scores)),
                "evaluations": scored.evaluations,
            }
            # multi-process rule: every rank participated in the scoring
            # collectives above (DistributedScorer gathers across
            # processes); only rank 0 touches the shared output directory
            if jax.process_index() == 0:
                with Timed("save scores"):
                    write_scores(
                        os.path.join(ds_output, "scores"),
                        scored.scores,
                        records_per_file=1 << 20,
                        model_id=model_id,
                        uids=scored.unique_ids,
                        labels=np.asarray(data.dataset.host_array("labels")),
                        weights=np.asarray(
                            data.dataset.host_array("weights")
                        ),
                    )
        if len(paths) > 1:
            summary = dict(summary, input_data_path=str(path))
        if jax.process_index() == 0:
            with open(
                os.path.join(ds_output, "scoring-summary.json"), "w"
            ) as f:
                from photon_ml_tpu.cli.game_training_driver import _json_safe

                json.dump(_json_safe(summary), f, indent=2, default=float)
        summaries.append(summary)
        if journal is not None:
            # per-dataset liveness heartbeat (ISSUE 12): which dataset the
            # multi-dataset loop last finished, with registry deltas, in
            # the crash-durable journal stage; inert on worker ranks
            from photon_ml_tpu.telemetry import default_registry

            journal.heartbeat(
                registry=default_registry(), stage="game_scoring",
                dataset_index=di, num_datasets=len(paths),
                num_scored=summary.get("num_scored"),
            )

    if len(paths) == 1:
        return summaries[0]
    combined = {
        "num_scored": int(sum(s["num_scored"] for s in summaries)),
        "num_datasets": len(summaries),
        "datasets": summaries,
    }
    if jax.process_index() == 0:
        with open(os.path.join(output_dir, "scoring-summary.json"), "w") as f:
            from photon_ml_tpu.cli.game_training_driver import _json_safe

            json.dump(_json_safe(combined), f, indent=2, default=float)
    return combined


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="game_scoring_driver")
    p.add_argument("--input-data-path", required=True, action="append",
                   help="dataset to score; repeat to score several datasets "
                        "in one run (the model is parsed and placed ONCE; "
                        "each dataset writes under "
                        "<output-dir>/dataset-NNNN)")
    p.add_argument("--model-input-dir", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--feature-shard-configurations", action="append", default=None)
    p.add_argument("--index-maps-dir")
    p.add_argument("--evaluators", default="")
    p.add_argument("--model-id", default="")
    p.add_argument("--input-format", default="avro", choices=["avro", "libsvm"])
    p.add_argument("--compact-random-effect-threshold", type=int,
                   default=DEFAULT_COMPACT_RE_THRESHOLD,
                   help="random-effect coordinates whose feature space "
                        "exceeds this load as compact per-entity tables "
                        "(never materializing [entities, dim])")
    p.add_argument("--distributed", action="store_true",
                   help="score through the mesh-sharded SPMD scoring "
                        "program over all devices")
    p.add_argument("--mesh", default="",
                   help="device mesh layout 'data=8,model=1' (implies "
                        "--distributed; model>1 shards the fixed-effect "
                        "feature/coefficient axis — required for "
                        "column-sharded giant-d models)")
    p.add_argument("--partitioned-io", action="store_true",
                   help="multi-process runs: each rank decodes ~1/P of the "
                        "input and writes its own part-NNNNN.avro score "
                        "shard into the SHARED --output-dir (no "
                        "process_allgather funnel; no --evaluators yet)")
    p.add_argument("--on-corrupt", default="raise",
                   choices=["raise", "quarantine"],
                   help="corrupt Avro blocks: 'raise' (strict, default) "
                        "or 'quarantine' (skip-and-count; spans journaled)")
    p.add_argument("--telemetry-dir",
                   help="write a rank-0 JSONL run journal (phase timings, "
                        "io + resilience counters) here — on the failure "
                        "path too")
    p.add_argument("--trace-dir",
                   help="write per-rank Chrome-trace span timelines "
                        "(trace-{rank:05d}.json, open in Perfetto) + a "
                        "rank-merged straggler report here; flushed on "
                        "success and failure")
    return p


def main(argv: Sequence[str] | None = None) -> dict:
    logging.basicConfig(level=logging.INFO)
    args = build_arg_parser().parse_args(argv)
    shards = None
    if args.feature_shard_configurations:
        shards = dict(
            parse_feature_shard_config(s) for s in args.feature_shard_configurations
        )
    paths = args.input_data_path
    return run(
        input_data_path=paths[0] if len(paths) == 1 else paths,
        model_input_dir=args.model_input_dir,
        output_dir=args.output_dir,
        feature_shards=shards,
        index_maps_dir=args.index_maps_dir,
        evaluators=tuple(x.strip() for x in args.evaluators.split(",") if x.strip()),
        model_id=args.model_id,
        input_format=args.input_format,
        compact_random_effect_threshold=args.compact_random_effect_threshold,
        distributed=args.distributed,
        mesh_shape=_parse_mesh_shape(args.mesh),
        partitioned_io=args.partitioned_io,
        on_corrupt=args.on_corrupt,
        telemetry_dir=args.telemetry_dir,
        trace_dir=args.trace_dir,
    )


if __name__ == "__main__":
    main()
