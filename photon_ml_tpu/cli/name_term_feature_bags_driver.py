"""Name-and-term feature bag extraction driver.

Reference parity: photon-client data/avro/NameAndTermFeatureBagsDriver.scala
:153-229 — scan the data, extract the distinct (name, term) pairs of each
feature bag, write them as text files (one "name\\tterm" line per feature)
for downstream index building.
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import Sequence

from photon_ml_tpu.io.data_reader import read_avro_records, read_libsvm, _record_bags

logger = logging.getLogger(__name__)


def run(
    *,
    input_data_path: str,
    output_dir: str,
    feature_bags: Sequence[str],
    input_format: str = "avro",
) -> dict[str, int]:
    records = (
        read_avro_records(input_data_path)
        if input_format == "avro"
        else read_libsvm(input_data_path)
    )
    wanted = set(feature_bags)
    pairs: dict[str, set[tuple[str, str]]] = {b: set() for b in wanted}
    for record in records:
        for bag, feats in _record_bags(record).items():
            if bag in wanted:
                for feat in feats:
                    pairs[bag].add((feat["name"], feat.get("term", "") or ""))

    counts = {}
    for bag, found in pairs.items():
        bag_dir = os.path.join(output_dir, bag)
        os.makedirs(bag_dir, exist_ok=True)
        with open(os.path.join(bag_dir, "part-00000.tsv"), "w", encoding="utf-8") as f:
            for name, term in sorted(found):
                f.write(f"{name}\t{term}\n")
        counts[bag] = len(found)
        logger.info("bag '%s': %d distinct (name, term) pairs", bag, len(found))
    return counts


def main(argv: Sequence[str] | None = None) -> dict[str, int]:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(prog="name_term_feature_bags_driver")
    p.add_argument("--input-data-path", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--feature-bags", required=True, help="comma-separated bag names")
    p.add_argument("--input-format", default="avro", choices=["avro", "libsvm"])
    args = p.parse_args(argv)
    return run(
        input_data_path=args.input_data_path,
        output_dir=args.output_dir,
        feature_bags=[b.strip() for b in args.feature_bags.split(",") if b.strip()],
        input_format=args.input_format,
    )


if __name__ == "__main__":
    main()
