"""Feature indexing driver: build + persist per-shard feature index maps.

Reference parity: photon-client index/FeatureIndexingDriver.scala:177-290 —
scan the data once, collect distinct (name, term) per shard, build
partitioned index stores (PalDB there; the native mmap store or text keys
here), save to the output dir for later training/scoring runs.
"""

from __future__ import annotations

import argparse
import logging
from typing import Sequence

from photon_ml_tpu.cli.configs import parse_feature_shard_config
from photon_ml_tpu.io.data_reader import build_index_maps, read_avro_records, read_libsvm

logger = logging.getLogger(__name__)


def run(
    *,
    input_data_path: str,
    output_dir: str,
    feature_shards: dict,
    input_format: str = "avro",
    store_format: str = "plain",
    num_partitions: int = 1,
) -> dict[str, int]:
    records = (
        read_avro_records(input_data_path)
        if input_format == "avro"
        else read_libsvm(input_data_path)
    )
    index_maps = build_index_maps(records, feature_shards)
    sizes = {}
    for shard_id, imap in index_maps.items():
        if store_format == "offheap":
            # partitioned native mmap stores (reference PalDB layout,
            # index/FeatureIndexingDriver.scala:227-290)
            from photon_ml_tpu.io.offheap_index_map import build_offheap_store

            build_offheap_store(
                output_dir, imap, num_partitions=num_partitions, name=shard_id
            )
        else:
            imap.save(output_dir, shard_id)
        sizes[shard_id] = imap.size
        logger.info("shard '%s': %d features indexed", shard_id, imap.size)
    return sizes


def main(argv: Sequence[str] | None = None) -> dict[str, int]:
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser(prog="feature_indexing_driver")
    p.add_argument("--input-data-path", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--feature-shard-configurations", action="append", required=True)
    p.add_argument("--input-format", default="avro", choices=["avro", "libsvm"])
    p.add_argument("--index-store-format", default="plain",
                   choices=["plain", "offheap"],
                   help="offheap = partitioned native mmap stores "
                        "(reference PalDB analogue)")
    p.add_argument("--num-partitions", type=int, default=1)
    args = p.parse_args(argv)
    shards = dict(
        parse_feature_shard_config(s) for s in args.feature_shard_configurations
    )
    return run(
        input_data_path=args.input_data_path,
        output_dir=args.output_dir,
        feature_shards=shards,
        input_format=args.input_format,
        store_format=args.index_store_format,
        num_partitions=args.num_partitions,
    )


if __name__ == "__main__":
    main()
