"""OWL-QN: Orthant-Wise Limited-memory Quasi-Newton for L1 regularization.

Reference parity: photon-lib optimization/OWLQN.scala:40-86 (breeze OWLQN
wrapper; mutable l1RegularizationWeight for the elastic-net regularization
path). The L2 part of elastic net stays in the smooth objective; this solver
adds λ₁‖w‖₁ via the pseudo-gradient and orthant projection (Andrew & Gao 2007).

Jittable: one lax.while_loop, fixed-shape circular L-BFGS history, masked
projection — vmaps over entities like the plain L-BFGS solver.
"""

from __future__ import annotations

from typing import Callable

import flax.struct
import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.common import (
    ConvergenceReason,
    SolverResult,
    check_convergence,
    run_while,
)
from photon_ml_tpu.optim.lbfgs import two_loop_direction

Array = jax.Array


def pseudo_gradient(w: Array, g: Array, l1: Array) -> Array:
    """Pseudo-gradient of f(w) = L(w) + l1*‖w‖₁ (Andrew & Gao 2007, eq. 4)."""
    right = g + l1
    left = g - l1
    return jnp.where(
        w > 0.0,
        right,
        jnp.where(
            w < 0.0,
            left,
            jnp.where(right < 0.0, right, jnp.where(left > 0.0, left, 0.0)),
        ),
    )


@flax.struct.dataclass
class _OWLQNState:
    w: Array
    f: Array  # smooth + L1 value
    g: Array  # smooth gradient
    s_hist: Array
    y_hist: Array
    rho: Array
    count: Array
    head: Array
    iteration: Array
    reason: Array
    g0_norm: Array
    value_history: Array
    grad_norm_history: Array


def minimize_owlqn(
    value_and_grad_fn: Callable[[Array], tuple[Array, Array]],
    w0: Array,
    *,
    l1_weight: float,
    max_iter: int = 100,
    history: int = 10,
    tolerance: float = 1e-7,
    rel_function_tolerance: float | None = None,
    max_line_search_steps: int = 30,
    host_loop: bool = False,
    state_observer=None,
    resume_state: "_OWLQNState | None" = None,
) -> SolverResult:
    """Minimize smooth(w) + l1_weight * ‖w‖₁.

    ``value_and_grad_fn`` covers only the smooth part (loss + optional L2).
    ``rel_function_tolerance``: live function-decrease stop for warm-started
    vmapped lanes (None = use ``tolerance``; optim/common.check_convergence).
    ``host_loop=True``: identical body math driven from Python so
    ``value_and_grad_fn`` may be a host-level streaming epoch accumulator
    (optim/common.run_while).

    ``state_observer`` / ``resume_state`` (host_loop only): per-iteration
    state hook + checkpointed re-entry for crash-safe streaming solves —
    same contract as optim/lbfgs.minimize_lbfgs.
    """
    if (state_observer is not None or resume_state is not None) and not host_loop:
        raise ValueError(
            "state_observer/resume_state require host_loop=True (solver-"
            "state checkpointing exists for host-driven streaming solves)"
        )
    dtype = w0.dtype
    d = w0.shape[0]
    m = history
    l1 = jnp.asarray(l1_weight, dtype)

    def full_value(w, smooth_f):
        return smooth_f + l1 * jnp.sum(jnp.abs(w))

    if resume_state is not None:
        init = resume_state
    else:
        w0 = jnp.asarray(w0, dtype)
        sf0, g0 = value_and_grad_fn(w0)
        f0 = full_value(w0, sf0)
        pg0 = pseudo_gradient(w0, g0, l1)
        g0_norm = jnp.linalg.norm(pg0)

        nan_hist = jnp.full((max_iter + 1,), jnp.nan, dtype)
        init = _OWLQNState(
            w=w0,
            f=f0,
            g=g0,
            s_hist=jnp.zeros((m, d), dtype),
            y_hist=jnp.zeros((m, d), dtype),
            rho=jnp.zeros((m,), dtype),
            count=jnp.int32(0),
            head=jnp.int32(0),
            iteration=jnp.int32(0),
            reason=jnp.where(
                g0_norm <= tolerance,
                jnp.int32(ConvergenceReason.GRADIENT_WITHIN_TOLERANCE),
                jnp.int32(ConvergenceReason.NOT_CONVERGED),
            ),
            g0_norm=g0_norm,
            value_history=nan_hist.at[0].set(f0),
            grad_norm_history=nan_hist.at[0].set(g0_norm),
        )

    def cond(state: _OWLQNState):
        return (state.iteration < max_iter) & (
            state.reason == ConvergenceReason.NOT_CONVERGED
        )

    def body(state: _OWLQNState):
        pg = pseudo_gradient(state.w, state.g, l1)
        direction = two_loop_direction(
            pg, state.s_hist, state.y_hist, state.rho, state.count, state.head
        )
        # Constrain direction to the descent orthant of -pg.
        direction = jnp.where(direction * (-pg) > 0.0, direction, 0.0)
        # Fall back to steepest descent on the pseudo-gradient if degenerate.
        degenerate = jnp.vdot(direction, pg) >= 0.0
        direction = jnp.where(degenerate, -pg, direction)

        # Orthant of the search: sign(w), or sign(-pg) where w == 0.
        xi = jnp.where(state.w != 0.0, jnp.sign(state.w), jnp.sign(-pg))

        t_init = jnp.where(
            state.count == 0,
            1.0 / jnp.maximum(jnp.linalg.norm(pg), 1.0),
            jnp.ones((), dtype),
        )

        # Projected backtracking: evaluate the full (smooth + L1) objective at
        # the orthant-projected trial point; Armijo decrease measured against
        # actual displacement dotted with the pseudo-gradient.
        c1 = 1e-4

        def ls_body(ls_state):
            i, t, w_best, f_best, g_best, done = ls_state
            cand = state.w + t * direction
            cand = jnp.where(cand * xi > 0.0, cand, 0.0)  # orthant projection
            sf, sg = value_and_grad_fn(cand)
            f_t = full_value(cand, sf)
            decrease = jnp.vdot(pg, cand - state.w)
            ok = (
                (f_t <= state.f + c1 * decrease)
                & ~(jnp.isnan(f_t) | jnp.isinf(f_t))
                & (f_t < state.f)
            )
            return (i + 1, t * 0.5, cand, f_t, sg, ok)

        def ls_cond(ls_state):
            i, _t, _w, _f, _g, done = ls_state
            return (i < max_line_search_steps) & ~done

        _, _, w_new, f_new, g_new, ls_ok = run_while(
            ls_cond,
            ls_body,
            (jnp.int32(0), t_init, state.w, state.f, state.g, jnp.asarray(False)),
            host=host_loop,
        )

        s = w_new - state.w
        y = g_new - state.g  # smooth gradients, per Andrew & Gao
        sy = jnp.vdot(s, y)
        keep_pair = ls_ok & (sy > 1e-10)

        new_head = jnp.where(
            state.count == 0, jnp.int32(0), (state.head + 1) % m
        )
        new_head = jnp.where(keep_pair, new_head, state.head)
        write_head = jnp.where(state.count == 0, jnp.int32(0), (state.head + 1) % m)
        s_hist = jnp.where(keep_pair, state.s_hist.at[write_head].set(s), state.s_hist)
        y_hist = jnp.where(keep_pair, state.y_hist.at[write_head].set(y), state.y_hist)
        rho = jnp.where(
            keep_pair,
            state.rho.at[write_head].set(1.0 / jnp.maximum(sy, 1e-30)),
            state.rho,
        )
        count = jnp.where(keep_pair, jnp.minimum(state.count + 1, m), state.count)

        pg_new = pseudo_gradient(w_new, g_new, l1)
        gnorm = jnp.linalg.norm(pg_new)
        reason = jnp.where(
            ls_ok,
            check_convergence(
                value=f_new,
                prev_value=state.f,
                grad_norm=gnorm,
                initial_grad_norm=state.g0_norm,
                tolerance=tolerance,
                rel_function_tolerance=rel_function_tolerance,
            ),
            jnp.int32(ConvergenceReason.LINE_SEARCH_FAILED),
        )

        it = state.iteration + 1
        return _OWLQNState(
            w=jnp.where(ls_ok, w_new, state.w),
            f=jnp.where(ls_ok, f_new, state.f),
            g=jnp.where(ls_ok, g_new, state.g),
            s_hist=s_hist,
            y_hist=y_hist,
            rho=rho,
            count=count,
            head=new_head,
            iteration=it,
            reason=reason,
            g0_norm=state.g0_norm,
            value_history=state.value_history.at[it].set(jnp.where(ls_ok, f_new, state.f)),
            grad_norm_history=state.grad_norm_history.at[it].set(gnorm),
        )

    final = run_while(cond, body, init, host=host_loop, observer=state_observer)
    reason = jnp.where(
        final.reason == ConvergenceReason.NOT_CONVERGED,
        jnp.int32(ConvergenceReason.MAX_ITERATIONS),
        final.reason,
    )
    pg_final = pseudo_gradient(final.w, final.g, l1)
    return SolverResult(
        coefficients=final.w,
        value=final.f,
        gradient_norm=jnp.linalg.norm(pg_final),
        iterations=final.iteration,
        reason=reason,
        value_history=final.value_history,
        grad_norm_history=final.grad_norm_history,
    )
