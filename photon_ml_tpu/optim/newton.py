"""Damped Newton (IRLS) with an explicit Cholesky solve — the small-d solver.

No reference analogue: the reference solves every per-entity random-effect
subproblem with the iterative LBFGS/TRON family (RandomEffectOptimizationProblem
+ Optimizer.scala template loop), which is the right call on a JVM executor.
On TPU the r5 sweep decomposition (experiments/sweep_decompose_r5.py,
BASELINE.md) showed those vmapped iterative solves are OP-COUNT-bound, not
bandwidth-bound: ~2 ms per RE coordinate per L-BFGS iteration on a
[2000, 128, 16] bucket whose data could stream in ~50 µs — the two-loop
recursion plus a Wolfe line search whose batched while_loop runs every lane
until the WORST lane satisfies the conditions, tens of tiny [e, d] ops per
iteration.

For the small dense dimensions where per-entity solves live (d ≲ a few
hundred), Newton's method is the op-minimal shape: one Hessian pass
(a batched [e, cap, d]ᵀ[e, cap, d] MXU contraction), one d-step
Gauss-Jordan solve (NOT an XLA cholesky — batched small decompositions
serialize per matrix on TPU, measured 3.4 ms vs 0.09 ms hand-rolled at
[2000, 16, 16], newton_piece_probe_r5.log), one fixed 4-point step-shrink
(a vmapped value evaluation that shares the feature read across the 4
candidates — no divergent line-search loop), one gradient pass. ~15 fused
ops per iteration regardless of entity count. For the squared loss one
full step is EXACT (ridge normal equations), so warm-started sweeps
converge in one accepted step plus one convergence check.

GLM Hessians are PSD and every RE coordinate carries l2 > 0, so H + l2·I is
PD; a trace-scaled Levenberg jitter plus a gradient-direction fallback guard
the elimination against degenerate all-padding entities (their H is l2·I,
which eliminates cleanly — the fallback only fires on non-finite input).

Opt-in via ``OptimizerType.NEWTON``; LBFGS stays the default everywhere, so
reference-parity solver behavior is unchanged unless asked for.
"""

from __future__ import annotations

from typing import Callable

import flax.struct
import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.common import ConvergenceReason, SolverResult

Array = jax.Array

#: fixed step-shrink candidates: the current point (alpha=0 — the baseline
#: every accept/convergence decision compares against, through the same
#: value path), a full Newton step, and three shrinks for over-shooting
#: steps. Evaluated with one vmapped value pass (the candidates share
#: every feature read). Overshoots beyond the 16x shrink range are handled
#: by the adaptive LM damping, not by more candidates.
_ALPHAS = (0.0, 1.0, 0.5, 0.25, 0.0625)


def _solve_pd(h: Array, g: Array) -> Array:
    """Solve H p = g for PD H by unpivoted Gauss-Jordan elimination,
    vectorized over any batch dims with a fori over columns.

    XLA's native decompositions are the wrong tool for BATCHED small
    systems on TPU: on [2000, 16, 16] this measured 0.088 ms vs 3.39 ms
    for cholesky+cho_solve and 8.97 ms for jnp.linalg.solve
    (experiments/newton_piece_probe_r5.log — their row-sequential inner
    loops serialize per matrix). PD systems need no pivoting (every pivot
    is a positive Schur complement diagonal; the caller's Levenberg jitter
    keeps them away from zero under f32)."""
    d = h.shape[-1]
    a = jnp.concatenate([h, g[..., None]], axis=-1)  # [..., d, d+1]

    def elim(i, a):
        piv = a[..., i, :] / a[..., i, i][..., None]  # [..., d+1]
        factors = a[..., :, i]  # [..., d]
        a = a - factors[..., None] * piv[..., None, :]
        return a.at[..., i, :].set(piv)

    a = lax.fori_loop(0, d, elim, a)
    return a[..., :, d]


@flax.struct.dataclass
class _NewtonState:
    w: Array
    f: Array
    g: Array
    #: Levenberg-Marquardt damping as a FRACTION of trace(H)/d: grows x64
    #: on a rejected round (a Newton step overshooting by more than the
    #: fixed alphas' 16x range — reachable from flat regions of Poisson /
    #: weakly-regularized logistic), decays x0.25 on acceptance. The
    #: fixed-shape replacement for an unbounded backtracking loop.
    damping: Array
    iteration: Array
    reason: Array
    value_history: Array
    grad_norm_history: Array


def minimize_newton(
    value_and_grad_fn: Callable[[Array], tuple[Array, Array]],
    hessian_matrix_fn: Callable[[Array], Array],
    w0: Array,
    *,
    value_fn: Callable[[Array], Array] | None = None,
    max_iter: int = 15,
    tolerance: float = 1e-7,
    rel_function_tolerance: float | None = None,
) -> SolverResult:
    """Minimize a twice-differentiable convex objective by damped Newton
    (Levenberg-Marquardt safeguarded).

    ``hessian_matrix_fn(w)`` returns the full [d, d] Hessian INCLUDING any
    regularizer (GLMObjective.hessian_matrix semantics). Convergence when
    ‖g‖ <= tolerance * max(‖g0‖, 1) (the LBFGS/TRON relative test) or on a
    clean round whose best step changes the value by <= tolerance
    relative (the test that actually fires in f32). A round where even the
    16x-shrunk step fails to improve — a Newton overshoot from a flat
    region (Poisson, weakly-regularized logistic) — grows the LM damping
    x64 and retries rather than terminating, so the solver always makes
    progress instead of silently returning w0. jit- and vmap-safe (fixed
    shapes, no divergent inner loops).

    ``rel_function_tolerance`` (None = use ``tolerance``, unchanged
    behavior): a separate threshold for the function-decrease stop — the
    live-stop knob the LBFGS/OWLQN family adopted from this solver's
    pattern (optim/common.check_convergence).
    """
    dtype = w0.dtype
    w0 = jnp.asarray(w0, dtype)
    d = w0.shape[-1]
    if value_fn is None:
        value_fn = lambda w: value_and_grad_fn(w)[0]
    f0, g0 = value_and_grad_fn(w0)
    g0_norm = jnp.linalg.norm(g0)
    alphas = jnp.asarray(_ALPHAS, dtype)
    ftol = tolerance if rel_function_tolerance is None else rel_function_tolerance

    nan_hist = jnp.full((max_iter + 1,), jnp.nan, dtype)
    init = _NewtonState(
        w=w0,
        f=f0,
        g=g0,
        damping=jnp.asarray(0.0, dtype),
        iteration=jnp.int32(0),
        # warm starts arrive already-stationary: stop before the first solve
        reason=jnp.where(
            g0_norm <= tolerance,
            jnp.int32(ConvergenceReason.GRADIENT_WITHIN_TOLERANCE),
            jnp.int32(ConvergenceReason.NOT_CONVERGED),
        ),
        value_history=nan_hist.at[0].set(f0),
        grad_norm_history=nan_hist.at[0].set(g0_norm),
    )

    def cond(state: _NewtonState):
        return (state.iteration < max_iter) & (
            state.reason == ConvergenceReason.NOT_CONVERGED
        )

    def body(state: _NewtonState):
        h = hessian_matrix_fn(state.w)
        # trace-scaled Levenberg jitter (f32 PD safety) + the adaptive LM
        # damping carried in the state. The scale is floored so the damping
        # still regularizes a zero-trace Hessian (all-zero H with l2=0,
        # reachable outside the RE path): without the floor the jitter
        # collapses to 1e-30 and damping growth multiplies zero, leaving
        # the gradient fallback's 1e-12 divisor to produce huge steps.
        scale = jnp.maximum(jnp.trace(h) / d, 1e-12)
        jitter = (1e-7 + state.damping) * scale + 1e-30
        p = -_solve_pd(h + jitter * jnp.eye(d, dtype=h.dtype), state.g)
        # degenerate Hessian (non-finite solve): steepest descent scaled
        # by the largest curvature — only reachable on non-finite input
        ok = jnp.all(jnp.isfinite(p))
        p_fallback = -state.g / jnp.maximum(jnp.max(jnp.diag(h)), 1e-12)
        p = jnp.where(ok, p, p_fallback)

        # fixed step-shrink: ONE vmapped value pass over all candidates,
        # alpha=0 included so every accept/convergence comparison below is
        # between evaluations of the SAME value path (value_fn) — state.f
        # may come from the Pallas kernel, whose ~5e-6 relative delta vs
        # the autodiff value would otherwise decide accepts near optimum
        vals = jax.vmap(lambda a: value_fn(state.w + a * p))(alphas)
        vals = jnp.where(jnp.isfinite(vals), vals, jnp.inf)
        best = jnp.argmin(vals[1:]) + 1  # best NONZERO step
        improved = vals[best] < vals[0]
        # nothing at solver tolerance left to gain in this direction: the
        # function-decrease test is what actually fires in f32 (an exact
        # Newton step leaves ‖g‖ at rounding scale, which warm-started RE
        # solves' large g0 never map below the relative gradient
        # tolerance, and without a live stop every vmapped lane pays
        # max_iter full iterations — the 81 ms sweep in
        # newton_sweep_probe_r5.log)
        f_delta_small = jnp.abs(vals[0] - vals[best]) <= ftol * (
            jnp.abs(vals[0]) + 1e-30
        )
        w_new = jnp.where(improved, state.w + alphas[best] * p, state.w)
        # rejected round: w_new == state.w, so the value+grad it carries is
        # already exact — reuse it. lax.cond skips the pass entirely on
        # un-vmapped solves; vmapped lanes lower to a select-both-branches
        # (no worse than the unconditional recompute this replaces).
        f_new, g_new = lax.cond(
            improved,
            lambda: value_and_grad_fn(w_new),
            lambda: (state.f, state.g),
        )

        # LM damping: a rejected round means the step overshot past the
        # alphas' 16x range — damp hard and retry; acceptance decays the
        # damping back toward pure Newton
        damping = jnp.where(
            improved,
            state.damping * 0.25,
            jnp.maximum(state.damping * 64.0, 1e-6),
        )

        gnorm = jnp.linalg.norm(g_new)
        g0n = state.grad_norm_history[0]
        # converged only on a clean (undamped-ish) ACCEPTED flat round:
        # heavy damping makes steps artificially tiny, and a rejected-but-
        # flat round (best nonzero step within tolerance but slightly
        # worse) must take one damped — more gradient-like — retry before
        # declaring convergence, in case only the undamped Newton direction
        # was poor
        flat_round = f_delta_small & improved & (state.damping <= 1e-3)
        reason = jnp.where(
            gnorm <= tolerance * jnp.maximum(g0n, 1.0),
            jnp.int32(ConvergenceReason.GRADIENT_WITHIN_TOLERANCE),
            jnp.where(
                flat_round,
                jnp.int32(ConvergenceReason.FUNCTION_VALUES_WITHIN_TOLERANCE),
                jnp.int32(ConvergenceReason.NOT_CONVERGED),
            ),
        )
        it = state.iteration + 1
        return _NewtonState(
            w=w_new,
            f=f_new,
            g=g_new,
            damping=damping,
            iteration=it,
            reason=reason,
            value_history=state.value_history.at[it].set(f_new),
            grad_norm_history=state.grad_norm_history.at[it].set(gnorm),
        )

    final = lax.while_loop(cond, body, init)
    reason = jnp.where(
        final.reason == ConvergenceReason.NOT_CONVERGED,
        jnp.int32(ConvergenceReason.MAX_ITERATIONS),
        final.reason,
    )
    return SolverResult(
        coefficients=final.w,
        value=final.f,
        gradient_norm=jnp.linalg.norm(final.g),
        iterations=final.iteration,
        reason=reason,
        value_history=final.value_history,
        grad_norm_history=final.grad_norm_history,
    )
