"""TRON: trust-region Newton method with truncated conjugate-gradient inner loop.

Reference parity: photon-lib optimization/TRON.scala (a LIBLINEAR port):
outer trust-region loop with eta/sigma update rules (TRON.scala:152-253),
inner truncated CG calling hessianVector per step (TRON.scala:278-338),
defaults maxIter=15, tolerance=1e-5, maxNumImprovementFailures — here the CG
cap defaults to 20 like the reference (TRON.scala:257-262).

TPU-native: outer loop and CG are nested lax.while_loops in one XLA program;
each CG step is one Hessian-vector product (a jvp-of-grad — two fused passes
over the data block on the MXU). TRON needs only O(4) work vectors vs
L-BFGS's 2m, which is why the reference positions it for high-dimensional
L2 problems — the same argument holds for sharded 1B-coefficient vectors
(SURVEY.md §7).
"""

from __future__ import annotations

from typing import Callable

import flax.struct
import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.common import ConvergenceReason, SolverResult, run_while

Array = jax.Array

# LIBLINEAR trust-region constants (TRON.scala:168-175)
ETA0, ETA1, ETA2 = 1e-4, 0.25, 0.75
SIGMA1, SIGMA2, SIGMA3 = 0.25, 0.5, 4.0


def _truncated_cg(hv_fn, g: Array, delta: Array, max_cg: int, cg_tol: Array,
                  host_loop: bool = False):
    """Solve H z ≈ -g within the trust region ‖z‖ <= delta.

    Returns (z, hit_boundary, cg_iters). Steihaug-Toint truncated CG
    (reference TRON.truncatedConjugateGradientMethod, TRON.scala:278-338).
    ``host_loop=True`` drives the same CG body from Python so ``hv_fn`` may
    be a host-level streaming epoch accumulator (optim/common.run_while).
    """
    d0 = -g
    r0 = -g

    def boundary_step(z, dvec):
        # tau >= 0 with ‖z + tau*d‖ = delta
        zz = jnp.vdot(z, z)
        zd = jnp.vdot(z, dvec)
        dd = jnp.maximum(jnp.vdot(dvec, dvec), 1e-30)
        rad = jnp.sqrt(jnp.maximum(zd * zd + dd * (delta * delta - zz), 0.0))
        tau = (-zd + rad) / dd
        return z + tau * dvec

    def body(state):
        z, r, dvec, i, _hit, _done = state
        hd = hv_fn(dvec)
        dhd = jnp.vdot(dvec, hd)
        rr = jnp.vdot(r, r)
        # Negative curvature (non-convex edge case): go to the boundary.
        neg_curv = dhd <= 0.0
        alpha = rr / jnp.maximum(dhd, 1e-30)
        z_try = z + alpha * dvec
        outside = jnp.linalg.norm(z_try) >= delta
        z_bound = boundary_step(z, dvec)
        take_boundary = neg_curv | outside
        z_new = jnp.where(take_boundary, z_bound, z_try)
        r_new = r - alpha * hd
        rr_new = jnp.vdot(r_new, r_new)
        converged = jnp.sqrt(rr_new) <= cg_tol
        beta = rr_new / jnp.maximum(rr, 1e-30)
        d_new = r_new + beta * dvec
        done = take_boundary | converged
        return (z_new, r_new, d_new, i + 1, take_boundary, done)

    def cond(state):
        _z, _r, _d, i, _hit, done = state
        return (i < max_cg) & ~done

    z0 = jnp.zeros_like(g)
    z, _r, _d, iters, hit, _done = run_while(
        cond, body,
        (z0, r0, d0, jnp.int32(0), jnp.asarray(False), jnp.asarray(False)),
        host=host_loop,
    )
    return z, hit, iters


@flax.struct.dataclass
class _TRONState:
    w: Array
    f: Array
    g: Array
    delta: Array
    iteration: Array
    reason: Array
    value_history: Array
    grad_norm_history: Array


def minimize_tron(
    value_and_grad_fn: Callable[[Array], tuple[Array, Array]],
    hessian_vector_fn: Callable[[Array, Array], Array],
    w0: Array,
    *,
    max_iter: int = 15,
    tolerance: float = 1e-5,
    rel_function_tolerance: float | None = None,
    max_cg_iter: int = 20,
    cg_forcing: float = 0.1,
    host_loop: bool = False,
    state_observer=None,
    resume_state: "_TRONState | None" = None,
) -> SolverResult:
    """Minimize a twice-differentiable convex objective with TRON.

    ``hessian_vector_fn(w, v)`` returns H(w) @ v. Convergence when
    ‖g‖ <= tolerance * ‖g0‖ (LIBLINEAR's test, TRON.scala:208).

    ``host_loop=True``: the identical outer/CG body math driven from
    Python loops so both callbacks may be host-level streaming epoch
    accumulators (optim/common.run_while).

    ``rel_function_tolerance`` (None = reference behavior, no function
    test): live relative function-decrease stop on accepted rounds — the
    same warm-start exit the LBFGS/OWLQN/NEWTON family gained
    (optim/common.check_convergence semantics).

    ``state_observer`` / ``resume_state`` (host_loop only): per-outer-
    iteration state hook + checkpointed re-entry for crash-safe streaming
    solves — same contract as optim/lbfgs.minimize_lbfgs. The inner CG
    loop is never observed or resumed mid-flight: an outer iteration is
    the atomic (epoch-boundary) unit.
    """
    if (state_observer is not None or resume_state is not None) and not host_loop:
        raise ValueError(
            "state_observer/resume_state require host_loop=True (solver-"
            "state checkpointing exists for host-driven streaming solves)"
        )
    dtype = w0.dtype
    if resume_state is not None:
        init = resume_state
    else:
        w0 = jnp.asarray(w0, dtype)
        f0, g0 = value_and_grad_fn(w0)
        g0_norm = jnp.linalg.norm(g0)

        nan_hist = jnp.full((max_iter + 1,), jnp.nan, dtype)
        init = _TRONState(
            w=w0,
            f=f0,
            g=g0,
            delta=g0_norm,
            iteration=jnp.int32(0),
            # Warm starts arrive already-stationary: stop before paying a
            # CG loop. (The in-loop test is relative to g0; at iteration 0
            # only an absolute test is meaningful.)
            reason=jnp.where(
                g0_norm <= tolerance,
                jnp.int32(ConvergenceReason.GRADIENT_WITHIN_TOLERANCE),
                jnp.int32(ConvergenceReason.NOT_CONVERGED),
            ),
            value_history=nan_hist.at[0].set(f0),
            grad_norm_history=nan_hist.at[0].set(g0_norm),
        )

    def cond(state: _TRONState):
        return (state.iteration < max_iter) & (
            state.reason == ConvergenceReason.NOT_CONVERGED
        )

    def body(state: _TRONState):
        gnorm = jnp.linalg.norm(state.g)
        hv = lambda v: hessian_vector_fn(state.w, v)
        step, hit_boundary, _cg_iters = _truncated_cg(
            hv, state.g, state.delta, max_cg_iter, cg_forcing * gnorm,
            host_loop=host_loop,
        )

        gs = jnp.vdot(state.g, step)
        shs = jnp.vdot(step, hv(step))
        prered = -(gs + 0.5 * shs)
        f_new, g_new = value_and_grad_fn(state.w + step)
        actred = state.f - f_new

        snorm = jnp.linalg.norm(step)
        # Trust-region radius update (LIBLINEAR-style, TRON.scala:214-236)
        delta = state.delta
        # alpha interpolation factor for severe failures
        alpha = jnp.where(
            f_new - state.f - gs <= 0.0,
            SIGMA3,
            jnp.maximum(SIGMA1, -0.5 * (gs / jnp.minimum(f_new - state.f - gs, -1e-30))),
        )
        delta = jnp.where(
            actred < ETA0 * prered,
            jnp.minimum(jnp.maximum(alpha, SIGMA1) * snorm, SIGMA2 * delta),
            jnp.where(
                actred < ETA1 * prered,
                jnp.maximum(SIGMA1 * delta, jnp.minimum(alpha * snorm, SIGMA2 * delta)),
                jnp.where(
                    actred < ETA2 * prered,
                    jnp.maximum(SIGMA1 * delta, jnp.minimum(alpha * snorm, SIGMA3 * delta)),
                    jnp.where(
                        hit_boundary,
                        jnp.minimum(SIGMA3 * delta, jnp.maximum(delta, snorm)),
                        jnp.maximum(delta, jnp.minimum(alpha * snorm, SIGMA3 * delta)),
                    ),
                ),
            ),
        )

        accept = (actred > ETA0 * prered) & ~(jnp.isnan(f_new) | jnp.isinf(f_new))
        w_acc = jnp.where(accept, state.w + step, state.w)
        f_acc = jnp.where(accept, f_new, state.f)
        g_acc = jnp.where(accept, g_new, state.g)

        gnorm_acc = jnp.linalg.norm(g_acc)
        g0n = state.grad_norm_history[0]
        reason = jnp.where(
            gnorm_acc <= tolerance * jnp.maximum(g0n, 1e-30),
            jnp.int32(ConvergenceReason.GRADIENT_WITHIN_TOLERANCE),
            jnp.int32(ConvergenceReason.NOT_CONVERGED),
        )
        # A collapsed trust region means no further progress is possible.
        reason = jnp.where(
            delta < 1e-12,
            jnp.int32(ConvergenceReason.FUNCTION_VALUES_WITHIN_TOLERANCE),
            reason,
        )
        if rel_function_tolerance is not None:
            # live stop: an ACCEPTED round whose relative decrease is below
            # threshold (same test as optim/common.check_convergence)
            rel_delta = jnp.abs(f_acc - state.f) / jnp.maximum(
                jnp.maximum(jnp.abs(f_acc), jnp.abs(state.f)), 1.0
            )
            reason = jnp.where(
                accept
                & (rel_delta <= rel_function_tolerance)
                & (reason == ConvergenceReason.NOT_CONVERGED),
                jnp.int32(ConvergenceReason.FUNCTION_VALUES_WITHIN_TOLERANCE),
                reason,
            )

        it = state.iteration + 1
        return _TRONState(
            w=w_acc,
            f=f_acc,
            g=g_acc,
            delta=delta,
            iteration=it,
            reason=reason,
            value_history=state.value_history.at[it].set(f_acc),
            grad_norm_history=state.grad_norm_history.at[it].set(gnorm_acc),
        )

    final = run_while(cond, body, init, host=host_loop, observer=state_observer)
    reason = jnp.where(
        final.reason == ConvergenceReason.NOT_CONVERGED,
        jnp.int32(ConvergenceReason.MAX_ITERATIONS),
        final.reason,
    )
    return SolverResult(
        coefficients=final.w,
        value=final.f,
        gradient_norm=jnp.linalg.norm(final.g),
        iterations=final.iteration,
        reason=reason,
        value_history=final.value_history,
        grad_norm_history=final.grad_norm_history,
    )
