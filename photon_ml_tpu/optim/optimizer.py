"""Optimizer facade: config objects + dispatch to the jittable solvers.

Reference parity: photon-lib optimization/Optimizer.scala (template method +
convergence config), OptimizerFactory.scala, and the per-optimizer config in
OptimizerConfig/GLMOptimizationConfiguration. The reference's optimizer
objects are stateful; here an Optimizer is a frozen config whose ``solve``
is a pure function, so one compiled program serves every coordinate-descent
iteration, λ-grid point, and (vmapped) every random-effect entity.
"""

from __future__ import annotations

import dataclasses
import enum

import jax
import jax.numpy as jnp

from photon_ml_tpu.ops.objective import BoundObjective
from photon_ml_tpu.optim.common import SolverResult
from photon_ml_tpu.optim.lbfgs import minimize_lbfgs
from photon_ml_tpu.optim.newton import minimize_newton
from photon_ml_tpu.optim.owlqn import minimize_owlqn
from photon_ml_tpu.optim.tron import minimize_tron

Array = jax.Array


class OptimizerType(enum.Enum):
    """Reference: photon-lib optimization/OptimizerType.scala. NEWTON is a
    TPU-first extension with no reference analogue (optim/newton.py): the
    op-minimal solver for small-d vmapped per-entity solves. AUTO picks
    the fastest safe solver per coordinate KIND (resolve_auto_optimizer):
    NEWTON on eligible small-d dense vmapped solves (RE/MF buckets —
    the measured 18 vs 48 ms fused-sweep win), LBFGS everywhere else.
    Explicit LBFGS stays the reference-parity default."""

    LBFGS = "LBFGS"
    OWLQN = "OWLQN"
    LBFGSB = "LBFGSB"
    TRON = "TRON"
    NEWTON = "NEWTON"
    AUTO = "AUTO"


@dataclasses.dataclass(frozen=True)
class LaneSchedulerConfig:
    """Converged-lane scheduling for vmapped random-effect solves
    (algorithm/lane_scheduler.py; no reference analogue — the reference's
    per-entity RDD solves are independently scheduled by Spark's task
    scheduler, while vmapped lanes advance in lock-step to the worst lane).

    probe_iterations: short probe budget — every lane solves this many
        iterations, then only lanes that are still at MAX_ITERATIONS are
        host-compacted into power-of-two-padded rescue blocks and re-run
        with the remaining ``max_iterations - probe_iterations`` budget.
    freeze_coefficient_tolerance / freeze_gradient_tolerance: cross-sweep
        active sets — when BOTH are > 0, entities whose relative coefficient
        delta and final gradient norm fall below these thresholds after a
        sweep are frozen (skipped by later sweeps' solves, still rescored);
        the final sweep always runs everyone.
    """

    probe_iterations: int = 2
    freeze_coefficient_tolerance: float = 0.0
    freeze_gradient_tolerance: float = 0.0

    @property
    def freezes(self) -> bool:
        return (
            self.freeze_coefficient_tolerance > 0.0
            and self.freeze_gradient_tolerance > 0.0
        )


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Static solver configuration (reference OptimizerConfig.scala).

    ``box_constraints``: optional (lower, upper) arrays for LBFGSB / the
    reference's constraint-map projection (LBFGS.scala:70-76).

    ``rel_function_tolerance`` (None = reference behavior): separate live
    function-decrease stop threshold — the knob that lets warm-started
    vmapped lanes exit before max_iter (optim/common.check_convergence).

    ``scheduler`` (None = off, bitwise-identical to the unscheduled path):
    probe/rescue lane scheduling for vmapped random-effect solves. Consumed
    ABOVE :func:`solve` by algorithm/lane_scheduler.py; the solver dispatch
    below ignores it.
    """

    optimizer_type: OptimizerType = OptimizerType.LBFGS
    max_iterations: int = 100
    tolerance: float = 1e-7
    history: int = 10  # L-BFGS memory m
    max_cg_iterations: int = 20  # TRON inner loop cap
    l1_weight: float = 0.0  # OWLQN only; set by the elastic-net path
    rel_function_tolerance: float | None = None
    scheduler: LaneSchedulerConfig | None = None

    def with_l1(self, l1_weight: float) -> "OptimizerConfig":
        return dataclasses.replace(self, l1_weight=l1_weight)


def solve(
    config: OptimizerConfig,
    objective: BoundObjective,
    w0: Array,
    *,
    lower_bounds: Array | None = None,
    upper_bounds: Array | None = None,
    host_loop: bool = False,
    state_observer=None,
    resume_state=None,
) -> SolverResult:
    """Run the configured solver on a bound objective. Pure; jit/vmap-safe.

    ``host_loop=True`` drives the solver's identical per-iteration math
    from Python loops so the objective may be a host-level chunked-epoch
    accumulator (algorithm/streaming.py); LBFGS/OWLQN/TRON only — NEWTON
    needs a dense [d, d] Hessian no streaming objective materializes.

    ``state_observer`` / ``resume_state`` (host_loop only): the solver-
    state checkpoint hooks (io/checkpoint.SolverCheckpointer) — the
    observer sees the solver's state struct after every outer iteration,
    ``resume_state`` re-enters from a restored one. The matching state
    class is ``solver_state_class(config)``.
    """
    t = config.optimizer_type
    if t == OptimizerType.AUTO:
        # AUTO is a coordinate-layer concept: the safe/fast choice depends
        # on the SOLVE SHAPE (vmapped small-d dense vs big-d streamed),
        # which this dispatch cannot see — the coordinate call sites
        # resolve it before building jitted programs
        raise ValueError(
            "OptimizerType.AUTO must be resolved before solve() — call "
            "resolve_auto_optimizer(config, loss=..., small_dense=...) at "
            "the coordinate layer (estimators/coordinates/programs do this "
            "for their own specs)"
        )
    if (state_observer is not None or resume_state is not None) and (
        not host_loop or t == OptimizerType.NEWTON
    ):
        raise ValueError(
            "state_observer/resume_state cover the host-loop LBFGS/OWLQN/"
            "TRON solvers only (streaming solver checkpointing)"
        )
    if host_loop and t == OptimizerType.NEWTON:
        raise ValueError(
            "NEWTON has no host-loop (streaming) mode — it needs the dense "
            "[d, d] Hessian; use TRON for streamed second-order solves"
        )
    if (lower_bounds is not None or upper_bounds is not None) and t not in (
        OptimizerType.LBFGS, OptimizerType.LBFGSB
    ):
        raise ValueError(
            f"box constraints are only supported by the LBFGS family, not "
            f"{t.name} (the reference projects in LBFGS, LBFGS.scala:70-76)"
        )
    if t == OptimizerType.LBFGS:
        # a constraint map makes plain LBFGS project onto the box after each
        # step, exactly like the reference (LBFGS.scala:70-76)
        return minimize_lbfgs(
            objective.value_and_grad,
            w0,
            max_iter=config.max_iterations,
            history=config.history,
            tolerance=config.tolerance,
            rel_function_tolerance=config.rel_function_tolerance,
            lower_bounds=lower_bounds,
            upper_bounds=upper_bounds,
            host_loop=host_loop,
            state_observer=state_observer,
            resume_state=resume_state,
        )
    if t == OptimizerType.LBFGSB:
        if lower_bounds is None and upper_bounds is None:
            raise ValueError("LBFGSB requires box constraints")
        return minimize_lbfgs(
            objective.value_and_grad,
            w0,
            max_iter=config.max_iterations,
            history=config.history,
            tolerance=config.tolerance,
            rel_function_tolerance=config.rel_function_tolerance,
            lower_bounds=lower_bounds,
            upper_bounds=upper_bounds,
            host_loop=host_loop,
            state_observer=state_observer,
            resume_state=resume_state,
        )
    if t == OptimizerType.OWLQN:
        return minimize_owlqn(
            objective.value_and_grad,
            w0,
            l1_weight=config.l1_weight,
            max_iter=config.max_iterations,
            history=config.history,
            tolerance=config.tolerance,
            rel_function_tolerance=config.rel_function_tolerance,
            host_loop=host_loop,
            state_observer=state_observer,
            resume_state=resume_state,
        )
    if t == OptimizerType.TRON:
        loss = objective.objective.loss
        if not loss.twice_differentiable:
            raise ValueError(
                f"TRON requires a twice-differentiable loss, got {type(loss).__name__}"
                " (reference restricts smoothed-hinge to the LBFGS family)"
            )
        return minimize_tron(
            objective.value_and_grad,
            objective.hessian_vector,
            w0,
            max_iter=config.max_iterations,
            tolerance=config.tolerance,
            rel_function_tolerance=config.rel_function_tolerance,
            max_cg_iter=config.max_cg_iterations,
            host_loop=host_loop,
            state_observer=state_observer,
            resume_state=resume_state,
        )
    if t == OptimizerType.NEWTON:
        loss = objective.objective.loss
        if not loss.twice_differentiable:
            raise ValueError(
                f"NEWTON requires a twice-differentiable loss, got "
                f"{type(loss).__name__} (same restriction as TRON)"
            )
        # the generic BoundObjective always has the method; what matters is
        # whether the UNDERLYING objective can produce a dense [d, d] H
        inner = getattr(objective, "objective", objective)
        if not hasattr(inner, "hessian_matrix"):
            raise ValueError(
                "NEWTON needs an explicit [d, d] Hessian; "
                f"{type(inner).__name__} does not expose one — NEWTON is "
                "meant for small-d dense (per-entity) solves"
            )
        return minimize_newton(
            objective.value_and_grad,
            objective.hessian_matrix,
            w0,
            value_fn=objective.value,
            max_iter=config.max_iterations,
            tolerance=config.tolerance,
            rel_function_tolerance=config.rel_function_tolerance,
        )
    raise ValueError(f"Unknown optimizer type {t}")


def resolve_auto_optimizer(
    config: OptimizerConfig,
    *,
    loss=None,
    small_dense: bool = False,
) -> OptimizerConfig:
    """Resolve ``OptimizerType.AUTO`` into a concrete solver for one solve
    site; non-AUTO configs pass through untouched.

    ``small_dense=True`` marks the vmapped small-d dense per-entity solve
    shape (RE/MF buckets): there AUTO promotes to NEWTON — the op-minimal
    solver for that shape (fused_game_sweep_newton_ms = 18 vs 48 ms,
    BASELINE.md r5) — exactly when the dispatch guards in :func:`solve`
    would accept it (twice-differentiable ``loss``, no L1 term; box
    constraints are an LBFGS-family feature and AUTO never carries them
    here). Everything else (big-d FE solves, streamed host-loop
    objectives) resolves to LBFGS, the reference-parity default — except
    a config already carrying ``l1_weight`` > 0, which resolves to OWLQN
    directly: plain LBFGS never reads ``l1_weight``, so mapping AUTO+L1
    to LBFGS at a call site without its own ``uses_owlqn`` flip (the spec
    paths) would silently drop the penalty. Callers whose elastic-net
    flip runs later (``_solve_config``/``with_l1``) see the same end
    state either way.
    """
    if config.optimizer_type != OptimizerType.AUTO:
        return config
    if config.l1_weight > 0.0:
        resolved = OptimizerType.OWLQN
    else:
        eligible = (
            small_dense
            and loss is not None
            and getattr(loss, "twice_differentiable", False)
        )
        resolved = (
            OptimizerType.NEWTON if eligible else OptimizerType.LBFGS
        )
    return dataclasses.replace(config, optimizer_type=resolved)


def solver_state_class(config: OptimizerConfig):
    """The flax-struct state class ``solve(config, ..., host_loop=True)``
    hands to a ``state_observer`` — the (de)serialization contract of
    io/checkpoint.SolverCheckpointer. The effective solver for an
    elastic-net λ is OWLQN whenever ``l1_weight`` > 0 (estimators'
    per-λ switch), which this lookup mirrors via ``optimizer_type``."""
    from photon_ml_tpu.optim.lbfgs import _LBFGSState
    from photon_ml_tpu.optim.owlqn import _OWLQNState
    from photon_ml_tpu.optim.tron import _TRONState

    t = config.optimizer_type
    if t in (OptimizerType.LBFGS, OptimizerType.LBFGSB):
        return _LBFGSState
    if t == OptimizerType.OWLQN:
        return _OWLQNState
    if t == OptimizerType.TRON:
        return _TRONState
    raise ValueError(
        f"{t.name} has no host-loop (streaming) mode, so no checkpointable "
        "solver state"
    )


def default_config_for(optimizer_type: OptimizerType) -> OptimizerConfig:
    """Reference defaults: LBFGS maxIter=100 tol=1e-7 (LBFGS.scala:152-157);
    TRON maxIter=15 tol=1e-5 (TRON.scala:257-262)."""
    if optimizer_type == OptimizerType.TRON:
        return OptimizerConfig(
            optimizer_type=optimizer_type, max_iterations=15, tolerance=1e-5
        )
    return OptimizerConfig(optimizer_type=optimizer_type)
