"""Pure-JAX L-BFGS with weak-Wolfe line search and optional box projection.

Reference parity: photon-lib optimization/LBFGS.scala (breeze LBFGS wrapper,
defaults maxIter=100, m=10, tol=1e-7, LBFGS.scala:152-157; box-constraint
projection after each step, LBFGS.scala:70-76).

TPU-native design: the whole solve — two-loop recursion, line search,
convergence tests — is one ``lax.while_loop`` inside one XLA program. State
is a pytree with fixed shapes (circular [m, d] history buffers), so the
solver jits once, reuses the compiled program across coordinate-descent
iterations and λ-grid points, and vmaps over entities for random-effect
coordinates (replacing RandomEffectCoordinate.scala:104-153's per-entity
breeze solves).
"""

from __future__ import annotations

from typing import Callable

import flax.struct
import jax
import jax.numpy as jnp
from jax import lax

from photon_ml_tpu.optim.common import (
    ConvergenceReason,
    SolverResult,
    check_convergence,
    run_while,
    wolfe_line_search,
)

Array = jax.Array

DEFAULT_MAX_ITER = 100
DEFAULT_HISTORY = 10
DEFAULT_TOLERANCE = 1e-7


def two_loop_direction(
    g: Array, s_hist: Array, y_hist: Array, rho: Array, count: Array, head: Array
) -> Array:
    """L-BFGS two-loop recursion over a circular history buffer.

    s_hist/y_hist: [m, d]; rho: [m] (1/sᵀy); count: number of valid pairs;
    head: slot of the most recent pair. Invalid slots are masked by zeroing
    their alpha/beta contributions, keeping shapes static for jit.
    """
    m = s_hist.shape[0]

    def backward(i, carry):
        q, alphas = carry
        idx = (head - i) % m
        valid = i < count
        alpha = jnp.where(valid, rho[idx] * jnp.vdot(s_hist[idx], q), 0.0)
        q = q - alpha * y_hist[idx]
        return q, alphas.at[idx].set(alpha)

    q, alphas = lax.fori_loop(0, m, backward, (g, jnp.zeros((m,), dtype=g.dtype)))

    gamma = jnp.where(
        count > 0,
        jnp.vdot(s_hist[head], y_hist[head])
        / jnp.maximum(jnp.vdot(y_hist[head], y_hist[head]), 1e-30),
        1.0,
    )
    r = gamma * q

    def forward(i, r):
        # oldest-to-newest among valid entries
        idx = (head - (count - 1 - i)) % m
        valid = i < count
        beta = rho[idx] * jnp.vdot(y_hist[idx], r)
        return r + jnp.where(valid, (alphas[idx] - beta), 0.0) * s_hist[idx]

    r = lax.fori_loop(0, m, forward, r)
    return -r


@flax.struct.dataclass
class _LBFGSState:
    w: Array
    f: Array
    g: Array
    s_hist: Array
    y_hist: Array
    rho: Array
    count: Array
    head: Array
    iteration: Array
    reason: Array
    prev_f: Array
    g0_norm: Array
    value_history: Array
    grad_norm_history: Array


def minimize_lbfgs(
    value_and_grad_fn: Callable[[Array], tuple[Array, Array]],
    w0: Array,
    *,
    max_iter: int = DEFAULT_MAX_ITER,
    history: int = DEFAULT_HISTORY,
    tolerance: float = DEFAULT_TOLERANCE,
    rel_function_tolerance: float | None = None,
    lower_bounds: Array | None = None,
    upper_bounds: Array | None = None,
    max_line_search_steps: int = 25,
    host_loop: bool = False,
    state_observer=None,
    resume_state: "_LBFGSState | None" = None,
) -> SolverResult:
    """Minimize a smooth function with L-BFGS. Jit- and vmap-safe.

    ``host_loop=True`` runs the identical per-iteration body from a Python
    loop (optim/common.run_while) so ``value_and_grad_fn`` may be a HOST
    function — the out-of-core streaming epoch accumulator
    (algorithm/streaming.py). The default compiles exactly as before.

    ``state_observer`` / ``resume_state`` (host_loop only — crash-safe
    streaming solves, io/checkpoint.SolverCheckpointer): the observer sees
    the full ``_LBFGSState`` after every outer iteration (an epoch
    boundary — each iteration is an integral number of chunked epochs);
    ``resume_state`` re-enters the loop from a checkpointed state WITHOUT
    re-evaluating the initial point (the whole saving — the skipped
    iterations each cost epochs). Both default to None, which is bitwise
    the pre-existing solve.

    With ``lower_bounds``/``upper_bounds`` set, iterates are projected onto
    the box after every accepted step and convergence is tested on the
    projected gradient — the gradient-projection scheme the reference applies
    (LBFGS.scala:70-76); the dedicated LBFGSB entry point builds on this.

    ``rel_function_tolerance`` (None = reference behavior, use
    ``tolerance``): a separate live function-decrease stop inside the
    while_loop condition, so warm-started vmapped lanes can actually exit
    instead of paying max_iter (optim/common.check_convergence).
    """
    if (state_observer is not None or resume_state is not None) and not host_loop:
        raise ValueError(
            "state_observer/resume_state require host_loop=True (solver-"
            "state checkpointing exists for host-driven streaming solves)"
        )
    dtype = w0.dtype
    d = w0.shape[0]
    m = history

    has_box = lower_bounds is not None or upper_bounds is not None
    lo = jnp.full((d,), -jnp.inf, dtype) if lower_bounds is None else jnp.asarray(lower_bounds, dtype)
    hi = jnp.full((d,), jnp.inf, dtype) if upper_bounds is None else jnp.asarray(upper_bounds, dtype)

    def project(w):
        return jnp.clip(w, lo, hi) if has_box else w

    def projected_grad_norm(w, g):
        if not has_box:
            return jnp.linalg.norm(g)
        # norm of P(w - g) - w: zero iff w is box-stationary
        return jnp.linalg.norm(project(w - g) - w)

    if resume_state is not None:
        # checkpointed re-entry: the saved state already holds f/g/history
        # for its iterate — re-evaluating w0 would cost an epoch for
        # numbers the checkpoint carries
        init = resume_state
    else:
        w0 = project(jnp.asarray(w0, dtype))
        f0, g0 = value_and_grad_fn(w0)
        g0_norm = projected_grad_norm(w0, g0)

        nan_hist = jnp.full((max_iter + 1,), jnp.nan, dtype)
        init = _LBFGSState(
            w=w0,
            f=f0,
            g=g0,
            s_hist=jnp.zeros((m, d), dtype),
            y_hist=jnp.zeros((m, d), dtype),
            rho=jnp.zeros((m,), dtype),
            count=jnp.int32(0),
            head=jnp.int32(0),
            iteration=jnp.int32(0),
            reason=jnp.int32(ConvergenceReason.NOT_CONVERGED),
            prev_f=jnp.asarray(jnp.inf, dtype),
            g0_norm=g0_norm,
            value_history=nan_hist.at[0].set(f0),
            grad_norm_history=nan_hist.at[0].set(g0_norm),
        )

        # Already stationary at the initial point?
        init = init.replace(
            reason=jnp.where(
                g0_norm <= tolerance,
                jnp.int32(ConvergenceReason.GRADIENT_WITHIN_TOLERANCE),
                init.reason,
            )
        )

    def cond(state: _LBFGSState):
        return (state.iteration < max_iter) & (
            state.reason == ConvergenceReason.NOT_CONVERGED
        )

    def body(state: _LBFGSState):
        direction = two_loop_direction(
            state.g, state.s_hist, state.y_hist, state.rho, state.count, state.head
        )
        if has_box:
            # Active-set masking: don't push into an active bound
            # (projected L-BFGS; reference projects per step, LBFGS.scala:70-76).
            eps_b = 1e-10
            active = ((state.w <= lo + eps_b) & (direction < 0.0)) | (
                (state.w >= hi - eps_b) & (direction > 0.0)
            )
            direction = jnp.where(active, 0.0, direction)
            sd = -state.g
            sd = jnp.where(
                ((state.w <= lo + eps_b) & (sd < 0.0))
                | ((state.w >= hi - eps_b) & (sd > 0.0)),
                0.0,
                sd,
            )
            direction = jnp.where(jnp.vdot(state.g, direction) >= 0.0, sd, direction)
        else:
            # Guard: fall back to steepest descent if not a descent direction.
            direction = jnp.where(jnp.vdot(state.g, direction) >= 0.0, -state.g, direction)

        t_init = jnp.where(
            state.count == 0,
            1.0 / jnp.maximum(jnp.linalg.norm(state.g), 1.0),
            jnp.ones((), dtype),
        )

        if has_box:
            # Projected Armijo backtracking: trial points stay feasible, the
            # sufficient-decrease test uses the actual displacement.
            c1 = 1e-4

            def ls_body(s):
                i, t, _w, _f, _g, _ok = s
                cand = project(state.w + t * direction)
                f_t, g_t = value_and_grad_fn(cand)
                decrease = jnp.vdot(state.g, cand - state.w)
                ok = (
                    (f_t <= state.f + c1 * decrease)
                    & ~(jnp.isnan(f_t) | jnp.isinf(f_t))
                    & (f_t < state.f)
                )
                return (i + 1, t * 0.5, cand, f_t, g_t, ok)

            def ls_cond(s):
                i, _t, _w, _f, _g, ok = s
                return (i < max_line_search_steps) & ~ok

            _, _, w_new, f_new, g_new, ls_ok = run_while(
                ls_cond,
                ls_body,
                (jnp.int32(0), t_init, state.w, state.f, state.g, jnp.asarray(False)),
                host=host_loop,
            )
            ls_success = ls_ok
        else:
            ls = wolfe_line_search(
                value_and_grad_fn,
                state.w,
                state.f,
                state.g,
                direction,
                t_init,
                max_steps=max_line_search_steps,
                host_loop=host_loop,
            )
            w_new = state.w + ls.step * direction
            f_new, g_new = ls.value, ls.gradient
            ls_success = ls.success

        s = w_new - state.w
        y = g_new - state.g
        sy = jnp.vdot(s, y)
        keep_pair = ls_success & (sy > 1e-10)

        new_head = jnp.where(keep_pair, (state.head + 1) % m, state.head)
        # count==0 means head slot 0 is where the first pair goes
        write_head = jnp.where(state.count == 0, jnp.int32(0), new_head)
        new_head = jnp.where(state.count == 0, jnp.int32(0), new_head)
        s_hist = jnp.where(
            keep_pair, state.s_hist.at[write_head].set(s), state.s_hist
        )
        y_hist = jnp.where(
            keep_pair, state.y_hist.at[write_head].set(y), state.y_hist
        )
        rho = jnp.where(
            keep_pair,
            state.rho.at[write_head].set(1.0 / jnp.maximum(sy, 1e-30)),
            state.rho,
        )
        count = jnp.where(keep_pair, jnp.minimum(state.count + 1, m), state.count)

        gnorm = projected_grad_norm(w_new, g_new)
        reason = jnp.where(
            ls_success,
            check_convergence(
                value=f_new,
                prev_value=state.f,
                grad_norm=gnorm,
                initial_grad_norm=state.g0_norm,
                tolerance=tolerance,
                rel_function_tolerance=rel_function_tolerance,
            ),
            jnp.int32(ConvergenceReason.LINE_SEARCH_FAILED),
        )

        it = state.iteration + 1
        return _LBFGSState(
            w=jnp.where(ls_success, w_new, state.w),
            f=jnp.where(ls_success, f_new, state.f),
            g=jnp.where(ls_success, g_new, state.g),
            s_hist=s_hist,
            y_hist=y_hist,
            rho=rho,
            count=count,
            head=new_head,
            iteration=it,
            reason=reason,
            prev_f=state.f,
            g0_norm=state.g0_norm,
            value_history=state.value_history.at[it].set(jnp.where(ls_success, f_new, state.f)),
            grad_norm_history=state.grad_norm_history.at[it].set(gnorm),
        )

    final = run_while(cond, body, init, host=host_loop, observer=state_observer)
    reason = jnp.where(
        final.reason == ConvergenceReason.NOT_CONVERGED,
        jnp.int32(ConvergenceReason.MAX_ITERATIONS),
        final.reason,
    )
    return SolverResult(
        coefficients=final.w,
        value=final.f,
        gradient_norm=projected_grad_norm(final.w, final.g),
        iterations=final.iteration,
        reason=reason,
        value_history=final.value_history,
        grad_norm_history=final.grad_norm_history,
    )
