"""Shared optimizer machinery: results, convergence, line search.

Reference parity: photon-lib optimization/Optimizer.scala (template loop,
convergence by max-iter / loss-delta / gradient-norm, Optimizer.scala:135-149)
and OptimizationStatesTracker.scala (per-iteration state history).

Everything here is jit- and vmap-safe: fixed shapes, lax control flow, no
data-dependent python branching. ``vmap(minimize_*)`` over per-entity
objectives is the TPU replacement for the reference's per-entity RDD solves.
"""

from __future__ import annotations

import enum

import flax.struct
import jax
import jax.numpy as jnp
from jax import lax

Array = jax.Array


class ConvergenceReason(enum.IntEnum):
    """Why an optimizer stopped (reference util/ConvergenceReason.scala)."""

    NOT_CONVERGED = 0
    MAX_ITERATIONS = 1
    FUNCTION_VALUES_WITHIN_TOLERANCE = 2
    GRADIENT_WITHIN_TOLERANCE = 3
    LINE_SEARCH_FAILED = 4


def run_while(cond, body, init, *, host: bool = False, observer=None):
    """``lax.while_loop`` — or, with ``host=True``, the IDENTICAL loop body
    driven from Python with concrete arrays.

    The host mode exists for out-of-core streaming solves
    (algorithm/streaming.py): there ``value_and_grad_fn`` is a HOST
    function (one chunked epoch over data that never fits on device), so
    it cannot be traced into a ``lax.while_loop`` body — tracing would
    both consume the chunk stream at trace time and bake every chunk into
    the program as constants (the HTTP-413 landmine). Every per-iteration
    operation is the same jax code either way; only the control-flow
    driver changes, so the host loop follows the in-core solve's
    arithmetic step for step (differences come only from the chunked
    summation order inside the objective, i.e. float round-off).

    ``observer`` (host mode only): called with the state after every body
    step — the epoch-boundary hook solver-state checkpointing rides
    (io/checkpoint.SolverCheckpointer). It observes, never rewrites: the
    state it receives is the state the loop continues with, so a solve
    with an observer is bitwise the solve without one.

    The default (``host=False``) compiles to the exact same
    ``lax.while_loop`` call as before this parameter existed.
    """
    if not host:
        if observer is not None:
            raise ValueError(
                "run_while(observer=...) requires host=True — a compiled "
                "lax.while_loop body cannot call back to the host"
            )
        return lax.while_loop(cond, body, init)
    from photon_ml_tpu.telemetry import tracing

    state = init
    i = 0
    while bool(cond(state)):
        # per-iteration host wall-clock span (a streaming solve's iteration
        # IS an epoch or several); observes only — the body/observer
        # sequence is identical with tracing off
        with tracing.span("solver/iteration", cat="solver", i=i):
            state = body(state)
        if observer is not None:
            observer(state)
        i += 1
    return state


@flax.struct.dataclass
class SolverResult:
    """Final state + per-iteration history of one solve.

    ``value_history`` / ``grad_norm_history`` are fixed-size [max_iter + 1]
    arrays padded with NaN past ``iterations`` — the jittable analogue of
    OptimizationStatesTracker's bounded state queue.
    """

    coefficients: Array
    value: Array
    gradient_norm: Array
    iterations: Array  # int32 scalar
    reason: Array  # int32 scalar, ConvergenceReason code
    value_history: Array
    grad_norm_history: Array

    @property
    def converged(self) -> Array:
        return self.reason != ConvergenceReason.NOT_CONVERGED

    def states_table(self) -> str:
        """Printable per-iteration state table (reference
        OptimizationStatesTracker.toString, OptimizationStatesTracker.scala:
        82-101): iteration | objective value | gradient norm, ending with
        the convergence reason."""
        import numpy as np

        values = np.asarray(self.value_history)
        grads = np.asarray(self.grad_norm_history)
        n = int(self.iterations)
        lines = [f"{'iter':>6} {'value':>16} {'|gradient|':>16}"]
        for i in range(min(n + 1, len(values))):
            if np.isnan(values[i]):
                break
            lines.append(f"{i:>6} {values[i]:>16.8g} {grads[i]:>16.8g}")
        reason = ConvergenceReason(int(self.reason)).name
        lines.append(f"converged after {n} iterations: {reason}")
        return "\n".join(lines)


@flax.struct.dataclass
class LaneTrace:
    """Per-lane convergence scalars of a vmapped solve (one entry per solver
    lane: a λ-grid point or a random-effect entity).

    The jittable skeleton of the reference's per-problem
    OptimizationStatesTracker reporting (OptimizationStatesTracker.scala:
    82-101): vmapped solves cannot keep per-iteration host-side state, but
    XLA computes each lane's final iteration count / reason / value anyway —
    these are those scalars surfaced as tiny extra outputs. ``valid`` masks
    padding lanes (OOB-sentinel entity rows solve all-zero-weight batches
    and must not pollute convergence tallies). Consumed by
    telemetry/solver_trace.py for reason tallies across lanes — the
    "every lane pays max_iter" pathology (CLAUDE.md) made visible.
    """

    iterations: Array  # [lanes] int32
    reason: Array  # [lanes] int32 ConvergenceReason codes
    value: Array  # [lanes] final objective values
    gradient_norm: Array  # [lanes]
    valid: Array  # [lanes] bool; False = padding lane
    #: True when the lane scheduler (algorithm/lane_scheduler.py) produced
    #: this trace — it has already observed these lanes into the
    #: solver/lane_iters histogram, so telemetry consumers must not count
    #: them again (static metadata, not a pytree leaf)
    scheduled: bool = flax.struct.field(pytree_node=False, default=False)


class LaneTraces:
    """Per-bucket LaneTraces held AS the device arrays the solves returned.

    Deliberately not a pytree and never merged on device: eager
    ``jnp.concatenate`` dispatches cost a ~100 ms tunnel round-trip each on
    the remote-TPU platform (CLAUDE.md), so the merge happens host-side in
    numpy — and only when a telemetry consumer actually reads the traces
    (telemetry/solver_trace.py). A coordinate update with no telemetry
    attached pays nothing for carrying this object.
    """

    def __init__(self, buckets):
        self.buckets: tuple[LaneTrace, ...] = tuple(buckets)


def lane_trace_of(result: SolverResult, valid: Array | None = None) -> LaneTrace:
    """Build a LaneTrace from a (vmapped) SolverResult, dropping the
    per-iteration histories that padding lanes would make meaningless."""
    iterations = jnp.atleast_1d(result.iterations)
    if valid is None:
        valid = jnp.ones(iterations.shape, dtype=bool)
    return LaneTrace(
        iterations=iterations,
        reason=jnp.atleast_1d(result.reason),
        value=jnp.atleast_1d(result.value),
        gradient_norm=jnp.atleast_1d(result.gradient_norm),
        valid=jnp.atleast_1d(valid),
    )


def check_convergence(
    *,
    value: Array,
    prev_value: Array,
    grad_norm: Array,
    initial_grad_norm: Array,
    tolerance: float,
    rel_function_tolerance: float | None = None,
) -> Array:
    """Return a ConvergenceReason code (0 if not converged).

    Matches the reference's dual test (Optimizer.scala:135-149): relative
    change in objective value below tolerance, or gradient norm below
    tolerance relative to the initial gradient norm.

    ``rel_function_tolerance`` (default None = use ``tolerance``, the
    reference behavior) sets a SEPARATE threshold for the function-decrease
    test. This is the live stop that actually fires in f32 for warm-started
    vmapped lanes: an exact step leaves ‖g‖ at rounding scale, which a large
    warm-start g0 never maps below the relative gradient tolerance, and at
    the 1e-7 default the relative value delta sits at f32 rounding scale too
    — without a looser live function stop every lane pays max_iter
    (CLAUDE.md; the ~87% RE-solve share of the fused sweep, BASELINE.md r5).
    """
    rel_delta = jnp.abs(value - prev_value) / jnp.maximum(
        jnp.maximum(jnp.abs(value), jnp.abs(prev_value)), 1.0
    )
    ftol = tolerance if rel_function_tolerance is None else rel_function_tolerance
    func_ok = rel_delta <= ftol
    grad_ok = grad_norm <= tolerance * jnp.maximum(initial_grad_norm, 1.0)
    return jnp.where(
        grad_ok,
        jnp.int32(ConvergenceReason.GRADIENT_WITHIN_TOLERANCE),
        jnp.where(
            func_ok,
            jnp.int32(ConvergenceReason.FUNCTION_VALUES_WITHIN_TOLERANCE),
            jnp.int32(ConvergenceReason.NOT_CONVERGED),
        ),
    )


@flax.struct.dataclass
class LineSearchResult:
    step: Array
    value: Array
    gradient: Array
    success: Array  # bool


def wolfe_line_search(
    value_and_grad_fn,
    w: Array,
    f0: Array,
    g0: Array,
    direction: Array,
    t_init: Array,
    c1: float = 1e-4,
    c2: float = 0.9,
    max_steps: int = 25,
    host_loop: bool = False,
) -> LineSearchResult:
    """Weak-Wolfe bisection line search, fully jittable.

    ``host_loop=True`` drives the same trial-step body from Python (see
    :func:`run_while`) so a host-level chunked ``value_and_grad_fn`` can be
    searched over; the default stays the one ``lax.while_loop``.

    Bracketing bisection: shrink on Armijo failure, expand (or bisect within
    the bracket) on curvature failure. Each trial costs one value_and_grad —
    cheap once jitted, since the whole optimizer step lives in one XLA
    program (SURVEY.md §7 "keep the whole optimizer step inside one jit").

    Replaces breeze's StrongWolfeLineSearch used by the reference's LBFGS
    (optimization/LBFGS.scala:97-107).
    """
    dg0 = jnp.vdot(g0, direction)

    def body(state):
        i, t, lo, hi, t_best, f_best, g_best, has_best, _done = state
        f_t, g_t = value_and_grad_fn(w + t * direction)
        bad = jnp.isnan(f_t) | jnp.isinf(f_t)
        armijo = (f_t <= f0 + c1 * t * dg0) & ~bad
        curv = jnp.vdot(g_t, direction) >= c2 * dg0
        done = armijo & curv
        # Remember the best Armijo-satisfying point seen so far: if curvature
        # never holds within max_steps, we still return a genuine decrease
        # step instead of reporting a spurious line-search failure.
        better = armijo & (~has_best | (f_t < f_best))
        t_best = jnp.where(better, t, t_best)
        f_best = jnp.where(better, f_t, f_best)
        g_best = jax.tree.map(lambda a, b: jnp.where(better, a, b), g_t, g_best)
        has_best = has_best | armijo
        # Armijo failed -> step too long: shrink bracket from above.
        new_hi = jnp.where(~armijo, t, hi)
        # Armijo ok but curvature failed -> step too short: raise lower edge.
        new_lo = jnp.where(armijo & ~curv, t, lo)
        new_t = jnp.where(
            ~armijo,
            0.5 * (new_lo + new_hi),
            jnp.where(
                ~curv,
                jnp.where(jnp.isinf(new_hi), 2.0 * t, 0.5 * (new_lo + new_hi)),
                t,
            ),
        )
        return (i + 1, new_t, new_lo, new_hi, t_best, f_best, g_best, has_best, done)

    def cond(state):
        i, *_rest, done = state
        return (i < max_steps) & ~done

    inf = jnp.asarray(jnp.inf, dtype=f0.dtype)
    zero = jnp.zeros((), dtype=f0.dtype)
    init = (
        jnp.int32(0),
        t_init.astype(f0.dtype),
        zero,
        inf,
        zero,
        f0,
        g0,
        jnp.asarray(False),
        jnp.asarray(False),
    )
    _, _, _, _, t_best, f_best, g_best, has_best, _done = run_while(
        cond, body, init, host=host_loop
    )
    success = has_best & (f_best < f0)
    return LineSearchResult(step=t_best, value=f_best, gradient=g_best, success=success)
