from photon_ml_tpu.optim.common import (  # noqa: F401
    ConvergenceReason,
    SolverResult,
)
from photon_ml_tpu.optim.lbfgs import minimize_lbfgs  # noqa: F401
from photon_ml_tpu.optim.newton import minimize_newton  # noqa: F401
from photon_ml_tpu.optim.owlqn import minimize_owlqn  # noqa: F401
from photon_ml_tpu.optim.tron import minimize_tron  # noqa: F401
from photon_ml_tpu.optim.optimizer import (  # noqa: F401
    LaneSchedulerConfig,
    OptimizerConfig,
    OptimizerType,
    default_config_for,
    solve,
)
