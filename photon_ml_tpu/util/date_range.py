"""Date-range parsing and date-partitioned input path resolution.

Reference parity: photon-client util/DateRange.scala ("yyyyMMdd-yyyyMMdd"
ranges), util/DaysRange.scala ("N-M" days-ago ranges, converted to a
DateRange relative to today), and IOUtils.getInputPathsWithinDateRange —
resolving `<base>/daily/yyyy/MM/dd` directories inside a range, erroring
when no data exists.
"""

from __future__ import annotations

import dataclasses
import datetime
import os
import re
from typing import Sequence

_DATE_FMT = "%Y%m%d"
_RANGE_RE = re.compile(r"^(\d{8})-(\d{8})$")
_DAYS_RE = re.compile(r"^(\d+)-(\d+)$")


@dataclasses.dataclass(frozen=True)
class DateRange:
    """Inclusive [start, end] date range (reference DateRange.scala)."""

    start: datetime.date
    end: datetime.date

    def __post_init__(self):
        if self.start > self.end:
            raise ValueError(
                f"invalid date range: start {self.start} is after end {self.end}"
            )

    @classmethod
    def parse(cls, spec: str) -> "DateRange":
        """Parse "yyyyMMdd-yyyyMMdd"."""
        m = _RANGE_RE.match(spec.strip())
        if not m:
            raise ValueError(
                f"bad date range {spec!r}; expected yyyyMMdd-yyyyMMdd"
            )
        return cls(
            start=datetime.datetime.strptime(m.group(1), _DATE_FMT).date(),
            end=datetime.datetime.strptime(m.group(2), _DATE_FMT).date(),
        )

    def dates(self) -> list[datetime.date]:
        n = (self.end - self.start).days + 1
        return [self.start + datetime.timedelta(days=i) for i in range(n)]

    def __str__(self) -> str:
        return f"{self.start.strftime(_DATE_FMT)}-{self.end.strftime(_DATE_FMT)}"


@dataclasses.dataclass(frozen=True)
class DaysRange:
    """"start-end" days ago, start >= end (reference DaysRange.scala:
    '90-1' = from 90 days ago until yesterday)."""

    start_days_ago: int
    end_days_ago: int

    def __post_init__(self):
        if self.start_days_ago < self.end_days_ago:
            raise ValueError(
                "days range start must be further in the past than end: "
                f"{self.start_days_ago}-{self.end_days_ago}"
            )

    @classmethod
    def parse(cls, spec: str) -> "DaysRange":
        m = _DAYS_RE.match(spec.strip())
        if not m:
            raise ValueError(f"bad days range {spec!r}; expected N-M")
        return cls(start_days_ago=int(m.group(1)), end_days_ago=int(m.group(2)))

    def to_date_range(self, today: datetime.date | None = None) -> DateRange:
        today = today or datetime.date.today()
        return DateRange(
            start=today - datetime.timedelta(days=self.start_days_ago),
            end=today - datetime.timedelta(days=self.end_days_ago),
        )


def parse_date_or_days_range(
    spec: str, today: datetime.date | None = None
) -> DateRange:
    """Accept either grammar (drivers take both, reference GameDriver)."""
    if _RANGE_RE.match(spec.strip()):
        return DateRange.parse(spec)
    return DaysRange.parse(spec).to_date_range(today)


def daily_path(base: str | os.PathLike, date: datetime.date) -> str:
    """`<base>/daily/yyyy/MM/dd` (reference IOUtils daily dir layout)."""
    return os.path.join(str(base), "daily", f"{date.year:04d}", f"{date.month:02d}", f"{date.day:02d}")


def resolve_input_paths(
    base_paths: Sequence[str | os.PathLike],
    date_range: DateRange | None = None,
    *,
    error_on_missing: bool = True,
) -> list[str]:
    """Expand base paths into concrete data directories.

    Without a range: the base paths themselves. With one: every existing
    `<base>/daily/yyyy/MM/dd` within the range (reference
    IOUtils.getInputPathsWithinDateRange; raises when nothing exists).
    """
    if date_range is None:
        return [str(p) for p in base_paths]
    out: list[str] = []
    for base in base_paths:
        out.extend(
            p for d in date_range.dates() if os.path.isdir(p := daily_path(base, d))
        )
    if not out and error_on_missing:
        raise FileNotFoundError(
            f"no daily input directories found under {list(map(str, base_paths))} "
            f"within {date_range}"
        )
    return out
