"""Training lifecycle events.

Reference parity: photon-client event/ — Event, EventEmitter, EventListener;
concrete events PhotonSetupEvent, TrainingStartEvent, TrainingFinishEvent,
PhotonOptimizationLogEvent (emitted from Driver.scala:120-393). Listeners
hook external telemetry into driver runs without coupling.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class Event:
    """Base event; ``timestamp`` is seconds since epoch."""

    timestamp: float = dataclasses.field(default_factory=time.time, kw_only=True)


@dataclasses.dataclass(frozen=True)
class SetupEvent(Event):
    config_summary: str = ""


@dataclasses.dataclass(frozen=True)
class TrainingStartEvent(Event):
    job_name: str = ""


@dataclasses.dataclass(frozen=True)
class TrainingFinishEvent(Event):
    job_name: str = ""
    succeeded: bool = True


@dataclasses.dataclass(frozen=True)
class OptimizationLogEvent(Event):
    """Per-coordinate-update optimization telemetry (reference
    PhotonOptimizationLogEvent)."""

    coordinate_id: str = ""
    iteration: int = 0
    metrics: dict = dataclasses.field(default_factory=dict)


EventListener = Callable[[Event], None]


class EventEmitter:
    """Synchronous fan-out of events to registered listeners; listener
    exceptions are logged, never propagated (reference EventEmitter.scala)."""

    def __init__(self):
        self._listeners: list[EventListener] = []

    @property
    def has_listeners(self) -> bool:
        """True when a send() would reach anyone — producers that must pay
        real cost (host reads) to BUILD an event check this first."""
        return bool(self._listeners)

    def register(self, listener: EventListener) -> None:
        self._listeners.append(listener)

    def unregister(self, listener: EventListener) -> None:
        """Idempotent: unregistering a never-registered (or already removed)
        listener is a no-op — driver cleanup paths unregister defensively
        and must not die on a ValueError."""
        try:
            self._listeners.remove(listener)
        except ValueError:
            pass

    def send(self, event: Event) -> None:
        for listener in self._listeners:
            try:
                listener(event)
            except Exception:
                logger.exception("event listener failed on %r", event)
