"""Wall-clock profiling of named blocks.

Reference parity: photon-lib util/Timed.scala:33-77 — ``Timed("name"){...}``
logs the duration of the block; used pervasively by the drivers and the
coordinate-descent loop. Here a context manager / decorator; durations are
also collected in a process-wide registry so drivers can print a phase
summary, and each block emits a jax.profiler StepTraceAnnotation so phases
line up with device traces in TensorBoard.
"""

from __future__ import annotations

import contextlib
import logging
import time
from collections import defaultdict
from functools import wraps

logger = logging.getLogger("photon_ml_tpu.timing")

#: name -> list of durations (seconds)
_TIMINGS: dict[str, list[float]] = defaultdict(list)


class Timed(contextlib.AbstractContextManager):
    """``with Timed("read training data"): ...`` — logs and records."""

    def __init__(self, name: str, log_level: int = logging.INFO):
        self.name = name
        self.log_level = log_level
        self.duration: float | None = None

    def __enter__(self):
        self._annotation = None
        try:
            import jax.profiler

            self._annotation = jax.profiler.TraceAnnotation(self.name)
            self._annotation.__enter__()
        except Exception:  # profiler unavailable: timing still works
            self._annotation = None
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration = time.perf_counter() - self._start
        if self._annotation is not None:
            self._annotation.__exit__(exc_type, exc, tb)
        _TIMINGS[self.name].append(self.duration)
        logger.log(self.log_level, "%s took %.3f s", self.name, self.duration)
        return False


def timed(name: str | None = None):
    """Decorator form of Timed."""

    def decorate(fn):
        label = name or fn.__qualname__

        @wraps(fn)
        def wrapper(*args, **kwargs):
            with Timed(label):
                return fn(*args, **kwargs)

        return wrapper

    return decorate


@contextlib.contextmanager
def profile_trace(log_dir: str | None):
    """Capture a jax.profiler device trace for the enclosed block.

    ``with profile_trace("/tmp/trace"): train()`` writes a TensorBoard-
    loadable trace (XLA op timeline, HBM usage) — the TPU-native upgrade of
    the reference's wall-clock-only Timed blocks (util/Timed.scala:33-77;
    it had no device-level tracing, SURVEY.md §5). A None/empty ``log_dir``
    disables tracing so drivers can pass their flag through unconditionally.
    """
    if not log_dir:
        yield
        return
    import jax.profiler

    with jax.profiler.trace(str(log_dir)):
        yield
    logger.info("jax profiler trace written to %s", log_dir)


def timing_summary() -> dict[str, dict[str, float]]:
    """name -> {count, total, mean} over everything timed so far."""
    return {
        name: {
            "count": len(durations),
            "total": sum(durations),
            "mean": sum(durations) / len(durations),
        }
        for name, durations in _TIMINGS.items()
        if durations
    }


def reset_timings() -> None:
    _TIMINGS.clear()
