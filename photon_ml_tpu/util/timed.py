"""Wall-clock profiling of named blocks.

Reference parity: photon-lib util/Timed.scala:33-77 — ``Timed("name"){...}``
logs the duration of the block; used pervasively by the drivers and the
coordinate-descent loop. Here a context manager / decorator; durations feed
the process-wide metrics registry (telemetry/registry.py histograms under
``timing/<name>``) so drivers can print a phase summary with distribution
stats, and each block emits a jax.profiler StepTraceAnnotation so phases
line up with device traces in TensorBoard. Each block also records a
``phase/<name>`` span into the run tracer when one is installed
(telemetry/tracing.py — inert by default), so driver phases frame the
finer seam spans in the exported timeline.
"""

from __future__ import annotations

import contextlib
import logging
import time
from functools import wraps

from photon_ml_tpu.telemetry import tracing
from photon_ml_tpu.telemetry.registry import default_registry

logger = logging.getLogger("photon_ml_tpu.timing")

#: registry namespace for phase timings
_TIMING_PREFIX = "timing/"


class Timed(contextlib.AbstractContextManager):
    """``with Timed("read training data"): ...`` — logs and records."""

    def __init__(self, name: str, log_level: int = logging.INFO):
        self.name = name
        self.log_level = log_level
        self.duration: float | None = None

    def __enter__(self):
        self._annotation = None
        try:
            import jax.profiler

            self._annotation = jax.profiler.TraceAnnotation(self.name)
            self._annotation.__enter__()
        except Exception:  # profiler unavailable: timing still works
            self._annotation = None
        self._span = tracing.span("phase/" + self.name, cat="phase")
        self._span.__enter__()
        self._start = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration = time.perf_counter() - self._start
        self._span.__exit__(exc_type, exc, tb)
        if self._annotation is not None:
            self._annotation.__exit__(exc_type, exc, tb)
        default_registry().histogram(_TIMING_PREFIX + self.name).observe(
            self.duration
        )
        logger.log(self.log_level, "%s took %.3f s", self.name, self.duration)
        return False


def timed(name: str | None = None):
    """Decorator form of Timed."""

    def decorate(fn):
        label = name or fn.__qualname__

        @wraps(fn)
        def wrapper(*args, **kwargs):
            with Timed(label):
                return fn(*args, **kwargs)

        return wrapper

    return decorate


@contextlib.contextmanager
def profile_trace(log_dir: str | None):
    """Capture a jax.profiler device trace for the enclosed block.

    ``with profile_trace("/tmp/trace"): train()`` writes a TensorBoard-
    loadable trace (XLA op timeline, HBM usage) — the TPU-native upgrade of
    the reference's wall-clock-only Timed blocks (util/Timed.scala:33-77;
    it had no device-level tracing, SURVEY.md §5). A None/empty ``log_dir``
    disables tracing so drivers can pass their flag through unconditionally.
    """
    if not log_dir:
        yield
        return
    import jax.profiler

    with jax.profiler.trace(str(log_dir)):
        yield
    logger.info("jax profiler trace written to %s", log_dir)


def timing_summary() -> dict[str, dict[str, float]]:
    """name -> {count, total, mean, min, max, p50, p95} over everything
    timed so far (the ``timing/`` histograms of the metrics registry)."""
    return {
        name[len(_TIMING_PREFIX):]: hist.summary()
        for name, hist in default_registry().histograms(_TIMING_PREFIX).items()
        if hist.count
    }


def reset_timings() -> None:
    default_registry().remove_prefix(_TIMING_PREFIX)
