"""Job logger writing to a local file, copied to a final destination on close.

Reference parity: photon-lib util/PhotonLogger.scala:34-90 — an slf4j logger
that writes to a local tmp file and uploads it to HDFS when closed, with its
own level filtering. Here: a stdlib logging handler writing a local spool
file, atomically moved/copied to the requested path on ``close()`` (the
"HDFS" of this build is whatever filesystem the output dir lives on).
"""

from __future__ import annotations

import logging
import os
import shutil
import tempfile


class PhotonLogger:
    """``with PhotonLogger(dest_path) as log: log.info(...)``."""

    def __init__(
        self,
        destination_path: str | os.PathLike,
        *,
        level: int = logging.INFO,
        name: str = "photon_ml_tpu.job",
        capture_logger: str = "photon_ml_tpu",
    ):
        """The handler attaches to ``capture_logger`` (default: the package
        root), so Timed phase durations, estimator and optimizer logging all
        land in the job log, not just messages sent through this object."""
        self.destination_path = str(destination_path)
        self._tmp = tempfile.NamedTemporaryFile(
            mode="w", suffix=".log", delete=False, prefix="photon-"
        )
        self._tmp.close()
        self._handler = logging.FileHandler(self._tmp.name)
        self._handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)s %(name)s - %(message)s")
        )
        self._handler.setLevel(level)
        #: (logger, level it had before attach) — restored on close, so a
        #: job log cannot permanently lower a captured logger's level
        self._attached: list[tuple[logging.Logger, int]] = []

        def attach(lg: logging.Logger) -> None:
            prior_level = lg.level
            if lg.level == logging.NOTSET or lg.level > level:
                lg.setLevel(level)
            lg.addHandler(self._handler)
            self._attached.append((lg, prior_level))

        attach(logging.getLogger(capture_logger))
        self.logger = logging.getLogger(name)
        if name != capture_logger and not name.startswith(capture_logger + "."):
            attach(self.logger)  # messages via this object still reach the file
        self._closed = False

    def debug(self, msg, *args):
        self.logger.debug(msg, *args)

    def info(self, msg, *args):
        self.logger.info(msg, *args)

    def warning(self, msg, *args):
        self.logger.warning(msg, *args)

    def error(self, msg, *args):
        self.logger.error(msg, *args)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for lg, prior_level in self._attached:
            lg.removeHandler(self._handler)
            lg.setLevel(prior_level)
        self._handler.close()
        os.makedirs(os.path.dirname(self.destination_path) or ".", exist_ok=True)
        shutil.copyfile(self._tmp.name, self.destination_path)
        os.unlink(self._tmp.name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
