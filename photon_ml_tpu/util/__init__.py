"""Utilities: timing, logging, events (reference photon-lib util/, photon-client event/)."""

from photon_ml_tpu.util.events import (
    Event,
    EventEmitter,
    OptimizationLogEvent,
    SetupEvent,
    TrainingFinishEvent,
    TrainingStartEvent,
)
from photon_ml_tpu.util.logging_util import PhotonLogger
from photon_ml_tpu.util.timed import Timed, timed

__all__ = [
    "Event",
    "EventEmitter",
    "OptimizationLogEvent",
    "SetupEvent",
    "TrainingFinishEvent",
    "TrainingStartEvent",
    "PhotonLogger",
    "Timed",
    "timed",
]
