"""Univariate-step slice sampler (reference photon-lib
hyperparameter/SliceSampler.scala — Neal 2003, stepping-out + shrinkage),
used to sample GP kernel hyperparameters from their posterior.
"""

from __future__ import annotations

from typing import Callable

import numpy as np


def _sample_dim(
    log_prob: Callable[[np.ndarray], float],
    x: np.ndarray,
    logp_x: float,
    dim: int,
    rng: np.random.Generator,
    width: float,
    max_steps: int,
) -> tuple[np.ndarray, float]:
    """One stepping-out + shrinkage slice-sampling update of x[dim].

    ``logp_x`` is log_prob(x), threaded through so the (expensive) current
    point density is never recomputed. Returns (new_x, log_prob(new_x)).
    """
    y = logp_x + np.log(rng.uniform(1e-300, 1.0))

    lower = x.copy()
    upper = x.copy()
    offset = rng.uniform()
    lower[dim] -= offset * width
    upper[dim] += (1.0 - offset) * width

    for _ in range(max_steps):
        if log_prob(lower) <= y:
            break
        lower[dim] -= width
    for _ in range(max_steps):
        if log_prob(upper) <= y:
            break
        upper[dim] += width

    for _ in range(100):
        candidate = x.copy()
        candidate[dim] = rng.uniform(lower[dim], upper[dim])
        logp_candidate = log_prob(candidate)
        if logp_candidate > y:
            return candidate, logp_candidate
        # shrink
        if candidate[dim] < x[dim]:
            lower[dim] = candidate[dim]
        else:
            upper[dim] = candidate[dim]
    return x, logp_x  # degenerate slice; keep the current point


def slice_sample(
    log_prob: Callable[[np.ndarray], float],
    x0: np.ndarray,
    rng: np.random.Generator,
    *,
    num_samples: int = 1,
    burn_in: int = 0,
    width: float = 1.0,
    max_step_out: int = 32,
) -> np.ndarray:
    """Draw ``num_samples`` points from ``exp(log_prob)`` starting at x0.

    Coordinates are updated one at a time (random scan), matching the
    reference's per-dimension sampling. Returns [num_samples, d].
    """
    x = np.array(x0, dtype=np.float64, copy=True)
    logp = log_prob(x)
    d = x.shape[0]
    out = np.empty((num_samples, d))
    total = burn_in + num_samples
    for i in range(total):
        for dim in rng.permutation(d):
            x, logp = _sample_dim(log_prob, x, logp, int(dim), rng, width, max_step_out)
        if i >= burn_in:
            out[i - burn_in] = x
    return out
