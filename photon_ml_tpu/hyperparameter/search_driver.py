"""GP-driven model search over vmapped training-lane tournaments.

Reference parity: photon-lib hyperparameter/search/RandomSearch.scala:33-50
+ GaussianProcessSearch.scala drive a SEQUENTIAL outer loop of full driver
fits through EvaluationFunction.scala glue; this driver keeps the same
ask/tell math (Sobol warmup, GP posterior + expected improvement) but
evaluates each proposed batch of ``lane_budget`` configs as ONE vmapped
tournament on-mesh (algorithm/lane_search.py) with exact device metrics
(evaluation/sharded.py) — scores never round-trip to the host, only the
[L] metric scalars do.

Overlap discipline (the streaming-prefetch rule, PR 7): the GP fit is host
numpy, so each round dispatches its tournament + metric programs (JAX
dispatch is async), then fits/proposes the NEXT round's configs while the
device works, and only then blocks on the metric read. The GP therefore
runs one round behind ("tells" fold in just before the next proposal) —
deliberate, and deterministic under a fixed seed (one SeedSequence threads
Sobol, the slice sampler, and nothing else; EI is pure).

Warm starts: each lane starts from the nearest EVALUATED config's
coefficients (unit-cube / rescaled distance) — never an unevaluated lane's
garbage, and round 1 starts cold at zero. The live function-decrease stop
(``OptimizerConfig.rel_function_tolerance``) is what lets warm-started
heterogeneous lanes exit before worst-lane max_iter.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Sequence

import numpy as np

from photon_ml_tpu.algorithm.lane_search import (
    LaneConfigs,
    evaluate_tournament_on_device,
    run_lane_tournament,
)
from photon_ml_tpu.data.batch import LabeledPointBatch, compute_margins
from photon_ml_tpu.evaluation.evaluators import (
    EvaluationData,
    Evaluator,
    default_evaluator_for_task,
    parse_evaluator,
)
from photon_ml_tpu.evaluation.sharded import device_evaluator
from photon_ml_tpu.hyperparameter.rescaling import (
    DimensionSpec,
    VectorRescaling,
)
from photon_ml_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    RandomSearch,
)
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.optim.optimizer import OptimizerConfig
from photon_ml_tpu.telemetry.registry import default_registry
from photon_ml_tpu.types import TaskType

#: dimension names the lane tournament knows how to realize
_KNOWN_DIMS = ("lambda", "alpha", "tolerance", "box")


@dataclasses.dataclass(frozen=True)
class SearchSpace:
    """Named search dimensions over tournament lane configs.

    Grammar (one comma-separated term per dimension, see
    :func:`parse_search_space`)::

        lambda=1e-4:1e2:log , alpha=0:1 , tolerance=1e-9:1e-5:log , box=0:1

    ``lambda`` is required. ``alpha`` (elastic-net mix) folds into per-lane
    l1/l2 and forces an OWL-QN tournament; ``box`` (discrete 0/1) toggles
    the driver-supplied box per lane and rides projected L-BFGS — the two
    are mutually exclusive (same rule as train_glm/train_glm_grid).
    """

    dims: tuple[DimensionSpec, ...]

    def __post_init__(self):
        names = [d.name for d in self.dims]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate search dimensions: {names}")
        for n in names:
            if n not in _KNOWN_DIMS:
                raise ValueError(
                    f"unknown search dimension '{n}' (supported: "
                    f"{', '.join(_KNOWN_DIMS)})"
                )
        if "lambda" not in names:
            raise ValueError("search space needs a 'lambda' dimension")
        if "alpha" in names and "box" in names:
            raise ValueError(
                "'alpha' (OWL-QN lanes) and 'box' (projected L-BFGS lanes) "
                "cannot share a tournament"
            )

    @property
    def rescaling(self) -> VectorRescaling:
        return VectorRescaling(self.dims)

    @property
    def dim(self) -> int:
        return len(self.dims)

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(d.name for d in self.dims)

    def _column(self, values: np.ndarray, name: str):
        names = self.names
        if name not in names:
            return None
        return values[..., names.index(name)]

    def config_dicts(self, unit: np.ndarray) -> list[dict[str, float]]:
        values = self.rescaling.to_hyperparameters(np.atleast_2d(unit))
        return [
            {d.name: float(values[i, j]) for j, d in enumerate(self.dims)}
            for i in range(values.shape[0])
        ]

    def lane_configs(
        self,
        unit: np.ndarray,
        *,
        default_tolerance: float,
        feature_dim: int | None = None,
        box_lower: np.ndarray | None = None,
        box_upper: np.ndarray | None = None,
    ) -> LaneConfigs:
        """Realize a [L, dim] unit-cube batch as per-lane solver vectors.

        ``box`` lanes take the driver's global (box_lower, box_upper) [d]
        arrays; box-off lanes carry ±inf rows (the per-lane no-op box —
        tournament-level bounds=None is reserved for spaces WITHOUT a box
        dimension, preserving the unprojected bitwise path)."""
        unit = np.atleast_2d(np.asarray(unit, np.float64))
        values = self.rescaling.to_hyperparameters(unit)
        lam = np.asarray(self._column(values, "lambda"), np.float64)
        alpha_col = self._column(values, "alpha")
        alpha = (
            np.zeros_like(lam) if alpha_col is None
            else np.asarray(alpha_col, np.float64)
        )
        tol_col = self._column(values, "tolerance")
        tol = (
            np.full_like(lam, float(default_tolerance)) if tol_col is None
            else np.asarray(tol_col, np.float64)
        )
        lower = upper = None
        box_col = self._column(values, "box")
        if box_col is not None:
            if box_lower is None or box_upper is None or feature_dim is None:
                raise ValueError(
                    "a 'box' search dimension needs feature_dim plus the "
                    "box_lower/box_upper [d] arrays to toggle per lane"
                )
            on = np.asarray(box_col, np.float64) > 0.5
            lower = np.where(
                on[:, None],
                np.asarray(box_lower, np.float64)[None, :],
                -np.inf,
            )
            upper = np.where(
                on[:, None],
                np.asarray(box_upper, np.float64)[None, :],
                np.inf,
            )
        return LaneConfigs(
            l2=(1.0 - alpha) * lam,
            l1=alpha * lam,
            tolerance=tol,
            lower_bounds=lower,
            upper_bounds=upper,
        )


def parse_search_space(spec: str) -> SearchSpace:
    """Parse the CLI grammar: ``name=low:high[:log][:int]``, comma-separated.

    ``log`` selects log-scale interpolation (regularization weights,
    tolerances); ``int`` snaps to integers (the 'box' toggle). Example::

        lambda=1e-4:1e2:log,alpha=0:1,tolerance=1e-9:1e-5:log
    """
    dims = []
    for term in spec.split(","):
        term = term.strip()
        if not term:
            continue
        if "=" not in term:
            raise ValueError(
                f"bad search-space term '{term}' (want name=low:high[:log][:int])"
            )
        name, rng = term.split("=", 1)
        parts = rng.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"bad search-space range '{rng}' for '{name}' "
                "(want low:high[:log][:int])"
            )
        flags = {p.strip().lower() for p in parts[2:]}
        bad = flags - {"log", "int"}
        if bad:
            raise ValueError(
                f"unknown search-space flags {sorted(bad)} for '{name}'"
            )
        name = name.strip()
        discrete = "int" in flags or name == "box"
        dims.append(DimensionSpec(
            name=name,
            low=float(parts[0]), high=float(parts[1]),
            log_scale="log" in flags, discrete=discrete,
        ))
    return SearchSpace(dims=tuple(dims))


@dataclasses.dataclass
class SearchOutcome:
    """One finished tournament search."""

    best_model: GeneralizedLinearModel
    best_config: dict[str, float]
    best_metric: float
    evaluator_name: str
    #: per-round journal-shaped records (also written to the RunJournal)
    trajectory: list[dict]
    #: every (unit-cube candidate, metric value) in evaluation order
    observations: list[tuple[np.ndarray, float]]


def _nearest_warm_starts(
    round_units: np.ndarray,
    evaluated_units: list[np.ndarray],
    evaluated_coeffs: list[np.ndarray],
) -> tuple[np.ndarray | None, int]:
    """Per-lane warm starts from the nearest EVALUATED config by unit-cube
    (rescaled) distance; (None, 0) on the round-1 cold case — the tournament
    then starts every lane at zero, never at uninitialized memory. A GP
    proposal outside the evaluated hull still has a well-defined nearest
    neighbor, so no lane ever inherits an unevaluated config's garbage."""
    if not evaluated_units:
        return None, 0
    e = np.stack(evaluated_units)
    c = np.stack(evaluated_coeffs)
    d2 = np.sum(
        (round_units[:, None, :] - e[None, :, :]) ** 2, axis=-1
    )
    nearest = np.argmin(d2, axis=1)
    return c[nearest], len(nearest)


def _make_searcher(kind: str, dim: int, seed, *, candidate_pool: int,
                   min_observations: int) -> RandomSearch:
    if kind == "gp":
        return GaussianProcessSearch(
            dim, seed=seed, candidate_pool=candidate_pool,
            min_observations=min_observations,
        )
    if kind == "sobol":
        return RandomSearch(dim, seed=seed)
    raise ValueError(f"unknown searcher '{kind}' (want 'gp' or 'sobol')")


def run_model_search(
    batch: LabeledPointBatch,
    val_batch: LabeledPointBatch,
    task: TaskType,
    space: SearchSpace,
    *,
    rounds: int,
    lane_budget: int,
    optimizer: OptimizerConfig | None = None,
    seed: int = 0,
    searcher: str = "gp",
    evaluator: "Evaluator | str | None" = None,
    normalization=None,
    intercept_index: int | None = None,
    box_lower: np.ndarray | None = None,
    box_upper: np.ndarray | None = None,
    candidate_pool: int = 250,
    min_observations: int = 3,
    journal=None,
    registry=None,
    telemetry=None,
) -> SearchOutcome:
    """Ask/tell tournament search: ``rounds`` rounds of ``lane_budget``
    configs, each round ONE vmapped solve + ONE on-mesh metric program.

    ``journal``: optional telemetry.RunJournal — ``search_round`` rows per
    round (success) and a ``search_failure`` row before re-raising on any
    error. ``registry``: MetricsRegistry (default: the process default) —
    ``search/*`` counters + gauges. Deterministic under fixed ``seed``
    (SeedSequence-threaded Sobol + slice sampler; EI is pure).
    """
    if rounds < 1 or lane_budget < 1:
        raise ValueError(
            f"need rounds >= 1 and lane_budget >= 1, got {rounds}/{lane_budget}"
        )
    optimizer = optimizer or OptimizerConfig()
    registry = registry if registry is not None else default_registry()
    if evaluator is None:
        evaluator = default_evaluator_for_task(task)
    elif isinstance(evaluator, str):
        evaluator = parse_evaluator(evaluator)
    sign = -1.0 if evaluator.larger_is_better else 1.0

    eval_data = EvaluationData(
        labels=np.asarray(val_batch.labels, np.float64),
        offsets=np.asarray(val_batch.offsets, np.float64),
        weights=np.asarray(val_batch.weights, np.float64),
    )
    dev = device_evaluator(evaluator, eval_data)
    if dev is None:
        raise ValueError(
            f"evaluator {evaluator.name} has no device form; tournament "
            "metrics must reduce on-mesh (evaluation/sharded.py)"
        )

    # one objective serves the solve AND the metric program (its
    # normalization maps lanes to model space on device)
    from photon_ml_tpu.estimators import _objective_for_batch
    from photon_ml_tpu.ops.losses import loss_for_task

    objective = _objective_for_batch(
        batch, loss_for_task(task), 0.0, normalization
    )

    # ONE SeedSequence is the searcher's whole entropy source (Sobol
    # scramble + slice sampler; EI is pure) — int-seeded searchers keep
    # the legacy tuner derivation instead, so pass the sequence explicitly
    engine = _make_searcher(
        searcher, space.dim, np.random.SeedSequence(seed),
        candidate_pool=candidate_pool, min_observations=min_observations,
    )

    evaluated_units: list[np.ndarray] = []
    evaluated_coeffs: list[np.ndarray] = []
    observations: list[tuple[np.ndarray, float]] = []
    pending: list[tuple[np.ndarray, float]] = []
    trajectory: list[dict] = []
    best_metric = float("nan")
    best_model = None
    best_config: dict[str, float] = {}
    best_unit = None

    c_rounds = registry.counter("search/rounds")
    c_configs = registry.counter("search/configs_evaluated")
    c_gp = registry.counter("search/gp_proposal_rounds")
    c_sobol = registry.counter("search/sobol_proposal_rounds")
    c_warm = registry.counter("search/warm_start_lanes")
    c_cold = registry.counter("search/cold_start_lanes")

    round_units = engine.draw_candidates(lane_budget)  # Sobol warmup round
    source = "sobol"
    try:
        for rnd in range(rounds):
            configs = space.lane_configs(
                round_units,
                default_tolerance=optimizer.tolerance,
                feature_dim=batch.dim,
                box_lower=box_lower, box_upper=box_upper,
            )
            warm, _ = _nearest_warm_starts(
                round_units, evaluated_units, evaluated_coeffs
            )
            warm_lanes = lane_budget if warm is not None else 0
            c_warm.inc(warm_lanes)
            c_cold.inc(lane_budget - warm_lanes)
            t0 = time.perf_counter()
            tournament = run_lane_tournament(
                batch, task, configs,
                optimizer=optimizer, warm_start=warm,
                normalization=normalization,
                intercept_index=intercept_index,
                telemetry=telemetry,
            )
            metrics_dev = evaluate_tournament_on_device(
                objective, dev.compute, val_batch,
                tournament.results.coefficients, dev.consts,
                intercept_index,
            )
            # --- overlapped host work: tell the GP round r-1's results and
            # propose round r+1 while the device runs round r ---
            next_units = None
            next_source = source
            gp_ms = 0.0
            if rnd + 1 < rounds:
                t_gp = time.perf_counter()
                for u, m in pending:
                    engine.observe(u, sign * m)
                pending = []
                next_units = engine.propose_batch(lane_budget)
                next_source = engine.last_proposal_source
                gp_ms = (time.perf_counter() - t_gp) * 1e3
            # --- sync point: [L] scalars + lane coefficients to host ---
            metrics = np.asarray(metrics_dev, np.float64)
            coeffs = np.asarray(tournament.results.coefficients)
            round_ms = (time.perf_counter() - t0) * 1e3
            cfg_dicts = space.config_dicts(round_units)
            for i in range(lane_budget):
                u = np.array(round_units[i], np.float64)
                m = float(metrics[i])
                evaluated_units.append(u)
                evaluated_coeffs.append(coeffs[i])
                observations.append((u, m))
                pending.append((u, m))
                if not np.isnan(m) and evaluator.better_than(m, best_metric):
                    best_metric = m
                    best_model = tournament.models[i]
                    best_config = cfg_dicts[i]
                    best_unit = u
            c_rounds.inc()
            c_configs.inc(lane_budget)
            (c_gp if source == "gp" else c_sobol).inc()
            registry.gauge("search/best_metric").set(best_metric)
            row = {
                "round": rnd,
                "source": source,
                "lanes": lane_budget,
                "warm_lanes": warm_lanes,
                "round_ms": round_ms,
                "gp_overlap_ms": gp_ms,
                "best_metric": best_metric,
                "round_best": float(np.nanmax(metrics) if
                                    evaluator.larger_is_better
                                    else np.nanmin(metrics)),
                "metric": evaluator.name,
            }
            trajectory.append(row)
            if journal is not None:
                journal.record("search_round", **row)
            if next_units is not None:
                round_units = next_units
                source = next_source
    except Exception as exc:
        if journal is not None:
            journal.record(
                "search_failure",
                round=len(trajectory),
                error=f"{type(exc).__name__}: {exc}",
            )
        raise
    if best_model is None:
        raise ValueError(
            f"search produced no finite {evaluator.name} over "
            f"{rounds * lane_budget} configs"
        )
    out = SearchOutcome(
        best_model=best_model,
        best_config=best_config,
        best_metric=best_metric,
        evaluator_name=evaluator.name,
        trajectory=trajectory,
        observations=observations,
    )
    if journal is not None:
        journal.record(
            "search_complete",
            configs=len(observations),
            best_metric=best_metric,
            best_config=best_config,
            metric=evaluator.name,
        )
    return out


def host_metric_for_model(
    model: GeneralizedLinearModel,
    val_batch: LabeledPointBatch,
    evaluator: Evaluator,
) -> float:
    """Host-side cross-check of a selected model: same margins, the exact
    host evaluator (tests pin device == host on the winner)."""
    scores = np.asarray(
        compute_margins(val_batch, model.coefficients.means), np.float64
    )
    data = EvaluationData(
        labels=np.asarray(val_batch.labels, np.float64),
        offsets=np.asarray(val_batch.offsets, np.float64),
        weights=np.asarray(val_batch.weights, np.float64),
    )
    return float(evaluator.evaluate(scores, data))
