"""GAME <-> hyperparameter-search glue.

Reference parity: photon-client estimators/
GameEstimatorEvaluationFunction.scala (vectorize GAME configs <-> candidate
vectors; each evaluation is a full GameEstimator.fit) and
GameTrainingDriver.runHyperparameterTuning (GameTrainingDriver.scala:631-663:
RANDOM vs BAYESIAN mode, n iterations, tuned reg weights), plus
hyperparameter/HyperparameterSerialization.scala (config round trip).
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import Mapping, Sequence

import numpy as np

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.estimators import GameEstimator
from photon_ml_tpu.hyperparameter.rescaling import DimensionSpec, VectorRescaling
from photon_ml_tpu.hyperparameter.search import (
    GaussianProcessSearch,
    RandomSearch,
    SearchResult,
)


class HyperparameterTuningMode(enum.Enum):
    """Reference: HyperparameterTuningMode {NONE, RANDOM, BAYESIAN}."""

    NONE = "NONE"
    RANDOM = "RANDOM"
    BAYESIAN = "BAYESIAN"


@dataclasses.dataclass
class TuningResult:
    best_reg_weights: dict[str, float]
    best_value: float
    search: SearchResult
    #: (reg weights, fit result) per evaluated candidate — populated only
    #: with keep_models=True (ModelOutputMode.TUNED/ALL)
    tuned_results: list = dataclasses.field(default_factory=list)
    #: (reg weights, fit result) of the best tuning candidate — always
    #: tracked (O(1) memory) so best-over-all selection never needs the list
    best_result: tuple | None = None
    #: (reg weights, raw metric) per evaluated candidate — lightweight,
    #: always tracked; persisted so later runs can seed their search
    #: (reference HyperparameterSerialization priors)
    observations_reg: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class GameHyperparameterTuner:
    """Tunes per-coordinate L2 regularization weights of a GameEstimator.

    Each candidate evaluation clones the estimator with the candidate's reg
    weights, runs a full fit, and reads the first validation evaluator —
    negated when larger-is-better so the searchers always minimize (the
    reference flips via Evaluator.betterThan in the same way).
    """

    estimator: GameEstimator
    #: coordinate id -> (low, high) λ range searched on a log scale
    reg_ranges: Mapping[str, tuple[float, float]]
    mode: HyperparameterTuningMode = HyperparameterTuningMode.BAYESIAN
    seed: int = 0

    def __post_init__(self):
        self._coord_ids = list(self.reg_ranges.keys())
        self.rescaling = VectorRescaling(
            [
                DimensionSpec(cid, lo, hi, log_scale=True)
                for cid, (lo, hi) in self.reg_ranges.items()
            ]
        )

    def _apply(self, reg_weights: Mapping[str, float]) -> GameEstimator:
        configs = dict(self.estimator.coordinate_configs)
        for cid, lam in reg_weights.items():
            cfg = configs[cid]
            configs[cid] = dataclasses.replace(
                cfg,
                optimization=dataclasses.replace(cfg.optimization, l2_weight=float(lam)),
            )
        return dataclasses.replace(self.estimator, coordinate_configs=configs)

    def tune(
        self,
        dataset: GameDataset,
        validation_dataset: GameDataset,
        *,
        num_iterations: int = 10,
        prior_observations: Sequence[tuple[Mapping[str, float], float]] = (),
        keep_models: bool = False,
    ) -> TuningResult:
        from photon_ml_tpu.evaluation.evaluators import parse_evaluator

        if not self.estimator.validation_evaluators:
            raise ValueError("hyperparameter tuning needs validation_evaluators")
        evaluator = parse_evaluator(self.estimator.validation_evaluators[0])
        sign = -1.0 if evaluator.larger_is_better else 1.0
        tuned_results: list = []
        observations_reg: list = []
        best_seen: list = [None, np.inf]  # (reg, result), signed value

        def evaluate(candidate: np.ndarray) -> float:
            values = self.rescaling.to_hyperparameters(candidate)
            reg = dict(zip(self._coord_ids, values.tolist()))
            est = self._apply(reg)
            result = est.fit(dataset, validation_dataset=validation_dataset)
            if keep_models:
                tuned_results.append((reg, result))
            if not np.isnan(result.best_metric):  # keep the file strict JSON
                observations_reg.append((reg, float(result.best_metric)))
            value = sign * float(result.best_metric)
            if not np.isnan(value) and value < best_seen[1]:
                best_seen[0], best_seen[1] = (reg, result), value
            return value

        if self.mode == HyperparameterTuningMode.BAYESIAN:
            search: RandomSearch = GaussianProcessSearch(self.rescaling.dim, self.seed)
        elif self.mode == HyperparameterTuningMode.RANDOM:
            search = RandomSearch(self.rescaling.dim, self.seed)
        else:
            raise ValueError("tuning mode NONE — nothing to do")

        import logging

        for reg, value in prior_observations:
            if np.isnan(value):
                continue
            missing = [cid for cid in self._coord_ids if cid not in reg]
            if missing:
                # e.g. priors from a run with different coordinate names —
                # skip, don't crash after the grid already trained
                logging.getLogger(__name__).warning(
                    "skipping prior observation missing coordinates %s "
                    "(tunable: %s)", missing, self._coord_ids,
                )
                continue
            vec = np.array([reg[cid] for cid in self._coord_ids])
            search.observe_prior(self.rescaling.to_unit(vec), sign * value)
            # seed priors chain into this run's saved observations so a
            # sequence of seeded runs accumulates history
            observations_reg.append((dict(reg), float(value)))

        result = search.find(evaluate, num_iterations)
        best_values = self.rescaling.to_hyperparameters(result.best_candidate)
        return TuningResult(
            best_reg_weights=dict(zip(self._coord_ids, best_values.tolist())),
            best_value=sign * result.best_value,
            search=result,
            tuned_results=tuned_results,
            best_result=best_seen[0],
            observations_reg=observations_reg,
        )


def save_tuned_config(result: TuningResult, path: str) -> None:
    """JSON persistence of tuned reg weights (reference
    HyperparameterSerialization.scala)."""
    payload = {
        "best_reg_weights": result.best_reg_weights,
        "best_value": result.best_value,
        "observations": [
            {"candidate": o.candidate.tolist(), "value": o.value}
            for o in result.search.observations
        ],
        # hyperparameter-space observations, loadable as priors by a later
        # run (--hyperparameter-prior-json)
        "prior_observations": [
            {"reg_weights": reg, "metric": metric}
            for reg, metric in result.observations_reg
        ],
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=2)


def load_prior_observations(path: str) -> list[tuple[dict, float]]:
    """Read a previous run's tuned-hyperparameters.json into (reg weights,
    metric) priors for ``GameHyperparameterTuner.tune``."""
    with open(path) as f:
        payload = json.load(f)
    return [
        (dict(o["reg_weights"]), float(o["metric"]))
        for o in payload.get("prior_observations", [])
        if not np.isnan(float(o["metric"]))
    ]


def load_tuned_config(path: str) -> dict:
    with open(path) as f:
        return json.load(f)
