"""Random (Sobol) and Gaussian-process (Bayesian) hyperparameter search.

Reference parity: photon-lib hyperparameter/search/RandomSearch.scala:33-50
(Sobol candidate generation in the unit cube, evaluation loop with observed
and prior-observation seeding) and GaussianProcessSearch.scala (fit GP on
observations, pick the candidate maximizing expected improvement among a
fresh batch of Sobol draws, fall back to random until enough observations).

All search state lives in the unit cube [0,1]^d; VectorRescaling maps to and
from real hyperparameter ranges (log-scale λ grids etc.).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Protocol

import numpy as np
from scipy.stats import qmc

from photon_ml_tpu.hyperparameter.acquisition import expected_improvement
from photon_ml_tpu.hyperparameter.estimators import GaussianProcessEstimator
from photon_ml_tpu.hyperparameter.kernels import Kernel, Matern52


class EvaluationFunction(Protocol):
    """Maps a unit-cube candidate vector to an observed (to-minimize) value.

    Reference: photon-lib hyperparameter/EvaluationFunction.scala — the
    client glue (GameEstimatorEvaluationFunction) turns the vector into a
    full GAME training config, runs it, and returns the validation metric.
    """

    def __call__(self, candidate: np.ndarray) -> float: ...


@dataclasses.dataclass
class Observation:
    candidate: np.ndarray
    value: float


@dataclasses.dataclass
class SearchResult:
    best_candidate: np.ndarray
    best_value: float
    observations: list[Observation]


class RandomSearch:
    """Sobol-sequence random search (reference RandomSearch.scala:33-50).

    Seeded with a ``np.random.SeedSequence`` (the search_driver tournament
    path), ALL randomness threads from that one sequence: the Sobol scramble
    and the GP subclass's slice sampler draw from deterministic children of
    it (EI is pure), so a whole search trajectory replays bit-for-bit under
    a fixed seed — no ad-hoc seed arithmetic, no numpy global state
    (tests/test_lane_search.py pins the replay). An int seed keeps the
    historical derivation (Sobol seeded with the int, per-fit estimator
    seeds) so existing tuner trajectories are unchanged.
    """

    #: where the last propose_batch came from ("sobol" | "gp")
    last_proposal_source = "sobol"

    def __init__(self, dim: int, seed: "int | np.random.SeedSequence" = 0):
        self.dim = dim
        self.seed = seed
        if isinstance(seed, np.random.SeedSequence):
            sobol_child, model_child = seed.spawn(2)
            self._sobol = qmc.Sobol(
                d=dim, scramble=True, seed=np.random.default_rng(sobol_child)
            )
            #: one generator threaded through every surrogate-model fit
            self._model_rng = np.random.default_rng(model_child)
        else:
            self._sobol = qmc.Sobol(d=dim, scramble=True, seed=seed)
            self._model_rng = None
        self.observations: list[Observation] = []
        self.prior_observations: list[Observation] = []

    def draw_candidates(self, n: int) -> np.ndarray:
        return self._sobol.random(n)

    def propose_batch(self, n: int) -> np.ndarray:
        """The batch-ask API (search_driver tournaments): n fresh
        candidates; subclasses may rank a pool instead."""
        self.last_proposal_source = "sobol"
        return self.draw_candidates(n)

    def next_candidate(self) -> np.ndarray:
        return self.draw_candidates(1)[0]

    def observe(self, candidate: np.ndarray, value: float) -> None:
        self.observations.append(Observation(np.asarray(candidate, float), float(value)))

    def observe_prior(self, candidate: np.ndarray, value: float) -> None:
        """Seed the search with results from earlier runs (reference
        findWithPriors / observePrior)."""
        self.prior_observations.append(
            Observation(np.asarray(candidate, float), float(value))
        )

    def find(self, evaluation_function: EvaluationFunction, n: int) -> SearchResult:
        for _ in range(n):
            cand = self.next_candidate()
            self.observe(cand, evaluation_function(cand))
        return self._result()

    def _result(self) -> SearchResult:
        all_obs = self.observations + self.prior_observations
        if not all_obs:
            raise ValueError("no observations recorded")
        best = min(all_obs, key=lambda o: o.value)
        return SearchResult(
            best_candidate=best.candidate,
            best_value=best.value,
            observations=list(self.observations),
        )


class GaussianProcessSearch(RandomSearch):
    """Bayesian search: GP surrogate + expected improvement
    (reference GaussianProcessSearch.scala)."""

    def __init__(
        self,
        dim: int,
        seed: int = 0,
        *,
        kernel: Kernel | None = None,
        min_observations: int = 3,
        candidate_pool: int = 250,
        num_kernel_samples: int = 3,
        burn_in: int = 8,
    ):
        super().__init__(dim, seed)
        self.kernel = kernel or Matern52()
        self.min_observations = min_observations
        self.candidate_pool = candidate_pool
        self.num_kernel_samples = num_kernel_samples
        self.burn_in = burn_in

    def _fit_surrogate(self, all_obs: list[Observation]):
        x = np.stack([o.candidate for o in all_obs])
        y = np.array([o.value for o in all_obs])
        estimator = GaussianProcessEstimator(
            kernel=self.kernel,
            num_kernel_samples=self.num_kernel_samples,
            burn_in=self.burn_in,
            # SeedSequence-seeded searches thread ONE generator; int seeds
            # keep the historical per-fit derivation (tuner trajectories
            # must not move under existing seeds)
            seed=(self.seed + len(all_obs)
                  if self._model_rng is None else 0),
            rng=self._model_rng,
        )
        return estimator.fit(x, y), y

    def next_candidate(self) -> np.ndarray:
        all_obs = self.observations + self.prior_observations
        if len(all_obs) < self.min_observations:
            return super().next_candidate()
        model, y = self._fit_surrogate(all_obs)
        pool = self.draw_candidates(self.candidate_pool)
        mean, var = model.predict(pool)
        ei = expected_improvement(mean, var, best_value=float(y.min()))
        return pool[int(np.argmax(ei))]

    def propose_batch(self, n: int) -> np.ndarray:
        """One GP fit, one EI ranking of a fresh Sobol pool, top-n distinct
        candidates — the tournament-round ask (search_driver.py). Falls
        back to Sobol until min_observations are told back."""
        all_obs = self.observations + self.prior_observations
        if len(all_obs) < self.min_observations:
            self.last_proposal_source = "sobol"
            return self.draw_candidates(n)
        model, y = self._fit_surrogate(all_obs)
        pool = self.draw_candidates(max(self.candidate_pool, n))
        mean, var = model.predict(pool)
        ei = expected_improvement(mean, var, best_value=float(y.min()))
        self.last_proposal_source = "gp"
        order = np.argsort(-ei)
        return pool[order[:n]]
