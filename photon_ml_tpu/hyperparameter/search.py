"""Random (Sobol) and Gaussian-process (Bayesian) hyperparameter search.

Reference parity: photon-lib hyperparameter/search/RandomSearch.scala:33-50
(Sobol candidate generation in the unit cube, evaluation loop with observed
and prior-observation seeding) and GaussianProcessSearch.scala (fit GP on
observations, pick the candidate maximizing expected improvement among a
fresh batch of Sobol draws, fall back to random until enough observations).

All search state lives in the unit cube [0,1]^d; VectorRescaling maps to and
from real hyperparameter ranges (log-scale λ grids etc.).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Protocol

import numpy as np
from scipy.stats import qmc

from photon_ml_tpu.hyperparameter.acquisition import expected_improvement
from photon_ml_tpu.hyperparameter.estimators import GaussianProcessEstimator
from photon_ml_tpu.hyperparameter.kernels import Kernel, Matern52


class EvaluationFunction(Protocol):
    """Maps a unit-cube candidate vector to an observed (to-minimize) value.

    Reference: photon-lib hyperparameter/EvaluationFunction.scala — the
    client glue (GameEstimatorEvaluationFunction) turns the vector into a
    full GAME training config, runs it, and returns the validation metric.
    """

    def __call__(self, candidate: np.ndarray) -> float: ...


@dataclasses.dataclass
class Observation:
    candidate: np.ndarray
    value: float


@dataclasses.dataclass
class SearchResult:
    best_candidate: np.ndarray
    best_value: float
    observations: list[Observation]


class RandomSearch:
    """Sobol-sequence random search (reference RandomSearch.scala:33-50)."""

    def __init__(self, dim: int, seed: int = 0):
        self.dim = dim
        self.seed = seed
        self._sobol = qmc.Sobol(d=dim, scramble=True, seed=seed)
        self.observations: list[Observation] = []
        self.prior_observations: list[Observation] = []

    def draw_candidates(self, n: int) -> np.ndarray:
        return self._sobol.random(n)

    def next_candidate(self) -> np.ndarray:
        return self.draw_candidates(1)[0]

    def observe(self, candidate: np.ndarray, value: float) -> None:
        self.observations.append(Observation(np.asarray(candidate, float), float(value)))

    def observe_prior(self, candidate: np.ndarray, value: float) -> None:
        """Seed the search with results from earlier runs (reference
        findWithPriors / observePrior)."""
        self.prior_observations.append(
            Observation(np.asarray(candidate, float), float(value))
        )

    def find(self, evaluation_function: EvaluationFunction, n: int) -> SearchResult:
        for _ in range(n):
            cand = self.next_candidate()
            self.observe(cand, evaluation_function(cand))
        return self._result()

    def _result(self) -> SearchResult:
        all_obs = self.observations + self.prior_observations
        if not all_obs:
            raise ValueError("no observations recorded")
        best = min(all_obs, key=lambda o: o.value)
        return SearchResult(
            best_candidate=best.candidate,
            best_value=best.value,
            observations=list(self.observations),
        )


class GaussianProcessSearch(RandomSearch):
    """Bayesian search: GP surrogate + expected improvement
    (reference GaussianProcessSearch.scala)."""

    def __init__(
        self,
        dim: int,
        seed: int = 0,
        *,
        kernel: Kernel | None = None,
        min_observations: int = 3,
        candidate_pool: int = 250,
        num_kernel_samples: int = 3,
        burn_in: int = 8,
    ):
        super().__init__(dim, seed)
        self.kernel = kernel or Matern52()
        self.min_observations = min_observations
        self.candidate_pool = candidate_pool
        self.num_kernel_samples = num_kernel_samples
        self.burn_in = burn_in

    def next_candidate(self) -> np.ndarray:
        all_obs = self.observations + self.prior_observations
        if len(all_obs) < self.min_observations:
            return super().next_candidate()
        x = np.stack([o.candidate for o in all_obs])
        y = np.array([o.value for o in all_obs])
        estimator = GaussianProcessEstimator(
            kernel=self.kernel,
            num_kernel_samples=self.num_kernel_samples,
            burn_in=self.burn_in,
            seed=self.seed + len(all_obs),
        )
        model = estimator.fit(x, y)
        pool = self.draw_candidates(self.candidate_pool)
        mean, var = model.predict(pool)
        ei = expected_improvement(mean, var, best_value=float(y.min()))
        return pool[int(np.argmax(ei))]
