"""Acquisition functions for Bayesian hyperparameter search.

Reference parity: photon-lib hyperparameter/criteria/
ExpectedImprovement.scala and ConfidenceBound.scala. Both are phrased for
*minimization* (the searcher negates metrics whose direction is
maximize-is-better, matching the reference's betterThan handling).
"""

from __future__ import annotations

import numpy as np
from scipy.stats import norm


def expected_improvement(
    mean: np.ndarray, variance: np.ndarray, best_value: float, xi: float = 0.0
) -> np.ndarray:
    """EI(x) = E[max(best − f(x) − ξ, 0)] under f(x) ~ N(mean, variance)."""
    std = np.sqrt(np.maximum(variance, 1e-18))
    improvement = best_value - mean - xi
    z = improvement / std
    return improvement * norm.cdf(z) + std * norm.pdf(z)


def confidence_bound(
    mean: np.ndarray, variance: np.ndarray, beta: float = 2.0
) -> np.ndarray:
    """Lower confidence bound, returned as a to-maximize score:
    −(mean − β·std), so argmax picks the most optimistic minimizer."""
    std = np.sqrt(np.maximum(variance, 1e-18))
    return -(mean - beta * std)
