"""Hyperparameter search (reference photon-lib hyperparameter/**).

Driver-side machinery — like the reference, which runs Sobol/GP search on
the Spark driver with Breeze, this runs on the host in float64 numpy; each
candidate evaluation launches full (jitted, TPU) training runs.
"""

from photon_ml_tpu.hyperparameter.acquisition import (
    confidence_bound,
    expected_improvement,
)
from photon_ml_tpu.hyperparameter.estimators import (
    GaussianProcessEstimator,
    GaussianProcessModel,
)
from photon_ml_tpu.hyperparameter.kernels import Matern52, RBF, Kernel
from photon_ml_tpu.hyperparameter.rescaling import VectorRescaling
from photon_ml_tpu.hyperparameter.search import (
    EvaluationFunction,
    GaussianProcessSearch,
    RandomSearch,
)
from photon_ml_tpu.hyperparameter.search_driver import (
    SearchOutcome,
    SearchSpace,
    parse_search_space,
    run_model_search,
)
from photon_ml_tpu.hyperparameter.slice_sampler import slice_sample

__all__ = [
    "SearchOutcome",
    "SearchSpace",
    "parse_search_space",
    "run_model_search",
    "confidence_bound",
    "expected_improvement",
    "GaussianProcessEstimator",
    "GaussianProcessModel",
    "Kernel",
    "Matern52",
    "RBF",
    "VectorRescaling",
    "EvaluationFunction",
    "GaussianProcessSearch",
    "RandomSearch",
    "slice_sample",
]
