"""Unit-cube <-> hyperparameter-space rescaling.

Reference parity: photon-client hyperparameter/VectorRescaling.scala —
candidates live in [0,1]^d for the searchers; each dimension maps to a real
range, linearly or log-scale (regularization weights are log-scale), with
optional discrete snapping.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class DimensionSpec:
    name: str
    low: float
    high: float
    log_scale: bool = False
    discrete: bool = False

    def __post_init__(self):
        if not self.high > self.low:
            raise ValueError(f"{self.name}: need high > low, got [{self.low}, {self.high}]")
        if self.log_scale and self.low <= 0:
            raise ValueError(f"{self.name}: log-scale needs low > 0, got {self.low}")


@dataclasses.dataclass(frozen=True)
class VectorRescaling:
    dims: Sequence[DimensionSpec]

    @property
    def dim(self) -> int:
        return len(self.dims)

    def to_hyperparameters(self, unit: np.ndarray) -> np.ndarray:
        """[0,1]^d -> real hyperparameter values."""
        unit = np.asarray(unit, dtype=np.float64)
        out = np.empty_like(unit)
        for i, spec in enumerate(self.dims):
            u = np.clip(unit[..., i], 0.0, 1.0)
            if spec.log_scale:
                lo, hi = np.log(spec.low), np.log(spec.high)
                v = np.exp(lo + u * (hi - lo))
            else:
                v = spec.low + u * (spec.high - spec.low)
            if spec.discrete:
                v = np.round(v)
            out[..., i] = v
        return out

    def to_unit(self, values: np.ndarray) -> np.ndarray:
        """Real hyperparameter values -> [0,1]^d (for seeding priors)."""
        values = np.asarray(values, dtype=np.float64)
        out = np.empty_like(values)
        for i, spec in enumerate(self.dims):
            v = values[..., i]
            if spec.log_scale:
                lo, hi = np.log(spec.low), np.log(spec.high)
                u = (np.log(np.maximum(v, spec.low)) - lo) / (hi - lo)
            else:
                u = (v - spec.low) / (spec.high - spec.low)
            out[..., i] = np.clip(u, 0.0, 1.0)
        return out
