"""Gaussian-process regression with slice-sampled kernel hyperparameters.

Reference parity: photon-lib hyperparameter/estimators/
GaussianProcessEstimator.scala:36-60 (fit = sample kernel configurations
from their posterior via slice sampling, keep the ensemble) and
GaussianProcessModel.scala (posterior mean/variance, averaged over the
sampled kernels).
"""

from __future__ import annotations

import dataclasses

import numpy as np
from scipy.linalg import cho_factor, cho_solve

from photon_ml_tpu.hyperparameter.kernels import Kernel, Matern52
from photon_ml_tpu.hyperparameter.slice_sampler import slice_sample


@dataclasses.dataclass
class _FittedKernel:
    kernel: Kernel
    chol: tuple
    alpha: np.ndarray  # (K + σ²I)⁻¹ y


@dataclasses.dataclass
class GaussianProcessModel:
    """Posterior over a scalar response, ensemble-averaged over kernels."""

    x_train: np.ndarray
    y_mean: float
    y_std: float
    fitted: list[_FittedKernel]

    def predict(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Return (mean, variance) at candidate points [m, d], in the
        original (un-standardized) response units."""
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        means, variances = [], []
        for f in self.fitted:
            k_star = f.kernel(x, self.x_train)  # [m, n]
            mu = k_star @ f.alpha
            v = cho_solve(f.chol, k_star.T)  # [n, m]
            # stationary kernel: prior variance is the constant amplitude²
            prior = np.full(len(x), f.kernel.amplitude**2)
            var = np.maximum(prior - np.einsum("mn,nm->m", k_star, v), 1e-12)
            means.append(mu)
            variances.append(var)
        mean = np.mean(means, axis=0)
        # law of total variance across the kernel ensemble
        var = np.mean(variances, axis=0) + np.var(means, axis=0)
        return mean * self.y_std + self.y_mean, var * self.y_std**2


@dataclasses.dataclass
class GaussianProcessEstimator:
    """Fit a GP by slice-sampling kernel hyperparameters from the marginal
    likelihood × prior (reference GaussianProcessEstimator.scala:36-60)."""

    kernel: Kernel = dataclasses.field(default_factory=Matern52)
    num_kernel_samples: int = 5
    burn_in: int = 10
    seed: int = 0
    #: explicit generator for the slice sampler — searchers thread ONE
    #: generator through every fit so trajectories replay deterministically
    #: (None = a fresh default_rng(seed) per fit, the standalone behavior)
    rng: np.random.Generator | None = None
    #: log-normal prior scale on (log amplitude, log noise, log lengthscale)
    prior_scale: float = 2.0

    def fit(self, x: np.ndarray, y: np.ndarray) -> GaussianProcessModel:
        x = np.atleast_2d(np.asarray(x, dtype=np.float64))
        y = np.asarray(y, dtype=np.float64).ravel()
        y_mean = float(y.mean())
        y_std = float(y.std()) or 1.0
        ys = (y - y_mean) / y_std
        d = x.shape[1]

        def unpack(theta: np.ndarray) -> Kernel:
            return self.kernel.with_params(
                amplitude=float(np.exp(theta[0])),
                noise=float(np.exp(theta[1])),
                lengthscale=np.exp(theta[2 : 2 + d]),
            )

        def log_marginal(theta: np.ndarray) -> float:
            if np.any(np.abs(theta) > 20.0):
                return -np.inf
            kern = unpack(theta)
            k = kern(x)
            try:
                chol = cho_factor(k, lower=True)
            except np.linalg.LinAlgError:
                return -np.inf
            alpha = cho_solve(chol, ys)
            log_det = 2.0 * np.sum(np.log(np.diag(chol[0])))
            ll = -0.5 * ys @ alpha - 0.5 * log_det
            prior = -0.5 * float(theta @ theta) / self.prior_scale**2
            return float(ll + prior)

        theta0 = np.zeros(2 + d)
        theta0[1] = np.log(0.1)  # start with moderate noise
        rng = self.rng if self.rng is not None else np.random.default_rng(self.seed)
        thetas = slice_sample(
            log_marginal,
            theta0,
            rng,
            num_samples=self.num_kernel_samples,
            burn_in=self.burn_in,
        )

        fitted = []
        for theta in thetas:
            kern = unpack(theta)
            chol = cho_factor(kern(x), lower=True)
            fitted.append(
                _FittedKernel(kernel=kern, chol=chol, alpha=cho_solve(chol, ys))
            )
        return GaussianProcessModel(
            x_train=x, y_mean=y_mean, y_std=y_std, fitted=fitted
        )
