"""Covariance kernels for GP-based hyperparameter search.

Reference parity: photon-lib hyperparameter/estimators/kernels/ — RBF and
Matern52 with amplitude, per-dimension lengthscales, and a noise floor;
`StationaryKernel` expected-improvement machinery works on the same
hyperparameters (amplitude, noise, lengthScale).
"""

from __future__ import annotations

import dataclasses

import numpy as np


def _scaled_sqdist(x1: np.ndarray, x2: np.ndarray, lengthscale: np.ndarray) -> np.ndarray:
    """Pairwise squared distance of rows after per-dim lengthscale division."""
    a = x1 / lengthscale
    b = x2 / lengthscale
    aa = (a * a).sum(axis=1)[:, None]
    bb = (b * b).sum(axis=1)[None, :]
    sq = aa + bb - 2.0 * (a @ b.T)
    return np.maximum(sq, 0.0)


@dataclasses.dataclass(frozen=True)
class Kernel:
    """amplitude² · k(r/lengthscale) (+ noise² on the diagonal of K(X, X))."""

    amplitude: float = 1.0
    noise: float = 1e-4
    lengthscale: np.ndarray | float = 1.0

    def _ls(self, dim: int) -> np.ndarray:
        ls = np.asarray(self.lengthscale, dtype=np.float64)
        if ls.ndim == 0:
            ls = np.full((dim,), float(ls))
        return ls

    def __call__(self, x1: np.ndarray, x2: np.ndarray | None = None) -> np.ndarray:
        x1 = np.atleast_2d(np.asarray(x1, dtype=np.float64))
        symmetric = x2 is None
        x2m = x1 if symmetric else np.atleast_2d(np.asarray(x2, dtype=np.float64))
        k = self.amplitude**2 * self._corr(_scaled_sqdist(x1, x2m, self._ls(x1.shape[1])))
        if symmetric:
            k = k + self.noise**2 * np.eye(len(x1))
        return k

    def _corr(self, sqdist: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def with_params(self, amplitude: float, noise: float, lengthscale) -> "Kernel":
        return dataclasses.replace(
            self, amplitude=amplitude, noise=noise, lengthscale=lengthscale
        )


@dataclasses.dataclass(frozen=True)
class RBF(Kernel):
    """Squared-exponential kernel (reference kernels/RBF.scala)."""

    def _corr(self, sqdist: np.ndarray) -> np.ndarray:
        return np.exp(-0.5 * sqdist)


@dataclasses.dataclass(frozen=True)
class Matern52(Kernel):
    """Matérn 5/2 kernel (reference kernels/Matern52.scala) — the
    reference's default for hyperparameter response surfaces."""

    def _corr(self, sqdist: np.ndarray) -> np.ndarray:
        r = np.sqrt(5.0 * sqdist)
        return (1.0 + r + (5.0 / 3.0) * sqdist) * np.exp(-r)
