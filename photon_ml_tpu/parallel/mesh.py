"""Device mesh + named shardings: the distributed substrate.

Reference parity: the Spark seams — RDDLike.scala, broadcast wrappers
(SURVEY.md §2.5, PARITY.md L25) — dissolved rather than ported. This module
replaces the reference's entire Spark communication layer
(SURVEY.md §2.5): RDD treeAggregate -> XLA psum reduction trees over ICI;
driver broadcast -> replicated sharding; custom partitioners
(LongHashPartitioner, RandomEffectDataSetPartitioner) -> named shardings of
the sample and entity axes. There is no hand-written collective call in the
training path: data enters sharded, jit inserts the collectives.

Mesh convention:
- "data":  sample axis (and entity axis for random-effect buckets) — DP/EP
- "model": feature axis for giant fixed-effect coordinates — sharded
  coefficient vectors with reduce-scattered gradients (SURVEY.md §7,
  1B-coefficient case)

Multi-host: build the mesh over jax.devices() after jax.distributed
initialization; ICI carries within-slice axes, DCN across slices.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.data.batch import LabeledPointBatch


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """Version-compat ``shard_map``: new jax exposes ``jax.shard_map`` with
    ``check_vma``; older installs only have
    ``jax.experimental.shard_map.shard_map`` with the equivalent knob named
    ``check_rep``. Every shard_map in this package routes through here so
    the multi-chip paths work on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def make_mesh(
    data: int | None = None,
    model: int = 1,
    *,
    devices=None,
) -> Mesh:
    """Create a ("data", "model") mesh. Defaults to all devices on "data"."""
    devices = list(devices if devices is not None else jax.devices())
    if data is None:
        data = len(devices) // model
    if data * model != len(devices):
        devices = devices[: data * model]
    grid = np.array(devices).reshape(data, model)
    return Mesh(grid, axis_names=("data", "model"))


def replicate(tree, mesh: Mesh):
    """Place a pytree fully replicated (the 'broadcast' of the reference —
    done once, not per iteration; reference re-broadcast the coefficient
    vector every optimizer step, FixedEffectCoordinate.scala:143)."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def shard_batch(batch: LabeledPointBatch, mesh: Mesh, *, feature_sharded: bool = False) -> LabeledPointBatch:
    """Shard a batch along the sample axis ("data"); optionally shard the
    feature axis along "model" for giant coordinates."""
    fspec = P("data", "model" if feature_sharded else None)
    vspec = P("data")
    n = batch.num_samples
    per = mesh.shape["data"]
    if n % per != 0:
        batch = batch.pad_to(((n + per - 1) // per) * per)
    return LabeledPointBatch(
        features=jax.device_put(batch.features, NamedSharding(mesh, fspec)),
        labels=jax.device_put(batch.labels, NamedSharding(mesh, vspec)),
        offsets=jax.device_put(batch.offsets, NamedSharding(mesh, vspec)),
        weights=jax.device_put(batch.weights, NamedSharding(mesh, vspec)),
    )


def shard_game_dataset(dataset, mesh: Mesh):
    """Shard a GameDataset's sample-axis arrays over "data". Entity-bucket
    tensors shard their entity axis over "data" when solved (the vmapped
    solver's batch dimension)."""
    vspec = NamedSharding(mesh, P("data"))

    n = dataset.num_samples
    per = mesh.shape["data"]
    if n % per != 0:
        raise ValueError(
            f"sample count {n} not divisible by data-axis size {per}; "
            "pad with zero-weight rows first"
        )
    dataset = dataclasses.replace(
        dataset,
        labels=jax.device_put(dataset.labels, vspec),
        offsets=jax.device_put(dataset.offsets, vspec),
        weights=jax.device_put(dataset.weights, vspec),
        feature_shards={
            k: jax.device_put(v, NamedSharding(mesh, P("data", None)))
            for k, v in dataset.feature_shards.items()
        },
        entity_idx={
            k: jax.device_put(v, vspec) for k, v in dataset.entity_idx.items()
        },
    )
    return dataset
