"""Mesh-sharded full-GAME training step: one jitted SPMD program.

This is the TPU replacement for the reference's entire distributed training
round (photon-api algorithm/FixedEffectCoordinate.scala:91-165 treeAggregate
optimization + algorithm/RandomEffectCoordinate.scala:104-153 per-entity RDD
solves + photon-lib algorithm/CoordinateDescent.scala:198-255 residual
choreography). One call = one full block-coordinate-descent sweep:

    FE solve (samples sharded over "data", features optionally over "model")
    -> residual score update
    -> per-RE-type vmapped entity solves (entities sharded over "data")
    -> residual score updates
    -> final training loss

Everything lives inside a single jit, so XLA inserts every collective:
gradient psums over the "data" axis where Spark ran treeAggregate, feature-
axis reduce-scatters/all-gathers over "model" where the reference broadcast
the coefficient vector, and gather/scatter collectives where the reference
ran RDD joins. Multi-host pods: build the mesh over all processes' devices
after jax.distributed.initialize; the same program then spans ICI + DCN.

Sharding convention (parallel/mesh.py): axis "data" carries both sample-DP
and entity-parallelism (the "EP" of this model family, SURVEY.md §2.5);
axis "model" carries the feature axis of giant fixed-effect coordinates
(the tensor-parallel analogue — 1B-coefficient FE vectors, SURVEY.md §7).
"""

from __future__ import annotations

import dataclasses
import logging
from functools import partial
from typing import Mapping, Sequence

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.algorithm.coordinates import (
    solve_entity_bucket,
    solve_entity_bucket_indexmap,
    solve_entity_bucket_random,
)
from photon_ml_tpu.algorithm.mf_coordinate import solve_mf_side_bucket
from photon_ml_tpu.models.matrix_factorization import score_matrix_factorization
from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.data.game_data import GameDataset, RandomEffectDataset
from photon_ml_tpu.data.sparse_batch import SparseShard, sparse_margins
from photon_ml_tpu.ops.sparse_objective import SparseGLMObjective
from photon_ml_tpu.models.game import score_random_effect
from photon_ml_tpu.projector.projectors import ProjectorType
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim.optimizer import OptimizerConfig, solve
from photon_ml_tpu.telemetry.program_ledger import ledger_jit
from photon_ml_tpu.types import TaskType

Array = jax.Array

logger = logging.getLogger(__name__)


@flax.struct.dataclass
class GameTrainState:
    """Device-resident model state for one training step.

    fe_coefficients: [d_fe] — the fixed-effect coefficient vector; shard its
        (only) axis over "model" for giant coordinates, replicate otherwise.
    re_tables: RE type -> [num_entities, d_re] coefficient table; the entity
        axis shards over "data".
    mf_rows / mf_cols: MF coordinate name -> [num_entities, k] latent-factor
        tables (row / col side); entity axes shard over "data".
    extra_fe: feature shard id -> [d] coefficient vector for ADDITIONAL
        fixed-effect coordinates beyond the primary (reference
        GameEstimator.scala:746-828 trains arbitrary coordinate sets; the
        fused step keeps one primary FE — the only one that may be sparse
        or feature-sharded — and any number of dense replicated extras).
    """

    fe_coefficients: Array
    re_tables: dict[str, Array]
    mf_rows: dict[str, Array] = flax.struct.field(default_factory=dict)
    mf_cols: dict[str, Array] = flax.struct.field(default_factory=dict)
    extra_fe: dict[str, Array] = flax.struct.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class RandomEffectStepSpec:
    """Static description of one RE coordinate inside the fused step.

    projector: must match the RandomEffectDataset's projector_type.
    INDEX_MAP solves each entity over its observed columns via the
    scratch-column gather/scatter (IndexMapProjectorRDD.scala:218-257);
    RANDOM solves in the sketched space and back-projects. The model table
    stays [E, dim] in original space either way, so scoring and residual
    updates are projector-agnostic."""

    re_type: str
    feature_shard_id: str
    optimizer: OptimizerConfig
    l2_weight: float = 0.0
    projector: ProjectorType = ProjectorType.IDENTITY
    #: intercept column of the feature shard — required when the
    #: coordinate's normalization carries shifts (STANDARDIZATION): model-
    #: space conversion absorbs each entity's margin shift into it
    intercept_index: int | None = None


@dataclasses.dataclass(frozen=True)
class FixedEffectStepSpec:
    """Static description of the fixed-effect coordinate.

    down_sampling_rate < 1 trains the FE solve on down-sampled weights
    (reference DistributedOptimizationProblem.runWithSampling:145-160):
    ``train_distributed`` computes a per-sweep stable-id multiplier with the
    same splitmix64 sampler the CD path uses and feeds it into the step as
    ``data["fe_weight_multiplier"]``; scoring and the training loss still
    cover every sample."""

    feature_shard_id: str
    optimizer: OptimizerConfig
    l2_weight: float = 0.0
    down_sampling_rate: float = 1.0
    #: intercept column of the feature shard — consulted for NON-primary
    #: (extra) FE coordinates whose normalization carries shifts; the
    #: primary FE's intercept rides the state_to_game_model /
    #: game_model_to_state ``intercept_index`` argument (historical API).
    intercept_index: int | None = None


@dataclasses.dataclass(frozen=True)
class MatrixFactorizationStepSpec:
    """Static description of one MF coordinate inside the fused step (the
    model family the reference declares but never implemented —
    algorithm/mf_coordinate.py)."""

    name: str
    row_effect_type: str
    col_effect_type: str
    num_latent_factors: int
    optimizer: OptimizerConfig
    l2_weight: float = 0.0
    num_alternations: int = 1
    seed: int = 0


def _data_pytree(dataset: GameDataset, re_specs: Sequence[RandomEffectStepSpec],
                 fe_shard: str,
                 mf_specs: Sequence[MatrixFactorizationStepSpec] = (),
                 extra_fe_shards: Sequence[str] = ()) -> dict:
    shards = {fe_shard} | {s.feature_shard_id for s in re_specs} | set(extra_fe_shards)
    id_types = {s.re_type for s in re_specs}
    for m in mf_specs:
        id_types |= {m.row_effect_type, m.col_effect_type}
    from photon_ml_tpu.data.sparse_batch import (
        SparseLabeledPointBatch,
        SparseShard,
    )

    fe_sparse = isinstance(dataset.feature_shards[fe_shard], SparseShard)
    # sparse RE shards ride as compact per-entry mappings (see
    # prepare_inputs), never as dense blocks
    for k in shards:
        if isinstance(dataset.feature_shards[k], SparseShard) and k != fe_shard:
            # extra-FE shards are dense-only even when the same shard also
            # feeds a random-effect coordinate (sparse shards never enter
            # data["features"], which the extra-FE solve reads from)
            if k in extra_fe_shards or k not in {
                s.feature_shard_id for s in re_specs
            }:
                raise ValueError(
                    f"feature shard '{k}' is sparse (giant-d) but is not "
                    "the PRIMARY fixed-effect shard or a random-effect "
                    "shard (additional fixed effects are dense-only; make "
                    "the sparse one the primary)"
                )
    labels = jnp.asarray(dataset.labels)
    weights = jnp.asarray(dataset.weights)
    data = {
        "labels": labels,
        "offsets": jnp.asarray(dataset.offsets),
        "weights": weights,
        "features": {
            k: jnp.asarray(dataset.feature_shards[k])
            for k in shards
            if not isinstance(dataset.feature_shards[k], SparseShard)
        },
        "entity_idx": {
            t: jnp.asarray(dataset.entity_idx[t]) for t in sorted(id_types)
        },
    }
    if fe_sparse:
        # flat-COO FE batch: offsets filled per step (residual scores);
        # the static `dim` rides the pytree treedef, so sparse-vs-dense is
        # a compile-time branch in the step
        data["fe_sparse_batch"] = SparseLabeledPointBatch.from_shard(
            dataset.feature_shards[fe_shard], labels,
            jnp.zeros_like(labels), weights,
        )
    return data


def _buckets_pytree(
    re_datasets: Mapping[str, RandomEffectDataset],
    re_specs: Sequence[RandomEffectStepSpec] = (),
    normalized_re_types: "set[str]" = frozenset(),
) -> dict:
    spec_projector = {s.re_type: s.projector for s in re_specs}
    for k, ds in re_datasets.items():
        if (
            k in normalized_re_types
            and ds.projector_type in (ProjectorType.INDEX_MAP,
                                      ProjectorType.RANDOM)
            and not ds.pre_normalized
        ):
            raise ValueError(
                f"random-effect coordinate '{k}': projected coordinates "
                "with normalization require the RandomEffectDataset to be "
                "built with the same normalization "
                "(build_random_effect_dataset(normalization=...))"
            )
        if ds.pre_normalized and k not in normalized_re_types:
            raise ValueError(
                f"random-effect coordinate '{k}': the RandomEffectDataset "
                "was built pre-normalized but the program spec carries no "
                "normalization context for it — tables would leave the "
                "step in normalized space unconverted"
            )
        expected = spec_projector.get(k, ProjectorType.IDENTITY)
        if ds.projector_type != expected:
            raise ValueError(
                f"random-effect dataset '{k}' uses projector "
                f"{ds.projector_type.name} but the step spec declares "
                f"{expected.name} — the step's solve/scatter logic is "
                "compiled per projector, so they must match"
            )

    def bucket_dict(b, ds) -> dict:
        out = {
            "features": b.features,
            "labels": b.labels,
            "weights": b.weights,
            "sample_rows": b.sample_rows,
            "entity_rows": b.entity_rows,
        }
        if ds.projector_type == ProjectorType.INDEX_MAP:
            out["col_index"] = b.col_index
        return out

    out = {
        k: [bucket_dict(b, ds) for b in ds.buckets]
        for k, ds in re_datasets.items()
    }
    projections = {
        k: jnp.asarray(ds.projection.matrix)
        for k, ds in re_datasets.items()
        if ds.projector_type == ProjectorType.RANDOM
    }
    if projections:
        out["__projections__"] = projections
    return out


class GameTrainProgram:
    """A compiled full-GAME training step bound to static specs.

    Build once per (task, coordinate specs); call ``step`` repeatedly — the
    jitted program is cached. Use ``shard_inputs`` to lay data and state out
    over a mesh first; the same program runs single-chip when no mesh is
    given (the SPMD partitioner simply sees one device).
    """

    def __init__(
        self,
        task: TaskType,
        fe: FixedEffectStepSpec,
        re_specs: Sequence[RandomEffectStepSpec] = (),
        *,
        mf_specs: Sequence[MatrixFactorizationStepSpec] = (),
        extra_fes: Sequence[FixedEffectStepSpec] = (),
        update_order: Sequence[str] | None = None,
        normalization: NormalizationContext | None = None,
        re_normalizations: Mapping[str, NormalizationContext] | None = None,
        extra_fe_normalizations: Mapping[str, NormalizationContext] | None = None,
        use_pallas_fe: bool | None = None,
        mesh: Mesh | None = None,
        fe_feature_sharded: bool = False,
    ):
        self.task = task
        # AUTO resolution happens ONCE, at program build: FE coordinates
        # (big-d, possibly sharded/streamed) take LBFGS; RE/MF coordinates
        # (small-d dense vmapped buckets) take NEWTON when the loss is
        # eligible (optim/optimizer.resolve_auto_optimizer) — the measured
        # 18 vs 48 ms fused-sweep win, now reachable without naming the
        # solver. Explicit configs pass through untouched.
        from photon_ml_tpu.optim.optimizer import resolve_auto_optimizer

        _loss_for_auto = loss_for_task(task)

        def _resolved(spec, small_dense):
            opt = resolve_auto_optimizer(
                spec.optimizer, loss=_loss_for_auto, small_dense=small_dense
            )
            return (
                spec if opt is spec.optimizer
                else dataclasses.replace(spec, optimizer=opt)
            )

        fe = _resolved(fe, False)
        self.fe = fe
        self.re_specs = tuple(_resolved(s, True) for s in re_specs)
        self.mf_specs = tuple(_resolved(s, True) for s in mf_specs)
        self.extra_fes = tuple(_resolved(s, False) for s in extra_fes)
        re_specs = self.re_specs
        mf_specs = self.mf_specs
        extra_fes = self.extra_fes
        # coordinate names share one namespace: residual skip keys and the
        # GameModel coordinate ids of state_to_game_model (where each FE
        # coordinate is named after its feature shard)
        names = (
            [fe.feature_shard_id]
            + [s.feature_shard_id for s in self.extra_fes]
            + [s.re_type for s in self.re_specs]
            + [m.name for m in self.mf_specs]
        )
        dupes = {n for n in names if names.count(n) > 1}
        if dupes:
            raise ValueError(
                f"coordinate names must be unique across the FE feature "
                f"shards, RE types, and MF names (duplicates: {sorted(dupes)})"
            )
        # sweep order inside one fused step (reference
        # CoordinateDescent.scala:198-255 trains coordinates in the
        # CONFIGURED order — order changes what residuals each solve sees).
        # Default: primary FE, extra FEs, REs, MFs (the historical order).
        if update_order is None:
            self.update_order: tuple[str, ...] = tuple(names)
        else:
            if sorted(update_order) != sorted(names):
                raise ValueError(
                    f"update_order must be a permutation of the coordinate "
                    f"names {sorted(names)}; got {list(update_order)}"
                )
            self.update_order = tuple(update_order)
        self._kind = {fe.feature_shard_id: "fe"}
        self._kind.update({s.feature_shard_id: "extra_fe" for s in self.extra_fes})
        self._kind.update({s.re_type: "re" for s in self.re_specs})
        self._kind.update({m.name: "mf" for m in self.mf_specs})
        self._extra_fe_by_name = {s.feature_shard_id: s for s in self.extra_fes}
        self._re_by_name = {s.re_type: s for s in self.re_specs}
        self._mf_by_name = {m.name: m for m in self.mf_specs}
        reserved = {"__mf__", "__projections__"} & set(names)
        if reserved:
            raise ValueError(
                f"{sorted(reserved)} are reserved (internal bucket-group "
                "keys); rename the coordinate"
            )
        loss = loss_for_task(task)
        self._loss = loss
        self.normalization = normalization
        # use_pallas=False everywhere in the fused program by default: its
        # batches may be GSPMD mesh-sharded, and XLA cannot partition a
        # pallas_call. The single-pass kernel reaches the (un-vmapped,
        # dense) primary-FE solve two ways:
        #  - single device: use_pallas_fe opts this GLMObjective in
        #    (None = TPU auto, True = force/interpret, False = off);
        #  - multi-device mesh (pass ``mesh``): a shard_map wrapper runs
        #    the kernel per-device on local rows and psums — the
        #    reference's one-pass seqOp on every executor
        #    (ValueAndGradientAggregator.scala:133-154, :236-251). Not
        #    built when the FE block is feature-sharded over "model"
        #    (that path is sparse/column-sharded) or use_pallas_fe=False.
        # Callers that never pass a mesh keep the conservative False
        # default: their batches may be GSPMD-sharded later, where a
        # baked-in pallas_call cannot be partitioned.
        n_mesh_devices = int(mesh.devices.size) if mesh is not None else 1
        multi_device = mesh is not None and n_mesh_devices > 1
        if mesh is None and use_pallas_fe is None:
            use_pallas_fe = False  # topology unknown: keep the kernel out
        self._fe_objective = GLMObjective(
            loss, l2_weight=fe.l2_weight, normalization=normalization,
            use_pallas=False if (multi_device or use_pallas_fe is False)
            else use_pallas_fe,
        )
        self._fe_sharded_objective = None
        if multi_device and use_pallas_fe is not False and not fe_feature_sharded:
            from photon_ml_tpu.parallel.sharded_dense import (
                ShardedDenseGLMObjective,
            )

            self._fe_sharded_objective = ShardedDenseGLMObjective(
                loss, mesh, l2_weight=fe.l2_weight,
                normalization=normalization, use_pallas=use_pallas_fe,
            )
        # sparse twin, used when the FE shard arrives as flat COO (the
        # giant-d path); shares the normalization context so jit caches of
        # both variants stay identity-keyed
        self._fe_sparse_objective = SparseGLMObjective(
            loss, l2_weight=fe.l2_weight, normalization=normalization
        )
        # additional (dense, replicated) FE coordinates
        extra_fe_normalizations = dict(extra_fe_normalizations or {})
        for s in self.extra_fes:
            ctx = extra_fe_normalizations.get(s.feature_shard_id)
            if (
                ctx is not None and ctx.shifts is not None
                and s.intercept_index is None
            ):
                raise ValueError(
                    f"fixed-effect coordinate '{s.feature_shard_id}': "
                    "normalization with shifts (STANDARDIZATION) requires "
                    "the spec's intercept_index"
                )
        self._extra_fe_objectives = {
            s.feature_shard_id: GLMObjective(
                loss, l2_weight=s.l2_weight,
                normalization=extra_fe_normalizations.get(s.feature_shard_id),
                use_pallas=False,
            )
            for s in self.extra_fes
        }
        # RE normalization: the full factor+shift algebra. Factors scale the
        # effective coefficients; shifts subtract each entity's margin-shift
        # scalar in scoring (_re_coordinate_score) and are absorbed into the
        # shard's intercept on model-space conversion — the spec must carry
        # intercept_index then (same contract as the FE/CD paths,
        # ValueAndGradientAggregator.scala:36-49).
        re_normalizations = dict(re_normalizations or {})
        for s in self.re_specs:
            ctx = re_normalizations.get(s.re_type)
            if (
                ctx is not None and ctx.shifts is not None
                and s.intercept_index is None
            ):
                raise ValueError(
                    f"random-effect coordinate '{s.re_type}': normalization "
                    "with shifts (STANDARDIZATION) requires the spec's "
                    "intercept_index (the intercept absorbs each entity's "
                    "margin shift in model space)"
                )
        self._re_objectives = {
            s.re_type: GLMObjective(
                loss, l2_weight=s.l2_weight,
                normalization=re_normalizations.get(s.re_type),
                use_pallas=False,
            )
            for s in self.re_specs
        }
        # projected (INDEX_MAP/RANDOM) + normalization: entity blocks
        # arrive pre-normalized (build_random_effect_dataset(
        # normalization=...)), so their SOLVES use a plain objective;
        # scoring/table conversion keep the context
        self._re_solve_objectives = {
            s.re_type: (
                GLMObjective(loss, l2_weight=s.l2_weight, use_pallas=False)
                if (
                    s.projector in (ProjectorType.INDEX_MAP,
                                    ProjectorType.RANDOM)
                    and re_normalizations.get(s.re_type) is not None
                )
                else self._re_objectives[s.re_type]
            )
            for s in self.re_specs
        }
        self._mf_objectives = {
            m.name: GLMObjective(loss, l2_weight=m.l2_weight,
                                 use_pallas=False)
            for m in self.mf_specs
        }
        # ledger-labeled programs (telemetry/program_ledger.py): the whole
        # CD sweep and the validation score, the two hottest signatures of
        # a training run
        self._step = ledger_jit(self._step_impl, label="train/step")
        self._score = ledger_jit(self._score_impl, label="train/score")

    def fe_coefficients_model_space(self, state: GameTrainState,
                                    intercept_index: int | None = None) -> Array:
        """Convert the state's normalized-space FE vector to original feature
        space for persistence/scoring outside the step."""
        return self._fe_objective.normalization.to_model_space(
            state.fe_coefficients, intercept_index
        )

    # -- state / input preparation ------------------------------------------

    def init_state(self, dataset: GameDataset,
                   re_datasets: Mapping[str, RandomEffectDataset],
                   mf_datasets: Mapping[str, "MFDataset"] | None = None,
                   dtype=None) -> GameTrainState:
        from photon_ml_tpu.models.matrix_factorization import init_factors

        from photon_ml_tpu.data.batch import solve_dtype_of

        fe_dim = dataset.feature_shards[self.fe.feature_shard_id].shape[1]
        dtype = solve_dtype_of(
            dtype or dataset.feature_shards[self.fe.feature_shard_id].dtype
        )
        tables = {
            s.re_type: jnp.zeros(
                (re_datasets[s.re_type].num_entities,
                 re_datasets[s.re_type].table_width),  # K in compact mode
                dtype=dtype,
            )
            for s in self.re_specs
        }
        mf_rows: dict[str, Array] = {}
        mf_cols: dict[str, Array] = {}
        for m in self.mf_specs:
            mf = (mf_datasets or {})[m.name]
            row, col = init_factors(
                mf.num_row_entities, mf.num_col_entities,
                m.num_latent_factors, seed=m.seed, dtype=dtype,
            )
            # zero the factors of vocab entities with no samples (they are
            # never solved; random init would leak noise into their scores)
            row_mask, col_mask = mf.trained_masks()
            mf_rows[m.name] = jnp.where(jnp.asarray(row_mask)[:, None], row, 0.0)
            mf_cols[m.name] = jnp.where(jnp.asarray(col_mask)[:, None], col, 0.0)
        return GameTrainState(
            fe_coefficients=jnp.zeros((fe_dim,), dtype=dtype),
            re_tables=tables,
            mf_rows=mf_rows,
            mf_cols=mf_cols,
            extra_fe={
                s.feature_shard_id: jnp.zeros(
                    (dataset.feature_shards[s.feature_shard_id].shape[1],),
                    dtype=dtype,
                )
                for s in self.extra_fes
            },
        )

    def _attach_re_sparse(self, data: dict, dataset: GameDataset,
                          re_datasets: Mapping[str, RandomEffectDataset]):
        """Compact (sparse-shard) RE coordinates: per-entry (entity, table
        position, row, value) mappings for O(nnz) scoring inside the step
        (models/game.compact_entry_positions against the TRAINING
        active-column lists)."""
        from photon_ml_tpu.models.game import compact_entry_positions

        for s in self.re_specs:
            shard = dataset.feature_shards[s.feature_shard_id]
            ds = re_datasets.get(s.re_type) if re_datasets else None
            if not isinstance(shard, SparseShard):
                continue
            if ds is None or ds.active_cols is None:
                raise ValueError(
                    f"random-effect coordinate '{s.re_type}' uses a sparse "
                    "feature shard; its RandomEffectDataset (with "
                    "active_cols) is required to prepare inputs"
                )
            ent, pos, rows, vals = compact_entry_positions(
                shard,
                np.asarray(dataset.host_array(f"entity_idx/{s.re_type}")),
                ds.active_cols,
            )
            norm = self._re_objectives[s.re_type].normalization
            if norm.factors is not None:
                # normalized compact coordinate: the state's table lives in
                # normalized space, so residual scoring needs normalized
                # entry values x' = x * factor[col] (SCALE-only; entry
                # order matches coalesced(), which compact_entry_positions
                # reads)
                from photon_ml_tpu.ops.normalization import host_factors

                _, cols_s, _ = shard.coalesced()
                vals = np.asarray(vals) * host_factors(norm).astype(
                    np.asarray(vals).dtype
                )[np.asarray(cols_s)]
            data.setdefault("re_sparse", {})[s.re_type] = {
                "ent": jnp.asarray(ent),
                "pos": jnp.asarray(pos),
                "rows": jnp.asarray(rows),
                "vals": jnp.asarray(vals),
            }
        return data

    def prepare_inputs(self, dataset: GameDataset,
                       re_datasets: Mapping[str, RandomEffectDataset],
                       mf_datasets: Mapping[str, "MFDataset"] | None = None):
        data = _data_pytree(
            dataset, self.re_specs, self.fe.feature_shard_id, self.mf_specs,
            extra_fe_shards=tuple(self._extra_fe_by_name),
        )
        data = self._attach_re_sparse(data, dataset, re_datasets)
        buckets = _buckets_pytree(
            {s.re_type: re_datasets[s.re_type] for s in self.re_specs},
            self.re_specs,
            normalized_re_types={
                k for k in self._re_solve_objectives
                if self._re_solve_objectives[k] is not self._re_objectives[k]
            },
        )
        buckets["__mf__"] = {
            m.name: {
                side: [
                    {
                        "labels": b.labels,
                        "weights": b.weights,
                        "sample_rows": b.sample_rows,
                        "entity_rows": b.entity_rows,
                    }
                    for b in side_buckets
                ]
                for side, side_buckets in (
                    ("row", (mf_datasets or {})[m.name].row_buckets),
                    ("col", (mf_datasets or {})[m.name].col_buckets),
                )
            }
            for m in self.mf_specs
        }
        return data, buckets

    def _shard_data(self, mesh: Mesh, data, *, fe_feature_sharded: bool = False,
                    put_fn=None):
        """Lay a data pytree (training or scoring) out over the mesh:
        sample-axis arrays over "data", the FE feature axis over "model"
        when requested."""
        put = put_fn if put_fn is not None else jax.device_put
        vec = NamedSharding(mesh, P("data"))
        data_axis = int(mesh.shape["data"])
        fe_fspec = P("data", "model") if fe_feature_sharded else P("data", None)

        def put_feats(shard_id, arr):
            spec = fe_fspec if shard_id == self.fe.feature_shard_id else P("data", None)
            return put(arr, NamedSharding(mesh, spec))

        data = dict(data)
        data["labels"] = put(data["labels"], vec)
        data["offsets"] = put(data["offsets"], vec)
        data["weights"] = put(data["weights"], vec)
        data["features"] = {k: put_feats(k, v) for k, v in data["features"].items()}
        data["entity_idx"] = {k: put(v, vec) for k, v in data["entity_idx"].items()}
        if "fe_sparse_batch" in data:
            # flat entry arrays shard over "data" (nnz axis); per-sample
            # vectors over "data"; GSPMD inserts the psum that combines
            # per-shard partial margins and the model-axis collectives for
            # a "model"-sharded coefficient gather
            sb = data["fe_sparse_batch"]
            sb = sb.pad_nnz(sb.nnz + (-sb.nnz) % data_axis)
            sb = sb.replace(
                values=put(sb.values, vec),
                col_indices=put(sb.col_indices, vec),
                row_ids=put(sb.row_ids, vec),
                labels=put(sb.labels, vec),
                offsets=put(sb.offsets, vec),
                weights=put(sb.weights, vec),
            )
            if sb.has_ell_view:
                # [n, L] rides the sample axis like a dense feature block
                sb = sb.replace(
                    ell_vals=put(sb.ell_vals, NamedSharding(mesh, P("data", None))),
                    ell_cols=put(sb.ell_cols, NamedSharding(mesh, P("data", None))),
                )
            if sb.has_hybrid_view:
                # the dense hot head [n, k_hot] rides the sample axis too;
                # the k_hot global column ids are model-sized and replicate
                sb = sb.replace(
                    hot_vals=put(sb.hot_vals, NamedSharding(mesh, P("data", None))),
                    hot_col_ids=put(sb.hot_col_ids, NamedSharding(mesh, P())),
                )
            if sb.has_column_sorted_view:
                sb = sb.replace(
                    vals_by_col=put(sb.vals_by_col, vec),
                    rows_by_col=put(sb.rows_by_col, vec),
                    cols_sorted=put(sb.cols_sorted, vec),
                )
                if sb.col_bounds is not None:
                    # [dim+1] run boundaries ride with the coefficient
                    # vector's layout (replicated; model-sharding of giant d
                    # splits the batch by columns before it gets here)
                    sb = sb.replace(
                        col_bounds=put(sb.col_bounds, NamedSharding(mesh, P()))
                    )
            data["fe_sparse_batch"] = sb
        if "re_sparse" in data:
            # compact RE entry mappings: nnz axis over "data"; pads carry
            # value 0 + the last row id (keeps the row segment-sum's sorted
            # promise) + entity 0 (their zero values contribute nothing)
            placed = {}
            for k, sp in data["re_sparse"].items():
                nnz = int(sp["vals"].shape[0])
                pad = (-nnz) % data_axis
                if pad:
                    last_row = (
                        sp["rows"][-1:] if nnz else jnp.zeros(1, jnp.int32)
                    )
                    sp = {
                        "ent": jnp.pad(sp["ent"], (0, pad)),
                        "pos": jnp.pad(sp["pos"], (0, pad)),
                        "rows": jnp.concatenate(
                            [sp["rows"], jnp.broadcast_to(last_row, (pad,))]
                        ),
                        "vals": jnp.pad(sp["vals"], (0, pad)),
                    }
                placed[k] = {n_: put(v, vec) for n_, v in sp.items()}
            data["re_sparse"] = placed
        return data

    def shard_inputs(self, mesh: Mesh, data, buckets, state,
                     *, fe_feature_sharded: bool = False, put_fn=None):
        """Lay out inputs over the mesh: samples and entities over "data",
        FE features (and coefficient vector) over "model" when requested.

        put_fn: placement function (array, sharding) -> Array. Defaults to
        jax.device_put; pass parallel.multihost.global_put when the mesh
        spans multiple processes (each feeds its addressable shards)."""
        put = put_fn if put_fn is not None else jax.device_put
        rep = NamedSharding(mesh, P())
        data_axis = int(mesh.shape["data"])
        data = self._shard_data(
            mesh, data, fe_feature_sharded=fe_feature_sharded, put_fn=put_fn
        )

        ent3 = NamedSharding(mesh, P("data", None, None))
        ent2 = NamedSharding(mesh, P("data", None))
        ent1 = NamedSharding(mesh, P("data"))

        def put_bucket(b: dict) -> dict:
            # Pad the entity axis to a multiple of the mesh "data" axis.
            # Padding lanes carry weight 0 and an out-of-range entity row:
            # JAX clamps out-of-bounds gathers (warm-start reads are junk but
            # harmless) and DROPS out-of-bounds scatter updates, so padded
            # lanes never write into the coefficient tables.
            e = int(b["entity_rows"].shape[0])
            pad = (-e) % data_axis
            if pad:
                b = dict(b)
                b["labels"] = jnp.pad(b["labels"], ((0, pad), (0, 0)))
                b["weights"] = jnp.pad(b["weights"], ((0, pad), (0, 0)))
                b["sample_rows"] = jnp.pad(
                    b["sample_rows"], ((0, pad), (0, 0)), constant_values=-1
                )
                b["entity_rows"] = jnp.pad(
                    b["entity_rows"], (0, pad),
                    constant_values=jnp.iinfo(jnp.int32).max,
                )
                if "features" in b:
                    b["features"] = jnp.pad(
                        b["features"], ((0, pad), (0, 0), (0, 0))
                    )
                if "col_index" in b:
                    # padded lanes' entity_rows are OOB, so the whole 2-D
                    # scatter row drops regardless of these column values
                    b["col_index"] = jnp.pad(b["col_index"], ((0, pad), (0, 0)))
            out = {
                "labels": put(b["labels"], ent2),
                "weights": put(b["weights"], ent2),
                "sample_rows": put(b["sample_rows"], ent2),
                "entity_rows": put(b["entity_rows"], ent1),
            }
            if "features" in b:
                out["features"] = put(b["features"], ent3)
            if "col_index" in b:
                out["col_index"] = put(b["col_index"], ent2)
            return out

        sharded_buckets: dict = {
            k: [put_bucket(b) for b in bs]
            for k, bs in buckets.items()
            if k not in ("__mf__", "__projections__")
        }
        if "__projections__" in buckets:
            sharded_buckets["__projections__"] = {
                k: put(v, rep)
                for k, v in buckets["__projections__"].items()
            }
        if "__mf__" in buckets:
            sharded_buckets["__mf__"] = {
                name: {
                    side: [put_bucket(b) for b in side_buckets]
                    for side, side_buckets in sides.items()
                }
                for name, sides in buckets["__mf__"].items()
            }
        def put_table(v):
            # entity axis padded to a mesh multiple; padded rows are never
            # read (entity indices stay < E) nor written (scatter targets
            # are real rows), and are sliced off again on exit
            pad = (-int(v.shape[0])) % data_axis
            if pad:
                v = jnp.pad(v, ((0, pad), (0, 0)))
            return put(v, ent2)

        fe_sharding = NamedSharding(mesh, P("model")) if fe_feature_sharded else rep
        state = GameTrainState(
            fe_coefficients=put(state.fe_coefficients, fe_sharding),
            re_tables={k: put_table(v) for k, v in state.re_tables.items()},
            mf_rows={k: put_table(v) for k, v in state.mf_rows.items()},
            mf_cols={k: put_table(v) for k, v in state.mf_cols.items()},
            # extra FE vectors replicate (only the primary may feature-shard)
            extra_fe={k: put(v, rep) for k, v in state.extra_fe.items()},
        )
        return data, sharded_buckets, state

    # -- the fused step ------------------------------------------------------

    def step(self, data, buckets, state: GameTrainState):
        """One full CD sweep. Returns (new_state, training_loss)."""
        return self._step(data, buckets, state)

    def _weighted_loss(self, labels, weights, total_margin):
        losses = self._loss.loss(total_margin, labels)
        wsum = jnp.maximum(jnp.sum(weights), 1.0)
        return jnp.sum(weights * losses) / wsum

    def _sum_scores(self, base, scores, skip=None):
        """base + every coordinate score except ``skip`` — the residual-
        offset sum of the CD recursion, as its own jittable piece for the
        scheduled sweep."""
        total = base
        for k, v in scores.items():
            if k != skip:
                total = total + v
        return total

    def _scheduled_jits(self):
        """Per-coordinate jitted pieces of the sweep, for step_scheduled:
        the scheduler needs host control between the probe and rescue
        solves, so the one-jit sweep is traded for a handful of cached
        per-coordinate programs (compiled once, reused every sweep)."""
        jits = getattr(self, "_sched_jits", None)
        if jits is None:
            jits = {
                "scores": ledger_jit(self._coordinate_scores,
                                     label="train/sched_scores"),
                "fe_solve": ledger_jit(self._solve_primary_fe,
                                       label="train/sched_fe_solve"),
                "fe_margin": ledger_jit(self._fe_margin_score,
                                        label="train/sched_fe_margin"),
                "extra_fe_solve": ledger_jit(
                    self._solve_extra_fe, label="train/sched_extra_fe_solve",
                    static_argnums=(1,)
                ),
                "extra_fe_margin": ledger_jit(
                    self._extra_fe_margin,
                    label="train/sched_extra_fe_margin", static_argnums=(1,)
                ),
                "re_solve": ledger_jit(self._solve_re,
                                       label="train/sched_re_solve",
                                       static_argnums=(2,)),
                "re_score": ledger_jit(
                    self._re_coordinate_score, label="train/sched_re_score",
                    static_argnums=(1, 3)
                ),
                "mf_solve": ledger_jit(self._solve_mf,
                                       label="train/sched_mf_solve",
                                       static_argnums=(2,)),
                "offsets": ledger_jit(self._sum_scores,
                                      label="train/sched_offsets",
                                      static_argnums=(2,)),
                "loss": ledger_jit(self._weighted_loss,
                                   label="train/sched_loss"),
            }
            self._sched_jits = jits
        return jits

    def step_scheduled(self, data, buckets, state: GameTrainState, *,
                       schedulers: Mapping[str, object],
                       final_sweep: bool = True):
        """One full CD sweep with probe/rescue lane scheduling on the
        random-effect coordinates (algorithm/lane_scheduler.py).

        Same Gauss-Seidel recursion as :meth:`step` in the same
        ``update_order``, but host-driven: each coordinate runs as its own
        cached jitted program so the scheduler can read per-lane converged
        flags between the probe and rescue solves and compact only the
        unconverged lanes. Strictly opt-in — ``train_distributed`` uses it
        only when an RE spec's OptimizerConfig carries a scheduler config.
        Multi-process runs use schedulers built with the training mesh
        (``make_schedulers``): rank-local compaction into a fixed
        [num_ranks * R] rescue-block signature, collectives on every rank.

        schedulers: re_type -> LaneScheduler, persisted across sweeps by
        the caller (bucket host caches + cross-sweep active sets live
        there). REs absent from the mapping solve unscheduled.
        """
        jits = self._scheduled_jits()
        scores = dict(jits["scores"](data, state))
        labels, weights = data["labels"], data["weights"]
        base = data["offsets"]
        fe_w = state.fe_coefficients
        extra_fe = dict(state.extra_fe)
        tables = dict(state.re_tables)
        mf_rows = dict(state.mf_rows)
        mf_cols = dict(state.mf_cols)
        for name in self.update_order:
            kind = self._kind[name]
            off = jits["offsets"](base, scores, name)
            if kind == "fe":
                fe_w = jits["fe_solve"](data, off, weights, fe_w)
                scores[name] = jits["fe_margin"](data, fe_w)
            elif kind == "extra_fe":
                extra_fe[name] = jits["extra_fe_solve"](
                    data, name, off, labels, weights, extra_fe[name]
                )
                scores[name] = jits["extra_fe_margin"](data, name, extra_fe[name])
            elif kind == "re":
                spec = self._re_by_name[name]
                scheduler = schedulers.get(name)
                if scheduler is None:
                    tables[name] = jits["re_solve"](
                        data, buckets, name, off, tables[name]
                    )
                else:
                    matrix = buckets.get("__projections__", {}).get(name)
                    tables[name], _traces, _stats = scheduler.solve(
                        self._re_solve_objectives[name], spec.optimizer,
                        buckets[name], off, tables[name],
                        projector=spec.projector, matrix=matrix,
                        final_sweep=final_sweep,
                    )
                scores[name] = jits["re_score"](
                    data, name, tables[name], spec.feature_shard_id
                )
            else:  # mf
                mf_rows[name], mf_cols[name], scores[name] = jits["mf_solve"](
                    data, buckets, name, off, mf_rows[name], mf_cols[name]
                )
        total = jits["offsets"](base, scores, None)
        loss = jits["loss"](labels, weights, total)
        new_state = GameTrainState(
            fe_coefficients=fe_w, re_tables=tables,
            mf_rows=mf_rows, mf_cols=mf_cols, extra_fe=extra_fe,
        )
        return new_state, loss

    # -- whole-model scoring (validation / best-model tracking) --------------

    def prepare_scoring_inputs(
        self, dataset: GameDataset,
        re_datasets: Mapping[str, RandomEffectDataset] | None = None,
    ) -> dict:
        """Data pytree for :meth:`score` over an arbitrary dataset (e.g. the
        validation split) — same layout the training step consumes, no
        entity buckets needed. Compact (sparse-shard) RE coordinates need
        ``re_datasets`` (the TRAINING datasets: their active-column lists
        define the table layout being scored)."""
        data = _data_pytree(
            dataset, self.re_specs, self.fe.feature_shard_id, self.mf_specs,
            extra_fe_shards=tuple(self._extra_fe_by_name),
        )
        return self._attach_re_sparse(data, dataset, re_datasets or {})

    def shard_scoring_inputs(self, mesh: Mesh, data, *,
                             fe_feature_sharded: bool = False, put_fn=None):
        return self._shard_data(
            mesh, data, fe_feature_sharded=fe_feature_sharded, put_fn=put_fn
        )

    def score(self, data, state: GameTrainState) -> Array:
        """[n] total model scores (margins INCLUDING the data offsets) at
        ``state`` — the validation-scoring analogue of the reference's
        per-update ``GameModel.scoreAndValidate``
        (CoordinateDescent.scala:291-356), as one jitted SPMD program over
        the same mesh shardings as the training step."""
        return self._score(data, state)

    def _score_impl(self, data, state: GameTrainState) -> Array:
        total = data["offsets"]
        for v in self._coordinate_scores(data, state).values():
            total = total + v
        return total

    # -- scoring helpers shared by the step and the post-hoc variance path --

    def _re_coordinate_score(self, data, k: str, table: Array,
                             shard_id: str) -> Array:
        """Tables hold normalized-space coefficients when the coordinate is
        normalized; score through the full effective-coefficient algebra
        (factor scaling, and the per-entity margin-shift term for
        standardized coordinates)."""
        sp = data.get("re_sparse", {}).get(k)
        if sp is not None:
            # compact [E, K] table over per-entity active columns; when the
            # coordinate is SCALE-normalized, both the table and the entry
            # values (scaled in _attach_re_sparse) live in normalized space
            # — their product is the data-space margin, no shift term
            from photon_ml_tpu.models.game import score_random_effect_compact

            return score_random_effect_compact(
                table, sp["ent"], sp["pos"], sp["rows"], sp["vals"],
                data["labels"].shape[0],
            )
        norm = self._re_objectives[k].normalization
        eff = norm.effective_coefficients(table)
        scores = score_random_effect(
            eff, data["features"][shard_id], data["entity_idx"][k]
        )
        if norm.shifts is not None:
            # per-entity margin-shift scalar: (w_e ⊙ f) · shifts
            idx = data["entity_idx"][k]
            ent_shift = eff @ norm.shifts
            scores = scores - jnp.where(
                idx >= 0, ent_shift[jnp.maximum(idx, 0)], 0.0
            )
        return scores

    def _fe_margin_score(self, data, fe_w: Array) -> Array:
        """The FE coordinate's pure margin (no offsets) from normalized-space
        coefficients, dense or flat-COO."""
        fe_sparse = data.get("fe_sparse_batch")
        objective = (
            self._fe_sparse_objective if fe_sparse is not None
            else self._fe_objective
        )
        norm = objective.normalization
        eff = norm.effective_coefficients(fe_w)
        if fe_sparse is not None:
            # fe_sparse keeps its zero offsets, so this is the pure margin
            return sparse_margins(fe_sparse, eff) - norm.margin_shift(eff)
        return (
            data["features"][self.fe.feature_shard_id] @ eff
            - norm.margin_shift(eff)
        )

    def _extra_fe_margin(self, data, shard_id: str, w: Array) -> Array:
        """Pure margin of a non-primary (dense, replicated) FE coordinate."""
        norm = self._extra_fe_objectives[shard_id].normalization
        eff = norm.effective_coefficients(w)
        return data["features"][shard_id] @ eff - norm.margin_shift(eff)

    def _coordinate_scores(self, data, state: GameTrainState) -> dict[str, Array]:
        """name -> score of EVERY coordinate at the state (primary FE
        margin, extra FE margins, RE scores, MF scores) — the residual
        terms of the CD recursion, in canonical name order (FEs, REs, MFs)
        so residual sums accumulate in a deterministic order."""
        scores = {
            self.fe.feature_shard_id:
                self._fe_margin_score(data, state.fe_coefficients)
        }
        for s in self.extra_fes:
            scores[s.feature_shard_id] = self._extra_fe_margin(
                data, s.feature_shard_id, state.extra_fe[s.feature_shard_id]
            )
        for s in self.re_specs:
            scores[s.re_type] = self._re_coordinate_score(
                data, s.re_type, state.re_tables[s.re_type], s.feature_shard_id
            )
        for m in self.mf_specs:
            scores[m.name] = score_matrix_factorization(
                state.mf_rows[m.name],
                state.mf_cols[m.name],
                data["entity_idx"][m.row_effect_type],
                data["entity_idx"][m.col_effect_type],
            )
        return scores

    def _step_impl(self, data, buckets, state: GameTrainState):
        labels, weights = data["labels"], data["weights"]
        base_offsets = data["offsets"]

        # Gauss-Seidel recursion over self.update_order: `scores` always
        # holds each coordinate's score at its LATEST coefficients, so a
        # coordinate solved later in the sweep sees the residuals of the
        # ones already updated (reference CoordinateDescent.scala:198-255 —
        # the configured order is semantic, not cosmetic).
        scores = self._coordinate_scores(data, state)

        def offsets_excluding(skip=None):
            return self._sum_scores(base_offsets, scores, skip)

        fe_w = state.fe_coefficients
        extra_fe = dict(state.extra_fe)
        tables = dict(state.re_tables)
        mf_rows = dict(state.mf_rows)
        mf_cols = dict(state.mf_cols)

        for name in self.update_order:
            kind = self._kind[name]
            if kind == "fe":
                fe_w = self._solve_primary_fe(
                    data, offsets_excluding(name), weights, fe_w
                )
                scores[name] = self._fe_margin_score(data, fe_w)
            elif kind == "extra_fe":
                extra_fe[name] = self._solve_extra_fe(
                    data, name, offsets_excluding(name), labels, weights,
                    extra_fe[name],
                )
                scores[name] = self._extra_fe_margin(data, name, extra_fe[name])
            elif kind == "re":
                tables[name] = self._solve_re(
                    data, buckets, name, offsets_excluding(name), tables[name]
                )
                scores[name] = self._re_coordinate_score(
                    data, name, tables[name],
                    self._re_by_name[name].feature_shard_id,
                )
            else:  # mf
                mf_rows[name], mf_cols[name], scores[name] = self._solve_mf(
                    data, buckets, name, offsets_excluding(name),
                    mf_rows[name], mf_cols[name],
                )

        total_margin = offsets_excluding()
        train_loss = self._weighted_loss(labels, weights, total_margin)
        new_state = GameTrainState(
            fe_coefficients=fe_w, re_tables=tables,
            mf_rows=mf_rows, mf_cols=mf_cols, extra_fe=extra_fe,
        )
        return new_state, train_loss

    def _solve_primary_fe(self, data, fe_offsets, weights, fe_w0):
        """Primary fixed-effect solve (samples sharded; grads psum over the
        mesh; the only coordinate that may be sparse / feature-sharded).

        Optional down-sampling trains the FE solve on multiplied weights
        (0 = dropped, 1/rate = kept negative); every other use of
        ``weights`` — other solves, the training loss — stays full-sample.
        The returned vector lives in normalized space (warm starts stay
        there across steps); callers score through the same effective-
        coefficient algebra the objective uses, so residuals stay in data
        space.
        """
        fe_sparse = data.get("fe_sparse_batch")
        fe_mult = data.get("fe_weight_multiplier")
        fe_weights = weights if fe_mult is None else weights * fe_mult
        if fe_sparse is not None:
            fe_batch = fe_sparse.replace(offsets=fe_offsets, weights=fe_weights)
            fe_objective = self._fe_sparse_objective
        else:
            fe_batch = LabeledPointBatch(
                features=data["features"][self.fe.feature_shard_id],
                labels=data["labels"],
                offsets=fe_offsets,
                weights=fe_weights,
            )
            # multi-device mesh: per-device single-pass kernel + psum
            # (parallel/sharded_dense.py) instead of the GSPMD autodiff path
            fe_objective = (
                self._fe_sharded_objective
                if self._fe_sharded_objective is not None
                else self._fe_objective
            )
        return solve(
            self.fe.optimizer, fe_objective.bind(fe_batch), fe_w0
        ).coefficients

    def _solve_extra_fe(self, data, name, full_offsets, labels, weights, w0):
        """A non-primary FE coordinate: dense replicated solve, same
        residual + down-sampling contract as the primary."""
        mult = data.get("extra_fe_weight_multipliers", {}).get(name)
        fe_weights = weights if mult is None else weights * mult
        batch = LabeledPointBatch(
            features=data["features"][name],
            labels=labels,
            offsets=full_offsets,
            weights=fe_weights,
        )
        spec = self._extra_fe_by_name[name]
        return solve(
            spec.optimizer, self._extra_fe_objectives[name].bind(batch), w0
        ).coefficients

    def _solve_re(self, data, buckets, k, full_offsets, table):
        """One random-effect coordinate (entities sharded, vmapped solves)."""
        spec = self._re_by_name[k]
        objective = self._re_solve_objectives[k]
        if spec.projector == ProjectorType.INDEX_MAP:
            # scratch-column solve in each entity's observed columns
            # (ports algorithm/coordinates.py's single-chip path into
            # the SPMD program; IndexMapProjectorRDD.scala:218-257)
            table_ext = jnp.concatenate(
                [table, jnp.zeros((table.shape[0], 1), table.dtype)],
                axis=1,
            )
            for b in buckets[k]:
                table_ext = solve_entity_bucket_indexmap(
                    objective, spec.optimizer,
                    b["features"], b["labels"], b["weights"],
                    b["sample_rows"], b["entity_rows"], b["col_index"],
                    full_offsets, table_ext,
                )
            return table_ext[:, :-1]
        if spec.projector == ProjectorType.RANDOM:
            matrix = buckets["__projections__"][k]
            for b in buckets[k]:
                table = solve_entity_bucket_random(
                    objective, spec.optimizer,
                    b["features"], b["labels"], b["weights"],
                    b["sample_rows"], b["entity_rows"], matrix,
                    full_offsets, table,
                )
            return table
        for b in buckets[k]:
            table = solve_entity_bucket(
                objective,
                spec.optimizer,
                b["features"],
                b["labels"],
                b["weights"],
                b["sample_rows"],
                b["entity_rows"],
                full_offsets,
                table,
            )
        return table

    def _solve_mf(self, data, buckets, name, full_offsets, rows, cols):
        """One matrix-factorization coordinate (alternating vmapped solves).
        Returns (rows, cols, score)."""
        m = self._mf_by_name[name]
        row_idx = data["entity_idx"][m.row_effect_type]
        col_idx = data["entity_idx"][m.col_effect_type]
        objective = self._mf_objectives[name]
        mf_buckets = buckets["__mf__"][name]
        for _ in range(m.num_alternations):
            for b in mf_buckets["row"]:
                rows = solve_mf_side_bucket(
                    objective, m.optimizer, b["labels"], b["weights"],
                    b["entity_rows"], b["sample_rows"], col_idx, cols,
                    full_offsets, rows,
                )
            for b in mf_buckets["col"]:
                cols = solve_mf_side_bucket(
                    objective, m.optimizer, b["labels"], b["weights"],
                    b["entity_rows"], b["sample_rows"], row_idx, rows,
                    full_offsets, cols,
                )
        return rows, cols, score_matrix_factorization(
            rows, cols, row_idx, col_idx
        )


def compute_state_variances(
    program: GameTrainProgram,
    state: GameTrainState,
    dataset: GameDataset,
    re_datasets: Mapping[str, RandomEffectDataset] | None = None,
    *,
    variance_mode: str = "auto",
    re_types: "set[str] | None" = None,
) -> tuple[Array, dict[str, Array]]:
    """Post-hoc coefficient variances for a fused-trained state.

    ``re_types`` selects which random-effect coordinates get variances
    (None = all) — only SELECTED coordinates must satisfy the
    no-projection rule, matching the CD path's per-coordinate
    compute_variance semantics.

    The reference computes variances inside each optimization problem at
    the optimum (DistributedOptimizationProblem.computeVariances for the
    FE, SingleNodeOptimizationProblem for each entity); the fused step
    skips them (they are pure output, not part of the training recursion).
    This recomputes each coordinate's residual offsets from the final
    state — the same Hessians the reference evaluates — and returns
    (fe_variances, {re_type: [E, d] variance table},
    {extra_fe_shard: [d] variances}), all mapped to original model space.
    NaN rows mark entities no bucket trained.

    Requires ``re_datasets`` when the program has RE coordinates (their
    buckets carry the per-entity training views). Projected coordinates are
    fully supported, matching the CD path: INDEX_MAP/compact variances are
    computed in the solve space and scattered back through the entity index
    maps; RANDOM variances are propagated through the sketch as
    diag(P H_k⁻¹ Pᵀ).
    """
    from photon_ml_tpu.algorithm.coordinates import (
        _jitted_re_bucket_variances,
        _jitted_re_bucket_variances_diagonal,
        _jitted_re_bucket_variances_indexmap,
        _jitted_re_bucket_variances_indexmap_diagonal,
        _jitted_re_bucket_variances_random,
        _jitted_re_bucket_variances_random_diagonal,
    )
    from photon_ml_tpu.ops.variance import (
        coefficient_variances,
        resolve_variance_mode,
        validate_variance_mode,
    )

    # fail configuration errors BEFORE any device work (CD-path convention)
    validate_variance_mode(variance_mode)
    selected = [
        s for s in program.re_specs
        if re_types is None or s.re_type in re_types
    ]
    if program.re_specs:
        missing = [
            s.re_type for s in program.re_specs
            if re_datasets is None or s.re_type not in re_datasets
        ]
        if missing:
            raise ValueError(
                "compute_state_variances needs re_datasets entries for the "
                f"program's random-effect coordinates; missing: {missing}"
            )

    data = _data_pytree(
        dataset, program.re_specs, program.fe.feature_shard_id, program.mf_specs,
        extra_fe_shards=tuple(program._extra_fe_by_name),
    )
    # compact RE coordinates score through their entry mappings even here
    # (their scores are residual offsets for the other coordinates' Hessians)
    data = program._attach_re_sparse(data, dataset, re_datasets or {})
    base_offsets = data["offsets"]
    labels, weights = data["labels"], data["weights"]
    fe_sparse = data.get("fe_sparse_batch")

    # the exact residual-offset algebra of the fused step, via its own
    # scoring helpers (one definition for both the recursion and this path);
    # includes every FE coordinate's margin
    scores = program._coordinate_scores(data, state)

    def offsets_excluding(skip=None):
        return program._sum_scores(base_offsets, scores, skip)

    # fixed effects: Hessian at the final coefficients with every other
    # coordinate's score as residual offset
    fe_offsets = offsets_excluding(program.fe.feature_shard_id)
    if fe_sparse is not None:
        fe_batch = fe_sparse.replace(offsets=fe_offsets)
        fe_objective = program._fe_sparse_objective
    else:
        fe_batch = LabeledPointBatch(
            features=data["features"][program.fe.feature_shard_id],
            labels=labels, offsets=fe_offsets, weights=weights,
        )
        fe_objective = program._fe_objective
    fe_variances = fe_objective.normalization.variances_to_model_space(
        coefficient_variances(
            fe_objective, state.fe_coefficients, fe_batch, mode=variance_mode
        )
    )
    extra_fe_variances: dict[str, Array] = {}
    for s in program.extra_fes:
        k = s.feature_shard_id
        objective = program._extra_fe_objectives[k]
        batch = LabeledPointBatch(
            features=data["features"][k], labels=labels,
            offsets=offsets_excluding(k), weights=weights,
        )
        extra_fe_variances[k] = objective.normalization.variances_to_model_space(
            coefficient_variances(
                objective, state.extra_fe[k], batch, mode=variance_mode
            )
        )

    re_variances: dict[str, Array] = {}
    for spec in selected:
        ds = re_datasets[spec.re_type]
        table = state.re_tables[spec.re_type]
        full_offsets = offsets_excluding(skip=spec.re_type)
        max_bucket = max((b.entity_rows.shape[0] for b in ds.buckets), default=1)
        norm = program._re_objectives[spec.re_type].normalization
        if spec.projector == ProjectorType.RANDOM:
            # propagated through the sketch: var(w) = diag(P H_k⁻¹ Pᵀ) — an
            # improvement over the reference, which passes the k-dim
            # projected variances through unchanged
            # (ProjectionMatrixBroadcast.scala:76)
            from photon_ml_tpu.algorithm.coordinates import (
                random_variance_mode,
            )

            # PLAIN solve objective: features/coefficients are k-dim
            # sketch-space (and pre-normalized at build when a context
            # exists) — the d-length context must not touch them
            objective = program._re_solve_objectives[spec.re_type]
            resolved = random_variance_mode(
                variance_mode, ds.dim, int(ds.projection.matrix.shape[1]),
                max_bucket,
            )
            kernel = (
                _jitted_re_bucket_variances_random if resolved == "full"
                else _jitted_re_bucket_variances_random_diagonal
            )
            matrix = jnp.asarray(ds.projection.matrix, dtype=table.dtype)
            var_table = jnp.full_like(table, jnp.nan)
            for b in ds.buckets:
                var_table = kernel(
                    objective, b.features, b.labels, b.weights,
                    b.sample_rows, b.entity_rows, matrix,
                    full_offsets, table, var_table,
                )
        elif spec.projector == ProjectorType.INDEX_MAP:
            # solve-space diag(H⁻¹) scattered back through the entity index
            # maps (IndexMapProjectorRDD.scala:103); serves dense INDEX_MAP
            # and compact (sparse-shard) coordinates alike — col_index holds
            # original columns (pad=dim) resp. local positions (pad=K)
            objective = program._re_solve_objectives[spec.re_type]
            width = max(
                (int(b.features.shape[2]) for b in ds.buckets), default=1
            )
            resolved = resolve_variance_mode(variance_mode, width,
                                             num_problems=max_bucket)
            kernel = (
                _jitted_re_bucket_variances_indexmap if resolved == "full"
                else _jitted_re_bucket_variances_indexmap_diagonal
            )
            table_ext = jnp.concatenate(
                [table, jnp.zeros((table.shape[0], 1), table.dtype)], axis=1
            )
            var_ext = jnp.full_like(table_ext, jnp.nan)
            for b in ds.buckets:
                var_ext = kernel(
                    objective, b.features, b.labels, b.weights,
                    b.sample_rows, b.entity_rows, b.col_index,
                    full_offsets, table_ext, var_ext,
                )
            var_table = var_ext[:, :-1]
            if ds.is_compact and norm.factors is not None:
                re_variances[spec.re_type] = (
                    norm.variances_to_model_space_compact(
                        var_table, jnp.asarray(ds.active_cols)
                    )
                )
                continue
        else:
            objective = program._re_objectives[spec.re_type]
            resolved = resolve_variance_mode(variance_mode, ds.dim,
                                             num_problems=max_bucket)
            kernel = (
                _jitted_re_bucket_variances if resolved == "full"
                else _jitted_re_bucket_variances_diagonal
            )
            var_table = jnp.full_like(table, jnp.nan)
            for b in ds.buckets:
                var_table = kernel(
                    objective, b.features, b.labels, b.weights,
                    b.sample_rows, b.entity_rows, full_offsets, table,
                    var_table,
                )
        re_variances[spec.re_type] = (
            norm.variances_to_model_space(var_table)
        )
    return fe_variances, re_variances, extra_fe_variances


def state_to_game_model(
    program: GameTrainProgram,
    state: GameTrainState,
    dataset: GameDataset,
    *,
    intercept_index: int | None = None,
    compute_variance: bool = False,
    variance_mode: str = "auto",
    re_datasets: Mapping[str, RandomEffectDataset] | None = None,
    variance_re_types: "set[str] | None" = None,
):
    """Convert a fused-step ``GameTrainState`` into a ``GameModel`` so
    multi-chip-trained models flow into the standard persistence/scoring
    stack (io/model_io.save_game_model, transformers.GameTransformer).

    Coordinate ids: the FE coordinate is named after its feature shard; RE
    coordinates after their RE type; MF coordinates after their spec name.
    The FE vector is converted back to original feature space (warm starts
    live in normalized space inside the step).

    compute_variance=True attaches post-hoc diag(H⁻¹)-style variances from
    :func:`compute_state_variances` (pass ``re_datasets`` for RE
    coordinates).
    """
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.models.game import (
        FixedEffectModel,
        GameModel,
        RandomEffectModel,
    )
    from photon_ml_tpu.models.glm import GeneralizedLinearModel
    from photon_ml_tpu.models.matrix_factorization import (
        MatrixFactorizationModel,
    )

    fe_variances = None
    re_variances: dict[str, Array] = {}
    extra_fe_variances: dict[str, Array] = {}
    if compute_variance:
        fe_variances, re_variances, extra_fe_variances = compute_state_variances(
            program, state, dataset, re_datasets, variance_mode=variance_mode,
            re_types=variance_re_types,
        )

    models: dict[str, object] = {}
    fe_means = program.fe_coefficients_model_space(state, intercept_index)
    models[program.fe.feature_shard_id] = FixedEffectModel(
        glm=GeneralizedLinearModel(
            Coefficients(means=fe_means, variances=fe_variances), program.task
        ),
        feature_shard_id=program.fe.feature_shard_id,
    )
    for s in program.extra_fes:
        k = s.feature_shard_id
        norm = program._extra_fe_objectives[k].normalization
        models[k] = FixedEffectModel(
            glm=GeneralizedLinearModel(
                Coefficients(
                    means=norm.to_model_space(
                        state.extra_fe[k], s.intercept_index
                    ),
                    variances=extra_fe_variances.get(k),
                ),
                program.task,
            ),
            feature_shard_id=k,
        )
    for spec in program.re_specs:
        # normalized coordinates hold normalized-space tables in the state;
        # models are always persisted in original space (factors only, so
        # no intercept index is needed)
        re_norm = program._re_objectives[spec.re_type].normalization
        ds = (re_datasets or {}).get(spec.re_type)
        is_compact = ds is not None and ds.active_cols is not None
        if isinstance(
            dataset.feature_shards[spec.feature_shard_id], SparseShard
        ) and not is_compact:
            raise ValueError(
                f"random-effect coordinate '{spec.re_type}' trained on a "
                "sparse shard; pass its RandomEffectDataset via re_datasets "
                "so the compact model keeps its active-column lists"
            )
        models[spec.re_type] = RandomEffectModel(
            coefficients=(
                re_norm.to_model_space_compact(
                    state.re_tables[spec.re_type],
                    jnp.asarray(ds.active_cols),
                )
                if is_compact
                else re_norm.to_model_space(
                    state.re_tables[spec.re_type], spec.intercept_index
                )
            ),
            entity_keys=dataset.entity_vocabs[spec.re_type],
            random_effect_type=spec.re_type,
            feature_shard_id=spec.feature_shard_id,
            task=program.task,
            variances=re_variances.get(spec.re_type),
            active_cols=ds.active_cols if is_compact else None,
            feature_dim=ds.dim if is_compact else None,
        )
    for m in program.mf_specs:
        models[m.name] = MatrixFactorizationModel(
            row_factors=state.mf_rows[m.name],
            col_factors=state.mf_cols[m.name],
            row_effect_type=m.row_effect_type,
            col_effect_type=m.col_effect_type,
            row_keys=dataset.entity_vocabs[m.row_effect_type],
            col_keys=dataset.entity_vocabs[m.col_effect_type],
            task=program.task,
        )
    return GameModel(models=models)


def _remap_compact_rows(
    values: np.ndarray,
    model_cols: np.ndarray | None,
    target_cols: np.ndarray,
    dim: int,
) -> np.ndarray:
    """Re-key per-entity coefficient rows onto new active-column lists.

    values: [E, Km] compact (with model_cols [E, Km], sorted, pad=dim) or
    [E, dim] dense (model_cols None). target_cols: [E, Kt] sorted pad=dim.
    Returns [E, Kt]; columns absent from the source row are 0.
    """
    from photon_ml_tpu.models.game import match_active_positions

    e, kt = target_cols.shape
    if model_cols is None:  # dense source: plain per-row gather
        safe = np.minimum(target_cols, dim - 1)
        out = values[np.arange(e)[:, None], safe]
        return (out * (target_cols < dim)).astype(values.dtype)
    km = model_cols.shape[1]
    ent = np.repeat(np.arange(e, dtype=np.int64), kt)
    pos = match_active_positions(ent, target_cols.ravel(), model_cols, dim)
    vals_ext = np.concatenate(
        [values, np.zeros((e, 1), values.dtype)], axis=1
    )
    return vals_ext[ent, pos].reshape(e, kt).astype(values.dtype)


def game_model_to_state(
    program: GameTrainProgram,
    model,
    dataset: GameDataset,
    *,
    intercept_index: int | None = None,
    missing_ok: bool = False,
    re_datasets: Mapping[str, RandomEffectDataset] | None = None,
    mf_datasets: Mapping[str, "MFDataset"] | None = None,
) -> GameTrainState:
    """Inverse of :func:`state_to_game_model`: warm-start the fused step from
    a (possibly loaded-from-Avro) GameModel.

    Coefficient tables are re-aligned to the dataset's entity vocabs by key,
    so a model trained/saved against one dataset warm-starts training on
    another whose vocab ordering differs; entities absent from the model
    start at zero. The FE vector is converted into normalized space (the
    step's warm-start convention).

    missing_ok=True cold-starts (zeros / fresh factors) any coordinate the
    model lacks instead of raising — needed when a partial model warm-starts
    a program with more coordinates (reference GameEstimator.getInitialModel
    tolerates absent coordinates the same way). Requires ``re_datasets`` /
    ``mf_datasets`` for the cold-started coordinates' table shapes.
    """
    def coordinate_model(cid: str):
        try:
            return model.get(cid)
        except KeyError:
            if missing_ok:
                return None
            raise

    norm = program._fe_objective.normalization
    fe_model = coordinate_model(program.fe.feature_shard_id)
    if fe_model is None:
        fe_dim = dataset.feature_shards[program.fe.feature_shard_id].shape[1]
        dtype = dataset.feature_shards[program.fe.feature_shard_id].dtype
        fe_w = jnp.zeros((fe_dim,), dtype=dtype)
    else:
        fe_w = norm.from_model_space(
            jnp.asarray(fe_model.glm.coefficients.means), intercept_index
        )
    extra_fe: dict[str, Array] = {}
    for s in program.extra_fes:
        k = s.feature_shard_id
        m = coordinate_model(k)
        if m is None:
            extra_fe[k] = jnp.zeros(
                (dataset.feature_shards[k].shape[1],), dtype=fe_w.dtype
            )
        else:
            extra_fe[k] = program._extra_fe_objectives[k].normalization.from_model_space(
                jnp.asarray(m.glm.coefficients.means), s.intercept_index
            )

    def align(table, model_keys, vocab, coordinate: str) -> Array:
        table = np.asarray(table)
        row_of = {k: i for i, k in enumerate(np.asarray(model_keys).tolist())}
        pairs = [
            (i, row_of[key])
            for i, key in enumerate(np.asarray(vocab).tolist())
            if key in row_of
        ]
        if not pairs and len(row_of) and len(vocab):
            # a warm start that matches nothing is almost certainly the wrong
            # model/vocab pairing — degrade loudly, not to a silent cold start
            raise ValueError(
                f"warm-start model for coordinate '{coordinate}' shares no "
                f"entity keys with the dataset vocab ({len(row_of)} model "
                f"keys vs {len(vocab)} vocab keys) — wrong model directory "
                "or entity namespace?"
            )
        out = np.zeros((len(vocab), table.shape[1]), dtype=table.dtype)
        if pairs:
            vi, mi = (np.asarray(p, dtype=np.intp) for p in zip(*pairs))
            out[vi] = table[mi]
        return jnp.asarray(out)

    re_tables = {}
    for spec in program.re_specs:
        m = coordinate_model(spec.re_type)
        ds = (re_datasets or {}).get(spec.re_type)
        ds_compact = ds is not None and ds.active_cols is not None
        if m is None:
            if ds is None:
                raise ValueError(
                    f"missing_ok warm start: coordinate '{spec.re_type}' is "
                    "absent from the model AND re_datasets — cannot size the "
                    "cold-start table"
                )
            re_tables[spec.re_type] = jnp.zeros(
                (ds.num_entities, ds.table_width), dtype=fe_w.dtype
            )
            continue
        aligned = align(
            m.coefficients, m.entity_keys,
            dataset.entity_vocabs[spec.re_type], spec.re_type,
        )
        model_compact = getattr(m, "active_cols", None) is not None
        if model_compact and not ds_compact:
            # compact model warm-starting a DENSE dataset: expand each
            # entity's active columns into a dense row (the dataset being
            # dense means dim is materializable by definition)
            mc = np.asarray(align(
                m.active_cols, m.entity_keys,
                dataset.entity_vocabs[spec.re_type], spec.re_type,
            )).astype(np.int64)
            vals = np.asarray(aligned)
            e_rows = np.repeat(np.arange(vals.shape[0]), mc.shape[1])
            flat_cols = mc.ravel()
            dim = int(dataset.feature_shards[spec.feature_shard_id].shape[1])
            live = flat_cols < dim
            dense = np.zeros((vals.shape[0], dim), dtype=vals.dtype)
            dense[e_rows[live], flat_cols[live]] = vals.ravel()[live]
            aligned = jnp.asarray(dense)
        elif ds_compact or model_compact:
            # compact-layout warm starts re-key per entity from the model's
            # active columns to the dataset's (a grid re-fit on the same
            # data keeps identical lists; cross-dataset fits remap, columns
            # absent from the new list are dropped, new ones start at 0)
            model_cols = None
            if getattr(m, "active_cols", None) is not None:
                # align the model's column lists to the dataset vocab order
                model_cols = np.asarray(align(
                    m.active_cols, m.entity_keys,
                    dataset.entity_vocabs[spec.re_type], spec.re_type,
                )).astype(np.int64)
                # rows absent from the model aligned to all-zeros — make
                # them all-pads instead so nothing matches
                absent = ~np.isin(
                    np.asarray(dataset.entity_vocabs[spec.re_type]).astype(str),
                    np.asarray(m.entity_keys).astype(str),
                )
                model_cols[absent] = ds.dim
            aligned = jnp.asarray(_remap_compact_rows(
                np.asarray(aligned), model_cols,
                np.asarray(ds.active_cols, dtype=np.int64), ds.dim,
            ))
        re_norm = program._re_objectives[spec.re_type].normalization
        re_tables[spec.re_type] = (
            re_norm.from_model_space_compact(
                aligned, jnp.asarray(ds.active_cols)
            )
            if ds_compact
            else re_norm.from_model_space(aligned, spec.intercept_index)
        )
    mf_rows, mf_cols = {}, {}
    for spec in program.mf_specs:
        m = coordinate_model(spec.name)
        if m is None:
            from photon_ml_tpu.models.matrix_factorization import init_factors

            mf = (mf_datasets or {}).get(spec.name)
            if mf is None:
                raise ValueError(
                    f"missing_ok warm start: MF coordinate '{spec.name}' is "
                    "absent from the model AND mf_datasets — cannot size the "
                    "cold-start factors"
                )
            row, col = init_factors(
                mf.num_row_entities, mf.num_col_entities,
                spec.num_latent_factors, seed=spec.seed, dtype=fe_w.dtype,
            )
            row_mask, col_mask = mf.trained_masks()
            mf_rows[spec.name] = jnp.where(
                jnp.asarray(row_mask)[:, None], row, 0.0
            )
            mf_cols[spec.name] = jnp.where(
                jnp.asarray(col_mask)[:, None], col, 0.0
            )
            continue
        model_k = np.asarray(m.row_factors).shape[1]
        if model_k != spec.num_latent_factors:
            raise ValueError(
                f"warm-start MF model for coordinate '{spec.name}' has "
                f"latent dimension {model_k} but the spec configures "
                f"num_latent_factors={spec.num_latent_factors} — retrain or "
                "match the spec to the saved model"
            )
        mf_rows[spec.name] = align(
            m.row_factors, m.row_keys,
            dataset.entity_vocabs[spec.row_effect_type], spec.name,
        )
        mf_cols[spec.name] = align(
            m.col_factors, m.col_keys,
            dataset.entity_vocabs[spec.col_effect_type], spec.name,
        )
    return GameTrainState(
        fe_coefficients=fe_w, re_tables=re_tables,
        mf_rows=mf_rows, mf_cols=mf_cols, extra_fe=extra_fe,
    )


@dataclasses.dataclass
class DistributedTrainResult:
    """Result of :func:`train_distributed`.

    Iterates as ``(state, losses)`` for backward compatibility with the
    2-tuple this function used to return. ``best_state``/``best_metric``/
    ``metric_history`` are populated when validation evaluators were given
    (reference CoordinateDescent best-model tracking, :183-192, :323-356);
    otherwise ``best_state`` is None and callers should treat the final
    state as best.
    """

    state: GameTrainState
    losses: list[float]
    best_state: GameTrainState | None = None
    best_metric: float = float("nan")
    metric_history: list[dict] = dataclasses.field(default_factory=list)

    def __iter__(self):
        return iter((self.state, self.losses))


def _host_scores(scores: Array, n: int) -> np.ndarray:
    """Gather a (possibly mesh-sharded, possibly multi-process) score vector
    to the host and drop mesh-padding rows."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        scores = multihost_utils.process_allgather(scores, tiled=True)
    return np.asarray(jax.device_get(scores))[:n]


def train_distributed(
    program: GameTrainProgram,
    dataset: GameDataset,
    re_datasets: Mapping[str, RandomEffectDataset],
    *,
    mf_datasets: Mapping[str, "MFDataset"] | None = None,
    mesh: Mesh | None = None,
    num_iterations: int = 1,
    fe_feature_sharded: bool = False,
    state: GameTrainState | None = None,
    checkpointer=None,
    checkpoint_every: int = 1,
    resume: bool = True,
    put_fn=None,
    validation_dataset: GameDataset | None = None,
    validation_evaluators: Sequence = (),
    validation_eval_data=None,
    training_evaluator=None,
    training_eval_data=None,
    down_sampling_seed: int = 0,
    check_finite: bool = True,
    on_sweep=None,
) -> DistributedTrainResult:
    """Run ``num_iterations`` fused CD sweeps, optionally mesh-sharded.

    on_sweep: optional observer ``(sweep_done, num_iterations, loss)``
    called at the end of every sweep (ISSUE 12: the estimator wires the
    journal heartbeat through it). Observe-only — it runs after all of the
    sweep's collectives, on every rank, and must never gate one.

    put_fn: placement function forwarded to ``shard_inputs``. Defaults to
    ``jax.device_put`` single-process and to ``multihost.global_put`` when
    this is a multi-process run (each process feeds its addressable shards),
    so the same call works on a laptop and on a pod.

    checkpointer: optional ``io.checkpoint.TrainingCheckpointer``. Saves the
    full ``GameTrainState`` (host-gathered) every ``checkpoint_every`` sweeps;
    with ``resume=True`` the latest checkpoint short-circuits completed
    sweeps. Restored arrays are re-laid-out over the mesh by the normal
    ``shard_inputs`` path, so a run checkpointed on one topology restores
    onto another (elastic recovery — absent in the reference, SURVEY.md §5).

    Validation (reference CoordinateDescent.scala:183-192, 291-356): when
    ``validation_dataset`` + ``validation_evaluators`` (+
    ``validation_eval_data``, an evaluation.EvaluationData over the
    *unpadded* validation split) are given, each sweep scores the validation
    split through the program's jitted scoring program over the same mesh,
    evaluates every evaluator host-side, and tracks the best state by the
    FIRST evaluator's ``better_than`` direction. ``training_evaluator`` +
    ``training_eval_data`` add a per-sweep ``train:<name>`` metric.

    Datasets whose sample counts don't divide the mesh "data" axis are
    padded with zero-weight rows automatically (pad_game_dataset).

    Returns a :class:`DistributedTrainResult` (unpacks as
    ``(final_state, losses)``).
    """
    start_sweep = 0
    prior_losses: list[float] = []
    best_state: GameTrainState | None = None
    best_metric = float("nan")
    history: list[dict] = []
    # An explicit caller-supplied state takes precedence over resume: passing
    # both a warm start and a stale checkpoint must not silently ignore the
    # warm start.
    if checkpointer is not None and resume and state is None:
        ckpt = checkpointer.restore()
        if ckpt is not None:
            if "fe_coefficients" not in ckpt.arrays:
                # e.g. a CD-path checkpoint (model/... keys) in the same dir
                raise ValueError(
                    f"checkpoint at {checkpointer.directory} is not a "
                    "distributed-training checkpoint (no 'fe_coefficients' "
                    f"array; found keys like {sorted(ckpt.arrays)[:3]}). Pass "
                    "resume=False or use a fresh checkpoint directory."
                )
            def by_prefix(prefix, arrays=None):
                arrays = ckpt.arrays if arrays is None else arrays
                return {
                    k[len(prefix):]: jnp.asarray(v)
                    for k, v in arrays.items()
                    if k.startswith(prefix) and "/" not in k[len(prefix):]
                }
            state = GameTrainState(
                fe_coefficients=jnp.asarray(ckpt.arrays["fe_coefficients"]),
                re_tables=by_prefix("re_tables/"),
                mf_rows=by_prefix("mf_rows/"),
                mf_cols=by_prefix("mf_cols/"),
                extra_fe=by_prefix("extra_fe/"),
            )
            expected = {
                "re_tables": {s.re_type for s in program.re_specs},
                "mf_rows": {m.name for m in program.mf_specs},
                "mf_cols": {m.name for m in program.mf_specs},
                "extra_fe": {s.feature_shard_id for s in program.extra_fes},
            }
            found = {
                "re_tables": set(state.re_tables),
                "mf_rows": set(state.mf_rows),
                "mf_cols": set(state.mf_cols),
                "extra_fe": set(state.extra_fe),
            }
            if expected != found:
                raise ValueError(
                    f"checkpoint at {checkpointer.directory} is incompatible "
                    f"with the program's coordinate specs: checkpoint has "
                    f"{found}, program expects {expected}. Pass resume=False "
                    "or use a fresh checkpoint directory."
                )
            if "best/fe_coefficients" in ckpt.arrays:
                best_state = GameTrainState(
                    fe_coefficients=jnp.asarray(ckpt.arrays["best/fe_coefficients"]),
                    re_tables=by_prefix("best/re_tables/"),
                    mf_rows=by_prefix("best/mf_rows/"),
                    mf_cols=by_prefix("best/mf_cols/"),
                    extra_fe=by_prefix("best/extra_fe/"),
                )
            best_metric = float(ckpt.meta.get("best_metric", float("nan")))
            # journaled restore evidence (resilience/checkpoint_restores)
            from photon_ml_tpu.telemetry import resilience_counters

            resilience_counters.record_checkpoint_restore()
            start_sweep = min(int(ckpt.step), num_iterations)
            prior_losses = [float(x) for x in ckpt.meta.get("losses", [])][:start_sweep]
            history = [
                h for h in ckpt.meta.get("metric_history", [])
                if int(h.get("iteration", 0)) < start_sweep
            ]

    n_train = dataset.num_samples
    n_val = validation_dataset.num_samples if validation_dataset is not None else 0
    if mesh is not None:
        from photon_ml_tpu.data.game_data import pad_game_dataset

        data_axis = int(mesh.shape["data"])
        # buckets reference sample rows by index, which appending zero-weight
        # rows leaves intact — pad AFTER the caller built re_datasets
        dataset, n_train = pad_game_dataset(dataset, data_axis)
        if validation_dataset is not None:
            validation_dataset, n_val = pad_game_dataset(
                validation_dataset, data_axis
            )

    data, buckets = program.prepare_inputs(dataset, re_datasets, mf_datasets)
    if state is None:
        state = program.init_state(dataset, re_datasets, mf_datasets)

    # probe/rescue lane scheduling (algorithm/lane_scheduler.py): opt-in per
    # RE spec via OptimizerConfig.scheduler. Multi-process runs use the
    # collective-safe SPMD mode (rank-local compaction into a fixed
    # [num_ranks * R] rescue-block signature, per-lane flags through tiled
    # allgathers — collectives on every rank); single-process keeps the
    # host mode unchanged. No more multi-process fallback.
    schedulers = None
    scheduled_specs = [
        s for s in program.re_specs if s.optimizer.scheduler is not None
    ]
    if scheduled_specs:
        if jax.process_count() > 1 and mesh is None:
            logger.warning(
                "lane scheduler configured on %s but this multi-process run "
                "has no mesh — falling back to the unscheduled fused step; "
                "pass mesh= (the SPMD scheduler assembles rescue blocks "
                "over it)",
                [s.re_type for s in scheduled_specs],
            )
        else:
            from photon_ml_tpu.algorithm.lane_scheduler import make_schedulers

            schedulers = make_schedulers(scheduled_specs, mesh=mesh)

    # per-sweep FE down-sampling multipliers (stable-id splitmix64, identical
    # to the CD path's FixedEffectCoordinate seed rotation); keyed per FE
    # coordinate ("" = primary)
    samplers: dict[str, object] = {}
    from photon_ml_tpu.sampling import down_sampler_for_task

    for key, fe_spec in [("", program.fe)] + [
        (s.feature_shard_id, s) for s in program.extra_fes
    ]:
        if fe_spec.down_sampling_rate < 1.0:
            samplers[key] = down_sampler_for_task(
                program.task, fe_spec.down_sampling_rate
            )
    if samplers:
        samp_labels = dataset.host_array("labels")
        samp_weights = dataset.host_array("weights")
        samp_uids = np.asarray(dataset.unique_ids)
        samp_dtype = np.asarray(samp_weights).dtype

    def sweep_multiplier(sampler, sweep: int):
        new_w = sampler.down_sample_weights(
            samp_labels, samp_weights, samp_uids,
            seed=down_sampling_seed + sweep,
        )
        mult = np.where(
            samp_weights > 0, new_w / np.where(samp_weights > 0, samp_weights, 1.0), 0.0
        ).astype(samp_dtype)
        if mesh is not None:
            put = put_fn if put_fn is not None else jax.device_put
            return put(jnp.asarray(mult), NamedSharding(mesh, P("data")))
        return jnp.asarray(mult)

    val_data = None
    evaluators = list(validation_evaluators)
    if validation_dataset is not None and evaluators and validation_eval_data is not None:
        val_data = program.prepare_scoring_inputs(
            validation_dataset, re_datasets
        )

    # true entity counts, to slice off any mesh-padding rows on the way out
    table_sizes = {
        "re_tables": {s.re_type: re_datasets[s.re_type].num_entities
                      for s in program.re_specs},
        "mf_rows": {m.name: (mf_datasets or {})[m.name].num_row_entities
                    for m in program.mf_specs},
        "mf_cols": {m.name: (mf_datasets or {})[m.name].num_col_entities
                    for m in program.mf_specs},
    }

    def unpadded(state_: GameTrainState) -> GameTrainState:
        def trim(tables, sizes):
            return {k: v[: sizes[k]] for k, v in tables.items()}
        return GameTrainState(
            fe_coefficients=state_.fe_coefficients,
            re_tables=trim(state_.re_tables, table_sizes["re_tables"]),
            mf_rows=trim(state_.mf_rows, table_sizes["mf_rows"]),
            mf_cols=trim(state_.mf_cols, table_sizes["mf_cols"]),
            extra_fe=dict(state_.extra_fe),
        )
    if mesh is not None:
        if put_fn is None:
            from photon_ml_tpu.parallel.multihost import default_put

            put_fn = default_put()
        data, buckets, state = program.shard_inputs(
            mesh, data, buckets, state, fe_feature_sharded=fe_feature_sharded,
            put_fn=put_fn,
        )
        if val_data is not None:
            val_data = program.shard_scoring_inputs(
                mesh, val_data, fe_feature_sharded=fe_feature_sharded,
                put_fn=put_fn,
            )

    if val_data is not None and mesh is not None:
        # device twins of the evaluators (evaluation/sharded.py): consts
        # (labels/weights/query codes) are padded to the mesh length and
        # placed sharded over "data" alongside the scores they reduce with.
        # Prepared AFTER put_fn resolution so multi-process runs place
        # through global_put like every other sharded input. mesh=None runs
        # keep the exact host evaluators — there is no giant-n funnel to
        # avoid, and the device AUC is a histogram approximation.
        from photon_ml_tpu.evaluation.sharded import (
            mesh_data_placer,
            prepare_device_evaluators,
        )

        device_evals = prepare_device_evaluators(
            evaluators, validation_eval_data,
            n_pad=validation_dataset.num_samples,
            place=mesh_data_placer(mesh, put_fn),
        )
    else:
        device_evals = [None] * len(evaluators)

    def to_host(v):
        """Host copy of a (possibly multi-process sharded) array. The
        allgather is a COLLECTIVE — every process must call it, even those
        that discard the result (rank-0-only writes)."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(v, tiled=True))
        return jax.device_get(v)

    def state_arrays(state_: GameTrainState, prefix: str = "") -> dict:
        clean = unpadded(state_)
        arrays = {prefix + "fe_coefficients": to_host(clean.fe_coefficients)}
        for sub, tables in (
            ("re_tables/", clean.re_tables),
            ("mf_rows/", clean.mf_rows),
            ("mf_cols/", clean.mf_cols),
            ("extra_fe/", clean.extra_fe),
        ):
            for k, v in tables.items():
                arrays[prefix + sub + k] = to_host(v)
        return arrays

    losses = list(prior_losses)
    for sweep in range(start_sweep, num_iterations):
        for key, sampler in samplers.items():
            mult = sweep_multiplier(sampler, sweep)
            if key == "":
                data["fe_weight_multiplier"] = mult
            else:
                data.setdefault("extra_fe_weight_multipliers", {})[key] = mult
        if schedulers is not None:
            state, loss = program.step_scheduled(
                data, buckets, state, schedulers=schedulers,
                final_sweep=(sweep + 1 == num_iterations),
            )
        else:
            state, loss = program.step(data, buckets, state)
        losses.append(float(loss))
        if check_finite and not np.isfinite(losses[-1]):
            # raise BEFORE the checkpoint save below would overwrite the
            # last finite state with NaNs (CD-path DivergenceError contract,
            # coordinate_descent.py)
            from photon_ml_tpu.io.checkpoint import DivergenceError

            raise DivergenceError(
                f"fused training step produced non-finite loss "
                f"{losses[-1]} at sweep {sweep}"
                + (
                    f"; last good checkpoint: step "
                    f"{checkpointer.latest_step()} in {checkpointer.directory}"
                    if checkpointer is not None else ""
                )
            )

        metrics: dict[str, float] = {}
        if training_evaluator is not None and training_eval_data is not None:
            train_scores = _host_scores(program.score(data, state), n_train)
            metrics[f"train:{training_evaluator.name}"] = float(
                training_evaluator.evaluate(train_scores, training_eval_data)
            )
        if val_data is not None:
            # device-side evaluation (evaluation/sharded.py): on a mesh,
            # metrics reduce ON it from the still-sharded score vector;
            # only scalars cross to the host — the giant-n validation pass
            # never funnels [n] rows through one core (the reference's
            # executor-side Evaluator/MultiEvaluator, Evaluator.scala:39-49).
            # Evaluators without a device form (custom types), and every
            # evaluator on mesh=None runs, take the single host gather.
            from photon_ml_tpu.evaluation.sharded import evaluate_prepared

            val_scores = program.score(val_data, state)
            values = evaluate_prepared(
                evaluators, device_evals, val_scores, validation_eval_data,
                lambda: _host_scores(val_scores, n_val),
            )
            for i, (ev, v) in enumerate(zip(evaluators, values)):
                metrics[f"validate:{ev.name}"] = v
                if i == 0 and (
                    best_state is None or ev.better_than(v, best_metric)
                ):
                    best_state, best_metric = state, v
        if metrics:
            history.append({"iteration": sweep, "coordinate": "fused_sweep",
                            **metrics})

        if checkpointer is not None and (
            (sweep + 1) % max(1, checkpoint_every) == 0 or sweep + 1 == num_iterations
        ):
            # every process participates in the gathers (collectives); the
            # commit helper gates the write to process 0 (the shared
            # checkpoint directory convention; lint check 10)
            from photon_ml_tpu.io.checkpoint import commit_checkpoint

            arrays = state_arrays(state)
            if best_state is not None:
                arrays.update(state_arrays(best_state, prefix="best/"))
            commit_checkpoint(
                checkpointer, sweep + 1, arrays,
                {"losses": losses, "metric_history": history,
                 "best_metric": best_metric},
            )

        if on_sweep is not None:
            on_sweep(sweep + 1, num_iterations,
                     losses[-1] if losses else None)

    def result_state(state_: GameTrainState) -> GameTrainState:
        clean = unpadded(state_)
        if jax.process_count() > 1:
            # downstream (model conversion, Avro persistence) materializes
            # host arrays; a multi-process sharded state is not addressable,
            # so hand back fully-gathered host-backed arrays
            clean = GameTrainState(
                fe_coefficients=jnp.asarray(to_host(clean.fe_coefficients)),
                re_tables={k: jnp.asarray(to_host(v))
                           for k, v in clean.re_tables.items()},
                mf_rows={k: jnp.asarray(to_host(v))
                         for k, v in clean.mf_rows.items()},
                mf_cols={k: jnp.asarray(to_host(v))
                         for k, v in clean.mf_cols.items()},
                extra_fe={k: jnp.asarray(to_host(v))
                          for k, v in clean.extra_fe.items()},
            )
        return clean

    return DistributedTrainResult(
        state=result_state(state),
        losses=losses,
        # best == final collapses to None ("treat final as best") so callers
        # never convert/variance-compute the same state twice
        best_state=(
            None if best_state is None or best_state is state
            else result_state(best_state)
        ),
        best_metric=best_metric,
        metric_history=history,
    )


# ---------------------------------------------------------------------------
# Partitioned training: each rank feeds only its local ingest block
# ---------------------------------------------------------------------------


def _partitioned_guards(program: GameTrainProgram, prepared: dict) -> None:
    """The partitioned surface: dense or sparse (incl. hybrid) primary FE,
    dense extra FEs, and IDENTITY random effects. Everything else still
    trains through the full-read path — fail loudly, never silently
    mis-shard."""
    if program.mf_specs:
        raise ValueError(
            "partitioned training does not support matrix-factorization "
            "coordinates; use the full-read path"
        )
    for data, buckets in prepared.values():
        if "re_sparse" in data:
            raise ValueError(
                "partitioned training does not support sparse RANDOM-"
                "EFFECT shards (the primary fixed effect may be sparse); "
                "use the full-read path"
            )
        if "__projections__" in buckets:
            raise ValueError(
                "partitioned training does not support projected random "
                "effects; use the full-read path"
            )


def _assemble_sparse_fe(prepared: dict, ranks, mesh: Mesh,
                        num_ranks: int, put) -> "SparseLabeledPointBatch":
    """Assemble per-rank local sparse-FE batches into ONE mesh-sharded
    global batch (the sparse twin of the dense ``asm`` closure in
    prepare_partitioned_inputs).

    The per-sample arrays (labels/offsets/weights, the [n, L] ELL tail,
    the [n, k_hot] hybrid head) are per-rank ROW blocks and assemble like
    any dense field; the hot column ids are model-sized, must be IDENTICAL
    on every rank (io/partitioned_reader.py's global hot ranking
    guarantees it), and replicate. The flat COO overflow tail is padded to
    one agreed per-rank length (SparseShard.flat_block_nnz, also from the
    reader's layout exchange; pads carry value 0 / col 0 / the rank's last
    real row id) and assembles over "data" with each rank's row ids
    shifted into the global sample axis — the concatenation stays
    nondecreasing, preserving the flat segment-sum's sorted promise. An
    un-exchanged local batch (mismatched shapes) fails here loudly.
    """
    from photon_ml_tpu.data.sparse_batch import SparseLabeledPointBatch
    from photon_ml_tpu.parallel.multihost import assemble_partitioned

    sbs = {r: prepared[r][0]["fe_sparse_batch"] for r in ranks}
    first = sbs[ranks[0]]
    for r, sb in sbs.items():
        if not sb.has_ell_view:
            raise ValueError(
                f"rank {r}: the sparse FE batch has no ELL view; "
                "partitioned sparse training rides the fixed-width ELL "
                "layout (read through read_partitioned)"
            )
        if sb.dim != first.dim or (
            sb.ell_vals.shape != first.ell_vals.shape
        ) or sb.nnz != first.nnz:
            raise ValueError(
                f"rank {r}: sparse FE batch shapes disagree across ranks "
                f"(dim {sb.dim} vs {first.dim}, ELL "
                f"{sb.ell_vals.shape} vs {first.ell_vals.shape}, flat "
                f"{sb.nnz} vs {first.nnz}) — ingest through "
                "io/partitioned_reader.read_partitioned, which agrees the "
                "global layout"
            )
        if sb.has_hybrid_view != first.has_hybrid_view or (
            sb.has_hybrid_view
            and not bool(
                jnp.array_equal(sb.hot_col_ids, first.hot_col_ids)
            )
        ):
            raise ValueError(
                f"rank {r}: hybrid hot heads disagree across ranks — the "
                "hot ranking must be resolved globally "
                "(read_partitioned's hybrid_hot exchange)"
            )

    vec_spec = P("data")
    row2 = P("data", None)

    def asm(field, spec):
        blocks = {r: np.asarray(getattr(sbs[r], field)) for r in ranks}
        return assemble_partitioned(blocks, mesh, spec, num_ranks)

    # the fixed-length flat COO overflow tail (SparseShard.flat_block_nnz,
    # already padded per rank by from_shard): row ids shift by the rank's
    # base row into the global sample axis — each rank's block is
    # row-major and its pads carry the rank's last real row, so the
    # concatenation stays nondecreasing (the flat segment-sum's sorted
    # promise); pad values are 0, bitwise inert in every per-row sum
    n_rank = int(np.asarray(first.labels).shape[0])

    def asm_rows(r):
        return (
            np.asarray(sbs[r].row_ids, np.int64) + r * n_rank
        ).astype(np.int32)

    extra = {}
    if first.has_hybrid_view:
        extra = dict(
            hot_vals=asm("hot_vals", row2),
            hot_col_ids=put(
                np.asarray(first.hot_col_ids), NamedSharding(mesh, P())
            ),
        )
    return SparseLabeledPointBatch(
        values=asm("values", vec_spec),
        col_indices=asm("col_indices", vec_spec),
        row_ids=assemble_partitioned(
            {r: asm_rows(r) for r in ranks}, mesh, vec_spec, num_ranks
        ),
        labels=asm("labels", vec_spec),
        offsets=asm("offsets", vec_spec),
        weights=asm("weights", vec_spec),
        dim=int(first.dim),
        ell_vals=asm("ell_vals", row2),
        ell_cols=asm("ell_cols", row2),
        **extra,
    )


def prepare_partitioned_inputs(
    program: GameTrainProgram,
    parts: "Mapping[int, tuple[GameDataset, Mapping[str, RandomEffectDataset]]]",
    mesh: Mesh,
    num_ranks: int,
    *,
    fe_feature_sharded: bool = False,
    state: GameTrainState | None = None,
):
    """(data, buckets, state) for :meth:`GameTrainProgram.step` where the
    global sample/entity axes are assembled from per-rank LOCAL blocks
    (io/partitioned_reader.py layout: ``num_ranks`` equal blocks, padding
    rows/lanes inert) via ``multihost.assemble_partitioned`` — no host
    ever materializes a global-size array.

    parts: rank -> (local padded GameDataset, rank-local RE datasets from
    ``build_random_effect_dataset_partitioned``). Multi-process callers
    pass only their own rank; single-process simulations (tests, virtual
    ranks) pass all of them. The model state is replicated/entity-sharded
    exactly as ``shard_inputs`` lays it out.
    """
    from photon_ml_tpu.parallel.multihost import (
        assemble_partitioned,
        default_put,
    )

    ranks = sorted(parts)
    prepared = {
        r: program.prepare_inputs(ds, res, None) for r, (ds, res) in parts.items()
    }
    _partitioned_guards(program, prepared)

    vec = P("data")
    row2 = P("data", None)
    fe_fspec = P("data", "model") if fe_feature_sharded else row2
    put = default_put()

    def asm(getter, spec):
        blocks = {r: np.asarray(getter(prepared[r][0])) for r in ranks}
        return assemble_partitioned(blocks, mesh, spec, num_ranks)

    data = {
        "labels": asm(lambda d: d["labels"], vec),
        "offsets": asm(lambda d: d["offsets"], vec),
        "weights": asm(lambda d: d["weights"], vec),
        "features": {
            k: asm(
                lambda d, _k=k: d["features"][_k],
                fe_fspec if k == program.fe.feature_shard_id else row2,
            )
            for k in prepared[ranks[0]][0]["features"]
        },
        "entity_idx": {
            t: asm(lambda d, _t=t: d["entity_idx"][_t], vec)
            for t in prepared[ranks[0]][0]["entity_idx"]
        },
    }
    if "fe_sparse_batch" in prepared[ranks[0]][0]:
        # sparse (possibly hybrid) primary FE: per-rank row blocks of the
        # hot head / ELL tail assemble like dense fields; the reader's
        # global layout exchange guarantees the shapes agree
        data["fe_sparse_batch"] = _assemble_sparse_fe(
            prepared, ranks, mesh, num_ranks, put
        )

    def asm_b(key, i, field, spec):
        blocks = {
            r: np.asarray(prepared[r][1][key][i][field]) for r in ranks
        }
        return assemble_partitioned(blocks, mesh, spec, num_ranks)

    buckets: dict = {"__mf__": {}}
    for key, bucket_list in prepared[ranks[0]][1].items():
        if key == "__mf__":  # guarded empty (no MF specs)
            continue
        counts = {len(prepared[r][1][key]) for r in ranks}
        if len(counts) != 1:
            raise ValueError(
                f"random-effect coordinate '{key}': ranks disagree on the "
                f"bucket list ({counts}); build the RE views with "
                "build_random_effect_dataset_partitioned"
            )
        buckets[key] = [
            {
                "labels": asm_b(key, i, "labels", row2),
                "weights": asm_b(key, i, "weights", row2),
                "sample_rows": asm_b(key, i, "sample_rows", row2),
                "entity_rows": asm_b(key, i, "entity_rows", vec),
                "features": asm_b(key, i, "features", P("data", None, None)),
            }
            for i in range(len(bucket_list))
        ]

    # model state: identical on every rank (zeros or a shared warm start)
    # — replicate / entity-shard exactly as shard_inputs does
    r0 = ranks[0]
    if state is None:
        state = program.init_state(parts[r0][0], parts[r0][1], None)
    rep = NamedSharding(mesh, P())
    ent2 = NamedSharding(mesh, P("data", None))
    data_axis = int(mesh.shape["data"])

    def put_table(v):
        pad = (-int(v.shape[0])) % data_axis
        if pad:
            v = np.concatenate(
                [np.asarray(v),
                 np.zeros((pad,) + tuple(v.shape[1:]), np.asarray(v).dtype)]
            )
        return put(v, ent2)

    fe_sharding = NamedSharding(mesh, P("model")) if fe_feature_sharded else rep
    state = GameTrainState(
        fe_coefficients=put(np.asarray(state.fe_coefficients), fe_sharding),
        re_tables={k: put_table(v) for k, v in state.re_tables.items()},
        mf_rows={},
        mf_cols={},
        extra_fe={k: put(np.asarray(v), rep) for k, v in state.extra_fe.items()},
    )
    return data, buckets, state


def _partition_fingerprint(program: GameTrainProgram, parts,
                           num_ranks: int) -> dict:
    """The agreement a partitioned checkpoint is only valid under: rank
    geometry (the per-rank block a restored table row maps to), the
    agreed GLOBAL sparse layout (``io/partitioned_reader.
    _resolve_global_sparse_layout``'s hybrid hot head / ELL width / flat
    overflow — per-partition statistics must pin the global decision they
    were trained with, arXiv:2004.02414), and the coordinate structure.
    A resume under a different rank count or layout agreement fails fast
    attributed (train_partitioned's restore check) instead of silently
    training on mis-mapped rows. Computed from any single rank's LOCAL
    part — these are exactly the globally-agreed quantities, identical on
    every rank by the reader's exchange."""
    import hashlib

    r0 = sorted(parts)[0]
    ds, res = parts[r0]
    fe_shard = ds.feature_shards[program.fe.feature_shard_id]
    if isinstance(fe_shard, SparseShard):
        policy = fe_shard.hybrid_policy
        hot = tuple(policy.hot_ids) if policy is not None and policy.hot_ids else ()
        layout = {
            "dim": int(fe_shard.feature_dim),
            "ell_width": (
                None if fe_shard.ell_width is None else int(fe_shard.ell_width)
            ),
            "flat_block_nnz": (
                None if fe_shard.flat_block_nnz is None
                else int(fe_shard.flat_block_nnz)
            ),
            "k_hot": len(hot),
            "hot_hash": hashlib.sha256(
                np.asarray(hot, np.int64).tobytes()
            ).hexdigest()[:16],
        }
    else:
        layout = {"dim": int(np.asarray(fe_shard).shape[1])}
    return {
        "num_ranks": int(num_ranks),
        "block_rows": int(ds.num_samples),
        "fe_shard": program.fe.feature_shard_id,
        "fe_layout": layout,
        "re_entities": {
            s.re_type: int(res[s.re_type].num_entities)
            for s in program.re_specs
        },
        "extra_fe": sorted(s.feature_shard_id for s in program.extra_fes),
    }


def train_partitioned(
    program: GameTrainProgram,
    parts: "Mapping[int, tuple[GameDataset, Mapping[str, RandomEffectDataset]]]",
    mesh: Mesh,
    num_ranks: int,
    *,
    num_iterations: int = 1,
    state: GameTrainState | None = None,
    fe_feature_sharded: bool = False,
    check_finite: bool = True,
    schedulers: "Mapping[str, object] | None" = None,
    checkpointer=None,
    checkpoint_every: int = 1,
    resume: bool = True,
    exchange=None,
    resume_step: "int | None" = None,
) -> DistributedTrainResult:
    """``train_distributed`` over partitioned ingest blocks: each rank
    contributes only its local slice of the data/bucket arrays (every rank
    decoded ~1/P of the input; see io/partitioned_reader.py), the fused
    step runs unchanged, and only the MODEL-sized final state is host-
    gathered. Scope: dense or sparse/hybrid primary FE + dense IDENTITY
    REs, no validation riders (score + evaluate partitioned via
    parallel/scoring.py).

    schedulers: optional re_type -> algorithm.lane_scheduler.LaneScheduler
    (see ``make_schedulers`` — SPMD mode on multi-process runs): sweeps
    then run through ``step_scheduled``, composing probe/rescue lane
    scheduling with partitioned ingestion. None keeps the one-jit step.

    checkpointer: optional ``io.checkpoint.TrainingCheckpointer`` —
    crash-safe resume for the production configuration. Every
    ``checkpoint_every`` sweeps the model-sized state is host-gathered on
    EVERY rank (collectives) and committed through
    ``io.checkpoint.commit_checkpoint``: rank 0 writes, and — when
    ``exchange`` (the run's ``MetadataExchange``) is attached — the commit
    is gated by its rank-attributed deadline barriers, so a checkpoint
    exists only for sweeps every rank completed. ``meta.json`` carries a
    fingerprint of the partition plan + the agreed global sparse layout
    (``_partition_fingerprint``): a resume under a different rank count or
    layout agreement FAILS FAST with the differing fields named instead of
    silently training restored rows against a re-mapped block. An
    explicitly-passed ``state`` (warm start) takes precedence over resume,
    as in ``train_distributed``. ``checkpointer=None`` is bitwise the
    un-checkpointed path.

    resume_step: pin the restore to ONE published step (ISSUE 15's
    coordinated rollback: every rank must restore the step rank 0
    resolved and published, never its own local newest) — a missing pinned
    step fails fast instead of silently resolving to a different one; 0
    means "restart from scratch" (the rollback found no checkpoint).
    None (default) keeps the newest-intact-step behavior."""
    fingerprint = None
    start_sweep = 0
    prior_losses: list[float] = []
    if resume_step == 0:
        resume = False
    if checkpointer is not None:
        freezing = sorted(
            k for k, sch in (schedulers or {}).items()
            if getattr(getattr(sch, "config", None), "freezes", False)
        )
        if freezing:
            # cross-sweep active sets (frozen_rows + carried values) are
            # scheduler-internal state the checkpoint does not capture: a
            # restart would re-probe every lane and diverge from the
            # uninterrupted run, breaking the resume-exactness contract
            raise ValueError(
                "partitioned checkpointing cannot yet resume cross-sweep "
                f"active-set state (freeze tolerances set on {freezing}); "
                "drop scheduler.freeze.tolerance/scheduler.freeze.gradient "
                "(probe/rescue scheduling resumes exactly) or disable "
                "checkpointing for this run"
            )
        fingerprint = _partition_fingerprint(program, parts, num_ranks)
        if resume and state is None:
            ckpt = checkpointer.restore(
                step=resume_step if resume_step else None
            )
            if ckpt is not None:
                from photon_ml_tpu.io.checkpoint import fingerprint_mismatch

                mismatch = fingerprint_mismatch(
                    ckpt.meta.get("partition_fingerprint"), fingerprint
                )
                if mismatch is not None:
                    raise ValueError(
                        f"partitioned checkpoint at {checkpointer.directory}"
                        f" was written under a different partition "
                        f"fingerprint ({mismatch}) — a restored table row "
                        "would map onto a different rank block / sparse "
                        "layout; resume with the original rank count and "
                        "layout agreement, or use a fresh checkpoint "
                        "directory"
                    )
                if int(ckpt.step) > num_iterations:
                    # never silently relabel an over-trained state as an
                    # N-sweep result: a shrunken num_iterations must fail
                    # fast, not return the sweep-{step} model
                    raise ValueError(
                        f"partitioned checkpoint at {checkpointer.directory}"
                        f" is at sweep {int(ckpt.step)}, beyond this run's "
                        f"num_iterations={num_iterations}; raise "
                        "num_iterations to continue training, or use a "
                        "fresh checkpoint directory"
                    )

                def by_prefix(prefix):
                    return {
                        k[len(prefix):]: np.asarray(v)
                        for k, v in ckpt.arrays.items()
                        if k.startswith(prefix) and "/" not in k[len(prefix):]
                    }

                # host arrays; prepare_partitioned_inputs re-places them
                # over the mesh exactly like a warm start (tables were
                # saved UNSLICED, so shapes — and the jit signature —
                # match the interrupted run's)
                state = GameTrainState(
                    fe_coefficients=np.asarray(ckpt.arrays["fe_coefficients"]),
                    re_tables=by_prefix("re_tables/"),
                    mf_rows={},
                    mf_cols={},
                    extra_fe=by_prefix("extra_fe/"),
                )
                start_sweep = int(ckpt.step)
                prior_losses = [
                    float(x) for x in ckpt.meta.get("losses", [])
                ][:start_sweep]
                from photon_ml_tpu.telemetry import resilience_counters

                resilience_counters.record_checkpoint_restore()
                # resumed sweeps are the fused path's epochs-not-redone
                resilience_counters.record_epochs_resumed(start_sweep)
                logger.info(
                    "resuming partitioned training from checkpoint sweep "
                    "%d/%d", start_sweep, num_iterations,
                )

    data, buckets, st = prepare_partitioned_inputs(
        program, parts, mesh, num_ranks,
        fe_feature_sharded=fe_feature_sharded, state=state,
    )
    r0 = sorted(parts)[0]
    table_sizes = {
        s.re_type: parts[r0][1][s.re_type].num_entities
        for s in program.re_specs
    }

    def to_host(v):
        """Model-sized arrays only (coefficients/tables) — every process
        joins the gather (collective), unlike the O(n) score funnel the
        partitioned path exists to remove."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(v, tiled=True))
        return jax.device_get(v)

    losses: list[float] = list(prior_losses)
    for sweep in range(start_sweep, num_iterations):
        if schedulers:
            st, loss = program.step_scheduled(
                data, buckets, st, schedulers=schedulers,
                final_sweep=(sweep + 1 == num_iterations),
            )
        else:
            st, loss = program.step(data, buckets, st)
        losses.append(float(loss))
        if check_finite and not np.isfinite(losses[-1]):
            from photon_ml_tpu.io.checkpoint import DivergenceError

            raise DivergenceError(
                f"partitioned training step produced non-finite loss "
                f"{losses[-1]} at sweep {sweep}"
                + (
                    f"; last good checkpoint: step "
                    f"{checkpointer.latest_step()} in {checkpointer.directory}"
                    if checkpointer is not None else ""
                )
            )
        if checkpointer is not None and (
            (sweep + 1) % max(1, checkpoint_every) == 0
            or sweep + 1 == num_iterations
        ):
            # every rank gathers (collectives) and calls the commit helper
            # (its barriers are exchange calls every rank must make); only
            # rank 0 writes the shared directory
            from photon_ml_tpu.io.checkpoint import commit_checkpoint

            arrays = {
                "fe_coefficients": np.asarray(to_host(st.fe_coefficients))
            }
            for k, v in st.re_tables.items():
                arrays[f"re_tables/{k}"] = np.asarray(to_host(v))
            for k, v in st.extra_fe.items():
                arrays[f"extra_fe/{k}"] = np.asarray(to_host(v))
            commit_checkpoint(
                checkpointer, sweep + 1, arrays,
                {"partition_fingerprint": fingerprint, "losses": losses},
                exchange=exchange,
            )

    final = GameTrainState(
        fe_coefficients=jnp.asarray(to_host(st.fe_coefficients)),
        re_tables={
            k: jnp.asarray(to_host(v))[: table_sizes[k]]
            for k, v in st.re_tables.items()
        },
        mf_rows={},
        mf_cols={},
        extra_fe={k: jnp.asarray(to_host(v)) for k, v in st.extra_fe.items()},
    )
    return DistributedTrainResult(state=final, losses=losses)
