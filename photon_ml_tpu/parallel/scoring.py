"""Mesh-sharded scoring of a trained GameModel: one jitted SPMD program.

Reference parity: the reference's scoring path is distributed end-to-end —
``GameTransformer.transform`` scores RDDs across executors
(photon-api transformers/GameTransformer.scala:156-203) and
``RandomEffectModel`` scores by RDD join (model/RandomEffectModel.scala).
Here the whole GAME score (Σ sub-model margins + offsets) compiles into one
jit over a ``Mesh("data", "model")``: samples shard over "data", a giant
fixed-effect coordinate's feature axis (and coefficient vector) over
"model" — so a column-sharded d=2²⁸⁺ model scores without any device ever
holding the full coefficient vector, closing VERDICT r3 missing #1 ("the
framework can train models it cannot score").

Placement mirrors the training program (parallel/distributed.py):
GSPMD inserts the gather/psum collectives that replace the reference's
scoring joins. Single-device (mesh=None) reproduces GameModel.score_dataset
numbers exactly, so the same entry point serves both scales.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from photon_ml_tpu.parallel.mesh import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.data.game_data import GameDataset, pad_game_dataset
from photon_ml_tpu.data.sparse_batch import SparseShard
from photon_ml_tpu.io.checkpoint import (
    fingerprint_mismatch as _fingerprint_mismatch,
)
from photon_ml_tpu.models.game import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
    compact_entry_positions,
    score_random_effect,
    score_random_effect_compact,
)
from photon_ml_tpu.models.matrix_factorization import MatrixFactorizationModel
from photon_ml_tpu.telemetry.program_ledger import ledger_jit

Array = jax.Array


def _pad_nnz(arrays: dict, data_axis: int, pad_values: dict | None = None,
             xp=jnp, target: int | None = None) -> dict:
    """Pad flat nnz-axis arrays to a mesh multiple — or, when ``target`` is
    given, to exactly that length (the partitioned path's agreed per-rank
    entry-block length). Values pad with 0 (they contribute nothing),
    "rows" repeats its last id (keeps the row segment-sum's sorted
    promise; an EMPTY block takes ``pad_values["rows"]`` so a rank's pad
    rows stay inside its own global row block), and ``pad_values``
    overrides the other keys. ``xp`` (numpy on mesh paths) keeps the
    padding on the host so placement never round-trips through the local
    device."""
    nnz = int(arrays["vals"].shape[0])
    pad = (target - nnz) if target is not None else (-nnz) % data_axis
    if pad < 0:
        raise ValueError(
            f"flat block has {nnz} entries but the agreed length is "
            f"{target}"
        )
    if not pad:
        return arrays
    last_row = (
        arrays["rows"][-1:] if nnz
        else xp.full(1, (pad_values or {}).get("rows", 0),
                     arrays["rows"].dtype)
    )
    out = {}
    for k, v in arrays.items():
        if k == "rows":
            out[k] = xp.concatenate([v, xp.broadcast_to(last_row, (pad,))])
        else:
            out[k] = xp.pad(v, (0, pad),
                            constant_values=(pad_values or {}).get(k, 0))
    return out


def _assembly_xp():
    """Array namespace for host-side data assembly before placement:
    numpy when the program spans processes (global_put slices host arrays
    zero-copy; a jnp intermediate would cost a D2H per array), jnp
    otherwise (device-resident inputs reshard on-device)."""
    return np if jax.process_count() > 1 else jnp


def params_layout_fingerprint(model: GameModel) -> dict:
    """Per-coordinate layout signature of a model's score-program params:
    kind, shard/effect identity, and every param leaf's (shape, dtype).
    Two models with EQUAL fingerprints produce pytrees of identical
    structure and avals, so swapping one for the other re-uses every
    compiled score program (zero recompiles — the DrJAX one-traced-program
    argument, arXiv:2403.07128); a differing fingerprint is exactly a
    layout change, and the serving swap guard rejects it naming these
    fields."""
    fp: dict = {"coordinates": ",".join(model.models)}

    def leaf(arr) -> str:
        a = np.asarray(arr) if not hasattr(arr, "shape") else arr
        return f"{tuple(int(s) for s in a.shape)}:{a.dtype}"

    for cid, m in model.models.items():
        if isinstance(m, FixedEffectModel):
            fp[f"{cid}/kind"] = "fe"
            fp[f"{cid}/shard"] = m.feature_shard_id
            fp[f"{cid}/w"] = leaf(m.glm.coefficients.means)
        elif isinstance(m, RandomEffectModel):
            fp[f"{cid}/kind"] = "re_compact" if m.is_compact else "re"
            fp[f"{cid}/shard"] = m.feature_shard_id
            fp[f"{cid}/re_type"] = m.random_effect_type
            fp[f"{cid}/table"] = leaf(m.coefficients)
            if m.is_compact:
                fp[f"{cid}/active_cols"] = leaf(m.active_cols)
        elif isinstance(m, MatrixFactorizationModel):
            fp[f"{cid}/kind"] = "mf"
            fp[f"{cid}/re_type"] = (
                f"{m.row_effect_type}x{m.col_effect_type}"
            )
            fp[f"{cid}/rows"] = leaf(m.row_factors)
            fp[f"{cid}/cols"] = leaf(m.col_factors)
        else:
            fp[f"{cid}/kind"] = type(m).__name__
    return fp


def _model_kinds(model: GameModel) -> dict[str, str]:
    kinds: dict[str, str] = {}
    for cid, m in model.models.items():
        if isinstance(m, FixedEffectModel):
            kinds[cid] = "fe"
        elif isinstance(m, RandomEffectModel):
            kinds[cid] = "re_compact" if m.is_compact else "re"
        elif isinstance(m, MatrixFactorizationModel):
            kinds[cid] = "mf"
        else:
            raise TypeError(
                f"coordinate '{cid}': cannot build a distributed scoring "
                f"program for sub-model type {type(m).__name__}"
            )
    return kinds


class DistributedScorer:
    """Scores a GameModel over a mesh as one jitted SPMD program.

    fe_feature_sharded: shard the named FE coordinate's feature axis (and
    its coefficient vector) over the mesh "model" axis — True picks the
    single FE coordinate (error if several), or pass the coordinate id.
    """

    def __init__(self, model: GameModel, mesh: Mesh | None = None, *,
                 fe_feature_sharded: "bool | str" = False):
        self.model = model
        self.mesh = mesh
        self._kinds = _model_kinds(model)
        fe_cids = [c for c, k in self._kinds.items() if k == "fe"]
        if fe_feature_sharded is True:
            if len(fe_cids) != 1:
                raise ValueError(
                    "fe_feature_sharded=True needs exactly one fixed-effect "
                    f"coordinate to pick; model has {fe_cids}. Pass the "
                    "coordinate id instead."
                )
            self.fe_sharded_cid: str | None = fe_cids[0]
        elif fe_feature_sharded:
            if self._kinds.get(fe_feature_sharded) != "fe":
                raise ValueError(
                    f"fe_feature_sharded={fe_feature_sharded!r} is not a "
                    f"fixed-effect coordinate of the model ({fe_cids})"
                )
            self.fe_sharded_cid = str(fe_feature_sharded)
        else:
            self.fe_sharded_cid = None
        if self.fe_sharded_cid is not None and mesh is None:
            raise ValueError("fe_feature_sharded requires a mesh")
        #: layout-signature -> placed params (see params_for_layouts)
        self._params_cache: dict = {}
        self._params_cache_bytes: int = 0
        # ledger-labeled program (telemetry/program_ledger.py): data and
        # params both enter as ARGUMENTS; the label keys compile/cost/
        # recompile accounting when a ProgramLedger is installed
        self._jit_score = ledger_jit(self._score_impl,
                                     label="score/score_dataset")

    # -- data preparation ----------------------------------------------------

    def prepare(self, dataset: GameDataset):
        """(data pytree, params pytree, n_true). With a mesh, the sample
        axis is padded to a mesh multiple and everything is placed with
        the program's shardings; params hold the model's device tables.

        On a MULTI-PROCESS mesh every array is assembled with HOST numpy
        (``xp = np``) and only then placed: committing to the local device
        first would cost a device round-trip per array under global_put
        (its docstring warns about exactly this). Single-process — mesh or
        not — keeps jnp assembly: device-resident inputs (e.g. a live
        model's tables) reshard on-device without a D2H."""
        n_true = dataset.num_samples
        xp = _assembly_xp()
        if self.mesh is not None:
            dataset, n_true = pad_game_dataset(
                dataset, int(self.mesh.shape["data"])
            )
        data, layouts = self._build_data_host(dataset, xp)
        params = self.params_for_layouts(layouts, xp=xp)
        if self.mesh is not None:
            data = self._place_data(data)
        return data, params, n_true

    def _build_host(self, dataset: GameDataset, xp):
        """(data, params) pytrees for ``_score_impl``, assembled host-side
        (or on the local device when xp=jnp) WITHOUT mesh padding or
        placement — the composition of the two separable halves, kept for
        the partitioned path which builds per-rank data blocks."""
        data, layouts = self._build_data_host(dataset, xp)
        return data, self._build_params_host(xp, layouts)

    def _build_data_host(self, dataset: GameDataset, xp):
        """The DATASET side of the score program's inputs: (data pytree,
        layouts). ``layouts`` maps each coordinate to its layout token
        ("dense"/"sparse" FE, "re", "entries"/"compact_dense" compact RE,
        "mf") — the per-dataset information :meth:`_build_params_host`
        needs, so model placement is separable from dataset assembly (the
        resident scorer re-runs only THIS half per micro-batch)."""
        data: dict = {"offsets": xp.asarray(dataset.offsets), "coords": {}}
        layouts: dict[str, str] = {}
        for cid, m in self.model.models.items():
            kind = self._kinds[cid]
            c: dict = {}
            if kind == "fe":
                feats = dataset.feature_shards[m.feature_shard_id]
                if cid == self.fe_sharded_cid:
                    # the sharded feature/coefficient axis must divide the
                    # mesh "model" axis: right-pad with zero columns /
                    # coefficients (contribute nothing), same convention as
                    # the training estimator's fe_pad
                    model_axis = int(self.mesh.shape["model"])
                    pad = (-int(np.shape(m.glm.coefficients.means)[0])) \
                        % model_axis
                    if pad and not isinstance(feats, SparseShard):
                        feats = xp.pad(xp.asarray(feats), ((0, 0), (0, pad)))
                if isinstance(feats, SparseShard):
                    rows, cols, vals = feats.coalesced()
                    # rows fit int32 (sample counts); cols keep a width
                    # that holds feature_dim (int64 needs jax x64 — the
                    # reader guards >2^31 dims at config time)
                    col_dt = (
                        np.int32 if feats.feature_dim <= np.iinfo(np.int32).max
                        else np.int64
                    )
                    c["sparse"] = {
                        "rows": xp.asarray(np.asarray(rows, np.int32)),
                        "cols": xp.asarray(np.asarray(cols, col_dt)),
                        "vals": xp.asarray(vals),
                    }
                    layouts[cid] = "sparse"
                else:
                    c["x"] = xp.asarray(feats)
                    layouts[cid] = "dense"
            elif kind == "re":
                c["x"] = xp.asarray(dataset.feature_shards[m.feature_shard_id])
                c["idx"] = xp.asarray(dataset.entity_idx[m.random_effect_type])
                layouts[cid] = "re"
            elif kind == "re_compact":
                feats = dataset.feature_shards[m.feature_shard_id]
                idx = np.asarray(
                    dataset.host_array(f"entity_idx/{m.random_effect_type}")
                )
                if isinstance(feats, SparseShard):
                    ent, pos, rows, vals = compact_entry_positions(
                        feats, idx, np.asarray(m.active_cols)
                    )
                    c["entries"] = {
                        "ent": xp.asarray(ent), "pos": xp.asarray(pos),
                        "rows": xp.asarray(rows), "vals": xp.asarray(vals),
                    }
                    layouts[cid] = "entries"
                else:
                    c["x"] = xp.asarray(feats)
                    c["idx"] = xp.asarray(idx)
                    layouts[cid] = "compact_dense"
            else:  # mf
                c["row_idx"] = xp.asarray(dataset.entity_idx[m.row_effect_type])
                c["col_idx"] = xp.asarray(dataset.entity_idx[m.col_effect_type])
                layouts[cid] = "mf"
            data["coords"][cid] = c
        return data, layouts

    def _build_params_host(self, xp, layouts, model: GameModel | None = None):
        """The MODEL side of the score program's inputs, buildable without
        any dataset: FE coefficient vectors, RE tables (full [E, d] or
        compact [E, K] + active columns), MF factors. ``layouts`` (from
        :meth:`_build_data_host`) only decides the compact-RE form — the
        dense-shard form carries active_cols on device, the sparse-entries
        form resolves positions host-side. ``model`` overrides the resident
        model for the hot-swap rebuild (:meth:`swap_model_params`), which
        must build the NEW params before committing the reference."""
        params: dict = {}
        for cid, m in (model or self.model).models.items():
            kind = self._kinds[cid]
            if kind == "fe":
                w = xp.asarray(m.glm.coefficients.means)
                if cid == self.fe_sharded_cid:
                    model_axis = int(self.mesh.shape["model"])
                    pad = (-int(w.shape[0])) % model_axis
                    if pad:
                        w = xp.pad(w, (0, pad))
                params[cid] = {"w": w}
            elif kind == "re":
                params[cid] = {"table": xp.asarray(m.coefficients)}
            elif kind == "re_compact":
                if layouts.get(cid) == "compact_dense":
                    params[cid] = {
                        "table": xp.asarray(m.coefficients),
                        "active_cols": xp.asarray(
                            np.asarray(m.active_cols, np.int32)
                        ),
                    }
                else:
                    params[cid] = {"table": xp.asarray(m.coefficients)}
            else:  # mf
                params[cid] = {
                    "rows": xp.asarray(m.row_factors),
                    "cols": xp.asarray(m.col_factors),
                }
        return params

    def params_for_layouts(self, layouts, xp=None):
        """Placed model params for one layout signature, built ONCE and
        cached: the model is frozen, so the params pytree (and its mesh
        placement) is identical for every dataset with the same layout —
        a multi-dataset scoring run or a resident serving loop pays the
        build + device placement on the first call only. The cache key is
        the per-coordinate layout map (typically one entry for a model's
        whole service lifetime)."""
        key = tuple(sorted(layouts.items()))
        # capture the cache OBJECT: swap_model_params commits a new model
        # by replacing the reference, so a miss that started building
        # before a swap inserts into the SUPERSEDED dict (never read
        # again) instead of poisoning the rebuilt cache with old params
        cache = self._params_cache
        cached = cache.get(key)
        if cached is None:
            params = self._build_params_host(
                xp if xp is not None else _assembly_xp(), layouts
            )
            if self.mesh is not None:
                params = self._place_params(params)
            cache[key] = cached = params
            # resident-params accounting (the HBM-forecast input of the
            # program ledger): total bytes across every cached layout's
            # placed params — metadata only, no device work
            self._params_cache_bytes = sum(
                leaf.nbytes
                for entry in self._params_cache.values()
                for leaf in jax.tree_util.tree_leaves(entry)
                if hasattr(leaf, "nbytes")
            )
        # re-fed on HITS too: reset_serving_metrics() mid-run (the serve
        # driver resets between its baseline and the replay) would
        # otherwise leave the gauge empty for the rest of the run
        from photon_ml_tpu.telemetry import serving_counters

        serving_counters.set_resident_params_bytes(
            int(self._params_cache_bytes)
        )
        return cached

    def swap_model_params(self, new_model: GameModel) -> None:
        """In-place model refresh: rebuild + re-place the layout-keyed
        params cache for ``new_model`` and swap the references — the
        zero-downtime half of incremental retraining (algorithm/refresh.py)
        riding the separable-placement split: the DATA half of the score
        program is untouched, the compiled programs key on shapes/dtypes
        only, and an EQUAL layout fingerprint guarantees those are
        unchanged, so a swap costs zero recompiles.

        A layout-changing model is rejected (ValueError naming the
        differing fields) BEFORE any state mutates; the rebuild happens
        fully off to the side and commits by reference assignment, so
        concurrent scoring threads see either the old or the new params,
        never a mix."""
        mismatch = _fingerprint_mismatch(
            params_layout_fingerprint(new_model),
            params_layout_fingerprint(self.model),
        )
        if mismatch is not None:
            # the ONE guard site; serving wraps this as ModelSwapError and
            # records the swap_rejected counter (serving/resident.py)
            raise ValueError(
                f"the new model's params layout {mismatch}; a "
                "layout-changing refresh must re-place from scratch "
                "(build a fresh scorer) instead of hot-swapping"
            )
        from photon_ml_tpu.telemetry import serving_counters

        rebuilt: dict = {}
        # snapshot the keys: a concurrently scoring thread may lazily
        # insert a new layout into the live cache mid-rebuild (its
        # old-model params are superseded by the commit below either way)
        for key in list(self._params_cache):
            params = self._build_params_host(
                _assembly_xp(), dict(key), model=new_model
            )
            if self.mesh is not None:
                params = self._place_params(params)
            rebuilt[key] = params
        # commit: plain reference assignments (atomic under the GIL)
        self.model = new_model
        self._params_cache = rebuilt
        self._params_cache_bytes = sum(
            leaf.nbytes
            for entry in rebuilt.values()
            for leaf in jax.tree_util.tree_leaves(entry)
            if hasattr(leaf, "nbytes")
        )
        # the HBM-forecast input must not keep reporting the stale model
        serving_counters.set_resident_params_bytes(
            int(self._params_cache_bytes)
        )

    def _place_data(self, data):
        from photon_ml_tpu.parallel.multihost import default_put

        mesh = self.mesh
        put = default_put()
        vec = NamedSharding(mesh, P("data"))
        row2 = NamedSharding(mesh, P("data", None))
        data_axis = int(mesh.shape["data"])

        data = dict(data)
        data["offsets"] = put(data["offsets"], vec)
        coords = {}
        for cid, c in data["coords"].items():
            kind = self._kinds[cid]
            out = {}
            if "x" in c:
                if kind == "fe" and cid == self.fe_sharded_cid:
                    out["x"] = put(c["x"], NamedSharding(mesh, P("data", "model")))
                else:
                    out["x"] = put(c["x"], row2)
            if "idx" in c:
                out["idx"] = put(c["idx"], vec)
            if "row_idx" in c:
                out["row_idx"] = put(c["row_idx"], vec)
                out["col_idx"] = put(c["col_idx"], vec)
            if "sparse" in c:
                out["sparse"] = {
                    k: put(v, vec)
                    for k, v in _pad_nnz(
                        c["sparse"], data_axis, xp=_assembly_xp()
                    ).items()
                }
            if "entries" in c:
                # pos pads point at the scratch slot; ent 0 is harmless
                # because vals pad with 0
                k_scratch = int(self.model.models[cid].coefficients.shape[1])
                out["entries"] = {
                    k: put(v, vec)
                    for k, v in _pad_nnz(
                        c["entries"], data_axis, pad_values={"pos": k_scratch},
                        xp=_assembly_xp(),
                    ).items()
                }
            coords[cid] = out
        data["coords"] = coords
        return data

    def _place_params(self, params):
        """Model tables/vectors placed over the mesh: FE coefficients
        replicated (or over "model" when feature-sharded), entity tables
        over "data" — shared by :meth:`prepare` and the partitioned path
        (model-sized arrays exist on every rank; only the DATA is
        partitioned)."""
        from photon_ml_tpu.parallel.multihost import default_put

        mesh = self.mesh
        put = default_put()
        rep = NamedSharding(mesh, P())
        ent2 = NamedSharding(mesh, P("data", None))
        data_axis = int(mesh.shape["data"])
        placed_params = {}
        for cid, p in params.items():
            kind = self._kinds[cid]
            out = {}
            for k, v in p.items():
                if kind == "fe" and k == "w":
                    out[k] = put(
                        v,
                        NamedSharding(mesh, P("model"))
                        if cid == self.fe_sharded_cid else rep,
                    )
                elif k in ("table", "rows", "cols", "active_cols"):
                    # entity axis over "data" like the training program;
                    # pad to a mesh multiple (padded rows are never indexed:
                    # entity ids stay < E)
                    pad = (-int(v.shape[0])) % data_axis
                    if pad:
                        v = _assembly_xp().pad(v, ((0, pad), (0, 0)))
                    out[k] = put(v, ent2)
                else:
                    out[k] = put(v, rep)
            placed_params[cid] = out
        return placed_params

    # -- the jitted program --------------------------------------------------

    def _ring_re_score(self, table: Array, x: Array, idx: Array) -> Array:
        """Dense RE scoring with the entity table KEPT entity-sharded.

        The naive ``table[idx]`` gather pairs an entity-sharded operand
        with sample-sharded indices — GSPMD resolves that by all-gathering
        the table, materializing the full [E, d] on every device (VERDICT
        r4 missing-scale #6; the reference avoids it with an RDD join,
        RandomEffectModel.scala). Here each device keeps only its
        [E/K, d] block and the blocks ROTATE around the mesh "data" ring
        (K-1 ppermutes): at step k a device scores the local samples whose
        entity rows sit in the block it currently holds. Peak per-device
        table memory is E/K·d — the ring trades the all-gather's K× memory
        for the same total bytes on ICI.
        """
        mesh_k = int(self.mesh.shape["data"])
        e_pad = int(table.shape[0])
        eb = e_pad // mesh_k
        if eb == 0:
            # untrained/empty RE table — contribute zeros, mirroring the
            # single-device score_random_effect guard (models/game.py)
            return jnp.zeros(x.shape[:1], x.dtype)

        def body(block, x_l, idx_l):
            me = jax.lax.axis_index("data")
            # bf16 feature shards: rows (f32) x x_l (bf16) promotes to f32
            acc_dtype = jnp.result_type(block.dtype, x_l.dtype)

            def accumulate(k, blk, acc):
                # after k forward rotations device `me` holds block
                # (me - k) mod K
                owner = (me - k) % mesh_k
                rel = idx_l - owner * eb
                hit = (rel >= 0) & (rel < eb) & (idx_l >= 0)
                rows = blk[jnp.clip(rel, 0, eb - 1)]
                return acc + jnp.where(
                    hit, jnp.einsum("nd,nd->n", rows, x_l), 0.0
                )

            def step(k, carry):
                blk, acc = carry
                acc = accumulate(k, blk, acc)
                blk = jax.lax.ppermute(
                    blk, "data",
                    [(i, (i + 1) % mesh_k) for i in range(mesh_k)],
                )
                return blk, acc

            # K-1 rotate+accumulate steps, then the last block accumulates
            # WITHOUT a final (discarded) rotation
            blk, acc = jax.lax.fori_loop(
                0, mesh_k - 1, step,
                (block, jnp.zeros(x_l.shape[:1], acc_dtype)),
            )
            return accumulate(mesh_k - 1, blk, acc)

        return shard_map(
            body,
            mesh=self.mesh,
            in_specs=(P("data", None), P("data", None), P("data")),
            out_specs=P("data"),
            check_vma=False,
        )(table, x, idx)

    def _score_impl(self, data, params) -> Array:
        total = data["offsets"]
        for cid, c in data["coords"].items():
            kind = self._kinds[cid]
            p = params.get(cid, {})
            if kind == "fe":
                w = p["w"]
                if "sparse" in c:
                    sp = c["sparse"]
                    contrib = sp["vals"] * w[sp["cols"]]
                    s = jax.ops.segment_sum(
                        contrib, sp["rows"], num_segments=total.shape[0],
                        indices_are_sorted=True,
                    )
                else:
                    # row-wise reduction, NOT x @ w: XLA's dot kernels pick
                    # shape-specialized tilings, so a matvec's low bits can
                    # change with the (padded) row count — the broadcast-
                    # multiply + per-row reduce is row-count-invariant at
                    # the bit level, which the serving shape-bucket pin
                    # (padded micro-batch == unpadded scores, BITWISE)
                    # requires. Same bytes read either way; the margin is
                    # bandwidth-bound, not MXU-bound.
                    s = (c["x"] * w).sum(axis=1)
            elif kind == "re":
                if self.mesh is not None and int(self.mesh.shape["data"]) > 1:
                    s = self._ring_re_score(p["table"], c["x"], c["idx"])
                else:
                    s = score_random_effect(p["table"], c["x"], c["idx"])
            elif kind == "re_compact":
                if "entries" in c:
                    e = c["entries"]
                    s = score_random_effect_compact(
                        p["table"], e["ent"], e["pos"], e["rows"], e["vals"],
                        int(total.shape[0]),
                    )
                else:
                    idx = c["idx"]
                    table = p["table"]
                    cols = p["active_cols"]
                    dim = int(c["x"].shape[1])
                    safe = jnp.maximum(idx, 0)
                    ccols = cols[safe]
                    x = jnp.take_along_axis(
                        c["x"], jnp.minimum(ccols, dim - 1), axis=1
                    ) * (ccols < dim)
                    s = jnp.where(
                        idx >= 0, jnp.einsum("nk,nk->n", x, table[safe]), 0.0
                    )
            else:  # mf
                from photon_ml_tpu.models.matrix_factorization import (
                    score_matrix_factorization,
                )

                s = score_matrix_factorization(
                    p["rows"], p["cols"], c["row_idx"], c["col_idx"]
                )
            total = total + s
        return total

    # -- public entry --------------------------------------------------------

    def _score_prepared(self, data, params) -> Array:
        if self.mesh is not None:
            with self.mesh:
                return self._jit_score(data, params)
        return self._jit_score(data, params)

    def _evaluate_scores(
        self, scores: Array, dataset: GameDataset, evaluator_specs,
        n_pad: int, host_scores_fn, use_device_forms: bool = True,
    ) -> dict[str, float]:
        """Evaluate still-sharded scores: metrics with a device form
        (evaluation/sharded.py — RMSE, MAE, the losses, exact AUC/AUPR,
        per-query RMSE/AUC/precision@k) reduce on the mesh and only
        scalars cross; the rest fall back to ``host_scores_fn``. The on-mesh
        analogue of the reference's executor-side evaluation
        (Evaluator.scala:39-49, MultiEvaluator.scala:40-88)."""
        from photon_ml_tpu.evaluation.evaluators import (
            EvaluationData,
            parse_evaluator,
        )
        from photon_ml_tpu.evaluation.sharded import (
            evaluate_prepared,
            mesh_data_placer,
            prepare_device_evaluators,
        )
        from photon_ml_tpu.parallel.multihost import default_put

        evaluators = [
            parse_evaluator(s) if isinstance(s, str) else s
            for s in evaluator_specs
        ]
        eval_data = EvaluationData(
            labels=np.asarray(dataset.host_array("labels")),
            offsets=np.asarray(dataset.host_array("offsets")),
            weights=np.asarray(dataset.host_array("weights")),
            ids=dataset.ids,
        )
        if self.mesh is not None and use_device_forms:
            device_evals = prepare_device_evaluators(
                evaluators, eval_data, n_pad=n_pad,
                place=mesh_data_placer(self.mesh, put_fn=default_put()),
            )
        else:
            # exact host evaluators (single device, or the scores were
            # gathered anyway): nothing to avoid
            device_evals = [None] * len(evaluators)
        values = evaluate_prepared(
            evaluators, device_evals, scores, eval_data, host_scores_fn
        )
        return {ev.name: v for ev, v in zip(evaluators, values)}

    def score_dataset(self, dataset: GameDataset) -> np.ndarray:
        """[n] host scores INCLUDING offsets (GameTransformer semantics) —
        gathered across processes, mesh padding rows dropped."""
        from photon_ml_tpu.parallel.distributed import _host_scores

        data, params, n_true = self.prepare(dataset)
        return _host_scores(self._score_prepared(data, params), n_true)

    # -- partitioned scoring: no O(n) gather, each rank keeps its rows ------

    def score_partitioned(self, parts, partition,
                          exchange=None) -> "dict[int, np.ndarray]":
        """Score partitioned-ingest blocks and return each provided rank's
        LOCAL scores — the replacement for the ``process_allgather`` score
        funnel: the [n] vector stays mesh-sharded end to end and every
        rank device-gets only its own unpadded rows (then writes them with
        io/score_writer.ShardedScoreWriter).

        parts: rank -> local padded GameDataset (io/partitioned_reader.py
        layout); multi-process callers pass their own rank only, single-
        process simulations pass all. partition: the reader's
        PartitionInfo. Model params are model-sized and placed normally.
        Sparse (incl. hybrid-read) FIXED-EFFECT coordinates are supported:
        per-rank flat entry triples pad to one agreed nnz block (rows
        shifted to the global sample axis) — multi-process runs must pass
        the run's MetadataExchange so ranks agree on the block length.
        Compact-RE coordinates are not supported; use score_dataset."""
        from photon_ml_tpu.parallel.multihost import assemble_partitioned

        if self.mesh is None:
            raise ValueError("score_partitioned requires a mesh")
        if partition.global_rows % int(self.mesh.shape["data"]):
            raise ValueError(
                f"partitioned sample axis {partition.global_rows} does not "
                f"divide the mesh data axis {int(self.mesh.shape['data'])}; "
                "read with pad_multiple = data_axis // num_ranks"
            )
        ranks = sorted(parts)
        # data half only per rank; the model half rides the layout-keyed
        # params cache below (a multi-dataset partitioned run places the
        # model once, and the R-1 redundant per-rank param builds of the
        # single-process simulation path are gone)
        built = {r: self._build_data_host(parts[r], np) for r in ranks}
        for r in ranks:
            for cid, c in built[r][0]["coords"].items():
                if "entries" in c:
                    raise ValueError(
                        f"coordinate '{cid}': compact random-effect "
                        "coordinates are not supported by partitioned "
                        "scoring; use score_dataset"
                    )

        vec = P("data")
        row2 = P("data", None)

        def asm(getter, spec):
            blocks = {r: np.asarray(getter(built[r][0])) for r in ranks}
            return assemble_partitioned(
                blocks, self.mesh, spec, partition.num_ranks
            )

        data = {
            "offsets": asm(lambda d: d["offsets"], vec),
            "coords": {},
        }
        for cid in built[ranks[0]][0]["coords"]:
            kind = self._kinds[cid]
            c = built[ranks[0]][0]["coords"][cid]
            out = {}
            if "x" in c:
                spec = (
                    P("data", "model")
                    if kind == "fe" and cid == self.fe_sharded_cid else row2
                )
                out["x"] = asm(lambda d, _c=cid: d["coords"][_c]["x"], spec)
            if "idx" in c:
                out["idx"] = asm(lambda d, _c=cid: d["coords"][_c]["idx"], vec)
            if "row_idx" in c:
                out["row_idx"] = asm(
                    lambda d, _c=cid: d["coords"][_c]["row_idx"], vec
                )
                out["col_idx"] = asm(
                    lambda d, _c=cid: d["coords"][_c]["col_idx"], vec
                )
            if "sparse" in c:
                out["sparse"] = self._assemble_sparse_coord(
                    cid, built, ranks, partition, exchange
                )
            data["coords"][cid] = out
        params = self.params_for_layouts(built[ranks[0]][1], xp=np)

        scores = self._score_prepared(data, params)
        return {
            r: self._extract_rank_rows(scores, partition, r) for r in ranks
        }

    def _assemble_sparse_coord(self, cid, built, ranks, partition,
                               exchange) -> dict:
        """One sparse FE coordinate's per-rank flat entry triples as global
        mesh-sharded arrays: each rank's (rows, cols, vals) pads to the
        agreed per-rank entry-block length (pads carry value 0 and the
        rank's LAST global row id, keeping the row segment-sum's sorted
        promise across rank boundaries), rows shift by the rank's base row
        into the global sample axis, and the blocks assemble over "data".
        """
        from photon_ml_tpu.parallel.multihost import assemble_partitioned

        local_nnz = {
            r: int(built[r][0]["coords"][cid]["sparse"]["vals"].shape[0])
            for r in ranks
        }
        if len(ranks) == partition.num_ranks:
            block_nnz = max(local_nnz.values())
        else:
            if exchange is None:
                raise ValueError(
                    f"coordinate '{cid}': multi-process partitioned "
                    "scoring of a sparse shard needs the run's "
                    "MetadataExchange (pass score_partitioned("
                    "exchange=...)) so ranks agree on the entry-block "
                    "length"
                )
            gathered = exchange.allgather(
                f"score_sparse_nnz/{cid}", max(local_nnz.values())
            )
            block_nnz = max(int(g) for g in gathered)
        data_axis = int(self.mesh.shape["data"])
        block_nnz = max(-(-block_nnz // data_axis) * data_axis, data_axis)

        blocks: dict[str, dict[int, np.ndarray]] = {
            "rows": {}, "cols": {}, "vals": {}
        }
        for r in ranks:
            sp = built[r][0]["coords"][cid]["sparse"]
            padded = _pad_nnz(
                {
                    "rows": np.asarray(sp["rows"], np.int64)
                    + r * partition.block_rows,
                    "cols": np.asarray(sp["cols"]),
                    "vals": np.asarray(sp["vals"]),
                },
                data_axis, xp=np, target=block_nnz,
                pad_values={"rows": r * partition.block_rows},
            )
            blocks["rows"][r] = padded["rows"].astype(np.int32)
            blocks["cols"][r] = padded["cols"]
            blocks["vals"][r] = padded["vals"]
        return {
            k: assemble_partitioned(
                v, self.mesh, P("data"), partition.num_ranks
            )
            for k, v in blocks.items()
        }

    @staticmethod
    def _extract_rank_rows(scores, partition, rank) -> np.ndarray:
        """One rank's true (unpadded) rows from the still-sharded global
        score vector, read from its ADDRESSABLE shards only — no
        cross-process gather. Model-axis replication may present the same
        rows on several local devices; identical copies overwrite."""
        start = rank * partition.block_rows
        stop = start + int(partition.local_rows[rank])
        out = np.zeros(stop - start, dtype=scores.dtype)
        filled = np.zeros(stop - start, dtype=bool)
        n = scores.shape[0]
        for shard in scores.addressable_shards:
            sl = shard.index[0] if shard.index else slice(0, n)
            s0 = 0 if sl.start is None else int(sl.start)
            s1 = n if sl.stop is None else int(sl.stop)
            lo, hi = max(s0, start), min(s1, stop)
            if lo >= hi:
                continue
            block = np.asarray(shard.data)
            out[lo - start: hi - start] = block[lo - s0: hi - s0]
            filled[lo - start: hi - start] = True
        if not filled.all():
            raise ValueError(
                f"rank {rank}: rows [{start}, {stop}) are not fully "
                "addressable from this process — each rank may only "
                "extract its own block"
            )
        return out

    def evaluate_dataset(
        self, dataset: GameDataset, evaluator_specs
    ) -> dict[str, float]:
        """Score + evaluate WITHOUT gathering [n] scores to the host
        (validation-style runs that never write scores)."""
        from photon_ml_tpu.parallel.distributed import _host_scores

        data, params, n_true = self.prepare(dataset)
        scores = self._score_prepared(data, params)
        return self._evaluate_scores(
            scores, dataset, evaluator_specs,
            n_pad=int(data["offsets"].shape[0]),
            host_scores_fn=lambda: _host_scores(scores, n_true),
        )

    def score_and_evaluate(
        self, dataset: GameDataset, evaluator_specs=()
    ) -> tuple[np.ndarray, dict[str, float]]:
        """(host scores, metrics) from ONE data-preparation/scoring pass —
        what GameTransformer.transform consumes when scores must be
        written anyway. The gather happens regardless (the scores are the
        product), so metrics use the EXACT host evaluators on it — a
        device-side approximation (histogram AUC) would trade exactness
        for a gather that is not avoided. evaluate_dataset is the entry
        that skips the gather."""
        from photon_ml_tpu.parallel.distributed import _host_scores

        data, params, n_true = self.prepare(dataset)
        scores = self._score_prepared(data, params)
        host = _host_scores(scores, n_true)
        evaluations = (
            self._evaluate_scores(
                scores, dataset, evaluator_specs,
                n_pad=int(data["offsets"].shape[0]),
                host_scores_fn=lambda: host,
                use_device_forms=False,
            )
            if evaluator_specs else {}
        )
        return host, evaluations
