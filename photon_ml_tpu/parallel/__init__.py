from photon_ml_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    replicate,
    shard_batch,
    shard_game_dataset,
)
