"""Multi-host / multi-slice process coordination and hybrid meshes.

No reference analogue as code: the reference's multi-node story is the
Spark driver/executor runtime (cluster bootstrap belonged to spark-submit
and YARN, not to any photon-ml source file) — YARN launches executors, the
driver coordinates, and all communication is shuffle/broadcast/treeAggregate
(SURVEY.md §2.5 — "Distributed communication backend"). The TPU-native
equivalent is:

- process coordination: ``jax.distributed.initialize`` — every host runs the
  same SPMD program, a coordinator rendezvouses them (this file);
- collectives: XLA over ICI within a slice, DCN across slices — chosen by
  device order in the mesh, not by hand-written NCCL/MPI calls.

``initialize()`` is a thin, idempotent wrapper suitable for CLI drivers:
single-process runs (tests, one-chip benches) skip coordination entirely,
multi-host runs pick up the standard cluster-env variables (GKE/GCE
metadata) or explicit arguments.

``make_hybrid_mesh()`` builds the ("data", "model") mesh the rest of the
framework assumes (parallel/mesh.py), but topology-aware for multi-slice
pods: the "model" (feature/tensor) axis — which carries the per-L-BFGS-step
all-gathers and reduce-scatters of giant fixed-effect coordinates — is laid
out over ICI inside a slice, while the "data" axis (sample/entity DP, one
psum per objective evaluation) spans the slower DCN between slices. This is
the standard scaling-book layout: chatty axes ride fast links.
"""

from __future__ import annotations

import logging

import jax
import numpy as np
from jax.sharding import Mesh

logger = logging.getLogger(__name__)

_INITIALIZED = False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids=None,
) -> None:
    """Idempotently initialize multi-host JAX.

    No-op when nothing indicates a multi-process run (no arguments and no
    cluster environment), so drivers can call it unconditionally — the same
    binary then works on a laptop CPU, one TPU chip, or a multi-host pod
    (the reference's spark-submit local[*] vs YARN split, without the two
    code paths).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    if not explicit:
        import os

        cluster_vars = (
            "COORDINATOR_ADDRESS",  # explicit
            "MEGASCALE_COORDINATOR_ADDRESS",  # multislice
        )
        # TPU_WORKER_HOSTNAMES counts only when it actually lists multiple
        # workers — a single tunnelled chip exports it too, with one entry.
        multi_worker = "," in os.environ.get("TPU_WORKER_HOSTNAMES", "")
        if not (multi_worker or any(os.environ.get(v) for v in cluster_vars)):
            logger.debug("single-process run; skipping jax.distributed")
            return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    except (ValueError, RuntimeError) as e:
        if explicit:
            raise
        # cluster-ish environment but no usable coordinator (e.g. a single
        # tunnelled chip that still exports TPU env vars): run single-process
        logger.warning("jax.distributed auto-init unavailable (%s); "
                       "continuing single-process", e)
        return
    _INITIALIZED = True
    logger.info(
        "jax.distributed initialized: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def make_hybrid_mesh(
    data: int | None = None,
    model: int = 1,
    *,
    devices=None,
) -> Mesh:
    """("data", "model") mesh, topology-aware across slices.

    Single-slice (or CPU) topologies fall back to a plain reshape (identical
    to parallel/mesh.make_mesh). On multi-slice TPU topologies the mesh is
    built with ``mesh_utils.create_hybrid_device_mesh`` so the "model" axis
    stays inside a slice (ICI) and only the "data" axis crosses DCN.
    """
    devices = list(devices if devices is not None else jax.devices())
    if data is None:
        data = len(devices) // model
    if data * model > len(devices):
        raise ValueError(
            f"mesh {data}x{model} needs {data * model} devices, have {len(devices)}"
        )
    devices = devices[: data * model]

    num_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if num_slices > 1:
        from jax.experimental import mesh_utils

        per_slice = len(devices) // num_slices
        if data % num_slices != 0 or model > per_slice:
            raise ValueError(
                f"hybrid mesh {data}x{model} cannot split over {num_slices} "
                "slices: the data axis must be divisible by the slice count "
                "and the model axis must fit inside one slice"
            )
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(data // num_slices, model),
            dcn_mesh_shape=(num_slices, 1),
            devices=devices,
        )
    else:
        grid = np.array(devices).reshape(data, model)
    return Mesh(grid, axis_names=("data", "model"))


def default_put():
    """The host->sharding placement function for the current topology:
    :func:`global_put` when the program spans processes (plain device_put
    cannot target shardings that include other processes' devices),
    ``jax.device_put`` otherwise. The one selection rule shared by the
    training path (distributed.train_distributed) and the scorer."""
    if jax.process_count() > 1:
        return global_put
    return jax.device_put


def global_put(arr, sharding):
    """Place a host array onto a (possibly multi-process) sharding.

    Works where plain ``jax.device_put`` may not: when the sharding spans
    devices of OTHER processes, each process materializes only its
    addressable shards from its own (identical) copy of the full array —
    the standard way to feed replicated host data into a multi-host SPMD
    program. Single-process it degrades to an ordinary placement, so it is
    a drop-in ``put_fn`` for GameTrainProgram.shard_inputs on pods.

    Host numpy inputs are sliced zero-copy; a device-resident input costs
    one device-to-host read first (prepare_inputs materializes pytrees on
    the local device), so at pod scale feed host-built arrays where the
    input pipeline allows.
    """
    value = np.asarray(arr)  # zero-copy for host numpy inputs
    return jax.make_array_from_callback(
        value.shape, sharding, lambda idx: value[idx]
    )
