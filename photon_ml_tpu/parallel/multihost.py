"""Multi-host / multi-slice process coordination and hybrid meshes.

No reference analogue as code: the reference's multi-node story is the
Spark driver/executor runtime (cluster bootstrap belonged to spark-submit
and YARN, not to any photon-ml source file) — YARN launches executors, the
driver coordinates, and all communication is shuffle/broadcast/treeAggregate
(SURVEY.md §2.5 — "Distributed communication backend"). The TPU-native
equivalent is:

- process coordination: ``jax.distributed.initialize`` — every host runs the
  same SPMD program, a coordinator rendezvouses them (this file);
- collectives: XLA over ICI within a slice, DCN across slices — chosen by
  device order in the mesh, not by hand-written NCCL/MPI calls.

``initialize()`` is a thin, idempotent wrapper suitable for CLI drivers:
single-process runs (tests, one-chip benches) skip coordination entirely,
multi-host runs pick up the standard cluster-env variables (GKE/GCE
metadata) or explicit arguments.

``make_hybrid_mesh()`` builds the ("data", "model") mesh the rest of the
framework assumes (parallel/mesh.py), but topology-aware for multi-slice
pods: the "model" (feature/tensor) axis — which carries the per-L-BFGS-step
all-gathers and reduce-scatters of giant fixed-effect coordinates — is laid
out over ICI inside a slice, while the "data" axis (sample/entity DP, one
psum per objective evaluation) spans the slower DCN between slices. This is
the standard scaling-book layout: chatty axes ride fast links.
"""

from __future__ import annotations

import itertools
import json
import logging
import re
import threading

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding

from photon_ml_tpu.telemetry import tracing

logger = logging.getLogger(__name__)

_INITIALIZED = False


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    local_device_ids=None,
) -> None:
    """Idempotently initialize multi-host JAX.

    No-op when nothing indicates a multi-process run (no arguments and no
    cluster environment), so drivers can call it unconditionally — the same
    binary then works on a laptop CPU, one TPU chip, or a multi-host pod
    (the reference's spark-submit local[*] vs YARN split, without the two
    code paths).
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    if not explicit:
        import os

        cluster_vars = (
            "COORDINATOR_ADDRESS",  # explicit
            "MEGASCALE_COORDINATOR_ADDRESS",  # multislice
        )
        # TPU_WORKER_HOSTNAMES counts only when it actually lists multiple
        # workers — a single tunnelled chip exports it too, with one entry.
        multi_worker = "," in os.environ.get("TPU_WORKER_HOSTNAMES", "")
        if not (multi_worker or any(os.environ.get(v) for v in cluster_vars)):
            logger.debug("single-process run; skipping jax.distributed")
            return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        )
    except (ValueError, RuntimeError) as e:
        if explicit:
            raise
        # cluster-ish environment but no usable coordinator (e.g. a single
        # tunnelled chip that still exports TPU env vars): run single-process
        logger.warning("jax.distributed auto-init unavailable (%s); "
                       "continuing single-process", e)
        return
    _INITIALIZED = True
    logger.info(
        "jax.distributed initialized: process %d/%d, %d local / %d global devices",
        jax.process_index(),
        jax.process_count(),
        jax.local_device_count(),
        jax.device_count(),
    )


def make_hybrid_mesh(
    data: int | None = None,
    model: int = 1,
    *,
    devices=None,
) -> Mesh:
    """("data", "model") mesh, topology-aware across slices.

    Single-slice (or CPU) topologies fall back to a plain reshape (identical
    to parallel/mesh.make_mesh). On multi-slice TPU topologies the mesh is
    built with ``mesh_utils.create_hybrid_device_mesh`` so the "model" axis
    stays inside a slice (ICI) and only the "data" axis crosses DCN.
    """
    devices = list(devices if devices is not None else jax.devices())
    if data is None:
        data = len(devices) // model
    if data * model > len(devices):
        raise ValueError(
            f"mesh {data}x{model} needs {data * model} devices, have {len(devices)}"
        )
    devices = devices[: data * model]

    num_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if num_slices > 1:
        from jax.experimental import mesh_utils

        per_slice = len(devices) // num_slices
        if data % num_slices != 0 or model > per_slice:
            raise ValueError(
                f"hybrid mesh {data}x{model} cannot split over {num_slices} "
                "slices: the data axis must be divisible by the slice count "
                "and the model axis must fit inside one slice"
            )
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(data // num_slices, model),
            dcn_mesh_shape=(num_slices, 1),
            devices=devices,
        )
    else:
        grid = np.array(devices).reshape(data, model)
    return Mesh(grid, axis_names=("data", "model"))


def default_put():
    """The host->sharding placement function for the current topology:
    :func:`global_put` when the program spans processes (plain device_put
    cannot target shardings that include other processes' devices),
    ``jax.device_put`` otherwise. The one selection rule shared by the
    training path (distributed.train_distributed) and the scorer."""
    if jax.process_count() > 1:
        return global_put
    return jax.device_put


def global_put(arr, sharding):
    """Place a host array onto a (possibly multi-process) sharding.

    Works where plain ``jax.device_put`` may not: when the sharding spans
    devices of OTHER processes, each process materializes only its
    addressable shards from its own (identical) copy of the full array —
    the standard way to feed replicated host data into a multi-host SPMD
    program. Single-process it degrades to an ordinary placement, so it is
    a drop-in ``put_fn`` for GameTrainProgram.shard_inputs on pods.

    Host numpy inputs are sliced zero-copy; a device-resident input costs
    one device-to-host read first (prepare_inputs materializes pytrees on
    the local device), so at pod scale feed host-built arrays where the
    input pipeline allows.
    """
    value = np.asarray(arr)  # zero-copy for host numpy inputs
    return jax.make_array_from_callback(
        value.shape, sharding, lambda idx: value[idx]
    )


# ---------------------------------------------------------------------------
# Host-side metadata exchange (partitioned I/O)
# ---------------------------------------------------------------------------
#
# The partitioned host-I/O layer (io/partitioned_reader.py,
# io/score_writer.py) needs each rank to agree on SMALL metadata — feature
# keys, entity vocabularies + counts, per-rank row counts, part numbering —
# without any rank reading the other ranks' bytes. The reference gets this
# from Spark's driver (a JVM object broadcast); the TPU-native equivalent
# rides jax.distributed's coordination service KV store: a host-side
# channel that works before (and independently of) any device computation,
# so ingestion metadata can rendezvous while the accelerator program is
# still being built. NOT for bulk data — payloads are JSON and should stay
# well under a few MB; array-sized exchanges belong on the devices.


#: default deadline (seconds) for exchange reads and barriers — generous
#: enough for a slow rank's multi-GB local decode, bounded enough that a
#: wedged run fails attributed instead of hanging a CI/driver forever
DEFAULT_EXCHANGE_TIMEOUT = 120.0


class MetadataExchange:
    """Rank-aware small-payload allgather + barrier for host-side I/O.

    Every rank must make the SAME sequence of calls (SPMD discipline, like
    collectives); tags are namespaced per call site and serialized with an
    internal counter so repeated exchanges never collide.

    Every read/barrier carries a DEADLINE: a rank that never publishes its
    key (crashed, wedged, skipped a collective) surfaces as a
    rank-attributed ``resilience.errors.ExchangeTimeout`` naming the tag,
    the missing key, and the rank expected to publish it — never an
    unbounded hang (ISSUE 3). Retry does NOT belong here: re-waiting one
    rank's exchange while the others do not desynchronizes the SPMD call
    sequence (resilience/policy.py module doc).

    GENERATION FENCING (ISSUE 15, used by resilience/coordinated.py):
    ``set_generation(g)`` moves every subsequent key/barrier id into a
    generation-``g`` namespace AND resets the per-instance call sequence,
    so a restarted attempt (whose ranks died at different points of the
    SPMD sequence, leaving their counters desynchronized) resynchronizes
    at seq 0 of the new generation — and a dead attempt's stale keys,
    living in the old generation's namespace, can never satisfy a new
    generation's get. ``generation=None`` (the default) is the legacy
    unfenced keyspace, byte-identical to pre-ISSUE-15 behavior.

    ABORT MARKERS: ``post_abort(info)`` best-effort-publishes a rank- and
    cause-attributed marker for the CURRENT generation; a fenced wait that
    observes a peer's marker raises a typed
    ``resilience.errors.PeerAbort`` naming the culprit instead of burning
    the full deadline. Markers are written ONLY on the failure path and
    checked only inside waits that are already blocked — a healthy run
    performs ZERO additional exchange operations.
    """

    rank: int = 0
    num_ranks: int = 1
    #: current fence generation (None = unfenced legacy keyspace)
    generation: "int | None" = None
    #: fence EPOCH: distinguishes successive fencing sessions over one
    #: transport (e.g. a driver ``run()`` called twice in one process, each
    #: attaching its own coordinator) — a new session's generation-0 keys
    #: must never collide with a previous session's. Incremented whenever a
    #: NEW fence starts (first ``set_generation``, or a non-increasing
    #: generation); SPMD-consistent because every rank fences at the same
    #: logical points.
    fence_epoch: int = 0

    def allgather(self, tag: str, payload) -> list:
        """All ranks' ``payload``s (JSON-able), ordered by rank."""
        raise NotImplementedError

    def barrier(self, tag: str) -> None:
        """Block until every rank reaches this barrier."""
        raise NotImplementedError

    def set_generation(self, generation: int) -> None:
        """Adopt the generation-``generation`` key namespace and reset the
        per-instance call sequence (every rank calls at the same logical
        point — the coordinator's restart rendezvous — so sequences agree
        again even after a mid-sequence death). A non-increasing generation
        starts a new fence EPOCH (see ``fence_epoch``)."""
        generation = int(generation)
        if self.generation is None or generation <= self.generation:
            self.fence_epoch += 1
        self.generation = generation

    def post_abort(self, info: dict) -> None:
        """Best-effort: publish an abort marker for the current generation
        (``info`` carries at least ``rank`` and ``cause``). Default: no-op
        (no peers to warn)."""

    def pending_abort(self) -> "dict | None":
        """A PEER's abort marker for the current generation, or None.
        Markers this rank posted itself are never returned (the culprit is
        already restarting; it must not abort on its own marker)."""
        return None

    def _shape_marker(self, marker) -> "dict | None":
        """Normalize a raw abort marker for ``pending_abort``: a corrupt
        (non-dict) payload still ends the wait typed and bounded — just
        unattributed (dev/faultinject.abort_marker_corruptor pins this);
        this rank's own marker is invisible."""
        if marker is None:
            return None
        if not isinstance(marker, dict):
            return {"rank": None, "cause": f"unparseable marker {marker!r}"}
        if marker.get("rank") == self.rank:
            return None
        return marker

    def _raise_abort(self, tag: str, marker: dict):
        """Raise the typed, culprit-attributed PeerAbort for ``marker``
        (one construction site for every transport)."""
        from photon_ml_tpu.resilience.errors import PeerAbort

        origin = marker.get("rank")
        raise PeerAbort(
            tag,
            origin_rank=None if origin is None else int(origin),
            cause=str(marker.get("cause", "")),
            generation=self.generation,
            rank=self.rank,
        )


class SingleProcessExchange(MetadataExchange):
    """The trivial exchange: one rank, no waiting. Still traced (zero-wait
    spans) so a single-process timeline shows where exchanges would sit."""

    def allgather(self, tag: str, payload) -> list:
        with tracing.span("exchange/allgather", cat=tracing.EXCHANGE_CAT,
                          tag=tag, rank=self.rank):
            return [payload]

    def barrier(self, tag: str) -> None:
        with tracing.span("exchange/barrier", cat=tracing.EXCHANGE_CAT,
                          tag=tag, rank=self.rank):
            return None


class InProcessExchange(MetadataExchange):
    """N virtual ranks inside one process (threads) — the test/simulation
    transport: lets the partitioned reader/writer run num_ranks>1 flows on
    a single host, e.g. against the virtual CPU mesh."""

    def __init__(self, store: dict, rank: int, num_ranks: int,
                 timeout: float = DEFAULT_EXCHANGE_TIMEOUT):
        self._store = store
        self.rank = rank
        self.num_ranks = num_ranks
        self.timeout = float(timeout)
        # per-instance call counter: repeated exchanges under the SAME tag
        # stay distinct (every rank makes the same sequence of calls — the
        # SPMD discipline — so counters agree), mirroring the KV transport
        self._seq = 0

    @classmethod
    def create_group(
        cls, num_ranks: int, timeout: float = DEFAULT_EXCHANGE_TIMEOUT
    ) -> "list[InProcessExchange]":
        store = {
            "cond": threading.Condition(),
            "gather": {},
        }
        return [cls(store, r, num_ranks, timeout=timeout)
                for r in range(num_ranks)]

    def set_generation(self, generation: int) -> None:
        super().set_generation(generation)
        # resync: every rank adopts the new namespace at the same logical
        # point (the coordinator's restart rendezvous), so resetting the
        # per-instance counter re-agrees the sequences even though the
        # ranks died at different points of the old one
        self._seq = 0

    def post_abort(self, info: dict) -> None:
        cond = self._store["cond"]
        with cond:
            # first writer wins per (epoch, generation): the marker
            # attributes the FIRST failure; a second rank failing in the
            # same window is a casualty, not a new culprit
            self._store.setdefault("aborts", {}).setdefault(
                (self.fence_epoch, self.generation),
                dict(info) if isinstance(info, dict) else info,
            )
            # wake every rank blocked in a wait_for — their predicates
            # consult pending_abort() below
            cond.notify_all()

    def pending_abort(self) -> "dict | None":
        return self._shape_marker(
            self._store.get("aborts", {}).get(
                (self.fence_epoch, self.generation)
            )
        )

    def allgather(self, tag: str, payload) -> list:
        from photon_ml_tpu.resilience.errors import ExchangeTimeout

        key = (self.fence_epoch, self.generation, self._seq, tag)
        self._seq += 1
        cond, slot = self._store["cond"], self._store["gather"]
        # the span OBSERVES the blocking wait (tag + seq + rank for the
        # straggler tables); it never gates or reorders the exchange
        with tracing.span("exchange/allgather", cat=tracing.EXCHANGE_CAT,
                          tag=tag, seq=key[2], rank=self.rank), cond:
            entry = slot.setdefault(key, {})
            entry[self.rank] = payload
            cond.notify_all()
            cond.wait_for(
                lambda: len(slot[key]) == self.num_ranks
                or self.pending_abort() is not None,
                timeout=self.timeout,
            )
            if len(slot[key]) != self.num_ranks:
                marker = self.pending_abort()
                if marker is not None:
                    # a peer declared the attempt dead: fail fast
                    # attributed instead of burning the rest of the
                    # deadline on a rank that is already restarting
                    self._raise_abort(tag, marker)
                missing = [r for r in range(self.num_ranks)
                           if r not in slot[key]]
                raise ExchangeTimeout(
                    tag,
                    missing_ranks=missing,
                    rank=self.rank,
                    timeout=self.timeout,
                    detail=f"{len(slot[key])}/{self.num_ranks} ranks "
                           "published",
                )
            out = [slot[key][r] for r in range(self.num_ranks)]
            # reclaim the slot once every rank has read it (payloads can
            # be sizable — feature-key lists — and exchanges are many)
            reads = self._store.setdefault("reads", {})
            reads[key] = reads.get(key, 0) + 1
            if reads[key] == self.num_ranks:
                del slot[key]
                del reads[key]
            return out

    def barrier(self, tag: str) -> None:
        self.allgather(f"__barrier__/{tag}", None)


#: process-global sequence for KV keys/barrier ids: the coordination
#: service's namespace is process-wide, so two exchange INSTANCES in one
#: process (e.g. a driver run() called twice) must never reuse a key or a
#: barrier id. Every rank constructs/calls exchanges in the same order
#: (SPMD discipline), so the counters agree across processes.
_kv_seq = itertools.count().__next__


#: how jaxlib's coordination-service client spells a missed deadline in
#: the RuntimeError it raises (the TYPE carries no signal)
_KV_DEADLINE_RE = re.compile(r"deadline|timed? ?out", re.IGNORECASE)


class DistributedKVExchange(MetadataExchange):
    """Multi-process transport over jax.distributed's coordination-service
    key-value store (the same rendezvous channel ``initialize`` uses) —
    host-side only, so partitioned ingestion metadata flows even before
    the first device computation.

    Resilience wiring: point-to-point KV set/get operations retry
    classified-transient coordinator errors (resilience/policy.py's KV
    policy — a retried set that finds its key already stored treats the
    first attempt as delivered); a blocking get or barrier that misses
    its deadline raises a rank-attributed
    ``resilience.errors.ExchangeTimeout`` naming the missing key and the
    rank expected to publish it. Barriers are never retried (barrier ids
    are single-use; only the deadline mapping applies).

    ``client``/``rank``/``num_ranks`` are injectable for chaos tests —
    production callers leave them None and get the live coordination
    client.
    """

    def __init__(self, timeout_ms: int = 120_000, *, client=None,
                 rank: int | None = None, num_ranks: int | None = None,
                 retry=None):
        if client is None:
            from jax._src import distributed

            client = distributed.global_state.client
            if client is None:
                raise RuntimeError(
                    "DistributedKVExchange needs jax.distributed.initialize "
                    "(multihost.initialize) to have run first"
                )
        self._client = client
        self._timeout_ms = timeout_ms
        self.rank = jax.process_index() if rank is None else int(rank)
        self.num_ranks = (
            jax.process_count() if num_ranks is None else int(num_ranks)
        )
        if retry is None:
            from photon_ml_tpu.resilience.policy import default_kv_policy

            retry = default_kv_policy()
        self._retry = retry
        #: per-instance sequence, used only in FENCED mode (generation set):
        #: within a generation every rank makes the same call sequence from
        #: the same reset point, so instance counters agree — and the
        #: (session nonce, generation) prefix keeps a restarted attempt —
        #: or a whole later fencing session — out of any dead keyspace.
        #: Unfenced mode keeps the process-global ``_kv_seq`` (two exchange
        #: instances in one process must not collide); fenced sessions get
        #: the same guarantee from the ``_fence_nonce`` drawn off that
        #: counter at fence time. ONE active fenced exchange per process,
        #: which the coordinator owns.
        self._gen_seq = 0
        self._fence_nonce = 0

    #: slice width for fenced blocking waits: between slices the wait
    #: checks the generation's abort key, so a peer's abort surfaces in
    #: ~this long instead of the full deadline. Only expired slices pay
    #: the extra read — a healthy (promptly-published) exchange performs
    #: zero additional operations.
    ABORT_POLL_MS = 500

    def set_generation(self, generation: int) -> None:
        new_fence = self.generation is None or int(
            generation
        ) <= self.generation
        super().set_generation(generation)
        if new_fence:
            # the coordination-service namespace is PROCESS-wide and its
            # barrier ids are single-use, so a second fencing session in
            # one process (driver run() called twice) must not reuse the
            # first session's e/g keyspace: draw the session nonce from
            # the process-global counter. SPMD-consistent — every rank
            # fences at the same logical point, so the draws agree.
            self._fence_nonce = _kv_seq()
        self._gen_seq = 0

    def _namespace(self) -> str:
        return f"e{self._fence_nonce}g{self.generation}"

    def _abort_key(self) -> str:
        return f"photon/abort/{self._namespace()}"

    def post_abort(self, info: dict) -> None:
        try:
            self._client.key_value_set(self._abort_key(), json.dumps(info))
        except RuntimeError as e:
            if "already_exists" in str(e).lower().replace(" ", "_"):
                return  # first writer wins per generation
            # best-effort by contract: the culprit is restarting either
            # way; peers fall back to their deadline (ExchangeTimeout)
            logger.warning("abort-marker write failed: %s", e)

    def pending_abort(self) -> "dict | None":
        if self.generation is None:
            return None
        try_get = getattr(self._client, "key_value_try_get", None)
        try:
            if try_get is not None:
                raw = try_get(self._abort_key())
            else:
                raw = self._client.blocking_key_value_get(
                    self._abort_key(), 1
                )
        except RuntimeError:
            return None  # absent key surfaces as an error: no marker
        try:
            marker = json.loads(raw)
        except (TypeError, ValueError):
            marker = raw  # corrupt payload: shaped unattributed below
        return self._shape_marker(marker)

    def _next_seq(self) -> int:
        if self.generation is None:
            return _kv_seq()
        seq, self._gen_seq = self._gen_seq, self._gen_seq + 1
        return seq

    def _key(self, tag: str, seq: int, rank: int) -> str:
        if self.generation is not None:
            return f"photon/xchg/{self._namespace()}/{seq}/{tag}/{rank}"
        return f"photon/xchg/{seq}/{tag}/{rank}"

    def _barrier_id(self, name: str) -> str:
        if self.generation is not None:
            return f"photon/bar/{self._namespace()}/{name}"
        return f"photon/bar/{name}"

    def _kv_set(self, key: str, value: str) -> None:
        def attempt():
            try:
                self._client.key_value_set(key, value)
            except RuntimeError as e:
                if "already_exists" in str(e).lower().replace(" ", "_"):
                    # a previous attempt's write landed but its ack was
                    # lost; keys are sequence-unique so the value matches
                    return
                raise

        with tracing.span("exchange/kv_set", cat=tracing.EXCHANGE_IO_CAT,
                          key=key, rank=self.rank):
            self._retry.call(attempt, description=f"kv_set {key}")

    def _kv_get(self, key: str, tag: str, expected_rank: int) -> str:
        from photon_ml_tpu.resilience.errors import ExchangeTimeout

        def timeout_error(e):
            return ExchangeTimeout(
                tag,
                key=key,
                missing_ranks=(expected_rank,),
                rank=self.rank,
                timeout=self._timeout_ms / 1000.0,
                detail=str(e),
            )

        def attempt():
            if self.generation is None:
                try:
                    return self._client.blocking_key_value_get(
                        key, self._timeout_ms
                    )
                except RuntimeError as e:
                    if _KV_DEADLINE_RE.search(str(e)):
                        raise timeout_error(e) from e
                    raise
            # fenced mode: slice the deadline so a peer's abort marker
            # surfaces within ~ABORT_POLL_MS instead of the full wait.
            # Only an EXPIRED slice pays the marker read — a promptly-
            # published key costs exactly one get, as before.
            remaining = int(self._timeout_ms)
            last = None
            while remaining > 0:
                chunk = min(self.ABORT_POLL_MS, remaining)
                try:
                    return self._client.blocking_key_value_get(key, chunk)
                except RuntimeError as e:
                    if not _KV_DEADLINE_RE.search(str(e)):
                        raise
                    last = e
                remaining -= chunk
                marker = self.pending_abort()
                if marker is not None:
                    self._raise_abort(tag, marker)
            raise timeout_error(last) from last

        with tracing.span("exchange/kv_get", cat=tracing.EXCHANGE_IO_CAT,
                          key=key, tag=tag, rank=self.rank):
            return self._retry.call(attempt, description=f"kv_get {key}")

    def _wait_barrier(self, barrier_id: str, tag: str) -> None:
        from photon_ml_tpu.resilience.errors import ExchangeTimeout

        try:
            self._client.wait_at_barrier(barrier_id, self._timeout_ms)
        except RuntimeError as e:
            if _KV_DEADLINE_RE.search(str(e)):
                # barrier ids are single-use, so the wait cannot be
                # sliced like a get: check the abort marker once at the
                # deadline so the failure is at least attributed
                marker = self.pending_abort()
                if marker is not None:
                    self._raise_abort(tag, marker)
                raise ExchangeTimeout(
                    tag,
                    key=barrier_id,
                    rank=self.rank,
                    timeout=self._timeout_ms / 1000.0,
                    detail=f"some rank never reached the barrier: {e}",
                ) from e
            raise

    def allgather(self, tag: str, payload) -> list:
        seq = self._next_seq()
        # one wait span per allgather (tag + seq + rank) — the kv_get/
        # kv_set sub-spans nest inside it; the straggler tables read only
        # this outer wait. Observes, never gates.
        with tracing.span("exchange/allgather", cat=tracing.EXCHANGE_CAT,
                          tag=tag, seq=seq, rank=self.rank):
            self._kv_set(self._key(tag, seq, self.rank), json.dumps(payload))
            out = []
            for r in range(self.num_ranks):
                raw = self._kv_get(self._key(tag, seq, r), tag, r)
                out.append(json.loads(raw))
            # every rank has read every key — reclaim our own entry so the
            # coordinator's KV store does not retain one payload per
            # exchange for the process lifetime (feature-key lists can be
            # MBs)
            self._wait_barrier(self._barrier_id(f"xchg-read/{seq}"), tag)
            try:
                self._client.key_value_delete(
                    self._key(tag, seq, self.rank)
                )
            except RuntimeError as e:
                # reclamation is best-effort; a leaked payload must not
                # fail an otherwise-complete exchange
                logger.warning("kv reclaim of %s failed: %s",
                               self._key(tag, seq, self.rank), e)
            return out

    def barrier(self, tag: str) -> None:
        with tracing.span("exchange/barrier", cat=tracing.EXCHANGE_CAT,
                          tag=tag, rank=self.rank):
            self._wait_barrier(
                self._barrier_id(f"{self._next_seq()}/{tag}"), tag
            )


def default_exchange() -> MetadataExchange:
    """The transport for the current topology: coordination-service KV when
    the program spans processes, the trivial exchange otherwise — the
    metadata twin of :func:`default_put`."""
    if jax.process_count() > 1:
        return DistributedKVExchange()
    return SingleProcessExchange()


def assemble_partitioned(
    blocks: "dict[int, np.ndarray]",
    mesh: Mesh,
    spec,
    num_ranks: int,
) -> jax.Array:
    """Global sharded array whose axis 0 is ``num_ranks`` equal-length
    per-rank blocks — each process supplies ONLY the blocks whose rows
    live on its addressable devices, so nothing of global size is ever
    materialized on one host (the partitioned twin of :func:`global_put`,
    built on ``jax.make_array_from_single_device_arrays``).

    blocks: rank -> [block_len, ...] host array; every provided block must
    share shape/dtype. Multi-process callers pass {my_rank: local_block};
    single-process simulations (virtual ranks on one host, tests) pass all
    of them. Requires the device layout to align rank blocks with
    addressable shards: the sharded axis size (num_ranks * block_len) must
    split so no device shard crosses a rank boundary.
    """
    sample = next(iter(blocks.values()))
    block_len = int(sample.shape[0])
    global_shape = (num_ranks * block_len,) + tuple(sample.shape[1:])
    sharding = NamedSharding(mesh, spec)
    arrays = []
    for dev, idx in sharding.addressable_devices_indices_map(
        global_shape
    ).items():
        sl = idx[0]
        start = 0 if sl.start is None else int(sl.start)
        stop = global_shape[0] if sl.stop is None else int(sl.stop)
        r = start // block_len if block_len else 0
        if stop > (r + 1) * block_len:
            raise ValueError(
                f"device shard rows [{start}, {stop}) cross the rank-"
                f"{r} block boundary (block_len={block_len}); pad each "
                "rank's block to a multiple of its local device count"
            )
        if r not in blocks:
            raise ValueError(
                f"device {dev} holds rows of rank {r} but no block for "
                f"that rank was provided (have {sorted(blocks)}); the "
                "mesh's device order must be process-contiguous along the "
                "sharded axis"
            )
        local = blocks[r][start - r * block_len: stop - r * block_len]
        rest = tuple(idx[1:])
        if rest:
            local = local[(slice(None),) + rest]
        arrays.append(jax.device_put(local, dev))
    return jax.make_array_from_single_device_arrays(
        global_shape, sharding, arrays
    )
