"""Sample-sharded dense GLM objective: the one-pass kernel on every device.

Reference parity: the reference's hot loop runs its one-pass seqOp *on every
executor* and merges with treeAggregate
(photon-lib function/glm/ValueAndGradientAggregator.scala:133-154 per-sample
add, :236-251 treeAggregate combine) — distribution and the one-pass loop
compose by construction. The GSPMD path here could not do the same: XLA
cannot partition a ``pallas_call``, so mesh-sharded solves used to forfeit
the single-pass kernel (ops/pallas_glm.py) and fall back to two autodiff
passes over X.

This module restores the composition with ``jax.shard_map``: each mesh
device runs the packed single-pass kernel (or the autodiff path off-TPU) on
its local ``[n/K, d]`` rows, and value / gradient / Σr combine with a psum
over the mesh "data" axis — the XLA collective that replaces
``treeAggregate``. Coefficients stay replicated, so the solver's vector
algebra outside the shard_map is unchanged.

The L2 term is added OUTSIDE the psum (each local objective runs with
l2=0): summing per-device values would count the regularizer K times.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from photon_ml_tpu.parallel.mesh import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.data.batch import LabeledPointBatch
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.ops.normalization import NormalizationContext, no_normalization
from photon_ml_tpu.ops.objective import GLMObjective, BoundObjective

Array = jax.Array


class ShardedDenseGLMObjective:
    """GLM objective over a sample-sharded dense batch on a device mesh.

    Drop-in for :class:`GLMObjective` at every solver call site
    (``bind(batch)`` feeds ``optim.optimizer.solve``): ``value``,
    ``value_and_gradient``, and ``hessian_vector`` each run as one
    ``shard_map`` over the mesh, with the sample axis split along
    ``data_axis`` and everything else (coefficients, normalization factors)
    replicated. Features sharded over a "model" axis are NOT supported here
    — that is the column-sharded objective's job (parallel/column_sharded.py).

    use_pallas: forwarded to the per-device local objective. ``None``
    (default) = the single-pass kernel on TPU, autodiff elsewhere; ``True``
    forces the kernel (interpret mode off-TPU — how the virtual-mesh tests
    exercise this exact code path); ``False`` forces autodiff. The vmap
    hazard that forbids the kernel elsewhere does not apply: the primary FE
    solve is never vmapped, and inside shard_map the batch is an ordinary
    local array.
    """

    def __init__(
        self,
        loss: PointwiseLoss,
        mesh: Mesh,
        l2_weight: float = 0.0,
        normalization: NormalizationContext | None = None,
        use_pallas: bool | None = None,
        data_axis: str = "data",
    ):
        self.loss = loss
        self.mesh = mesh
        self.data_axis = data_axis
        self.l2_weight = float(l2_weight)
        self.normalization = (
            normalization if normalization is not None else no_normalization()
        )
        # Local objective computes the DATA term only (l2=0, no axis_name):
        # the psum and the once-only L2 happen out here.
        self._local = GLMObjective(
            loss, l2_weight=0.0, normalization=self.normalization,
            use_pallas=use_pallas,
        )

    # Value-based identity so jit static-arg caching works across repeated
    # construction (same contract as GLMObjective._key).
    def _key(self):
        return (type(self.loss), self.l2_weight, self.data_axis,
                id(self.mesh), id(self.normalization), self._local.use_pallas)

    def __eq__(self, other):
        return (
            isinstance(other, ShardedDenseGLMObjective)
            and self._key() == other._key()
        )

    def __hash__(self):
        return hash(self._key())

    # -- plumbing ------------------------------------------------------------

    def _pad(self, batch: LabeledPointBatch) -> LabeledPointBatch:
        """Rows must split evenly over the data axis; zero-weight padding
        rows contribute nothing (train_distributed pads datasets up front,
        so this is a no-op there — it exists for direct callers)."""
        k = int(self.mesh.shape[self.data_axis])
        n = batch.num_samples
        if n % k == 0:
            return batch
        return batch.pad_to(n + (-n) % k)

    def _spec(self):
        da = self.data_axis
        return dict(
            mesh=self.mesh,
            in_specs=(P(), P(da, None), P(da), P(da), P(da)),
            check_vma=False,
        )

    def _args(self, batch: LabeledPointBatch):
        return batch.features, batch.labels, batch.offsets, batch.weights

    def _l2_value(self, w: Array) -> Array:
        return 0.5 * self.l2_weight * jnp.vdot(w, w)

    # -- the objective surface the solvers consume ---------------------------

    def value(self, w: Array, batch: LabeledPointBatch) -> Array:
        batch = self._pad(batch)

        def f(w_, x, y, o, ws):
            local = self._local.value(w_, LabeledPointBatch(x, y, o, ws))
            return jax.lax.psum(local, self.data_axis)

        total = shard_map(f, out_specs=P(), **self._spec())(
            w, *self._args(batch)
        )
        if self.l2_weight > 0.0:
            total = total + self._l2_value(w)
        return total

    def value_and_gradient(
        self, w: Array, batch: LabeledPointBatch
    ) -> tuple[Array, Array]:
        batch = self._pad(batch)

        def f(w_, x, y, o, ws):
            v, g = self._local.value_and_gradient(
                w_, LabeledPointBatch(x, y, o, ws)
            )
            return (
                jax.lax.psum(v, self.data_axis),
                jax.lax.psum(g, self.data_axis),
            )

        value, grad = shard_map(f, out_specs=(P(), P()), **self._spec())(
            w, *self._args(batch)
        )
        if self.l2_weight > 0.0:
            value = value + self._l2_value(w)
            grad = grad + self.l2_weight * w
        return value, grad

    def hessian_vector(
        self, w: Array, v: Array, batch: LabeledPointBatch
    ) -> Array:
        batch = self._pad(batch)

        def f(w_, v_, x, y, o, ws):
            hv = self._local.hessian_vector(
                w_, v_, LabeledPointBatch(x, y, o, ws)
            )
            return jax.lax.psum(hv, self.data_axis)

        spec = self._spec()
        spec["in_specs"] = (P(),) + spec["in_specs"]
        hv = shard_map(f, out_specs=P(), **spec)(
            w, v, *self._args(batch)
        )
        if self.l2_weight > 0.0:
            hv = hv + self.l2_weight * v
        return hv

    def bind(self, batch: LabeledPointBatch) -> BoundObjective:
        return BoundObjective(self, batch)
