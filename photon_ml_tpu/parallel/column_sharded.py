"""Column-sharded (model-parallel) sparse GLM training for giant d.

The reference's scale claim — "hundreds of billions of coefficients"
(README.md:77) — rests on Spark hash-partitioning feature sub-spaces across
executors and aggregating per-partition gradients
(function/glm/ValueAndGradientAggregator.scala:133-154 is the per-partition
sparse axpy; DistributedObjectiveFunction drives treeAggregate over them).
The TPU-native equivalent: partition the COO entries BY COLUMN BLOCK over
the mesh "model" axis so each device owns a contiguous coefficient range
and exactly the entries that touch it. Per evaluation:

    local partial margins  (gather + row segment-sum over OWN entries)
    -> psum over "model"  (the treeAggregate)
    -> pointwise loss (replicated, O(n))
    -> OWN-column gradient block, scatter-free (sorted-run prefix sums)

Nothing of size d is ever replicated: coefficients, gradient, solver work
vectors, and the per-column run bounds all live sharded P("model"). At
d = 10⁹ the f32 coefficient vector alone is 4 GB — this layout is the only
way it trains on real chips, and it is exactly the scaling-book "shard the
big axis, psum the small one" recipe: the [n] margin psum is the sole
collective, riding ICI.

The ``shard_map`` program keeps per-device compute identical to the
single-chip sorted-run path (ops/sparse_objective.py), so the LBFGS/OWLQN/
TRON solvers run UNCHANGED over the sharded vectors — their dots and
axpys lower to per-shard ops + psums under jit.
"""

from __future__ import annotations

import dataclasses
import logging
from functools import partial

import flax.struct
import jax
import jax.numpy as jnp

from photon_ml_tpu.parallel.mesh import shard_map
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.data.sparse_batch import (
    SparseShard,
    _hybrid_arrays,
    resolve_hybrid_policy,
)
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.ops.sparse_objective import _sorted_run_sums
from photon_ml_tpu.telemetry.layout import record_block_head

Array = jax.Array

logger = logging.getLogger(__name__)


@flax.struct.dataclass
class ColumnShardedSparseBatch:
    """Flat-COO entries grouped into per-device column blocks.

    Entry arrays are [K, m]: K column blocks (sharded over "model"), each
    padded to the widest block's m entries (pad entries carry value 0).
    Column ids are LOCAL to the block (col - k·block). Two sorted layouts
    of the same entries: row-sorted (margins) and column-sorted with run
    bounds (gradient/Hv, scatter-free).

    dim is the true coefficient count; block·K >= dim — coefficients beyond
    dim are padding lanes pinned at 0 by zero data + L2.
    """

    values: Array       # [K, m] row-sorted within block
    local_cols: Array   # [K, m] int32
    row_ids: Array      # [K, m] int32
    vals_by_col: Array  # [K, m] column-sorted within block
    rows_by_col: Array  # [K, m] int32
    local_bounds: Array  # [K, block+1] int32 run boundaries
    labels: Array       # [n]
    offsets: Array      # [n]
    weights: Array      # [n]
    dim: int = flax.struct.field(pytree_node=False)
    block: int = flax.struct.field(pytree_node=False)
    #: optional hybrid dense-head view (data/sparse_batch.HybridPolicy
    #: builder rule applied globally): each block's slice of the hot
    #: column set rides a dense [n, h] sub-block with LOCAL column ids —
    #: the head is "model"-sharded by the same contiguous-range rule as
    #: the tail, so each device still owns exactly the entries that touch
    #: its coefficient range. Pad slots carry local col 0 over an all-zero
    #: column (inert in gather and scatter). The COO/column-sorted arrays
    #: then hold ONLY the cold residual tail. None = hybrid off (the
    #: existing layout, bitwise unchanged).
    hot_vals: Array | None = None        # [K, n, h]
    hot_local_cols: Array | None = None  # [K, h] int32

    @property
    def has_hot_head(self) -> bool:
        return self.hot_vals is not None

    @property
    def num_blocks(self) -> int:
        return int(self.values.shape[0])

    @property
    def num_samples(self) -> int:
        return int(self.labels.shape[0])

    @property
    def padded_dim(self) -> int:
        return self.num_blocks * self.block

    @property
    def dtype(self):
        return self.values.dtype


def _block_hot_head(
    hot_block: np.ndarray, hot_ids: np.ndarray, k: int, block: int
) -> tuple[np.ndarray, np.ndarray]:
    """Regroup a global [n, k_hot] hot head into per-block [K, n, h] dense
    sub-blocks with LOCAL column ids — the same contiguous-range rule the
    tail's column blocks follow. Pad slots (h padding, and the global
    head's own lane padding) carry local col 0 over an all-zero column."""
    n = hot_block.shape[0]
    kh = hot_ids.shape[0]
    blk = (hot_ids // block).astype(np.int64)
    local = (hot_ids - blk * block).astype(np.int64)
    counts = np.bincount(blk, minlength=k) if kh else np.zeros(k, np.int64)
    h = max(int(counts.max(initial=0)), 1)
    starts = np.zeros(k + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    # hot_ids are sorted, so each block's ids are contiguous in the input
    slot = np.arange(kh) - starts[blk]
    out_v = np.zeros((k, n, h), dtype=hot_block.dtype)
    out_c = np.zeros((k, h), dtype=np.int32)
    if kh:
        out_v[blk, :, slot] = hot_block.T
        out_c[blk, slot] = local
    return out_v, out_c


def build_column_sharded_batch(
    shard: SparseShard,
    labels,
    num_blocks: int,
    *,
    offsets=None,
    weights=None,
    hybrid=None,
) -> ColumnShardedSparseBatch:
    """Group a SparseShard's entries into ``num_blocks`` column blocks.

    Host-side analogue of the reference's feature-space hash partitioner —
    except blocks are CONTIGUOUS ranges so each device's run bounds stay a
    dense [block+1] slice and locality survives (hash partitioning would
    randomize columns across devices and kill the sorted-run reduction).

    hybrid: None (default) inherits the shard's attached ``hybrid_policy``;
    False forces it off; a HybridPolicy/True enables the dense hot head —
    selected GLOBALLY by the same nnz ranking as the single-chip builder,
    then "model"-sharded per block alongside the cold tail.
    """
    rows, cols, vals = shard.coalesced()
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    vals = np.asarray(vals)
    n, dim = shard.shape
    k = int(num_blocks)
    block = -(-dim // k)

    policy = (
        shard.hybrid_policy if hybrid is None else resolve_hybrid_policy(hybrid)
    )
    hot_extra = {}
    if policy is not None:
        # pad=False: lane padding would land every duplicate pad id in the
        # last hot column's block and inflate the per-block width; blocks
        # re-pad to their own widest count below
        hot_block, hot_ids, rows, cols, vals = _hybrid_arrays(
            rows, cols, vals, n, dim, policy, pad=False
        )
        hv3, hc2 = _block_hot_head(hot_block, hot_ids, k, block)
        # every block pads to the widest block's hot count: hot ids
        # clustered into few contiguous blocks (e.g. insertion-ordered
        # index maps) blow the [K, n, h] head up toward K× the global
        # head — surface it instead of silently multiplying HBM/compute
        record_block_head(
            policy.label, width=hv3.shape[2], num_blocks=k,
            k_hot_padded=hot_ids.shape[0],
        )
        if hot_ids.shape[0] and hv3.shape[2] * k > 2 * hot_ids.shape[0]:
            logger.warning(
                "hybrid hot head is clustered across column blocks: "
                "per-block width %d x %d blocks vs %d global hot columns "
                "(%.1fx replicated zeros); a hashed/shuffled feature id "
                "assignment spreads the head",
                hv3.shape[2], k, hot_ids.shape[0],
                hv3.shape[2] * k / hot_ids.shape[0],
            )
        hot_extra = dict(
            hot_vals=jnp.asarray(hv3),
            hot_local_cols=jnp.asarray(hc2, dtype=jnp.int32),
        )

    blk = (cols // block).astype(np.int64)
    local = (cols - blk * block).astype(np.int64)
    counts = np.bincount(blk, minlength=k)
    m = max(int(counts.max(initial=0)), 1)

    def grouped(order_keys) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """[K, m] (vals, other, localcol) laid out by block in the given
        within-block order; pads carry value 0 / index 0."""
        order = np.lexsort(order_keys + (blk,))
        b, r, c, v = blk[order], rows[order], local[order], vals[order]
        starts = np.zeros(k + 1, dtype=np.int64)
        np.cumsum(counts, out=starts[1:])
        pos = np.arange(len(b)) - starts[b]
        out_v = np.zeros((k, m), dtype=vals.dtype)
        # pad slots: value 0 with the LAST row id (keeps per-block row ids
        # sorted for the margins' segment-sum promise) and local col 0
        out_r = np.full((k, m), max(n - 1, 0), dtype=np.int32)
        out_c = np.zeros((k, m), dtype=np.int32)
        out_v[b, pos] = v
        out_r[b, pos] = r
        out_c[b, pos] = c
        return out_v, out_r, out_c

    # row-sorted within block (margins' per-row segment sum wants sorted rows)
    v_row, r_row, c_row = grouped((local, rows))
    # column-sorted within block (gradient's run reduction)
    v_col, r_col, c_col = grouped((rows, local))
    # run bounds per block over local columns, from the TRUE entries only
    # (pad slots carry local col 0 and would corrupt counts): one combined
    # bincount over (block, local) keys instead of a per-block scan
    col_counts = np.bincount(
        blk * block + local, minlength=k * block
    ).reshape(k, block)
    bounds = np.zeros((k, block + 1), dtype=np.int64)
    np.cumsum(col_counts, axis=1, out=bounds[:, 1:])
    dtype = vals.dtype
    labels = np.asarray(labels, dtype=dtype)
    offsets = (
        np.zeros(n, dtype) if offsets is None else np.asarray(offsets, dtype)
    )
    weights = (
        np.ones(n, dtype) if weights is None else np.asarray(weights, dtype)
    )
    return ColumnShardedSparseBatch(
        values=jnp.asarray(v_row),
        local_cols=jnp.asarray(c_row),
        row_ids=jnp.asarray(r_row),
        vals_by_col=jnp.asarray(v_col),
        rows_by_col=jnp.asarray(r_col),
        local_bounds=jnp.asarray(bounds, dtype=jnp.int32),
        labels=jnp.asarray(labels),
        offsets=jnp.asarray(offsets),
        weights=jnp.asarray(weights),
        dim=int(dim),
        block=int(block),
        **hot_extra,
    )


def shard_column_batch(batch: ColumnShardedSparseBatch, mesh: Mesh,
                       put_fn=None) -> ColumnShardedSparseBatch:
    """Place the block axis over "model", per-sample vectors replicated.

    (A 2-D data×model layout would additionally shard [n]; the giant-d
    regime is model-bound — n·4 bytes is small next to d·4 — so replicated
    sample vectors keep the psum a plain ICI all-reduce.)"""
    put = put_fn if put_fn is not None else jax.device_put
    mdl = NamedSharding(mesh, P("model", None))
    rep = NamedSharding(mesh, P())
    hot_extra = {}
    if batch.has_hot_head:
        # the hot head shards over "model" with the tail (each device owns
        # its blocks' hot columns); the sample axis stays unsharded like
        # every other per-sample dimension here
        hot_extra = dict(
            hot_vals=put(batch.hot_vals,
                         NamedSharding(mesh, P("model", None, None))),
            hot_local_cols=put(batch.hot_local_cols, mdl),
        )
    return batch.replace(
        **hot_extra,
        values=put(batch.values, mdl),
        local_cols=put(batch.local_cols, mdl),
        row_ids=put(batch.row_ids, mdl),
        vals_by_col=put(batch.vals_by_col, mdl),
        rows_by_col=put(batch.rows_by_col, mdl),
        local_bounds=put(batch.local_bounds, mdl),
        labels=put(batch.labels, rep),
        offsets=put(batch.offsets, rep),
        weights=put(batch.weights, rep),
    )


class ColumnShardedGLMObjective:
    """BoundObjective-compatible GLM objective over a column-sharded batch.

    value / value_and_grad / hessian_vector run as one ``shard_map`` over
    the mesh "model" axis; coefficients and gradients are [K·block] arrays
    sharded P("model"). Feed ``bind(batch)`` to ``optim.optimizer.solve``
    like any other objective — the solvers' vector algebra stays sharded.
    """

    def __init__(self, loss: PointwiseLoss, mesh: Mesh,
                 l2_weight: float = 0.0):
        self.loss = loss
        self.mesh = mesh
        self.l2_weight = float(l2_weight)

    def _key(self):
        return (type(self.loss), self.l2_weight, id(self.mesh))

    def __eq__(self, other):
        return (
            isinstance(other, ColumnShardedGLMObjective)
            and self._key() == other._key()
        )

    def __hash__(self):
        return hash(self._key())

    def _shard_spec(self, hot: bool = False):
        e = P("model", None)
        hot_specs = (P("model", None, None), e) if hot else ()
        return dict(
            mesh=self.mesh,
            in_specs=(P("model"),) + hot_specs
            + (e, e, e, e, e, e, P(), P(), P()),
            check_vma=False,
        )

    def _check_blocks(self, batch: ColumnShardedSparseBatch) -> None:
        """The shard_map bodies consume exactly ONE block per device
        (``values[0]``); any other blocks-per-device ratio would silently
        drop entries — fail loudly instead."""
        model = int(self.mesh.shape["model"])
        if batch.num_blocks != model:
            raise ValueError(
                f"batch has {batch.num_blocks} column blocks but the mesh "
                f"'model' axis is {model}; build the batch with "
                f"num_blocks={model}"
            )

    # -- margins (the psum'd treeAggregate) ---------------------------------

    @staticmethod
    def _local_margins(w_l, values, local_cols, row_ids, n: int,
                       hot_vals=None, hot_cols=None) -> Array:
        contrib = values * w_l[local_cols]
        partial = jax.ops.segment_sum(
            contrib, row_ids, num_segments=n, indices_are_sorted=True
        )
        if hot_vals is not None:
            # dense hot head: one [n, h] matvec against this block's own
            # coefficient slice (pad columns are zero — inert)
            partial = partial + hot_vals @ w_l[hot_cols]
        return jax.lax.psum(partial, "model")

    @staticmethod
    def _unpack(hot: bool, args):
        """(hot_vals, hot_cols, tail-and-sample args) from a shard_map
        argument list that carries the hot head only when present."""
        if hot:
            return args[0], args[1], args[2:]
        return None, None, args

    def value(self, w: Array, batch: ColumnShardedSparseBatch) -> Array:
        self._check_blocks(batch)
        n = batch.num_samples
        hot = batch.has_hot_head

        def f(w_l, *args):
            hv, hc, (values, local_cols, row_ids, vbc, rbc, bounds,
                     labels, offsets, weights) = self._unpack(hot, args)
            margins = self._local_margins(
                w_l[0], values[0], local_cols[0], row_ids[0], n,
                hot_vals=None if hv is None else hv[0],
                hot_cols=None if hc is None else hc[0],
            ) + offsets
            total = jnp.sum(weights * self.loss.loss(margins, labels))
            if self.l2_weight > 0.0:
                total = total + 0.5 * self.l2_weight * jax.lax.psum(
                    jnp.vdot(w_l, w_l), "model"
                )
            return total

        return shard_map(
            f, out_specs=P(), **self._shard_spec(hot)
        )(w.reshape(batch.num_blocks, batch.block), *self._batch_args(batch))

    def value_and_gradient(
        self, w: Array, batch: ColumnShardedSparseBatch
    ) -> tuple[Array, Array]:
        self._check_blocks(batch)
        n = batch.num_samples
        hot = batch.has_hot_head

        def f(w_l, *args):
            hv, hc, (values, local_cols, row_ids, vbc, rbc, bounds,
                     labels, offsets, weights) = self._unpack(hot, args)
            margins = self._local_margins(
                w_l[0], values[0], local_cols[0], row_ids[0], n,
                hot_vals=None if hv is None else hv[0],
                hot_cols=None if hc is None else hc[0],
            ) + offsets
            losses, dz = self.loss.loss_and_dz(margins, labels)
            total = jnp.sum(weights * losses)
            dzw = weights * dz
            contrib = dzw[rbc[0]] * vbc[0]
            g_l = _sorted_run_sums(contrib, bounds[0])
            if hv is not None:
                # head transpose: ONE [n]·[n, h] matvec + an h-sized
                # scatter into this block's gradient slice
                g_l = g_l.at[hc[0]].add(dzw @ hv[0])
            if self.l2_weight > 0.0:
                total = total + 0.5 * self.l2_weight * jax.lax.psum(
                    jnp.vdot(w_l, w_l), "model"
                )
                g_l = g_l + self.l2_weight * w_l[0]
            return total, g_l[None, :]

        value, grad = shard_map(
            f, out_specs=(P(), P("model", None)), **self._shard_spec(hot)
        )(w.reshape(batch.num_blocks, batch.block), *self._batch_args(batch))
        return value, grad.reshape(-1)

    def hessian_vector(
        self, w: Array, v: Array, batch: ColumnShardedSparseBatch
    ) -> Array:
        """H v = Xᵀ diag(w_i l''_i) X v (+ λv): forward psum'd Jv, then the
        same local sorted-run transpose — TRON's CG ladder at giant d.
        With a hot head, both directions take the dense-head/sparse-tail
        split (the hybrid CG step of the d=10⁸ bench row)."""
        self._check_blocks(batch)
        n = batch.num_samples
        hot = batch.has_hot_head

        def f(w_l, v_l, *args):
            hv, hc, (values, local_cols, row_ids, vbc, rbc, bounds,
                     labels, offsets, weights) = self._unpack(hot, args)
            hot_kw = dict(
                hot_vals=None if hv is None else hv[0],
                hot_cols=None if hc is None else hc[0],
            )
            margins = self._local_margins(
                w_l[0], values[0], local_cols[0], row_ids[0], n, **hot_kw
            ) + offsets
            jv = self._local_margins(
                v_l[0], values[0], local_cols[0], row_ids[0], n, **hot_kw
            )
            d2w = self.loss.d2z(margins, labels) * weights
            t = d2w * jv
            contrib = t[rbc[0]] * vbc[0]
            hv_l = _sorted_run_sums(contrib, bounds[0])
            if hv is not None:
                hv_l = hv_l.at[hc[0]].add(t @ hv[0])
            if self.l2_weight > 0.0:
                hv_l = hv_l + self.l2_weight * v_l[0]
            return hv_l[None, :]

        spec = self._shard_spec(hot)
        spec["in_specs"] = (P("model"),) + spec["in_specs"]
        k, b = batch.num_blocks, batch.block
        hv = shard_map(f, out_specs=P("model", None), **spec)(
            w.reshape(k, b), v.reshape(k, b), *self._batch_args(batch)
        )
        return hv.reshape(-1)

    @staticmethod
    def _batch_args(batch: ColumnShardedSparseBatch):
        hot = (
            (batch.hot_vals, batch.hot_local_cols)
            if batch.has_hot_head else ()
        )
        return hot + (
            batch.values, batch.local_cols, batch.row_ids,
            batch.vals_by_col, batch.rows_by_col, batch.local_bounds,
            batch.labels, batch.offsets, batch.weights,
        )

    def bind(self, batch: ColumnShardedSparseBatch):
        from photon_ml_tpu.ops.objective import BoundObjective

        return BoundObjective(self, batch)

    # the duck-typed BoundObjective calls value_and_gradient via this alias
    def gradient(self, w: Array, batch) -> Array:
        return self.value_and_gradient(w, batch)[1]


def init_column_sharded_coefficients(
    batch: ColumnShardedSparseBatch, mesh: Mesh, dtype=None
) -> Array:
    """Zero [K·block] coefficient vector laid out P("model") — the solver's
    w0 (and with it every solver work vector) starts sharded."""
    dtype = dtype or batch.dtype
    return jax.device_put(
        jnp.zeros((batch.padded_dim,), dtype=dtype),
        NamedSharding(mesh, P("model")),
    )
