"""photon-ml-tpu: a TPU-native (JAX/XLA/pjit) framework for GLMs and GLMix/GAME models.

A ground-up rebuild of the capabilities of LinkedIn Photon-ML
(reference: /root/reference, Scala/Spark) designed for TPU hardware:

- GLM training (linear / logistic / Poisson regression, smoothed-hinge SVM)
  with L1 / L2 / elastic-net regularization and box constraints.
- Pure-JAX, fully jittable optimizers: L-BFGS, OWL-QN, box-projected L-BFGS,
  and TRON (trust-region Newton with truncated conjugate gradient).
- Feature normalization folded algebraically into the objective so raw data
  is never rewritten (reference: photon-lib function/glm/ValueAndGradientAggregator.scala:36-49).
- GAME/GLMix: fixed-effect + per-entity random-effect coordinates trained by
  block coordinate descent with residual offsets
  (reference: photon-lib algorithm/CoordinateDescent.scala).
- Data parallelism via jax.sharding (Mesh + NamedSharding + psum), replacing
  Spark treeAggregate; entity parallelism via vmap'd local solvers over
  padded entity blocks, replacing per-entity RDD solves.
- Evaluation (AUC, AUPR, RMSE, per-task losses, precision@k, per-query
  variants), hyper-parameter search (Sobol random + Gaussian-process
  Bayesian), model diagnostics, and Avro I/O end to end.
"""

__version__ = "0.1.0"

from photon_ml_tpu.types import TaskType  # noqa: F401

#: Lazy top-level API: the common user-facing names resolve on first access
#: without forcing every subsystem (and its jit compilations) at import time.
_LAZY = {
    "GameEstimator": ("photon_ml_tpu.estimators", "GameEstimator"),
    "FixedEffectCoordinateConfig": ("photon_ml_tpu.estimators", "FixedEffectCoordinateConfig"),
    "RandomEffectCoordinateConfig": ("photon_ml_tpu.estimators", "RandomEffectCoordinateConfig"),
    "MatrixFactorizationCoordinateConfig": ("photon_ml_tpu.estimators", "MatrixFactorizationCoordinateConfig"),
    "train_glm": ("photon_ml_tpu.estimators", "train_glm"),
    "train_glm_grid": ("photon_ml_tpu.estimators", "train_glm_grid"),
    "GameTransformer": ("photon_ml_tpu.transformers", "GameTransformer"),
    "build_game_dataset": ("photon_ml_tpu.data.game_data", "build_game_dataset"),
    "build_random_effect_dataset": ("photon_ml_tpu.data.game_data", "build_random_effect_dataset"),
    "LabeledPointBatch": ("photon_ml_tpu.data.batch", "LabeledPointBatch"),
    "CoordinateOptimizationConfig": ("photon_ml_tpu.algorithm.coordinates", "CoordinateOptimizationConfig"),
    "OptimizerConfig": ("photon_ml_tpu.optim.optimizer", "OptimizerConfig"),
    "OptimizerType": ("photon_ml_tpu.optim.optimizer", "OptimizerType"),
    "NormalizationType": ("photon_ml_tpu.ops.normalization", "NormalizationType"),
    "load_game_model": ("photon_ml_tpu.io.model_io", "load_game_model"),
    "save_game_model": ("photon_ml_tpu.io.model_io", "save_game_model"),
    "read_merged": ("photon_ml_tpu.io.data_reader", "read_merged"),
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module 'photon_ml_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
