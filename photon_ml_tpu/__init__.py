"""photon-ml-tpu: a TPU-native (JAX/XLA/pjit) framework for GLMs and GLMix/GAME models.

A ground-up rebuild of the capabilities of LinkedIn Photon-ML
(reference: /root/reference, Scala/Spark) designed for TPU hardware:

- GLM training (linear / logistic / Poisson regression, smoothed-hinge SVM)
  with L1 / L2 / elastic-net regularization and box constraints.
- Pure-JAX, fully jittable optimizers: L-BFGS, OWL-QN, box-projected L-BFGS,
  and TRON (trust-region Newton with truncated conjugate gradient).
- Feature normalization folded algebraically into the objective so raw data
  is never rewritten (reference: photon-lib function/glm/ValueAndGradientAggregator.scala:36-49).
- GAME/GLMix: fixed-effect + per-entity random-effect coordinates trained by
  block coordinate descent with residual offsets
  (reference: photon-lib algorithm/CoordinateDescent.scala).
- Data parallelism via jax.sharding (Mesh + NamedSharding + psum), replacing
  Spark treeAggregate; entity parallelism via vmap'd local solvers over
  padded entity blocks, replacing per-entity RDD solves.
- Evaluation (AUC, AUPR, RMSE, per-task losses, precision@k, per-query
  variants), hyper-parameter search (Sobol random + Gaussian-process
  Bayesian), model diagnostics, and Avro I/O end to end.
"""

__version__ = "0.1.0"

from photon_ml_tpu.types import TaskType  # noqa: F401
