"""GameTransformer: the scoring facade.

Reference parity: photon-api transformers/GameTransformer.scala:156-298 —
build the GAME dataset view, score with a GameModel (sum of sub-model
scores), optionally run evaluators. The reference scores RDDs across
executors (:156-203); here ``mesh=`` routes scoring through the jitted
SPMD program (parallel/scoring.DistributedScorer) with samples sharded
over "data" and — for column-sharded giant-d models —
``fe_feature_sharded`` putting the FE feature/coefficient axis over
"model", so nothing of size d is ever replicated.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.evaluation.evaluators import EvaluationData, parse_evaluator
from photon_ml_tpu.models.game import GameModel


@dataclasses.dataclass
class ScoredDataset:
    """Per-sample scores + optional evaluation results
    (reference ScoredGameDatum / scoring output)."""

    unique_ids: np.ndarray
    scores: np.ndarray
    evaluations: dict[str, float]


@dataclasses.dataclass
class GameTransformer:
    model: GameModel
    evaluator_specs: Sequence[str] = ()
    #: jax.sharding.Mesh ("data", "model") — scores through the jitted
    #: SPMD scoring program instead of the single-device path
    mesh: object | None = None
    #: shard the (single, or named) FE coordinate's feature axis over the
    #: mesh "model" axis — required to score a column-sharded giant-d model
    fe_feature_sharded: "bool | str" = False
    #: lazily-built DistributedScorer, REUSED across transform calls: its
    #: placed model params are cached per layout (params_for_layouts), so a
    #: multi-dataset scoring run places the model on device once.
    #: init=False: dataclasses.replace(t, model=...) must REBUILD the cache,
    #: never inherit a scorer bound to the old model/mesh
    _scorer: object | None = dataclasses.field(
        default=None, init=False, repr=False, compare=False
    )

    def transform(self, dataset: GameDataset) -> ScoredDataset:
        evaluations: dict[str, float] = {}
        if self.mesh is not None or self.fe_feature_sharded:
            from photon_ml_tpu.parallel.scoring import DistributedScorer

            if self._scorer is None:
                self._scorer = DistributedScorer(
                    self.model, self.mesh,
                    fe_feature_sharded=self.fe_feature_sharded,
                )
            scorer = self._scorer
            # one prepare/score pass; the scores gather regardless (they
            # are the product), so metrics use the exact host evaluators
            # on the gathered vector — gather-free on-mesh evaluation is
            # evaluate_dataset's job (validation-style runs)
            scores, evaluations = scorer.score_and_evaluate(
                dataset, self.evaluator_specs
            )
        else:
            scores = np.asarray(self.model.score_dataset(dataset)) + np.asarray(
                dataset.offsets
            )
        if self.evaluator_specs and not evaluations:
            data = EvaluationData(
                labels=np.asarray(dataset.host_array("labels")),
                offsets=np.asarray(dataset.host_array("offsets")),
                weights=np.asarray(dataset.host_array("weights")),
                ids=dataset.ids,
            )
            for spec in self.evaluator_specs:
                ev = parse_evaluator(spec)
                evaluations[ev.name] = ev.evaluate(scores, data)
        return ScoredDataset(
            unique_ids=dataset.unique_ids, scores=scores, evaluations=evaluations
        )
