"""GameTransformer: the scoring facade.

Reference parity: photon-api transformers/GameTransformer.scala:156-298 —
build the GAME dataset view, score with a GameModel (sum of sub-model
scores), optionally run evaluators.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.evaluation.evaluators import EvaluationData, parse_evaluator
from photon_ml_tpu.models.game import GameModel


@dataclasses.dataclass
class ScoredDataset:
    """Per-sample scores + optional evaluation results
    (reference ScoredGameDatum / scoring output)."""

    unique_ids: np.ndarray
    scores: np.ndarray
    evaluations: dict[str, float]


@dataclasses.dataclass
class GameTransformer:
    model: GameModel
    evaluator_specs: Sequence[str] = ()

    def transform(self, dataset: GameDataset) -> ScoredDataset:
        scores = np.asarray(self.model.score_dataset(dataset)) + np.asarray(dataset.offsets)
        evaluations: dict[str, float] = {}
        if self.evaluator_specs:
            data = EvaluationData(
                labels=np.asarray(dataset.labels),
                offsets=np.asarray(dataset.offsets),
                weights=np.asarray(dataset.weights),
                ids=dataset.ids,
            )
            for spec in self.evaluator_specs:
                ev = parse_evaluator(spec)
                evaluations[ev.name] = ev.evaluate(scores, data)
        return ScoredDataset(
            unique_ids=dataset.unique_ids, scores=scores, evaluations=evaluations
        )
