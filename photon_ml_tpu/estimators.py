"""GameEstimator: the high-level training facade.

Reference parity: photon-api estimators/GameEstimator.scala —
``fit(data, validationData, configs)`` builds per-coordinate datasets
(:496-584), training-loss evaluator (:592-614), validation evaluators
(:624-696), per-coordinate normalization (:698-727), then runs
CoordinateDescent per optimization configuration (:746-828), warm-starting
each configuration from the previous one's model (:352-366).

Also the single-GLM trainer (reference photon-api ModelTraining.scala:55-228):
loop over sorted regularization weights with warm start.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinate_descent import (
    CoordinateDescentResult,
    run_coordinate_descent,
)
from photon_ml_tpu.algorithm.coordinates import (
    Coordinate,
    CoordinateOptimizationConfig,
    FixedEffectCoordinate,
    ModelCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.algorithm.mf_coordinate import (
    MatrixFactorizationCoordinate,
    build_mf_dataset,
)
from photon_ml_tpu.data.batch import LabeledPointBatch, summarize
from photon_ml_tpu.data.game_data import (
    GameDataset,
    build_random_effect_dataset,
)
from photon_ml_tpu.evaluation.evaluators import (
    EvaluationData,
    Evaluator,
    default_evaluator_for_task,
    parse_evaluator,
)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.game import GameModel
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.normalization import (
    NormalizationContext,
    NormalizationType,
    build_normalization,
)
from photon_ml_tpu.data.sparse_batch import SparseLabeledPointBatch, SparseShard
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.sparse_objective import SparseGLMObjective
from photon_ml_tpu.ops.variance import (
    coefficient_variances,
    diag_inverse_from_hessian,
    inverse_of_diagonal,
    resolve_variance_mode_for,
    validate_variance_mode,
)
from photon_ml_tpu.optim.optimizer import (
    OptimizerConfig,
    OptimizerType,
    resolve_auto_optimizer,
    solve,
)
from photon_ml_tpu.telemetry.program_ledger import ledger_jit
from photon_ml_tpu.projector.projectors import ProjectorType
from photon_ml_tpu.types import TaskType

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class FixedEffectCoordinateConfig:
    """Reference: FixedEffectDataConfiguration + optimization config."""

    feature_shard_id: str
    optimization: CoordinateOptimizationConfig


@dataclasses.dataclass(frozen=True)
class RandomEffectCoordinateConfig:
    """Reference: RandomEffectDataConfiguration (:RE type, shard, bounds) +
    optimization config."""

    random_effect_type: str
    feature_shard_id: str
    optimization: CoordinateOptimizationConfig
    active_data_upper_bound: int | None = None
    active_data_lower_bound: int | None = None
    #: reference projector/ProjectorType.scala — INDEX_MAP trains each entity
    #: on its observed feature support; RANDOM on a shared Gaussian sketch
    projector_type: ProjectorType = ProjectorType.IDENTITY
    projected_dim: int | None = None  # RANDOM only
    #: per-entity Pearson feature selection: an entity with c samples keeps
    #: its ceil(ratio*c) best features (reference
    #: numFeaturesToSamplesRatioUpperBound, LocalDataSet.scala:221-280)
    features_to_samples_ratio: float | None = None


@dataclasses.dataclass(frozen=True)
class MatrixFactorizationCoordinateConfig:
    """MF coordinate over a (row entity, col entity) pair — the model family
    the reference declares (README.md:92-95, LatentFactorAvro.avsc) but
    never implemented."""

    row_effect_type: str
    col_effect_type: str
    num_latent_factors: int
    optimization: CoordinateOptimizationConfig
    num_alternations: int = 2
    active_data_upper_bound: int | None = None
    seed: int = 0


CoordinateConfig = (
    FixedEffectCoordinateConfig
    | RandomEffectCoordinateConfig
    | MatrixFactorizationCoordinateConfig
)


@dataclasses.dataclass
class TrainPartition:
    """Partitioned-ingest context for ``GameEstimator`` (multi-process
    runs where ``fit`` receives this rank's LOCAL padded block from
    io/partitioned_reader.py instead of the full dataset).

    info: the reader's PartitionInfo (rank geometry).
    exchange: the run's MetadataExchange (RE bucket structure rides it).
    lane_multiple: per-rank device count along the mesh "data" axis —
        keeps bucket/sample blocks aligned with addressable shards.
    entity_rank_presence: reader diagnostics (RE type -> ranks-per-entity)
        forwarded to the rank-local RE builder's cross-rank warning.
    """

    info: object
    exchange: object
    lane_multiple: int = 1
    entity_rank_presence: Mapping[str, np.ndarray] = dataclasses.field(
        default_factory=dict
    )


@dataclasses.dataclass
class GameEstimator:
    """Trains a GAME model: ordered coordinates, block coordinate descent."""

    task: TaskType
    coordinate_configs: Mapping[str, CoordinateConfig]
    update_sequence: Sequence[str] | None = None
    num_iterations: int = 1
    normalization: NormalizationType = NormalizationType.NONE
    validation_evaluators: Sequence[str] = ()
    locked_coordinates: frozenset[str] = frozenset()
    #: shard id -> index of the intercept column (exempt from normalization,
    #: absorbs the standardization margin shift). Required per shard when
    #: normalization is STANDARDIZATION.
    intercept_indices: Mapping[str, int] = dataclasses.field(default_factory=dict)
    #: optional io.checkpoint.TrainingCheckpointer for mid-training
    #: checkpoint/resume of the coordinate-descent loop (SURVEY.md §5 — a
    #: capability the reference lacks).
    checkpointer: object | None = None
    checkpoint_every: int = 1
    #: set False to ignore an existing checkpoint directory (fresh fit)
    resume: bool = True
    #: pin the partitioned restore to ONE published checkpoint step
    #: (ISSUE 15 coordinated rollback: every rank must restore the step
    #: rank 0 resolved, never its own local newest; 0 = from scratch).
    #: None keeps the newest-intact-step behavior.
    resume_step: int | None = None
    #: raise DivergenceError on non-finite coordinate updates
    check_finite: bool = True
    #: jax.sharding.Mesh ("data", "model") — when set, fit() trains through
    #: the fused mesh-sharded SPMD program (parallel/distributed.py) instead
    #: of the host-loop CD path: one jitted step per sweep spanning every
    #: coordinate, collectives inserted by XLA. This is the cluster-scale
    #: path of the reference (GameTrainingDriver.scala:822-843 →
    #: GameEstimator.fit over Spark executors), reachable from the same
    #: estimator facade.
    mesh: object | None = None
    #: shard the FE coordinate's feature axis over the mesh "model" axis
    #: (giant-d coordinates; requires mesh)
    fe_feature_sharded: bool = False
    #: single-pass Pallas GLM kernel on the primary FE solve. None (default)
    #: = auto: the kernel on TPU — per-device via shard_map when the mesh
    #: has >1 devices, direct when single-device — autodiff elsewhere.
    #: True forces it (interpret mode off-TPU; what the virtual-mesh tests
    #: use), False disables it.
    use_pallas: bool | None = None
    #: optional telemetry.SolverTelemetry: per-coordinate, per-outer-
    #: iteration convergence rows / OptimizationLogEvents from the CD loop
    #: (the drivers thread their run journal + event emitter through here)
    telemetry: object | None = None
    #: partitioned-ingest context (TrainPartition): fit() receives this
    #: rank's LOCAL block and trains through train_partitioned — each rank
    #: feeds only its addressable shards. Requires ``mesh``; v1 supports
    #: dense FE + IDENTITY REs without normalization/validation riders
    #: (see _fit_distributed's guard for the full list).
    partition: "TrainPartition | None" = None

    def fit(
        self,
        dataset: GameDataset,
        validation_dataset: GameDataset | None = None,
        initial_model: GameModel | None = None,
    ) -> CoordinateDescentResult:
        if self.partition is not None and self.mesh is None:
            # the CD path would silently train a full model on this rank's
            # 1/P block — fail before any work
            raise ValueError(
                "partitioned training requires a mesh (the per-rank blocks "
                "feed its addressable shards); pass GameEstimator(mesh=...)"
            )
        if self.mesh is not None:
            return self._fit_distributed(dataset, validation_dataset, initial_model)
        sequence, coordinates = self._build_coordinates(dataset, initial_model)

        train_eval_data = EvaluationData(
            labels=np.asarray(dataset.labels),
            offsets=np.asarray(dataset.offsets),
            weights=np.asarray(dataset.weights),
            ids=dataset.ids,
        )
        validation_scorer = None
        validation_data = None
        evaluators: list[Evaluator] = [parse_evaluator(s) for s in self.validation_evaluators]
        if validation_dataset is not None and evaluators:
            validation_data = EvaluationData(
                labels=np.asarray(validation_dataset.labels),
                offsets=np.asarray(validation_dataset.offsets),
                weights=np.asarray(validation_dataset.weights),
                ids=validation_dataset.ids,
            )

            def validation_scorer(model: GameModel):
                return np.asarray(model.score_dataset(validation_dataset)) + np.asarray(
                    validation_dataset.offsets
                )

        initial_models = dict(initial_model.models) if initial_model is not None else None
        return run_coordinate_descent(
            coordinates,
            sequence,
            self.num_iterations,
            initial_models=initial_models,
            locked_coordinates=self.locked_coordinates,
            training_evaluator=default_evaluator_for_task(self.task),
            training_data=train_eval_data,
            validation_evaluators=evaluators,
            validation_scorer=validation_scorer,
            validation_data=validation_data,
            checkpointer=self.checkpointer,
            checkpoint_every=self.checkpoint_every,
            resume=self.resume,
            check_finite=self.check_finite,
            telemetry=self.telemetry,
        )

    def _build_coordinates(
        self, dataset: GameDataset, initial_model: GameModel | None
    ):
        """The host-loop CD path's coordinate construction, shared by
        ``fit`` and ``refresh``: (sequence, coordinate map) with locked
        coordinates wrapped as ModelCoordinates."""
        sequence = list(self.update_sequence or self.coordinate_configs.keys())
        norms = self._prepare_normalization(dataset)
        coordinates: dict[str, Coordinate] = {}
        for cid in sequence:
            cfg = self.coordinate_configs[cid]
            if cid in self.locked_coordinates:
                if initial_model is None:
                    raise ValueError(
                        f"locked coordinate '{cid}' requires an initial model "
                        "(partial retraining needs a pre-trained model)"
                    )
                coordinates[cid] = ModelCoordinate(
                    coordinate_id=cid,
                    dataset=dataset,
                    model=initial_model.get(cid),
                )
            elif isinstance(cfg, FixedEffectCoordinateConfig):
                coordinates[cid] = FixedEffectCoordinate(
                    coordinate_id=cid,
                    dataset=dataset,
                    feature_shard_id=cfg.feature_shard_id,
                    task=self.task,
                    config=cfg.optimization,
                    normalization=norms.get(cfg.feature_shard_id),
                    intercept_index=self.intercept_indices.get(cfg.feature_shard_id),
                    use_pallas=self.use_pallas,
                )
            elif isinstance(cfg, MatrixFactorizationCoordinateConfig):
                mf_dataset = build_mf_dataset(
                    dataset,
                    cfg.row_effect_type,
                    cfg.col_effect_type,
                    active_data_upper_bound=cfg.active_data_upper_bound,
                    seed=cfg.seed,
                )
                coordinates[cid] = MatrixFactorizationCoordinate(
                    coordinate_id=cid,
                    dataset=dataset,
                    mf_dataset=mf_dataset,
                    task=self.task,
                    config=cfg.optimization,
                    num_latent_factors=cfg.num_latent_factors,
                    num_alternations=cfg.num_alternations,
                    seed=cfg.seed,
                )
            else:
                re_dataset = build_random_effect_dataset(
                    dataset,
                    cfg.random_effect_type,
                    cfg.feature_shard_id,
                    active_data_upper_bound=cfg.active_data_upper_bound,
                    active_data_lower_bound=cfg.active_data_lower_bound,
                    projector_type=cfg.projector_type,
                    projected_dim=cfg.projected_dim,
                    features_to_samples_ratio=cfg.features_to_samples_ratio,
                    # INDEX_MAP (and compact/sparse, which coerces to
                    # INDEX_MAP) + normalization: entity blocks are
                    # rewritten to normalized space at build time (the
                    # reference projects the context per entity,
                    # IndexMapProjectorRDD.scala:134-147)
                    normalization=_build_normalization_for(cfg, dataset, norms),
                )
                coordinates[cid] = RandomEffectCoordinate(
                    coordinate_id=cid,
                    dataset=dataset,
                    re_dataset=re_dataset,
                    task=self.task,
                    config=cfg.optimization,
                    normalization=norms.get(cfg.feature_shard_id),
                    intercept_index=self.intercept_indices.get(cfg.feature_shard_id),
                )
        return sequence, coordinates

    def refresh(
        self,
        dataset: GameDataset,
        resident_model: GameModel,
        policy=None,
        *,
        checkpointer=None,
        fingerprint: dict | None = None,
        resume: bool | None = None,
    ):
        """Incremental retrain (algorithm/refresh.py): re-solve only the
        random-effect entities the policy selects — declared-changed or
        gradient-screened — against frozen residuals from
        ``resident_model``'s scores, warm-started from its coefficients;
        everything unselected carries over bitwise. Strictly opt-in: the
        full-fit ``fit`` path is untouched. Host-loop path only (single
        process, no mesh)."""
        from photon_ml_tpu.algorithm.refresh import (
            RefreshPolicy,
            run_incremental_refresh,
        )

        if self.mesh is not None or self.partition is not None:
            raise ValueError(
                "incremental refresh is the single-process host path; "
                "drop mesh/partition and refresh on one host, or run the "
                "full fused fit to retrain at mesh scale"
            )
        sequence, coordinates = self._build_coordinates(
            dataset, resident_model
        )
        return run_incremental_refresh(
            coordinates,
            sequence,
            resident_model,
            policy if policy is not None else RefreshPolicy(),
            checkpointer=checkpointer if checkpointer is not None
            else self.checkpointer,
            resume=self.resume if resume is None else resume,
            check_finite=self.check_finite,
            telemetry=self.telemetry,
            fingerprint=fingerprint,
        )

    def _check_partition_supported(
        self, sequence, locked, dataset, validation_dataset
    ) -> None:
        """The partitioned-training surface (dense or sparse/hybrid primary
        FE + dense IDENTITY REs, scheduled or not, no global-statistics
        riders) — anything outside it must fail loudly BEFORE any
        rank-local work could silently diverge from the full-read
        semantics."""
        problems: list[str] = []
        if self.mesh is None:
            problems.append("a mesh is required")
        if locked:
            problems.append("locked coordinates")
        if validation_dataset is not None:
            problems.append(
                "validation datasets (score + evaluate partitioned via "
                "parallel/scoring.py instead)"
            )
        if self.normalization != NormalizationType.NONE:
            problems.append(
                "normalization (feature stats would be rank-local)"
            )
        # checkpointing composes since ISSUE 8: train_partitioned gathers
        # the model-sized state on every rank and commits through the
        # rank-0-gated, exchange-barrier'd io.checkpoint.commit_checkpoint,
        # with the partition plan + agreed sparse layout fingerprinted in
        # meta.json (a resume under a different topology fails fast)
        # the primary FE (first trainable fixed effect in the sequence) is
        # the one coordinate that may be sparse — its hybrid head / ELL
        # width were made globally consistent by the partitioned reader
        primary_fe = next(
            (cid for cid in sequence
             if cid not in locked and isinstance(
                 self.coordinate_configs[cid], FixedEffectCoordinateConfig
             )),
            None,
        )
        for cid in sequence:
            cfg = self.coordinate_configs[cid]
            if isinstance(cfg, MatrixFactorizationCoordinateConfig):
                problems.append(f"matrix-factorization coordinate '{cid}'")
                continue
            if isinstance(cfg, RandomEffectCoordinateConfig) and (
                cfg.projector_type != ProjectorType.IDENTITY
                or cfg.features_to_samples_ratio is not None
            ):
                problems.append(
                    f"projected/feature-selected random effect '{cid}'"
                )
            if cfg.optimization.down_sampling_rate < 1.0:
                problems.append(f"down-sampling on '{cid}'")
            if cfg.optimization.compute_variance:
                problems.append(f"compute_variance on '{cid}'")
            if cid != primary_fe and isinstance(
                dataset.feature_shards.get(cfg.feature_shard_id), SparseShard
            ):
                problems.append(
                    f"sparse feature shard on '{cid}' (only the primary "
                    "fixed effect may be sparse)"
                )
        if problems:
            raise ValueError(
                "partitioned training does not support: "
                + "; ".join(sorted(set(problems)))
                + " — use the full-read path for these"
            )

    def _fit_distributed(
        self,
        dataset: GameDataset,
        validation_dataset: GameDataset | None = None,
        initial_model: GameModel | None = None,
    ) -> CoordinateDescentResult:
        """fit() over the fused mesh-sharded SPMD program.

        One jitted step per sweep covers the full coordinate sequence in
        the CONFIGURED ``update_sequence`` order (the fused analogue of
        CoordinateDescent.scala:198-255 — order determines which residuals
        each solve sees), with per-sweep validation scoring and best-model
        tracking — the distributed analogue of run_coordinate_descent.
        Returns the same CoordinateDescentResult shape, so drivers/tuners
        work unchanged.

        Differences from the CD path, by design:
        - the FIRST trainable fixed-effect coordinate in the sequence is
          the primary (the only one that may be sparse / feature-sharded
          over the mesh "model" axis); additional FE coordinates train as
          dense replicated solves inside the same fused step;
        - locked coordinates contribute fixed score offsets (their models
          pass through to the output untouched);
        - variances are computed post-hoc at the final (and best) state:
          for the random-effect coordinates that request them, plus the
          fixed effect whenever any coordinate does.
        """
        from photon_ml_tpu.algorithm.coordinates import (
            ModelCoordinate,
            _solve_config,
        )
        from photon_ml_tpu.parallel.distributed import (
            FixedEffectStepSpec,
            GameTrainProgram,
            MatrixFactorizationStepSpec,
            RandomEffectStepSpec,
            game_model_to_state,
            state_to_game_model,
            train_distributed,
            train_partitioned,
        )

        sequence = list(self.update_sequence or self.coordinate_configs.keys())
        # AUTO resolution needs the solve SHAPE: RE/MF bucket solves are
        # the small-dense Newton-eligible kind, FE solves are not — the
        # spec sites below pass it so AUTO-through-the-estimator behaves
        # exactly like AUTO-through-GameTrainProgram
        task_loss = loss_for_task(self.task)
        locked = set(self.locked_coordinates)
        if locked and initial_model is None:
            raise ValueError(
                "locked coordinates require an initial model "
                "(partial retraining needs a pre-trained model)"
            )
        partition = self.partition
        if partition is not None:
            self._check_partition_supported(
                sequence, locked, dataset, validation_dataset
            )

        fe_ids = [
            cid for cid in sequence
            if cid not in locked
            and isinstance(self.coordinate_configs[cid], FixedEffectCoordinateConfig)
        ]
        # first trainable FE in the sequence is the PRIMARY (the only one
        # that may be sparse / feature-sharded); the rest become dense
        # replicated extra-FE coordinates inside the same fused step
        # (reference GameEstimator.scala:746-828 iterates arbitrary
        # coordinate sets).
        if fe_ids:
            fe_cid = fe_ids[0]
            fe_cfg: FixedEffectCoordinateConfig = self.coordinate_configs[fe_cid]
            fe_shard = fe_cfg.feature_shard_id
        else:
            # RE/MF-only (or locked-FE) layout: the fused step always carries
            # an FE coordinate, so synthesize a zero-width one — the d=0
            # solve is a no-op and its (empty) model is dropped on output
            fe_cid = None
            fe_shard = "__no_fe__"
            while fe_shard in dataset.feature_shards:
                fe_shard = "_" + fe_shard
            fe_cfg = FixedEffectCoordinateConfig(
                fe_shard,
                CoordinateOptimizationConfig(
                    optimizer=OptimizerConfig(max_iterations=1)
                ),
            )
            def with_empty_shard(ds):
                empty = jnp.zeros((ds.num_samples, 0), dtype=np.asarray(ds.labels).dtype)
                return dataclasses.replace(
                    ds, feature_shards={**ds.feature_shards, fe_shard: empty}
                )
            dataset = with_empty_shard(dataset)
            if validation_dataset is not None:
                validation_dataset = with_empty_shard(validation_dataset)
        fe_intercept = self.intercept_indices.get(fe_shard)

        # feature-axis ("model") sharding wants the FE dim divisible by the
        # mesh model axis: right-pad with zero columns (their coefficients
        # stay exactly 0 — zero data column + L2 — and are sliced off again
        # on output)
        fe_pad = 0
        if self.fe_feature_sharded and fe_cid is not None:
            model_axis = int(self.mesh.shape["model"])
            fe_dim = int(dataset.feature_shards[fe_shard].shape[1])
            fe_pad = (-fe_dim) % model_axis
        if fe_pad:
            def with_padded_fe(ds):
                shard = ds.feature_shards[fe_shard]
                host_cache = dict(ds.host_cache)
                if isinstance(shard, SparseShard):
                    shard = dataclasses.replace(
                        shard, feature_dim=shard.feature_dim + fe_pad,
                        _device=None,
                    )
                else:
                    arr = np.asarray(shard)
                    arr = np.concatenate(
                        [arr, np.zeros((arr.shape[0], fe_pad), arr.dtype)],
                        axis=1,
                    )
                    host_cache[f"shard/{fe_shard}"] = arr
                    shard = jnp.asarray(arr)
                return dataclasses.replace(
                    ds,
                    feature_shards={**ds.feature_shards, fe_shard: shard},
                    host_cache=host_cache,
                )
            dataset = with_padded_fe(dataset)
            if validation_dataset is not None:
                validation_dataset = with_padded_fe(validation_dataset)
        norms = self._prepare_normalization(dataset)

        re_specs: list[RandomEffectStepSpec] = []
        re_datasets = {}
        re_cid_of_type: dict[str, str] = {}
        mf_specs: list[MatrixFactorizationStepSpec] = []
        mf_datasets = {}
        re_normalizations: dict[str, NormalizationContext] = {}
        extra_fe_specs: list[FixedEffectStepSpec] = []
        extra_fe_cid_of_shard: dict[str, str] = {}
        for cid in sequence:
            if cid in locked or cid == fe_cid:
                continue
            cfg = self.coordinate_configs[cid]
            if isinstance(cfg, FixedEffectCoordinateConfig):
                if cfg.feature_shard_id in extra_fe_cid_of_shard or (
                    cfg.feature_shard_id == fe_shard
                ):
                    raise ValueError(
                        f"distributed training: fixed-effect coordinates "
                        f"'{cid}' and another share feature shard "
                        f"'{cfg.feature_shard_id}' — the fused step keys FE "
                        "coordinates by feature shard; merge or rename"
                    )
                extra_fe_cid_of_shard[cfg.feature_shard_id] = cid
                extra_fe_specs.append(FixedEffectStepSpec(
                    feature_shard_id=cfg.feature_shard_id,
                    optimizer=_solve_config(
                        cfg.optimization, loss=task_loss
                    ),
                    l2_weight=cfg.optimization.l2_weight,
                    down_sampling_rate=cfg.optimization.down_sampling_rate,
                    intercept_index=self.intercept_indices.get(
                        cfg.feature_shard_id
                    ),
                ))
                continue
            if isinstance(cfg, MatrixFactorizationCoordinateConfig):
                mf_datasets[cid] = build_mf_dataset(
                    dataset, cfg.row_effect_type, cfg.col_effect_type,
                    active_data_upper_bound=cfg.active_data_upper_bound,
                    seed=cfg.seed,
                )
                mf_specs.append(MatrixFactorizationStepSpec(
                    name=cid,
                    row_effect_type=cfg.row_effect_type,
                    col_effect_type=cfg.col_effect_type,
                    num_latent_factors=cfg.num_latent_factors,
                    optimizer=_solve_config(
                        cfg.optimization, loss=task_loss, small_dense=True
                    ),
                    l2_weight=cfg.optimization.l2_weight,
                    num_alternations=cfg.num_alternations,
                    seed=cfg.seed,
                ))
                continue
            re_type = cfg.random_effect_type
            if re_type in re_cid_of_type:
                raise ValueError(
                    f"distributed training: coordinates "
                    f"'{re_cid_of_type[re_type]}' and '{cid}' share random "
                    f"effect type '{re_type}' — the fused step keys its "
                    "coefficient tables by RE type; merge or rename"
                )
            re_cid_of_type[re_type] = cid
            if partition is not None:
                # rank-local buckets with exchanged global structure — the
                # guard above already limited the surface to dense IDENTITY
                from photon_ml_tpu.data.game_data import (
                    build_random_effect_dataset_partitioned,
                )

                re_datasets[re_type] = build_random_effect_dataset_partitioned(
                    dataset, re_type, cfg.feature_shard_id,
                    partition=partition.info,
                    exchange=partition.exchange,
                    active_data_upper_bound=cfg.active_data_upper_bound,
                    active_data_lower_bound=cfg.active_data_lower_bound,
                    lane_multiple=partition.lane_multiple,
                    entity_rank_presence=(
                        partition.entity_rank_presence.get(re_type)
                    ),
                    tag=cid,
                )
            else:
                re_datasets[re_type] = build_random_effect_dataset(
                    dataset, re_type, cfg.feature_shard_id,
                    active_data_upper_bound=cfg.active_data_upper_bound,
                    active_data_lower_bound=cfg.active_data_lower_bound,
                    projector_type=cfg.projector_type,
                    projected_dim=cfg.projected_dim,
                    features_to_samples_ratio=cfg.features_to_samples_ratio,
                    normalization=_build_normalization_for(cfg, dataset, norms),
                )
            norm = norms.get(cfg.feature_shard_id)
            if norm is not None:
                re_normalizations[re_type] = norm
            re_specs.append(RandomEffectStepSpec(
                re_type=re_type,
                feature_shard_id=cfg.feature_shard_id,
                optimizer=_solve_config(
                    cfg.optimization, loss=task_loss, small_dense=True
                ),
                l2_weight=cfg.optimization.l2_weight,
                # the dataset's projector, not the config's: sparse shards
                # coerce to the compact INDEX_MAP representation
                projector=re_datasets[re_type].projector_type,
                intercept_index=self.intercept_indices.get(cfg.feature_shard_id),
            ))

        # Variances are available for every projector: INDEX_MAP/compact in
        # the solve space scattered back with the means
        # (IndexMapProjectorRDD.scala:103); RANDOM propagated through the
        # sketch as diag(P H_k⁻¹ Pᵀ) — an improvement over the reference's
        # unchanged pass-through (ProjectionMatrixBroadcast.scala:76).

        # the fused sweep trains coordinates in the CONFIGURED sequence
        # order (CoordinateDescent.scala:198-255 — order determines which
        # residuals each solve sees); the synthetic zero-width FE (if any)
        # goes first, where it is a no-op
        cid_to_name: dict[str, str] = {}
        if fe_cid is not None:
            cid_to_name[fe_cid] = fe_shard
        cid_to_name.update({cid: sh for sh, cid in extra_fe_cid_of_shard.items()})
        cid_to_name.update({cid: t for t, cid in re_cid_of_type.items()})
        cid_to_name.update({m.name: m.name for m in mf_specs})
        update_order = [cid_to_name[cid] for cid in sequence
                        if cid not in locked]
        if fe_cid is None:
            update_order = [fe_shard] + update_order

        program = GameTrainProgram(
            self.task,
            FixedEffectStepSpec(
                feature_shard_id=fe_shard,
                optimizer=_solve_config(fe_cfg.optimization, loss=task_loss),
                l2_weight=fe_cfg.optimization.l2_weight,
                down_sampling_rate=fe_cfg.optimization.down_sampling_rate,
            ),
            tuple(re_specs),
            mf_specs=tuple(mf_specs),
            extra_fes=tuple(extra_fe_specs),
            update_order=update_order,
            normalization=norms.get(fe_shard),
            re_normalizations=re_normalizations,
            extra_fe_normalizations={
                sh: norms[sh] for sh in extra_fe_cid_of_shard if sh in norms
            },
            # the single-pass kernel reaches the dense FE solve directly on
            # a single-device mesh and via the shard_map wrapper on a
            # multi-device one (the program gates on the mesh)
            use_pallas_fe=self.use_pallas,
            mesh=self.mesh,
            fe_feature_sharded=self.fe_feature_sharded,
        )

        # locked coordinates: fixed residual offsets + pass-through models
        # (reference ModelCoordinate semantics inside one fused program)
        locked_models: dict[str, object] = {}
        train_ds, val_ds = dataset, validation_dataset
        if locked:
            def locked_total(ds) -> jnp.ndarray:
                total = jnp.zeros_like(ds.offsets)
                for cid in sequence:
                    if cid not in locked:
                        continue
                    m = initial_model.get(cid)
                    locked_models[cid] = m
                    total = total + ModelCoordinate(cid, ds, m).score(m)
                return total

            def with_extra_offsets(ds, extra):
                new_off = ds.offsets + extra
                return dataclasses.replace(
                    ds, offsets=new_off,
                    host_cache={**ds.host_cache, "offsets": np.asarray(new_off)},
                )

            train_ds = with_extra_offsets(dataset, locked_total(dataset))
            if validation_dataset is not None:
                val_ds = with_extra_offsets(
                    validation_dataset, locked_total(validation_dataset)
                )

        warm_state = None
        if initial_model is not None:
            # The estimator's GameModel keys are coordinate ids; the program
            # keys the FE by feature shard and REs by effect type. Re-key
            # before conversion — a mismatch here would silently cold-start
            # every coordinate (missing_ok is for genuinely absent ones).
            program_key: dict[str, str] = {}
            if fe_cid is not None:
                program_key[fe_cid] = fe_shard
            program_key.update(
                {cid: sh for sh, cid in extra_fe_cid_of_shard.items()}
            )
            program_key.update({cid: t for t, cid in re_cid_of_type.items()})
            remapped = {
                program_key.get(cid, cid): m
                for cid, m in initial_model.models.items()
            }
            if fe_pad and fe_shard in remapped:
                from photon_ml_tpu.models.game import FixedEffectModel

                means = np.asarray(remapped[fe_shard].glm.coefficients.means)
                means = np.concatenate([means, np.zeros(fe_pad, means.dtype)])
                remapped[fe_shard] = FixedEffectModel(
                    glm=GeneralizedLinearModel(
                        Coefficients(means=jnp.asarray(means)), self.task
                    ),
                    feature_shard_id=fe_shard,
                )
            warm_state = game_model_to_state(
                program, GameModel(models=remapped), train_ds,
                intercept_index=fe_intercept, missing_ok=True,
                re_datasets=re_datasets, mf_datasets=mf_datasets,
            )

        evaluators: list[Evaluator] = [
            parse_evaluator(s) for s in self.validation_evaluators
        ]
        train_eval_data = EvaluationData(
            labels=np.asarray(dataset.host_array("labels")),
            offsets=np.asarray(dataset.host_array("offsets")),
            weights=np.asarray(dataset.host_array("weights")),
            ids=dataset.ids,
        )
        val_eval_data = None
        if validation_dataset is not None and evaluators:
            val_eval_data = EvaluationData(
                labels=np.asarray(validation_dataset.host_array("labels")),
                offsets=np.asarray(validation_dataset.host_array("offsets")),
                weights=np.asarray(validation_dataset.host_array("weights")),
                ids=validation_dataset.ids,
            )

        if partition is not None:
            # this rank contributes only its local block; the fused step
            # sees the assembled global arrays. No validation/metric riders
            # (the guard rejected them) — score + evaluate partitioned via
            # parallel/scoring.py instead. Scheduled RE coordinates compose:
            # multi-process runs get the collective-safe SPMD scheduler.
            from photon_ml_tpu.algorithm.lane_scheduler import make_schedulers

            result = train_partitioned(
                program,
                {partition.info.rank: (train_ds, re_datasets)},
                self.mesh,
                partition.info.num_ranks,
                num_iterations=self.num_iterations,
                state=warm_state,
                fe_feature_sharded=self.fe_feature_sharded,
                check_finite=self.check_finite,
                schedulers=make_schedulers(re_specs, mesh=self.mesh) or None,
                checkpointer=self.checkpointer,
                checkpoint_every=self.checkpoint_every,
                resume=self.resume,
                resume_step=self.resume_step,
                # the ingest exchange also gates the checkpoint commit
                # barriers (exchange-consistent: a checkpoint exists only
                # for sweeps every rank completed)
                exchange=partition.exchange,
            )
        else:
            result = train_distributed(
                program,
                train_ds,
                re_datasets,
                mf_datasets=mf_datasets,
                mesh=self.mesh,
                num_iterations=self.num_iterations,
                fe_feature_sharded=self.fe_feature_sharded,
                state=warm_state,
                checkpointer=self.checkpointer,
                checkpoint_every=self.checkpoint_every,
                resume=self.resume,
                validation_dataset=val_ds if val_eval_data is not None else None,
                validation_evaluators=evaluators,
                validation_eval_data=val_eval_data,
                training_evaluator=default_evaluator_for_task(self.task),
                training_eval_data=train_eval_data,
                check_finite=self.check_finite,
                on_sweep=(
                    None if self.telemetry is None else
                    lambda sweep, total, loss: self.telemetry.heartbeat(
                        "fused_game", sweep=sweep, num_sweeps=total,
                        loss=loss,
                    )
                ),
            )

        trainable_cids = {} if fe_cid is None else {fe_shard: fe_cid}
        trainable_cids.update(extra_fe_cid_of_shard)
        trainable_cids.update(
            {t: cid for t, cid in re_cid_of_type.items()}
        )

        compute_var = any(
            self.coordinate_configs[cid].optimization.compute_variance
            for cid in sequence if cid not in locked
        )
        variance_re_types = {
            t for t, cid in re_cid_of_type.items()
            if self.coordinate_configs[cid].optimization.compute_variance
        }

        def to_game_model(state) -> GameModel:
            m = state_to_game_model(
                program, state, train_ds,
                intercept_index=fe_intercept,
                compute_variance=compute_var,
                variance_mode=fe_cfg.optimization.variance_mode,
                re_datasets=re_datasets,
                variance_re_types=variance_re_types,
            )
            models_by_name = dict(m.models)
            if fe_pad:
                # slice the zero coefficients of the model-axis padding
                # columns back off (persisted models keep the true dim)
                from photon_ml_tpu.models.game import FixedEffectModel

                c = models_by_name[fe_shard].glm.coefficients
                models_by_name[fe_shard] = FixedEffectModel(
                    glm=GeneralizedLinearModel(
                        Coefficients(
                            means=c.means[:-fe_pad],
                            variances=None if c.variances is None
                            else c.variances[:-fe_pad],
                        ),
                        self.task,
                    ),
                    feature_shard_id=fe_shard,
                )
            # re-key from the program's internal names (FE: feature shard
            # id; RE: effect type; MF: coordinate id) to coordinate ids,
            # preserving the update-sequence order — the CD path's contract
            renamed = {
                trainable_cids.get(k, k): v for k, v in models_by_name.items()
                if not (fe_cid is None and k == fe_shard)  # synthetic FE
            }
            renamed.update(locked_models)
            return GameModel(models={
                cid: renamed[cid] for cid in sequence if cid in renamed
            })

        final_model = to_game_model(result.state)
        best_model = (
            to_game_model(result.best_state)
            if result.best_state is not None else final_model
        )
        if self.telemetry is not None:
            # the fused step carries no per-lane solver state out of the
            # SPMD program; report what the sweep loop does surface —
            # per-sweep evaluation metrics under a synthetic coordinate id
            for i, m in enumerate(result.metric_history or []):
                self.telemetry.record_coordinate(
                    "fused-sweep", i, None, metrics=m
                )
        return CoordinateDescentResult(
            model=final_model,
            best_model=best_model,
            best_metric=result.best_metric,
            metric_history=result.metric_history,
        )

    def _prepare_normalization(self, dataset: GameDataset) -> dict[str, NormalizationContext]:
        """Per-feature-shard normalization from feature summaries (reference
        GameTrainingDriver.prepareNormalizationContexts:545-562)."""
        norms: dict[str, NormalizationContext] = {}
        if self.normalization == NormalizationType.NONE:
            return norms
        weights = np.asarray(dataset.weights)
        for shard_id, features in dataset.feature_shards.items():
            intercept = self.intercept_indices.get(shard_id)
            norm_type = self.normalization
            if norm_type == NormalizationType.STANDARDIZATION and intercept is None:
                # Mean-shifting needs an intercept to absorb the margin shift;
                # without one, fall back to variance scaling only (the
                # reference attaches an intercept to every shard by default,
                # FeatureShardConfiguration).
                logger.warning(
                    "shard '%s' has no intercept_indices entry; using "
                    "SCALE_WITH_STANDARD_DEVIATION instead of STANDARDIZATION",
                    shard_id,
                )
                norm_type = NormalizationType.SCALE_WITH_STANDARD_DEVIATION
            if hasattr(features, "summarize"):  # SparseShard: COO stats
                stats = features.summarize(weights)
                dtype = features.dtype
            else:
                feats = np.asarray(features)
                stats = summarize(feats, weights)
                # match the shard dtype: float64 stats scattered into float32
                # coefficient tables would trip jax's strict promotion rules
                dtype = feats.dtype
            norms[shard_id] = build_normalization(
                norm_type,
                mean=jnp.asarray(stats["mean"], dtype=dtype),
                variance=jnp.asarray(stats["variance"], dtype=dtype),
                max_magnitude=jnp.asarray(stats["max_magnitude"], dtype=dtype),
                intercept_index=intercept,
            )
        return norms


def _build_normalization_for(cfg: RandomEffectCoordinateConfig,
                             dataset: GameDataset, norms) -> "NormalizationContext | None":
    """Context to PRE-normalize an RE coordinate's entity blocks at dataset
    build: INDEX_MAP and RANDOM coordinates (RANDOM normalizes BEFORE
    sketching — exact), and sparse shards (which coerce to the compact
    INDEX_MAP representation). IDENTITY coordinates normalize through the
    objective's context instead; one predicate shared by the CD and fused
    paths so they cannot drift."""
    if cfg.projector_type in (
        ProjectorType.INDEX_MAP, ProjectorType.RANDOM
    ) or isinstance(dataset.feature_shards[cfg.feature_shard_id], SparseShard):
        return norms.get(cfg.feature_shard_id)
    return None


def train_glm_grid(
    batch: LabeledPointBatch,
    task: TaskType,
    *,
    optimizer: OptimizerConfig | None = None,
    regularization_weights: Sequence[float] = (0.0,),
    elastic_net_alpha: float = 0.0,
    normalization: NormalizationContext | None = None,
    intercept_index: int | None = None,
    compute_variance: bool = False,
    variance_mode: str = "auto",
    lower_bounds=None,
    upper_bounds=None,
    telemetry=None,
) -> dict[float, GeneralizedLinearModel]:
    """Train the whole regularization grid *simultaneously* with vmapped
    solver lanes.

    telemetry: optional ``telemetry.SolverTelemetry`` — reports per-λ-lane
    convergence rows plus the cross-lane convergence-reason tally (the
    "every lane pays max_iter" pathology made visible, CLAUDE.md).

    TPU-native alternative to the reference's sequential warm-start fold
    (ModelTraining.scala:202-220, mirrored by :func:`train_glm`): all λ
    lanes share every read of the `[n, d]` feature block, so the per-lane
    margin computation becomes one `X @ W` matmul on the MXU instead of |λ|
    separate matvecs — on HBM-bandwidth-bound problems this trains the full
    grid in roughly the time of one member (measured ~66x the sequential
    iteration rate at n=262k, d=512, 8 lanes). The trade: lanes start cold
    instead of warm-starting from the previous λ, costing a few extra
    iterations each — a price the MXU amortizes away.

    λ enters the objective as a *traced* per-lane value (the smooth L2 term
    and OWL-QN's l1_weight both accept tracers), so one compiled program
    serves any grid of the same size. Supports LBFGS and OWLQN lanes
    (elastic net included); TRON's trust-region loop is per-lane scalar
    control flow and stays on the sequential path.

    The lane-varying-L2-only special case of the config tournaments in
    algorithm/lane_search.py (per-lane l1/l2/tolerance/box vectors, warm
    starts — the GP model-search substrate); a uniform-config tournament is
    pinned bitwise-identical to this path (tests/test_lane_search.py).
    """
    optimizer = resolve_auto_optimizer(optimizer or OptimizerConfig())
    if optimizer.optimizer_type not in (
        OptimizerType.LBFGS, OptimizerType.OWLQN
    ):
        raise ValueError(
            "train_glm_grid supports LBFGS/OWLQN lanes; use train_glm for "
            f"{optimizer.optimizer_type.name}"
        )
    use_owlqn = (
        elastic_net_alpha > 0.0
        or optimizer.optimizer_type == OptimizerType.OWLQN
    )
    has_bounds = lower_bounds is not None or upper_bounds is not None
    if use_owlqn and has_bounds:
        raise ValueError(
            "box constraints cannot combine with OWL-QN / elastic-net lanes"
        )
    loss = loss_for_task(task)
    objective = _objective_for_batch(batch, loss, 0.0, normalization)
    # cheap typo check always; the full-vs-diagonal capability resolution
    # (L full Hessians at once; sparse objectives are diagonal-only) only
    # matters — and should only be able to fail — when variances are
    # actually requested
    validate_variance_mode(variance_mode)
    resolved_variance = None
    if compute_variance:
        resolved_variance = resolve_variance_mode_for(
            objective, variance_mode, batch.dim,
            num_problems=len(regularization_weights),
        )
    dtype = batch.solve_dtype
    lams = sorted(float(l) for l in regularization_weights)
    l2s = jnp.asarray([(1.0 - elastic_net_alpha) * l for l in lams], dtype)
    # Mirror the sequential path's L1 rule (train_glm): the elastic-net
    # component overrides the config's own l1_weight when alpha > 0.
    if elastic_net_alpha > 0.0:
        l1s = jnp.asarray([elastic_net_alpha * l for l in lams], dtype)
    else:
        l1s = jnp.full((len(lams),), optimizer.l1_weight, dtype)

    bounds = (
        jnp.asarray(lower_bounds, dtype) if lower_bounds is not None
        else jnp.full((batch.dim,), -jnp.inf, dtype),
        jnp.asarray(upper_bounds, dtype) if upper_bounds is not None
        else jnp.full((batch.dim,), jnp.inf, dtype),
    ) if has_bounds else None
    results = _jitted_grid_solve(
        objective, use_owlqn, optimizer.history,
        optimizer.max_iterations, optimizer.tolerance,
        optimizer.rel_function_tolerance, batch, l2s, l1s,
        bounds,
    )
    if telemetry is not None:
        telemetry.record_lanes(
            "glm-grid", results, keys=[{"lambda": lam} for lam in lams]
        )
    norm = objective.normalization
    lane_variances = None
    if compute_variance:
        if resolved_variance == "full":
            # reference-fidelity diag(H⁻¹) per lane; the [L, d, d] Hessian
            # stack shares one read of the feature block
            lane_variances = _jitted_grid_full_variances(
                objective, batch, results.coefficients, l2s
            )
        else:
            diags = _jitted_grid_diagonals(
                objective, batch, results.coefficients, l2s
            )
            lane_variances = inverse_of_diagonal(diags)
    models: dict[float, GeneralizedLinearModel] = {}
    for i, lam in enumerate(lams):
        w = results.coefficients[i]
        means = norm.to_model_space(w, intercept_index)
        variances = None
        if lane_variances is not None:
            variances = norm.variances_to_model_space(lane_variances[i])
        models[lam] = GeneralizedLinearModel(
            Coefficients(means=means, variances=variances), task
        )
    return models


@functools.partial(ledger_jit, label="glm/grid_solve", static_argnums=(0, 1, 2, 3, 4, 5))
def _jitted_grid_solve(objective, use_owlqn, history, max_iter, tolerance,
                       rel_function_tolerance, batch, l2v, l1v, bounds=None):
    """Module-level jit: one compiled vmapped-grid program per
    (objective, optimizer statics) pair, reused across train_glm_grid calls
    of the same shapes. ``bounds``: optional (lower[d], upper[d]) box shared
    by every lane. ``rel_function_tolerance``: the live function-decrease
    stop inside every lane's while_loop — the λ-grid shares the RE-bucket
    pathology of every lane paying the worst lane's max_iter (CLAUDE.md);
    the objective stays use_pallas=False because these lanes are vmapped."""
    from photon_ml_tpu.optim.lbfgs import minimize_lbfgs
    from photon_ml_tpu.optim.owlqn import minimize_owlqn

    bound = objective.bind(batch)
    dtype = l2v.dtype

    def solve_one(l2, l1):
        def vg(w):
            v, g = bound.value_and_grad(w)
            return v + 0.5 * l2 * jnp.vdot(w, w), g + l2 * w

        w0 = jnp.zeros((batch.dim,), dtype)
        if use_owlqn:
            return minimize_owlqn(
                vg, w0, l1_weight=l1,
                max_iter=max_iter, tolerance=tolerance, history=history,
                rel_function_tolerance=rel_function_tolerance,
            )
        return minimize_lbfgs(
            vg, w0, max_iter=max_iter, tolerance=tolerance, history=history,
            rel_function_tolerance=rel_function_tolerance,
            lower_bounds=None if bounds is None else bounds[0],
            upper_bounds=None if bounds is None else bounds[1],
        )

    return jax.vmap(solve_one)(l2v, l1v)


def train_glm_tournament(
    batch: LabeledPointBatch,
    task: TaskType,
    configs,
    *,
    optimizer: OptimizerConfig | None = None,
    warm_start=None,
    normalization: NormalizationContext | None = None,
    intercept_index: int | None = None,
    telemetry=None,
):
    """Train one vmapped config tournament (per-lane l1/l2/tolerance/box
    vectors — the generalization of :func:`train_glm_grid`'s λ-only lanes).

    ``configs``: algorithm.lane_search.LaneConfigs. Returns the
    TournamentResult (per-lane SolverResult stack + model-space GLMs); the
    GP ask/tell loop above it lives in hyperparameter/search_driver.py.
    """
    from photon_ml_tpu.algorithm.lane_search import run_lane_tournament

    return run_lane_tournament(
        batch, task, configs, optimizer=optimizer, warm_start=warm_start,
        normalization=normalization, intercept_index=intercept_index,
        telemetry=telemetry,
    )


def _objective_for_batch(batch, loss, l2_weight, normalization,
                         use_pallas: bool | None = False):
    """Dense or sparse objective by batch type — one train_glm[/grid] code
    path serves both the [n, d] block and the giant-d flat-COO layout.

    use_pallas: False for vmapped-lane consumers (train_glm_grid — a Pallas
    call inside a vmapped solver loop degrades to a serial per-lane loop),
    None (auto) for sequential solves (train_glm)."""
    if isinstance(batch, SparseLabeledPointBatch):
        return SparseGLMObjective(
            loss, l2_weight=l2_weight, normalization=normalization
        )
    return GLMObjective(loss, l2_weight=l2_weight, normalization=normalization,
                        use_pallas=use_pallas)


@functools.partial(ledger_jit, label="glm/grid_diagonals", static_argnums=(0,))
def _jitted_grid_diagonals(objective, batch, coeffs, l2v):
    """All lanes' Hessian diagonals in one shared read of the feature block."""
    per_lane = lambda w, l2: objective.hessian_diagonal(w, batch) + l2
    return jax.vmap(per_lane)(coeffs, l2v)


@functools.partial(ledger_jit, label="glm/grid_full_variances", static_argnums=(0,))
def _jitted_grid_full_variances(objective, batch, coeffs, l2v):
    """All lanes' diag(H⁻¹) (DistributedOptimizationProblem.scala:82-96)."""
    def per_lane(w, l2):
        h = objective.hessian_matrix(w, batch)
        h = h + l2 * jnp.eye(h.shape[0], dtype=h.dtype)
        return diag_inverse_from_hessian(h)

    return jax.vmap(per_lane)(coeffs, l2v)


def train_glm(
    batch: LabeledPointBatch,
    task: TaskType,
    *,
    optimizer: OptimizerConfig | None = None,
    regularization_weights: Sequence[float] = (0.0,),
    elastic_net_alpha: float = 0.0,
    normalization: NormalizationContext | None = None,
    intercept_index: int | None = None,
    compute_variance: bool = False,
    variance_mode: str = "auto",
    lower_bounds=None,
    upper_bounds=None,
    telemetry=None,
) -> dict[float, GeneralizedLinearModel]:
    """Single-GLM regularization path with warm starts.

    Reference: ModelTraining.trainGeneralizedLinearModel (ModelTraining.scala:
    106-228) — foldLeft over sorted λs, warm-starting each from the previous.
    elastic_net_alpha: fraction of λ on L1 (α λ ‖w‖₁ + (1-α) λ/2 ‖w‖²).
    Returned models are in original feature space (warm starts stay in
    normalized space internally).

    telemetry: optional ``telemetry.SolverTelemetry`` — one convergence row
    (iterations, reason, value history) per λ solve.
    """
    optimizer = resolve_auto_optimizer(optimizer or OptimizerConfig())
    validate_variance_mode(variance_mode)
    has_bounds = lower_bounds is not None or upper_bounds is not None
    if has_bounds and (
        elastic_net_alpha > 0.0
        or optimizer.optimizer_type
        not in (OptimizerType.LBFGS, OptimizerType.LBFGSB)
    ):
        # fail before any lambda trains; solve() enforces the same rule
        raise ValueError(
            "box constraints require the LBFGS family without L1 "
            "(elastic_net_alpha must be 0)"
        )
    loss = loss_for_task(task)
    models: dict[float, GeneralizedLinearModel] = {}
    w = jnp.zeros((batch.dim,), dtype=batch.solve_dtype)
    for lam in sorted(regularization_weights):
        l1 = elastic_net_alpha * lam
        l2 = (1.0 - elastic_net_alpha) * lam
        objective = _objective_for_batch(batch, loss, l2, normalization,
                                         use_pallas=None)
        opt = optimizer
        if l1 > 0.0:
            opt = dataclasses.replace(
                optimizer.with_l1(l1), optimizer_type=OptimizerType.OWLQN
            )
        result = solve(
            opt, objective.bind(batch), w,
            lower_bounds=None if lower_bounds is None else jnp.asarray(lower_bounds, batch.dtype),
            upper_bounds=None if upper_bounds is None else jnp.asarray(upper_bounds, batch.dtype),
        )
        w = result.coefficients
        if telemetry is not None:
            telemetry.record_solve("glm", result, extra={"lambda": lam})
            telemetry.heartbeat("glm", lam=lam,
                                n_lambdas=len(regularization_weights))
        norm = objective.normalization
        means = norm.to_model_space(w, intercept_index)
        variances = None
        if compute_variance:
            variances = norm.variances_to_model_space(
                coefficient_variances(objective, w, batch, mode=variance_mode)
            )
        models[lam] = GeneralizedLinearModel(
            Coefficients(means=means, variances=variances), task
        )
        logger.info(
            "trained λ=%g: value=%g iters=%d", lam, float(result.value), int(result.iterations)
        )
    return models


def _normalization_digest(norm) -> str | None:
    """16-hex content digest of a NormalizationContext's factor/shift
    arrays (None for no normalization) — the streaming checkpoint
    fingerprint field that makes a resume under DIFFERENT normalization
    statistics fail fast (the class name cannot: every non-NONE type is
    the same NormalizationContext)."""
    if norm is None:
        return None
    import hashlib

    h = hashlib.sha256()
    for part in (norm.factors, norm.shifts):
        if part is None:
            h.update(b"none")
        else:
            h.update(np.ascontiguousarray(jax.device_get(part)).tobytes())
    return h.hexdigest()[:16]


def train_glm_streaming(
    source,
    task: TaskType,
    *,
    optimizer: OptimizerConfig | None = None,
    regularization_weights: Sequence[float] = (0.0,),
    elastic_net_alpha: float = 0.0,
    normalization: NormalizationContext | None = None,
    intercept_index: int | None = None,
    telemetry=None,
    mesh=None,
    exchange=None,
    prefetch: bool = True,
    retry_policy=None,
    chunk_timeout: float | None = None,
    lower_bounds=None,
    upper_bounds=None,
    checkpointer=None,
) -> dict[float, GeneralizedLinearModel]:
    """Single-GLM regularization path over an OUT-OF-CORE chunk stream.

    The streaming twin of :func:`train_glm` (reference
    ModelTraining.scala:106-228's warm-started foldLeft over sorted λs):
    ``source`` is an ``io.stream_reader.ChunkSource`` whose data never
    materializes in core — every objective evaluation is one exact chunked
    epoch (algorithm/streaming.StreamingGLMObjective), host decode
    double-buffered behind device accumulation, and the solvers run their
    identical per-iteration math in ``host_loop`` mode. Final
    loss/coefficients match the in-core solve to float round-off (chunked
    summation order is the only difference; tests/test_streaming.py pins
    it on dense and hybrid-sparse fixtures).

    LBFGS/OWLQN/TRON only (NEWTON needs the dense [d, d] Hessian — use
    TRON for streamed second-order solves). ``exchange``: optional
    ``parallel.multihost.MetadataExchange`` — each rank streams its own
    block assignment and the per-epoch accumulators sum in rank order.
    ``prefetch=False`` decodes inline (the same-run OFF baseline the bench
    row measures against).

    ``checkpointer``: optional ``io.checkpoint.SolverCheckpointer`` —
    crash-safe resume for the streaming path. Every outer solver iteration
    (an epoch boundary: each iteration is an integral number of chunked
    epochs) persists the full optimizer state + λ-grid position + epoch
    cursor through the atomic checkpoint contract; a restarted run
    fast-forwards past completed λs, re-enters the in-flight solve
    MID-STATE (no epochs redone — counted on ``resilience/
    epochs_resumed``), and continues bitwise where it left off (one eval
    path, state arrays round-trip exactly). A checkpoint written under a
    different λ grid/optimizer/input fingerprint fails fast with the
    differing fields named. None (default) is bitwise the un-checkpointed
    path. With ``exchange``, only rank 0 writes (shared directory); every
    rank restores the same snapshot — the per-rank solves are
    deterministic replicas after the rank-ordered accumulator sums.
    """
    from photon_ml_tpu.algorithm.streaming import StreamingGLMObjective
    from photon_ml_tpu.io.stream_reader import DEFAULT_CHUNK_TIMEOUT
    from photon_ml_tpu.optim.optimizer import solver_state_class
    from photon_ml_tpu.telemetry import resilience_counters

    # AUTO -> LBFGS: a streamed host-loop objective is never the small-d
    # dense vmapped shape Newton promotion targets
    optimizer = resolve_auto_optimizer(optimizer or OptimizerConfig())
    if optimizer.optimizer_type == OptimizerType.NEWTON:
        raise ValueError(
            "NEWTON cannot stream (dense [d, d] Hessian); use TRON for "
            "streamed second-order solves"
        )
    has_bounds = lower_bounds is not None or upper_bounds is not None
    if has_bounds and (
        elastic_net_alpha > 0.0
        or optimizer.optimizer_type
        not in (OptimizerType.LBFGS, OptimizerType.LBFGSB)
    ):
        # same rule as train_glm: fail before any lambda trains
        raise ValueError(
            "box constraints require the LBFGS family without L1 "
            "(elastic_net_alpha must be 0)"
        )
    loss = loss_for_task(task)
    solve_dtype = jnp.float32
    src_dtype = getattr(source, "dtype", None)
    if src_dtype is None and hasattr(source, "features"):
        src_dtype = source.features.dtype
    if src_dtype is not None:
        from photon_ml_tpu.data.batch import solve_dtype_of

        solve_dtype = solve_dtype_of(src_dtype)
    lams = sorted(float(l) for l in regularization_weights)

    # -- crash-safe resume: the fingerprint pins everything a restored
    # solver state is only valid under; a stale/mismatched checkpoint
    # fails fast attributed instead of silently resuming a different solve
    fingerprint = None
    start_index = 0
    completed: list[tuple[float, np.ndarray]] = []
    resume_state_arrays = None
    epochs_total = 0
    resume_epochs_lambda = 0
    writes = exchange is None or exchange.rank == 0
    if checkpointer is not None:
        # EVERYTHING a restored solver state is only valid under — a
        # changed history size would mis-slot L-BFGS curvature pairs, a
        # changed tolerance/task/normalization would silently resume a
        # different solve; all of it fails fast attributed instead
        fingerprint = {
            "kind": "glm_streaming",
            "task": task.name,
            "lambdas": lams,
            "optimizer": optimizer.optimizer_type.name,
            "max_iterations": int(optimizer.max_iterations),
            "history": int(optimizer.history),
            "tolerance": float(optimizer.tolerance),
            "rel_function_tolerance": (
                None if optimizer.rel_function_tolerance is None
                else float(optimizer.rel_function_tolerance)
            ),
            "max_cg_iterations": int(optimizer.max_cg_iterations),
            "elastic_net_alpha": float(elastic_net_alpha),
            # content digest, not a class name: every non-NONE
            # normalization type builds the same NormalizationContext
            # class — only the factor/shift ARRAYS distinguish the solve
            # space a restored state is valid in
            "normalization": _normalization_digest(normalization),
            "intercept_index": (
                None if intercept_index is None else int(intercept_index)
            ),
            "bounded": bool(
                lower_bounds is not None or upper_bounds is not None
            ),
            "dim": int(source.dim),
            "num_chunks": int(source.num_chunks),
            "total_records": int(source.total_records),
            "num_ranks": 1 if exchange is None else int(exchange.num_ranks),
            # input IDENTITY, not just shape: a daily re-run against new
            # data of the same geometry must fail fast, not resume the old
            # run's mid-solve state against different bytes (file-backed
            # sources only; in-memory sources carry no stable identity)
            "input": (
                None if getattr(source, "files", None) is None
                else [
                    [os.path.basename(f), int(os.path.getsize(f))]
                    for f in source.files
                ]
            ),
        }
        progress = checkpointer.restore_progress(fingerprint)
        if progress is not None:
            start_index = progress.lam_index
            completed = list(progress.completed)
            resume_state_arrays = progress.state_arrays
            epochs_total = progress.epochs_total
            resume_epochs_lambda = progress.epochs_lambda
            resilience_counters.record_checkpoint_restore()
            resilience_counters.record_epochs_resumed(
                progress.epochs_total + progress.epochs_lambda
            )
            logger.info(
                "resuming streaming solve from checkpoint: λ %d/%d, "
                "iteration %d (%d epochs not redone)",
                start_index, len(lams), progress.iteration,
                progress.epochs_total + progress.epochs_lambda,
            )

    models: dict[float, GeneralizedLinearModel] = {}
    w = jnp.zeros((source.dim,), dtype=solve_dtype)
    for li, lam in enumerate(lams):
        l1 = elastic_net_alpha * lam
        l2 = (1.0 - elastic_net_alpha) * lam
        objective = StreamingGLMObjective(
            source, loss,
            l2_weight=l2,
            normalization=normalization,
            mesh=mesh,
            exchange=exchange,
            prefetch=prefetch,
            retry_policy=retry_policy,
            chunk_timeout=(
                DEFAULT_CHUNK_TIMEOUT if chunk_timeout is None
                else chunk_timeout
            ),
        )
        norm = objective.objective.normalization
        if li < start_index:
            # completed before the restored checkpoint: the saved
            # solve-space coefficients ARE the model (and the next λ's
            # warm start) — zero epochs spent
            w = jnp.asarray(completed[li][1], solve_dtype)
            models[lam] = GeneralizedLinearModel(
                Coefficients(means=norm.to_model_space(w, intercept_index)),
                task,
            )
            continue
        opt = optimizer
        if l1 > 0.0:
            opt = dataclasses.replace(
                optimizer.with_l1(l1), optimizer_type=OptimizerType.OWLQN
            )
        resume_state = None
        if li == start_index and resume_state_arrays is not None:
            cls = solver_state_class(opt)
            resume_state = cls(**{
                k: jnp.asarray(v) for k, v in resume_state_arrays.items()
            })
            objective.epochs = resume_epochs_lambda
        observers = []
        if telemetry is not None:
            # per-outer-iteration (== epoch-boundary) liveness heartbeat
            # (ISSUE 12): the epoch cursor a wedged run is diagnosed by,
            # appended to the crash-durable journal stage; observes only
            def _hb_observer(state, _li=li, _obj=objective):
                telemetry.heartbeat(
                    "glm_streaming", lam_index=_li, n_lambdas=len(lams),
                    iteration=int(state.iteration), epochs=_obj.epochs,
                )

            observers.append(_hb_observer)
        if checkpointer is not None and writes:
            def state_observer(state, _li=li, _obj=objective,
                               _mi=opt.max_iterations):
                if int(state.iteration) % checkpointer.save_every:
                    return  # cadence: model-sized snapshots are not free
                if int(state.reason) != 0 or int(state.iteration) >= _mi:
                    # the loop exits on this state; the λ-boundary
                    # snapshot right after solve() covers it — don't pay
                    # a second model-sized save for the same progress
                    return
                checkpointer.save_progress(
                    fingerprint=fingerprint,
                    lam_index=_li,
                    iteration=int(state.iteration),
                    epochs_total=epochs_total,
                    epochs_lambda=_obj.epochs,
                    completed=completed,
                    solver_state=state,
                )

            observers.append(state_observer)
        if not observers:
            state_observer = None
        elif len(observers) == 1:
            state_observer = observers[0]
        else:
            def state_observer(state, _obs=tuple(observers)):
                for obs in _obs:
                    obs(state)
        result = solve(
            opt, objective, w,
            lower_bounds=(
                None if lower_bounds is None
                else jnp.asarray(lower_bounds, solve_dtype)
            ),
            upper_bounds=(
                None if upper_bounds is None
                else jnp.asarray(upper_bounds, solve_dtype)
            ),
            host_loop=True,
            state_observer=state_observer,
            resume_state=resume_state,
        )
        w = result.coefficients
        if checkpointer is not None:
            completed.append((lam, np.asarray(jax.device_get(w))))
            epochs_total += objective.epochs
            if writes:
                # λ-boundary snapshot: a crash between λs resumes with
                # this λ done and no in-flight solver state
                checkpointer.save_progress(
                    fingerprint=fingerprint,
                    lam_index=li + 1,
                    iteration=0,
                    epochs_total=epochs_total,
                    epochs_lambda=0,
                    completed=completed,
                    solver_state=None,
                )
        if telemetry is not None:
            telemetry.record_solve(
                "glm_streaming", result,
                extra={"lambda": lam, "epochs": objective.epochs,
                       "chunks": source.num_chunks},
            )
        models[lam] = GeneralizedLinearModel(
            Coefficients(means=norm.to_model_space(w, intercept_index)), task
        )
        logger.info(
            "streamed λ=%g: value=%g iters=%d epochs=%d",
            lam, float(result.value), int(result.iterations),
            objective.epochs,
        )
    return models
