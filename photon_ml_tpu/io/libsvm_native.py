"""LibSVM -> CSR parsing with a native C++ fast path.

The parser (photon_ml_tpu/native/libsvm_loader.cpp) replaces the reference's
JVM-side LibSVM ingestion (photon-client io/deprecated/
LibSVMInputDataFormat.scala) with a single-pass C++ tokenizer; this module
exports it as numpy CSR arrays and falls back to a pure-Python parse when no
compiler is available. Semantic conventions (1-based indices by default,
±1 labels mapped to {0,1} for binary tasks) match io/data_reader.read_libsvm.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os

import numpy as np

from photon_ml_tpu.native.build import libsvm_native_available, load_libsvm_library


@dataclasses.dataclass
class LibSVMData:
    """CSR view of one or more LibSVM files.

    labels:      [n] float64, raw file labels
    row_offsets: [n+1] uint64
    cols:        [nnz] uint32 feature indices (0-based)
    vals:        [nnz] float64
    """

    labels: np.ndarray
    row_offsets: np.ndarray
    cols: np.ndarray
    vals: np.ndarray

    @property
    def num_rows(self) -> int:
        return int(self.labels.shape[0])

    @property
    def nnz(self) -> int:
        return int(self.cols.shape[0])

    @property
    def max_index(self) -> int:
        """Largest 0-based feature index, -1 when no features at all."""
        return int(self.cols.max()) if self.nnz else -1

    def mapped_labels(self) -> np.ndarray:
        """±1 binary convention -> {0,1}; other values pass through
        (same rule as data_reader.read_libsvm)."""
        binary = np.isin(self.labels, (-1.0, 1.0))
        return np.where(binary, (self.labels > 0).astype(np.float64), self.labels)

    def to_dense(self, num_cols: int | None = None, dtype=np.float64) -> np.ndarray:
        """[n, d] dense matrix (duplicate idx:val tokens accumulate)."""
        d = (self.max_index + 1) if num_cols is None else num_cols
        x = np.zeros((self.num_rows, d), dtype=dtype)
        row_idx = np.repeat(
            np.arange(self.num_rows, dtype=np.intp),
            np.diff(self.row_offsets).astype(np.intp),
        )
        keep = self.cols < d
        np.add.at(
            x,
            (row_idx[keep], self.cols[keep].astype(np.intp)),
            self.vals[keep].astype(dtype),
        )
        return x


def _parse_native(path: str, zero_based: bool) -> LibSVMData:
    lib = load_libsvm_library()
    err = ctypes.create_string_buffer(512)
    handle = lib.lsvm_parse(
        os.fsencode(path), int(zero_based), err, ctypes.c_uint64(len(err))
    )
    if not handle:
        raise ValueError(f"libsvm parse failed: {err.value.decode()}")
    try:
        n = lib.lsvm_num_rows(handle)
        nnz = lib.lsvm_nnz(handle)
        labels = np.empty(n, dtype=np.float64)
        row_offsets = np.empty(n + 1, dtype=np.uint64)
        cols = np.empty(nnz, dtype=np.uint32)
        vals = np.empty(nnz, dtype=np.float64)
        lib.lsvm_export(
            handle,
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            row_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            cols.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        )
        return LibSVMData(labels, row_offsets, cols, vals)
    finally:
        lib.lsvm_free(handle)


def _parse_python(path: str, zero_based: bool) -> LibSVMData:
    labels: list[float] = []
    offsets: list[int] = [0]
    cols: list[int] = []
    vals: list[float] = []
    with open(path, encoding="utf-8") as f:
        for line_no, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                if tok.startswith("#"):
                    break
                idx_s, sep, val_s = tok.partition(":")
                if not sep:
                    raise ValueError(
                        f"bad feature token {tok!r} at line {line_no} in {path}"
                    )
                idx = int(idx_s) - (0 if zero_based else 1)
                if idx < 0:
                    raise ValueError(
                        f"feature index out of range at line {line_no} in {path}"
                    )
                cols.append(idx)
                vals.append(float(val_s))
            offsets.append(len(cols))
    return LibSVMData(
        labels=np.asarray(labels, dtype=np.float64),
        row_offsets=np.asarray(offsets, dtype=np.uint64),
        cols=np.asarray(cols, dtype=np.uint32),
        vals=np.asarray(vals, dtype=np.float64),
    )


def parse_libsvm(
    path: str | os.PathLike, *, zero_based: bool = False, force_python: bool = False
) -> LibSVMData:
    """Parse one LibSVM file to CSR (native C++ when available)."""
    path = str(path)
    if os.path.isdir(path):
        raise IsADirectoryError(f"expected a LibSVM file, got directory: {path}")
    if not force_python and libsvm_native_available():
        return _parse_native(path, zero_based)
    return _parse_python(path, zero_based)


def concat_libsvm(parts: list[LibSVMData]) -> LibSVMData:
    """Concatenate several parsed files into one CSR block (date-range
    multi-path reads)."""
    if len(parts) == 1:
        return parts[0]
    labels = np.concatenate([p.labels for p in parts])
    cols = np.concatenate([p.cols for p in parts])
    vals = np.concatenate([p.vals for p in parts])
    offsets = [np.asarray([0], dtype=np.uint64)]
    base = np.uint64(0)
    for p in parts:
        offsets.append(p.row_offsets[1:] + base)
        base = base + p.row_offsets[-1]
    return LibSVMData(labels, np.concatenate(offsets), cols, vals)
