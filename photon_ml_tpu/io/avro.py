"""Minimal, dependency-free Apache Avro implementation.

The environment ships no Avro library (no fastavro / no avro-python3), and
the reference's entire data contract is Avro (photon-avro-schemas/*.avsc,
photon-client data/avro/AvroDataReader.scala, AvroUtils.scala). This module
implements the parts of the Avro 1.x specification the framework needs:

- binary encoding: zig-zag varint long/int, IEEE float/double, length-
  prefixed bytes/string, arrays, maps, unions, records, enums, fixed;
- object container files: magic ``Obj\\x01``, file-metadata map with
  ``avro.schema`` / ``avro.codec``, 16-byte sync marker, data blocks of
  (record count, byte size, payload, sync); codecs ``null`` and ``deflate``.

Records are plain Python dicts; schemas are the JSON-derived dict form.
This is a from-scratch implementation of the public Avro spec — no code
from the reference (which uses the Java Avro library via Spark).

Corrupt-input quarantine (``on_corrupt="quarantine"``): the container
readers can validate every block's framing (length bounds + trailing sync
marker) and full decode, SKIP corrupt blocks — resynchronizing on the next
16-byte sync marker, the recovery the Avro spec designed the marker for —
and count/journal the quarantined spans via telemetry
(``resilience/quarantined_blocks``). Strict raise stays the default and
its code path is byte-for-byte the pre-quarantine one
(tests/test_avro_native.py pins it).
"""

from __future__ import annotations

import io as _io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Iterable, Iterator

from photon_ml_tpu.telemetry import resilience_counters

MAGIC = b"Obj\x01"
DEFAULT_SYNC = bytes(range(16))

_PRIMITIVES = {"null", "boolean", "int", "long", "float", "double", "bytes", "string"}


class AvroError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Schema handling
# ---------------------------------------------------------------------------


class SchemaRegistry:
    """Resolves named-type references within one schema document."""

    def __init__(self):
        self.named: dict[str, dict] = {}

    def register(self, schema: dict):
        name = schema.get("name")
        if name:
            ns = schema.get("namespace")
            full = f"{ns}.{name}" if ns and "." not in name else name
            self.named[full] = schema
            self.named[name] = schema

    def resolve(self, schema: Any) -> Any:
        if isinstance(schema, str) and schema not in _PRIMITIVES:
            if schema not in self.named:
                raise AvroError(f"unknown named type {schema!r}")
            return self.named[schema]
        return schema


def parse_schema(schema: Any) -> tuple[Any, SchemaRegistry]:
    """Parse a schema (dict / JSON string), collecting named types."""
    if isinstance(schema, str) and (schema.startswith("{") or schema.startswith("[")):
        schema = json.loads(schema)
    registry = SchemaRegistry()

    def walk(s: Any):
        if isinstance(s, dict):
            t = s.get("type")
            if t in ("record", "enum", "fixed"):
                registry.register(s)
            if t == "record":
                for f in s["fields"]:
                    walk(f["type"])
            elif t == "array":
                walk(s["items"])
            elif t == "map":
                walk(s["values"])
            elif isinstance(t, (dict, list)):
                walk(t)
        elif isinstance(s, list):
            for branch in s:
                walk(branch)

    walk(schema)
    return schema, registry


def _schema_type(schema: Any) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    t = schema["type"]
    if isinstance(t, (dict, list)):
        return _schema_type(t)
    return t


# ---------------------------------------------------------------------------
# Binary encoding
# ---------------------------------------------------------------------------


def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def write_long(out: BinaryIO, n: int) -> None:
    n = _zigzag_encode(n)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes((b | 0x80,)))
        else:
            out.write(bytes((b,)))
            return


def read_long(inp: BinaryIO) -> int:
    shift = 0
    acc = 0
    while True:
        raw = inp.read(1)
        if not raw:
            raise EOFError("unexpected end of Avro data")
        b = raw[0]
        acc |= (b & 0x7F) << shift
        if not (b & 0x80):
            return _zigzag_decode(acc)
        shift += 7
        if shift > 70:
            raise AvroError("varint too long")


class BinaryEncoder:
    def __init__(self, out: BinaryIO, registry: SchemaRegistry):
        self.out = out
        self.registry = registry

    def write(self, schema: Any, datum: Any) -> None:
        schema = self.registry.resolve(schema)
        t = _schema_type(schema)
        out = self.out
        if t == "null":
            if datum is not None:
                raise AvroError(f"expected null, got {datum!r}")
        elif t == "boolean":
            out.write(b"\x01" if datum else b"\x00")
        elif t in ("int", "long"):
            write_long(out, int(datum))
        elif t == "float":
            out.write(struct.pack("<f", float(datum)))
        elif t == "double":
            out.write(struct.pack("<d", float(datum)))
        elif t == "bytes":
            write_long(out, len(datum))
            out.write(datum)
        elif t == "string":
            raw = datum.encode("utf-8") if isinstance(datum, str) else bytes(datum)
            write_long(out, len(raw))
            out.write(raw)
        elif t == "fixed":
            if len(datum) != schema["size"]:
                raise AvroError("fixed size mismatch")
            out.write(datum)
        elif t == "enum":
            write_long(out, schema["symbols"].index(datum))
        elif t == "array":
            if datum:
                write_long(out, len(datum))
                for item in datum:
                    self.write(schema["items"], item)
            write_long(out, 0)
        elif t == "map":
            if datum:
                write_long(out, len(datum))
                for k, v in datum.items():
                    self.write("string", k)
                    self.write(schema["values"], v)
            write_long(out, 0)
        elif t == "union":
            idx = self._union_branch(schema, datum)
            write_long(out, idx)
            self.write(schema[idx], datum)
        elif t == "record":
            for field in schema["fields"]:
                name = field["name"]
                if name in datum:
                    value = datum[name]
                elif "default" in field:
                    value = field["default"]
                else:
                    raise AvroError(f"missing field {name!r} for {schema['name']}")
                self.write(field["type"], value)
        else:
            raise AvroError(f"unsupported schema type {t!r}")

    def _union_branch(self, union: list, datum: Any) -> int:
        for i, branch in enumerate(union):
            bt = _schema_type(self.registry.resolve(branch))
            if datum is None and bt == "null":
                return i
            if datum is not None and bt != "null":
                if bt == "boolean" and not isinstance(datum, bool):
                    continue
                if bt in ("int", "long") and not isinstance(datum, int):
                    continue
                if bt in ("float", "double") and not isinstance(datum, (int, float)):
                    continue
                if bt in ("string", "enum") and not isinstance(datum, str):
                    continue
                if bt in ("bytes", "fixed") and not isinstance(datum, (bytes, bytearray)):
                    continue
                if bt == "array" and not isinstance(datum, (list, tuple)):
                    continue
                if bt in ("map", "record") and not isinstance(datum, dict):
                    continue
                return i
        raise AvroError(f"datum {datum!r} matches no union branch {union}")


class BinaryDecoder:
    def __init__(self, inp: BinaryIO, registry: SchemaRegistry):
        self.inp = inp
        self.registry = registry

    def read(self, schema: Any) -> Any:
        schema = self.registry.resolve(schema)
        t = _schema_type(schema)
        inp = self.inp
        if t == "null":
            return None
        if t == "boolean":
            return inp.read(1) == b"\x01"
        if t in ("int", "long"):
            return read_long(inp)
        if t == "float":
            return struct.unpack("<f", inp.read(4))[0]
        if t == "double":
            return struct.unpack("<d", inp.read(8))[0]
        if t == "bytes":
            return inp.read(read_long(inp))
        if t == "string":
            return inp.read(read_long(inp)).decode("utf-8")
        if t == "fixed":
            return inp.read(schema["size"])
        if t == "enum":
            return schema["symbols"][read_long(inp)]
        if t == "array":
            items = []
            while True:
                count = read_long(inp)
                if count == 0:
                    return items
                if count < 0:  # block with byte size
                    count = -count
                    read_long(inp)
                for _ in range(count):
                    items.append(self.read(schema["items"]))
        if t == "map":
            result: dict[str, Any] = {}
            while True:
                count = read_long(inp)
                if count == 0:
                    return result
                if count < 0:
                    count = -count
                    read_long(inp)
                for _ in range(count):
                    key = self.read("string")
                    result[key] = self.read(schema["values"])
        if t == "union":
            return self.read(schema[read_long(inp)])
        if t == "record":
            return {f["name"]: self.read(f["type"]) for f in schema["fields"]}
        raise AvroError(f"unsupported schema type {t!r}")


# ---------------------------------------------------------------------------
# Object container files
# ---------------------------------------------------------------------------

_META_SCHEMA = {"type": "map", "values": "bytes"}


def write_container(
    path: str | os.PathLike,
    schema: Any,
    records: Iterable[dict],
    *,
    codec: str = "deflate",
    block_records: int = 4096,
    sync: bytes = DEFAULT_SYNC,
) -> int:
    """Write an Avro object container file; returns the record count."""
    schema, registry = parse_schema(schema)
    meta_registry = SchemaRegistry()
    count = 0
    with open(path, "wb") as out:
        out.write(MAGIC)
        meta_enc = BinaryEncoder(out, meta_registry)
        meta_enc.write(
            _META_SCHEMA,
            {
                "avro.schema": json.dumps(schema).encode("utf-8"),
                "avro.codec": codec.encode("utf-8"),
            },
        )
        out.write(sync)

        buf = _io.BytesIO()
        enc = BinaryEncoder(buf, registry)
        in_block = 0

        def flush():
            nonlocal in_block
            if in_block == 0:
                return
            payload = buf.getvalue()
            if codec == "deflate":
                payload = zlib.compress(payload)[2:-4]  # raw deflate per spec
            elif codec != "null":
                raise AvroError(f"unsupported codec {codec!r}")
            write_long(out, in_block)
            write_long(out, len(payload))
            out.write(payload)
            out.write(sync)
            buf.seek(0)
            buf.truncate()
            in_block = 0

        for record in records:
            enc.write(schema, record)
            in_block += 1
            count += 1
            if in_block >= block_records:
                flush()
        flush()
    return count


def write_container_blocks(
    path: str | os.PathLike,
    schema: Any,
    blocks: "Iterable[tuple[int, bytes]]",
    *,
    codec: str = "deflate",
    sync: bytes = DEFAULT_SYNC,
) -> int:
    """Container framing over PRE-ENCODED record blocks ((count, payload)
    pairs of already-Avro-binary records) — the fast-writer entry point
    (vectorized encoders build payloads as numpy byte buffers; this adds
    the standard header/codec/sync framing)."""
    schema, _ = parse_schema(schema)
    count = 0
    with open(path, "wb") as out:
        out.write(MAGIC)
        BinaryEncoder(out, SchemaRegistry()).write(
            _META_SCHEMA,
            {
                "avro.schema": json.dumps(schema).encode("utf-8"),
                "avro.codec": codec.encode("utf-8"),
            },
        )
        out.write(sync)
        for n_records, payload in blocks:
            if n_records == 0:
                continue
            if codec == "deflate":
                # level 1: these payloads are mostly f64 noise where higher
                # levels buy little and cost ~3x the CPU
                payload = zlib.compress(payload, 1)[2:-4]  # raw deflate
            elif codec != "null":
                raise AvroError(f"unsupported codec {codec!r}")
            write_long(out, n_records)
            write_long(out, len(payload))
            out.write(payload)
            out.write(sync)
            count += n_records
    return count


def _check_on_corrupt(on_corrupt: str) -> None:
    if on_corrupt not in ("raise", "quarantine"):
        raise ValueError(
            f"on_corrupt must be 'raise' or 'quarantine', got {on_corrupt!r}"
        )


def read_container(
    path: str | os.PathLike, *, on_corrupt: str = "raise"
) -> Iterator[dict]:
    """Iterate records of an Avro object container file.

    on_corrupt: "raise" (default — strict, byte-identical to the original
    reader) or "quarantine" (skip corrupt blocks, resync on the sync
    marker, count+journal each skipped span; a block either decodes fully
    or contributes nothing)."""
    _check_on_corrupt(on_corrupt)
    if on_corrupt == "quarantine":
        yield from _read_container_quarantine(path)
        return
    with open(path, "rb") as inp:
        if inp.read(4) != MAGIC:
            raise AvroError(f"{path}: not an Avro container file")
        meta = BinaryDecoder(inp, SchemaRegistry()).read(_META_SCHEMA)
        schema, registry = parse_schema(meta["avro.schema"].decode("utf-8"))
        codec = meta.get("avro.codec", b"null").decode("utf-8")
        sync = inp.read(16)
        while True:
            try:
                n_records = read_long(inp)
            except EOFError:
                return
            size = read_long(inp)
            payload = inp.read(size)
            if codec == "deflate":
                payload = zlib.decompress(payload, -15)
            elif codec != "null":
                raise AvroError(f"unsupported codec {codec!r}")
            dec = BinaryDecoder(_io.BytesIO(payload), registry)
            for _ in range(n_records):
                yield dec.read(schema)
            if inp.read(16) != sync:
                raise AvroError(f"{path}: sync marker mismatch")


#: framing sanity bound: one block's record count / payload size can never
#: exceed the file size (a corrupt varint otherwise "allocates" petabytes)
def _plausible(n: int, limit: int) -> bool:
    return 0 <= n <= limit


def _resync(inp: BinaryIO, sync: bytes, start: int) -> int | None:
    """Scan forward from ``start`` for the next occurrence of the 16-byte
    sync marker; return the offset just AFTER it (the next block's start),
    or None when no further marker exists. Chunked with a 15-byte overlap
    so markers spanning chunk boundaries are found."""
    chunk = 1 << 16
    inp.seek(start)
    tail = b""
    while True:
        pos = inp.tell()
        data = inp.read(chunk)
        if not data:
            return None
        buf = tail + data
        hit = buf.find(sync)
        if hit >= 0:
            return pos - len(tail) + hit + 16
        tail = buf[-15:]


def _read_header(inp: BinaryIO, path) -> tuple[Any, SchemaRegistry, str, bytes]:
    """(schema, registry, codec, sync) of an open container, or AvroError.
    Header corruption is not quarantinable — without the schema and sync
    marker nothing downstream can be decoded or resynced."""
    if inp.read(4) != MAGIC:
        raise AvroError(f"{path}: not an Avro container file")
    meta = BinaryDecoder(inp, SchemaRegistry()).read(_META_SCHEMA)
    schema, registry = parse_schema(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    sync = inp.read(16)
    if len(sync) != 16:
        raise AvroError(f"{path}: truncated container header")
    return schema, registry, codec, sync


def _read_container_quarantine(path: str | os.PathLike) -> Iterator[dict]:
    """The skip-and-count reader behind ``on_corrupt='quarantine'``.

    Per block: validate count/size bounds, read the full payload, verify
    the trailing sync marker, decompress, decode ALL records — and only
    then yield them. Any failure quarantines the whole block (partial
    blocks never leak half-decoded records), records the byte span via
    telemetry, and resyncs on the next sync marker."""
    file_size = os.path.getsize(path)
    with open(path, "rb") as inp:
        schema, registry, codec, sync = _read_header(inp, path)
        if codec not in ("null", "deflate"):
            raise AvroError(f"{path}: unsupported codec {codec!r}")
        block_index = 0
        while True:
            block_start = inp.tell()
            if block_start >= file_size:
                return
            records: list[dict] = []
            try:
                n_records = read_long(inp)
                size = read_long(inp)
                if not _plausible(n_records, file_size) or not _plausible(
                    size, file_size - inp.tell()
                ):
                    raise AvroError(
                        f"implausible block framing (count={n_records}, "
                        f"size={size})"
                    )
                payload = inp.read(size)
                if len(payload) != size:
                    raise AvroError("truncated block payload")
                trailer = inp.read(16)
                if trailer != sync:
                    raise AvroError("sync marker mismatch")
                if codec == "deflate":
                    payload = zlib.decompress(payload, -15)
                buf = _io.BytesIO(payload)
                dec = BinaryDecoder(buf, registry)
                for _ in range(n_records):
                    records.append(dec.read(schema))
                if buf.read(1):
                    raise AvroError("trailing bytes after last record")
            # clean EOF returns before the try (block_start >= file_size),
            # so an EOFError here is corruption — a truncated tail or a
            # payload whose decode ran off its end — never end-of-data
            except (AvroError, EOFError, zlib.error, struct.error,
                    ValueError, IndexError, KeyError,
                    UnicodeDecodeError) as e:
                nxt = _resync(inp, sync, block_start + 1)
                end = file_size if nxt is None else nxt
                resilience_counters.record_quarantined_block(
                    str(path), block_index, block_start, end,
                    f"{type(e).__name__}: {e}",
                )
                block_index += 1
                if nxt is None:
                    return
                inp.seek(nxt)
                continue
            block_index += 1
            yield from records


def validate_container(
    path: str | os.PathLike,
) -> list[tuple[int, int, int, str]]:
    """Framing-only corruption scan: [(block_index, byte_start, byte_end,
    reason), ...] — empty means every block's length bounds and trailing
    sync marker check out. Cost is the header decode + one seek and a
    16-byte read per block (never a payload read), so the native decode
    path can gate on it cheaply before trusting a file
    (io/data_reader._read_merged_avro_native under quarantine)."""
    problems: list[tuple[int, int, int, str]] = []
    file_size = os.path.getsize(path)
    with open(path, "rb") as inp:
        _, _, codec, sync = _read_header(inp, path)
        if codec not in ("null", "deflate"):
            raise AvroError(f"{path}: unsupported codec {codec!r}")
        block_index = 0
        while True:
            block_start = inp.tell()
            if block_start >= file_size:
                return problems
            try:
                n_records = read_long(inp)
                size = read_long(inp)
                if not _plausible(n_records, file_size) or not _plausible(
                    size, file_size - inp.tell()
                ):
                    raise AvroError(
                        f"implausible block framing (count={n_records}, "
                        f"size={size})"
                    )
                inp.seek(size, os.SEEK_CUR)
                trailer = inp.read(16)
                if trailer != sync:
                    raise AvroError("sync marker mismatch")
            except EOFError:
                if block_start >= file_size:
                    return problems
                problems.append(
                    (block_index, block_start, file_size,
                     "truncated final block")
                )
                return problems
            except AvroError as e:
                nxt = _resync(inp, sync, block_start + 1)
                end = file_size if nxt is None else nxt
                problems.append((block_index, block_start, end, str(e)))
                block_index += 1
                if nxt is None:
                    return problems
                inp.seek(nxt)
                continue
            block_index += 1


def scan_block_index(
    path: str | os.PathLike, *, on_corrupt: str = "raise"
) -> list[tuple[int, int, int]]:
    """The container's block index: [(record_count, payload_bytes,
    payload_offset), ...] — scanned by SEEKING past every payload, so the
    cost is header decode + one seek per block, not a data read. This is
    what makes block-level partitioned ingestion cheap to plan
    (io/partitioned_reader.py splits few-large-files inputs by blocks).

    on_corrupt="quarantine" additionally VALIDATES each block's framing
    (length bounds + trailing sync marker — a 16-byte read per block) and
    drops corrupt spans from the index, counting each via telemetry; the
    default scan stays the seek-only fast path."""
    _check_on_corrupt(on_corrupt)
    if on_corrupt == "quarantine":
        return _scan_block_index_quarantine(path)
    blocks: list[tuple[int, int, int]] = []
    with open(path, "rb") as inp:
        if inp.read(4) != MAGIC:
            raise AvroError(f"{path}: not an Avro container file")
        BinaryDecoder(inp, SchemaRegistry()).read(_META_SCHEMA)
        inp.read(16)  # sync
        while True:
            try:
                n_records = read_long(inp)
            except EOFError:
                return blocks
            size = read_long(inp)
            blocks.append((n_records, size, inp.tell()))
            inp.seek(size + 16, os.SEEK_CUR)  # payload + sync


def _scan_block_index_quarantine(
    path: str | os.PathLike,
) -> list[tuple[int, int, int]]:
    """Framing-validated block index: corrupt spans are skipped-and-counted
    here (the planning pass is the authoritative skip decision for the
    blocks-mode partitioned read; the block-range reader then only ever
    decodes framing-intact blocks)."""
    file_size = os.path.getsize(path)
    blocks: list[tuple[int, int, int]] = []
    with open(path, "rb") as inp:
        _, _, codec, sync = _read_header(inp, path)
        if codec not in ("null", "deflate"):
            raise AvroError(f"{path}: unsupported codec {codec!r}")
        block_index = 0
        while True:
            block_start = inp.tell()
            if block_start >= file_size:
                return blocks
            try:
                n_records = read_long(inp)
                size = read_long(inp)
                if not _plausible(n_records, file_size) or not _plausible(
                    size, file_size - inp.tell()
                ):
                    raise AvroError(
                        f"implausible block framing (count={n_records}, "
                        f"size={size})"
                    )
                payload_offset = inp.tell()
                inp.seek(size, os.SEEK_CUR)
                if inp.read(16) != sync:
                    raise AvroError("sync marker mismatch")
            except EOFError:
                if block_start >= file_size:
                    return blocks
                resilience_counters.record_quarantined_block(
                    str(path), block_index, block_start, file_size,
                    "truncated final block",
                )
                return blocks
            except AvroError as e:
                nxt = _resync(inp, sync, block_start + 1)
                end = file_size if nxt is None else nxt
                resilience_counters.record_quarantined_block(
                    str(path), block_index, block_start, end, str(e)
                )
                block_index += 1
                if nxt is None:
                    return blocks
                inp.seek(nxt)
                continue
            blocks.append((n_records, size, payload_offset))
            block_index += 1


def read_container_block_range(
    path: str | os.PathLike, start_block: int, num_blocks: int,
    index: "list[tuple[int, int, int]] | None" = None,
    *, on_corrupt: str = "raise",
) -> Iterator[dict]:
    """Iterate the records of blocks [start_block, start_block+num_blocks)
    only — the partitioned reader's entry for a rank's block assignment.
    Seeks directly to the first selected payload via the block index
    (pass ``index`` from a prior :func:`scan_block_index` to skip the
    re-scan — the partitioned planner already holds it).

    on_corrupt="quarantine": a block whose payload fails to decompress or
    decode is skipped-and-counted instead of raising (framing corruption
    is the quarantining index scan's job — pass an index scanned with the
    same mode)."""
    _check_on_corrupt(on_corrupt)
    if num_blocks <= 0:
        return
    if index is None:
        index = scan_block_index(path, on_corrupt=on_corrupt)
    selected = index[start_block:start_block + num_blocks]
    if len(selected) != num_blocks:
        raise AvroError(
            f"{path}: block range [{start_block}, "
            f"{start_block + num_blocks}) exceeds {len(index)} blocks"
        )
    with open(path, "rb") as inp:
        inp.seek(4)
        meta = BinaryDecoder(inp, SchemaRegistry()).read(_META_SCHEMA)
        schema, registry = parse_schema(meta["avro.schema"].decode("utf-8"))
        codec = meta.get("avro.codec", b"null").decode("utf-8")
        for bi, (n_records, size, offset) in enumerate(selected):
            inp.seek(offset)
            payload = inp.read(size)
            if on_corrupt == "quarantine":
                records: list[dict] = []
                try:
                    if len(payload) != size:
                        raise AvroError("truncated block payload")
                    if codec == "deflate":
                        payload = zlib.decompress(payload, -15)
                    elif codec != "null":
                        raise AvroError(f"unsupported codec {codec!r}")
                    buf = _io.BytesIO(payload)
                    dec = BinaryDecoder(buf, registry)
                    for _ in range(n_records):
                        records.append(dec.read(schema))
                    if buf.read(1):
                        raise AvroError("trailing bytes after last record")
                except (AvroError, EOFError, zlib.error, struct.error,
                        ValueError, IndexError, KeyError,
                        UnicodeDecodeError) as e:
                    resilience_counters.record_quarantined_block(
                        str(path), start_block + bi, offset, offset + size,
                        f"{type(e).__name__}: {e}",
                    )
                    continue
                yield from records
                continue
            if codec == "deflate":
                payload = zlib.decompress(payload, -15)
            elif codec != "null":
                raise AvroError(f"unsupported codec {codec!r}")
            dec = BinaryDecoder(_io.BytesIO(payload), registry)
            for _ in range(n_records):
                yield dec.read(schema)


def read_container_schema(path: str | os.PathLike) -> dict:
    with open(path, "rb") as inp:
        if inp.read(4) != MAGIC:
            raise AvroError(f"{path}: not an Avro container file")
        meta = BinaryDecoder(inp, SchemaRegistry()).read(_META_SCHEMA)
        return json.loads(meta["avro.schema"].decode("utf-8"))


def list_avro_files(path: str | os.PathLike) -> list[str]:
    """The ``*.avro`` part files of a directory (sorted, Spark/OS markers
    skipped), or the path itself when it is a file — the ONE part-file
    listing rule shared by every reader."""
    p = str(path)
    if os.path.isfile(p):
        return [p]
    names = sorted(
        f for f in os.listdir(p)
        if f.endswith(".avro") and not f.startswith(("_", "."))
    )
    if not names:
        raise AvroError(f"no .avro files under {p}")
    return [os.path.join(p, name) for name in names]


def read_directory(
    path: str | os.PathLike, *, on_corrupt: str = "raise"
) -> Iterator[dict]:
    """Read every ``*.avro`` file under a directory (the reference reads
    HDFS directories of part files, AvroUtils.scala readAvroFiles)."""
    for name in list_avro_files(path):
        yield from read_container(name, on_corrupt=on_corrupt)
